"""Pandas fallback interpreter — the analog of the reference's
source-DataFrame scan path (SURVEY.md §4.4: rewrite failure ⇒ correct-but-
slow execution, never an error; BASELINE.json:7 keeps a CPU-fallback
config). Implements the same SELECT subset as the parser with the same
null semantics as the device kernels (comparisons with NULL are False,
nulls form their own group, COUNT(col) counts non-nulls), so the parity
harness can compare the two paths row for row.
"""

from __future__ import annotations

import re
import threading

import numpy as np
import pandas as pd

from tpu_olap.ir.expr import (BinOp, Col, FuncCall, Lit, Subquery,
                              WindowCall)
from tpu_olap.obs.trace import span as _obs_span
from tpu_olap.planner.exprutil import (contains_agg as _contains_agg,
                                       expr_key as _k, map_stmt_exprs,
                                       render as _auto_name,
                                       split_and as _split_and)
from tpu_olap.planner.sqlparse import (AGG_FUNCS, SelectStmt, UnionStmt)
from tpu_olap.resilience.errors import QueryError
from tpu_olap.segments.dictionary import _like_to_regex

_TIME_FUNCS = {"year", "month", "day", "dayofmonth", "quarter",
               "hour", "minute", "second"}
_THETA_SET_FNS = {"theta_sketch_intersect", "theta_sketch_union",
                  "theta_sketch_not"}


class FallbackError(QueryError):
    """The interpreter cannot serve this statement either (unsupported
    SQL shape, or a refused-at-scale result). The request itself is the
    problem, so the HTTP surface maps it to 400 — distinguishable from
    transient 429/503/504 resilience errors."""

    code = "unsupported_sql"
    retriable = False
    http_status = 400


def _run_inner_stmt(s, catalog, config) -> pd.DataFrame:
    """Execute a derived-table body: through the engine's statement
    executor when the catalog carries one (device path for rewritable
    inner aggregates — the reference's split: Spark consumed the
    subquery result, the rewritten inner pushed to Druid, SURVEY.md
    §3.1; soak r05 showed 100% of fuzz fallbacks were derived-table
    statements whose inner scans are exactly the device-eligible part),
    else the pandas interpreter."""
    runner = getattr(catalog, "device_runner", None)
    if runner is not None and config.fallback_derived_on_device:
        return _coerce_nullable_numeric(runner(s))
    return execute_fallback(s, catalog, config)


def _coerce_nullable_numeric(df: pd.DataFrame) -> pd.DataFrame:
    """Device frames render NULL numeric aggregates as None inside
    object columns; the interpreter's predicate evaluation (like pandas
    aggregation itself) expects float64 + NaN — normalize any
    all-numeric object column the way pandas would have produced it, so
    `WHERE m > 0` over a nullable max() keeps working (the "never an
    error" property, SURVEY.md §2 prop 2). Python bool is an int
    subclass, so booleans are EXCLUDED explicitly: a nullable BOOLEAN
    column must stay True/False/None, not silently coerce to 1.0/0.0
    float64 (which would survive comparisons but corrupt rendering and
    any downstream boolean logic)."""
    for c in df.columns:
        if df[c].dtype == object:
            vals = df[c][df[c].notna()]
            if len(vals) < len(df[c]) and len(vals) and all(
                    isinstance(v, (int, float, np.integer, np.floating))
                    and not isinstance(v, (bool, np.bool_))
                    for v in vals):
                df[c] = pd.to_numeric(df[c], errors="coerce")
    return df


def execute_fallback(stmt, catalog, config) -> pd.DataFrame:
    if isinstance(stmt, UnionStmt):
        return _execute_union(stmt, catalog, config)
    stmt = _resolve_subqueries(stmt, catalog, config)
    if stmt.derived is not None:
        # FROM (SELECT ...) alias: the derived result is the base frame.
        # Its scope is its own — reject outer-table qualifiers inside
        # the body (they would strip onto the inner frame silently).
        _check_uncorrelated(stmt.derived)
        with _obs_span("fallback-derived"):
            df = _run_inner_stmt(stmt.derived, catalog, config)
        time_col = None
    else:
        entry = catalog.get(stmt.table)
        if entry.parquet_paths and entry._frame is None and \
                (entry.parquet_rows or 0) > config.fallback_chunk_rows:
            # SF-scale parquet table: stream row-group chunks instead of
            # materializing one frame (SURVEY.md §2 property 2 at scale)
            with _obs_span("fallback-chunked"):
                return _execute_chunked(stmt, entry, catalog, config)
        df = entry.frame
        time_col = entry.time_column
        if any(isinstance(c, Lit) and c.value is False
               for c in _split_and(stmt.where)):
            # a statically-false WHERE conjunct (e.g. the decorrelator's
            # empty-input default probe): skip the full copy + time sort
            df = df.iloc[0:0].copy()
        elif time_col is not None and time_col in df.columns:
            # match the accelerated path's deterministic time-sorted row
            # order (segments are time-sorted, so unordered LIMIT picks
            # the same rows). Served from the entry's memoized sorted
            # frame — downstream operators never mutate it in place, so
            # no per-query defensive copy + O(n log n) re-sort.
            df = entry.time_sorted_frame()
        else:
            df = df.copy()

    with _obs_span("fallback-filter") as fsp:
        df = _join_and_filter(stmt, df, catalog, time_col, config)
        fsp.set(rows=len(df))

    out_names = []
    exprs = []
    for e, alias in stmt.projections:
        if isinstance(e, Col) and e.name == "*":
            for c in df.columns:
                out_names.append(c)
                exprs.append(Col(c))
            continue
        out_names.append(alias or _auto_name(e))
        exprs.append(e)

    has_agg = any(_contains_agg(e) for e in exprs)
    group_exprs = list(stmt.group_by)
    if stmt.distinct and not has_agg and not group_exprs:
        group_exprs = list(exprs)

    with _obs_span("fallback-agg"):
        if stmt.grouping_sets is not None:
            out = _grouping_sets_aggregate(df, exprs, out_names, stmt,
                                           time_col)
        elif group_exprs or has_agg:
            out = _aggregate(df, exprs, out_names, group_exprs, stmt,
                             time_col)
        else:
            out = pd.DataFrame(
                {n: _eval(e, df, time_col)
                 for n, e in zip(out_names, exprs)})
            out = out.reset_index(drop=True)

    if stmt.order_by and not (group_exprs or has_agg):
        keys, ascending = [], []
        for i, item in enumerate(stmt.order_by):
            name = _auto_name(item.expr)
            col = name if name in out.columns else None
            if col is None:
                col = f"__sort{i}"  # indexed: two computed keys coexist
                out[col] = _eval(item.expr, df, time_col).to_numpy()
            keys.append(col)
            ascending.append(not item.descending)
        out = _sort_order_items(out, keys, stmt.order_by,
                                default_low=False)
        out = out.drop(columns=[c for c in keys if c.startswith("__sort")])
    lo = stmt.offset
    hi = None if stmt.limit is None else lo + stmt.limit
    return out.iloc[lo:hi].reset_index(drop=True)


# ---------------------------------------------------------------------------
# Shapes outside the rewrite subset (UNION, derived tables, subqueries):
# the reference handed these to full Spark SQL (SURVEY.md §3.1); here the
# interpreter executes them compositionally.


def _execute_union(stmt: UnionStmt, catalog, config) -> pd.DataFrame:
    frames = [execute_fallback(p, catalog, config) for p in stmt.parts]
    cols = list(frames[0].columns)
    for f in frames[1:]:
        if len(f.columns) != len(cols):
            raise FallbackError(
                f"{stmt.op.upper()} branches have {len(cols)} vs "
                f"{len(f.columns)} columns")
    frames = [f.set_axis(cols, axis=1) for f in frames]
    if stmt.op == "union":
        out = pd.concat(frames, ignore_index=True)
        if not stmt.all:
            out = out.drop_duplicates(ignore_index=True)
    else:
        # INTERSECT / EXCEPT: set semantics (dedup first, like SQL)
        out = frames[0].drop_duplicates(ignore_index=True)
        for f in frames[1:]:
            keep = pd.MultiIndex.from_frame(out).isin(
                pd.MultiIndex.from_frame(f.drop_duplicates()))
            if stmt.op == "except":
                keep = ~keep
            out = out[keep].reset_index(drop=True)
    if stmt.order_by:
        keys, ascending = [], []
        for item in stmt.order_by:
            name = _auto_name(item.expr)
            if name not in cols:
                raise FallbackError(
                    f"UNION ORDER BY {name!r} is not an output column")
            keys.append(name)
            ascending.append(not item.descending)
        out = _sort_order_items(out, keys, stmt.order_by)
    lo = stmt.offset
    hi = None if stmt.limit is None else lo + stmt.limit
    return out.iloc[lo:hi].reset_index(drop=True)


def _norm_gcol(s: pd.Series) -> pd.Series:
    """Group-key column with numeric NaNs normalized to the string fill
    (matching _norm_key), so dict/merge/reindex keys line up."""
    if not (s.dtype == object
            or str(s.dtype).startswith(("str", "category"))):
        return s.astype(object).where(s.notna(), _FILL)
    return s


def _as_str_series(v, df, fn: str) -> pd.Series:
    """Coerce a string-function argument to a Series, with a legible
    error for non-string input (raw .str would raise AttributeError)."""
    s = v if isinstance(v, pd.Series) else pd.Series(v, index=df.index)
    if not (s.dtype == object or str(s.dtype).startswith(("str",
                                                          "category"))):
        raise FallbackError(
            f"{fn}() needs a string argument, got {s.dtype}")
    return s


def _check_uncorrelated(stmt):
    """Reject correlated subqueries LEGIBLY: a qualified column whose
    table prefix is not in the subquery's own FROM/JOIN scope references
    the outer query. Without this check the evaluator's qualifier
    stripping (name.split('.')[-1]) would silently resolve `outer.x`
    against the INNER frame and return wrong rows."""
    def scope_tables(s):
        if isinstance(s, UnionStmt):
            out = set()
            for p in s.parts:
                out |= scope_tables(p)
            return out
        return _scope_names(s)

    def walk_expr(e, tables):
        if e is None or isinstance(e, Lit):
            return
        if isinstance(e, Col):
            if "." in e.name:
                qual = e.name.rsplit(".", 1)[0]
                if qual not in tables:
                    raise FallbackError(
                        f"correlated subquery reference {e.name!r} is "
                        "not supported (rewrite as a join)")
            return
        if isinstance(e, Subquery):
            return  # nested scope checks itself when resolved
        if isinstance(e, BinOp):
            walk_expr(e.left, tables)
            walk_expr(e.right, tables)
        elif isinstance(e, WindowCall):
            for a in e.args:
                walk_expr(a, tables)
            for p in e.partition_by:
                walk_expr(p, tables)
            for oe, _ in e.order_by:
                walk_expr(oe, tables)
        elif isinstance(e, FuncCall):
            for a in e.args:
                walk_expr(a, tables)

    def walk_stmt(s):
        if isinstance(s, UnionStmt):
            for p in s.parts:
                walk_stmt(p)
            return
        tables = scope_tables(s)
        for e, _ in s.projections:
            walk_expr(e, tables)
        walk_expr(s.where, tables)
        walk_expr(s.having, tables)
        for e in s.group_by:
            walk_expr(e, tables)
        for item in s.order_by:
            walk_expr(item.expr, tables)
        for j in s.joins:
            walk_expr(j.on, tables)
            if j.derived is not None:
                walk_stmt(j.derived)
        if s.derived is not None:
            walk_stmt(s.derived)

    walk_stmt(stmt)
    return stmt


def _scalar_from(sub_df: pd.DataFrame):
    if sub_df.shape[1] != 1 or len(sub_df) > 1:
        raise FallbackError(
            f"scalar subquery returned shape {sub_df.shape}; need 1x1")
    if len(sub_df) == 0:
        return None
    v = sub_df.iloc[0, 0]
    if pd.isna(v):
        return None
    return v.item() if hasattr(v, "item") else v


def _scope_names(s) -> set:
    """Qualifier names resolvable in s's own FROM/JOIN scope. An alias
    HIDES the base table name (standard SQL): `FROM fact f2` makes
    `fact.x` an OUTER reference inside that scope."""
    names = {s.table_alias or s.table}
    names |= {j.alias or j.table for j in s.joins}
    return names


def _uncorrelated(stmt) -> bool:
    try:
        _check_uncorrelated(stmt)
        return True
    except FallbackError:
        return False


def _resolve_subqueries(stmt: SelectStmt, catalog, config,
                        run=None) -> SelectStmt:
    """Replace Subquery nodes (scalar) and in_subquery calls (IN lists)
    with literals by executing the nested statements, and LOOKUP(col,
    'name') references with their registered map inlined (the evaluator
    has no catalog access). Equality-correlated subqueries (the TPC-H
    class: scalar aggregates, EXISTS, IN) decorrelate into precomputed
    key->value maps evaluated per outer row; any other correlation shape
    keeps the legible rejection.

    `run` executes one nested statement -> DataFrame. The default is the
    pandas interpreter; the planner passes the engine's stmt executor so
    inner aggregates ride the device path (the reference's split: Spark
    ran the subquery, the rewritten outer query pushed to Druid —
    SURVEY.md §3.1)."""
    if run is None:
        run = lambda s: execute_fallback(s, catalog, config)  # noqa: E731
    hit = False
    outer_tables = _scope_names(stmt) if isinstance(stmt, SelectStmt) \
        else set()

    def walk(e):
        if e is None:
            return e
        from tpu_olap.ir.expr import map_expr
        return map_expr(e, special)

    def special(e):
        """Subquery-bearing nodes resolve to replacements; None lets
        the shared walker rebuild from mapped children."""
        nonlocal hit
        if isinstance(e, FuncCall) and e.name == "exists":
            # EXISTS (SELECT ...): true iff the subquery returns any row
            # — one row is enough, so cap it
            hit = True
            import dataclasses as _dc
            s = e.args[0].stmt
            if not _uncorrelated(s):
                try:
                    return _decorrelate_exists(s, outer_tables, catalog,
                                               config, run)
                except FallbackError as err:
                    return _nested_loop_corr(
                        "exists", s, None, stmt, outer_tables, catalog,
                        config, run, err)
            inner = _dc.replace(s, limit=1, order_by=[])
            sub = run(inner)
            return Lit(len(sub) > 0)
        if isinstance(e, Subquery):
            hit = True
            if not _uncorrelated(e.stmt):
                try:
                    return _decorrelate_scalar(e.stmt, outer_tables,
                                               catalog, config, run)
                except FallbackError as err:
                    return _nested_loop_corr(
                        "scalar", e.stmt, None, stmt, outer_tables,
                        catalog, config, run, err)
            return Lit(_scalar_from(run(e.stmt)))
        if isinstance(e, FuncCall) and e.name == "in_subquery":
            hit = True
            lhs = walk(e.args[0])
            if not _uncorrelated(e.args[1].stmt):
                try:
                    return _decorrelate_in(lhs, e.args[1].stmt,
                                           outer_tables, catalog,
                                           config, run)
                except FallbackError as err:
                    return _nested_loop_corr(
                        "in", e.args[1].stmt, lhs, stmt, outer_tables,
                        catalog, config, run, err)
            sub = run(e.args[1].stmt)
            if sub.shape[1] != 1:
                raise FallbackError(
                    f"IN subquery returned {sub.shape[1]} columns")
            if len(sub) > config.fallback_scan_row_cap:
                raise FallbackError(
                    "IN subquery result exceeds fallback_scan_row_cap")
            # one packed Lit holding every value — per-value Lit nodes
            # would allocate millions of objects for big subqueries.
            # NULLs are DROPPED: `x IN (SELECT ...)` never matches on a
            # NULL member (SQL; and the same rule the correlated
            # decorrelation applies) — unlike a LITERAL in-list, where
            # an explicit NULL matches null rows (Druid's in filter)
            vals = tuple(v.item() if hasattr(v, "item") else v
                         for v in sub.iloc[:, 0] if not pd.isna(v))
            return FuncCall("in_list_packed", (lhs, Lit(vals)))
        if isinstance(e, FuncCall) and e.name == "lookup" \
                and len(e.args) == 2 and isinstance(e.args[1], Lit):
            hit = True
            mapping = catalog.lookups.get(e.args[1].value)
            if mapping is None:
                raise FallbackError(f"unknown lookup {e.args[1].value!r}")
            return FuncCall("lookup_map",
                            (walk(e.args[0]),
                             Lit(tuple(sorted(mapping.items())))))
        return None

    from tpu_olap.planner.exprutil import map_stmt_exprs
    out = map_stmt_exprs(stmt, walk)
    return out if hit else stmt


# ---------------------------------------------------------------------------
def _outer_col_refs(s, outer_tables):
    """Every outer-scope Col referenced anywhere in the subquery (the
    nested-loop substitution targets), name-sorted for determinism.
    Refs inside doubly-nested Subquery nodes are not collected — after
    substitution those resolve (or fail legibly) at their own scope."""
    from tpu_olap.ir.expr import map_expr
    inner_tables = _scope_names(s)
    found = {}

    def collect(x):
        if isinstance(x, Col) and "." in x.name:
            qual = x.name.rsplit(".", 1)[0]
            if qual not in inner_tables and qual in outer_tables:
                found.setdefault(x.name, x)
        return None

    map_stmt_exprs(s, lambda e: e if e is None else map_expr(e, collect))
    return [found[n] for n in sorted(found)]


def _nested_loop_corr(kind, s, lhs, outer_stmt, outer_tables, catalog,
                      config, run, reason):
    """Bounded nested-loop decorrelation — the escape hatch for
    correlation shapes the magic-set rewrite cannot serve (VERDICT r4
    missing #2; SURVEY.md §2 property 2: rewrite failure must mean slow,
    never an error). Enumerates the outer scope's distinct correlated-
    column tuples (probe: DISTINCT over the outer FROM/JOIN tree with
    WHERE dropped — a superset is correct, the subquery re-applies its
    own predicates), refuses legibly past corr_nested_loop_cap, runs the
    subquery once per tuple with outer refs substituted as literals, and
    folds the results into the same corr_*_map nodes the rewrite emits.
    `reason` is the rewrite's FallbackError, re-raised when this hatch
    cannot apply (UNION shapes, no collectable refs)."""
    import dataclasses as _dc
    from tpu_olap.ir.expr import map_expr
    if not isinstance(s, SelectStmt) \
            or not isinstance(outer_stmt, SelectStmt):
        raise reason
    refs = _outer_col_refs(s, outer_tables)
    if not refs:
        raise reason
    cap = config.corr_nested_loop_cap
    probe = _dc.replace(
        outer_stmt,
        projections=[(c, f"__ok{i}") for i, c in enumerate(refs)],
        distinct=True, where=None, group_by=[], grouping_sets=None,
        having=None, order_by=[], limit=cap + 1, offset=0)
    outer_keys = run(probe)
    if len(outer_keys) > cap:
        raise FallbackError(
            f"correlated subquery did not decorrelate ({reason}); the "
            "nested-loop fallback is bounded at corr_nested_loop_cap="
            f"{cap} distinct outer key tuples and this outer scope "
            "has more")
    names = [c.name for c in refs]

    def substitute(kt):
        env = dict(zip(names, kt))

        def sub1(x):
            if isinstance(x, Col) and x.name in env:
                return Lit(env[x.name])
            return None

        return map_stmt_exprs(
            s, lambda e: e if e is None else map_expr(e, sub1))

    kcols = [outer_keys[f"__ok{i}"] for i in range(len(refs))]
    tuples = set(_key_rows(kcols))
    if kind == "scalar":
        items = [(kt, _plain(_scalar_from(run(substitute(kt)))))
                 for kt in tuples]
        return FuncCall("corr_scalar_map",
                        (Lit(tuple(items)), Lit(None)) + tuple(refs))
    if kind == "exists":
        keyset = {
            kt for kt in tuples
            if len(run(_dc.replace(substitute(kt), limit=1,
                                   order_by=[])))}
        return FuncCall("corr_exists_map",
                        (Lit(tuple(keyset)),) + tuple(refs))
    pairs = []
    for kt in tuples:
        res = run(substitute(kt))
        if res.shape[1] != 1:
            raise FallbackError(
                "IN subquery must project exactly one column")
        for v in res.iloc[:, 0]:
            pv = _plain(v)
            if pv is not None:  # NULL members never match
                pairs.append(kt + (pv,))
    return FuncCall("corr_in_map",
                    (Lit(tuple(pairs)), lhs) + tuple(refs))


# Decorrelation (SURVEY.md §3.1 margin the reference served via Spark SQL):
# an equality-correlated subquery  (... WHERE inner_expr = outer.col ...)
# becomes a pre-aggregated key->value map over the inner table, evaluated
# per outer row by corr_*_map — the classic magic-set rewrite of the
# TPC-H correlation class (Q2/Q4/Q17/Q21/Q22 shapes), without needing
# derived-frame join plumbing.


def _plain(v):
    """Frame cell -> hashable python scalar (None for SQL null)."""
    if v is None or (not isinstance(v, (str, bytes, tuple)) and pd.isna(v)):
        return None
    return v.item() if hasattr(v, "item") else v


def _key_rows(kser):
    """Row-major normalized key tuples from key Series — one .tolist()
    per column (C-level scalar conversion) instead of per-cell .iloc,
    since these maps evaluate on frames up to fallback_scan_row_cap."""
    cols = [[_plain(x) for x in s.tolist()] for s in kser]
    return zip(*cols)


def _and_all(conjs):
    out = None
    for c in conjs:
        out = c if out is None else BinOp("&&", out, c)
    return out


_CMP_FLIP = {">": "<", "<": ">", ">=": "<=", "<=": ">=", "!=": "!="}


def _corr_split(s, outer_tables, allow_cmp=False):
    """Split the subquery WHERE into correlation keys and residual:
    keys = [(inner_expr, outer Col)] from equality conjuncts referencing
    the outer scope; cmp_keys = [(inner_expr, op, outer Col)] from
    comparison conjuncts (collected only when allow_cmp — the EXISTS
    min/max reduction); residual = pure-inner conjuncts. Raises legibly
    for any other correlation shape (outer refs outside WHERE, refs to a
    scope that is neither inner nor the immediate outer)."""
    if isinstance(s, UnionStmt):
        raise FallbackError("correlated UNION subquery is not supported")
    inner_tables = _scope_names(s)

    def outer_col(x):
        return (isinstance(x, Col) and "." in x.name
                and x.name.rsplit(".", 1)[0] not in inner_tables)

    def refs_outer(x):
        if x is None or isinstance(x, (Lit, Subquery)):
            return False
        if isinstance(x, Col):
            return outer_col(x)
        if isinstance(x, BinOp):
            return refs_outer(x.left) or refs_outer(x.right)
        if isinstance(x, WindowCall):
            return (any(refs_outer(a) for a in x.args)
                    or any(refs_outer(p) for p in x.partition_by)
                    or any(refs_outer(oe) for oe, _ in x.order_by))
        if isinstance(x, FuncCall):
            return any(refs_outer(a) for a in x.args)
        return False

    keys, cmp_keys, residual = [], [], []
    for c in _split_and(s.where):
        if not refs_outer(c):
            residual.append(c)
            continue
        ok = False
        if isinstance(c, BinOp) and c.op == "==":
            for ie, oe in ((c.right, c.left), (c.left, c.right)):
                if outer_col(oe) and not refs_outer(ie):
                    qual = oe.name.rsplit(".", 1)[0]
                    if qual not in outer_tables:
                        raise FallbackError(
                            f"subquery reference {oe.name!r} names a "
                            "table in neither the subquery nor the "
                            "immediately enclosing query")
                    keys.append((ie, oe))
                    ok = True
                    break
        elif allow_cmp and isinstance(c, BinOp) and c.op in _CMP_FLIP:
            # normalize to inner_expr OP outer_col
            for ie, oe, op in ((c.left, c.right, c.op),
                               (c.right, c.left, _CMP_FLIP[c.op])):
                if outer_col(oe) and not refs_outer(ie):
                    qual = oe.name.rsplit(".", 1)[0]
                    if qual not in outer_tables:
                        raise FallbackError(
                            f"subquery reference {oe.name!r} names a "
                            "table in neither the subquery nor the "
                            "immediately enclosing query")
                    cmp_keys.append((ie, op, oe))
                    ok = True
                    break
        if not ok:
            raise FallbackError(
                "correlated subquery: only equality"
                + ("/comparison" if allow_cmp else "")
                + " correlation to an outer column is decorrelated "
                f"(got {_auto_name(c)!r})")
    if not keys and not cmp_keys:
        raise FallbackError(
            "correlated subquery reference outside WHERE is not "
            "supported (rewrite as a join)")
    for e, _ in s.projections:
        if refs_outer(e):
            raise FallbackError(
                "correlated subquery: outer references are only "
                "decorrelated inside WHERE equality conjuncts")
    for j in s.joins:
        if refs_outer(j.on):
            raise FallbackError(
                "correlated subquery: outer reference in a JOIN "
                "condition is not supported")
    for coll in (s.group_by, [i.expr for i in s.order_by]):
        for e in coll:
            if refs_outer(e):
                raise FallbackError(
                    "correlated subquery: outer references are only "
                    "decorrelated inside WHERE equality conjuncts")
    if s.having is not None and refs_outer(s.having):
        raise FallbackError(
            "correlated subquery: outer reference in HAVING is not "
            "supported")
    return keys, cmp_keys, residual


def _corr_shape_guard(s, what):
    if isinstance(s, UnionStmt):
        raise FallbackError(f"correlated {what}: UNION is not supported")
    if s.group_by or s.having is not None or s.derived is not None \
            or s.limit is not None or s.offset:
        raise FallbackError(
            f"correlated {what}: only a plain FROM/WHERE subquery is "
            "decorrelated (rewrite as a join)")


def _decorrelate_scalar(s, outer_tables, catalog, config, run):
    """(SELECT agg(...) FROM u WHERE u.k = t.k AND residual) -> a
    key->scalar map; outer rows with no matching key take the aggregate's
    empty-input value (NULL, or 0 for COUNT) computed by actually running
    the aggregate over zero rows."""
    import dataclasses as _dc
    _corr_shape_guard(s, "scalar subquery")
    if len(s.projections) != 1 or not _contains_agg(s.projections[0][0]):
        raise FallbackError(
            "correlated scalar subquery must project exactly one "
            "aggregate expression")
    keys, _cmp, residual = _corr_split(s, outer_tables)
    proj = s.projections[0][0]
    gproj = [(ie, f"__ck{i}") for i, (ie, _) in enumerate(keys)]
    inner = _dc.replace(
        s, projections=gproj + [(proj, "__sc")], distinct=False,
        group_by=[ie for ie, _ in keys], where=_and_all(residual),
        order_by=[], limit=None, offset=0)
    try:
        sub = run(inner)
        # empty-input probe: keep the pure-inner residual (comma joins
        # need their conditions) and conjoin a statically-false leaf
        empty = _dc.replace(s, where=_and_all(residual + [Lit(False)]),
                            order_by=[], limit=None, offset=0)
        default = _scalar_from(run(empty))
    except FallbackError as err:
        # e.g. an UNQUALIFIED outer reference in the SELECT list resolves
        # as an unknown inner column — surface it as the correlation
        # limit it is, not a phantom missing column
        raise FallbackError(
            f"correlated scalar subquery did not decorrelate: {err}")
    items = []
    kcols = [sub[f"__ck{j}"] for j in range(len(keys))]
    vals = [_plain(v) for v in sub["__sc"].tolist()]
    for kt, v in zip(_key_rows(kcols), vals):
        if any(k is None for k in kt):
            continue  # a NULL key never equals anything
        items.append((kt, v))
    return FuncCall("corr_scalar_map",
                    (Lit(tuple(items)), Lit(default))
                    + tuple(oe for _, oe in keys))


def _decorrelate_exists(s, outer_tables, catalog, config, run):
    """EXISTS (SELECT ... FROM u WHERE u.k = t.k AND residual) -> a
    membership set over the correlation keys (semi-join)."""
    import dataclasses as _dc
    _corr_shape_guard(s, "EXISTS")
    if any(_contains_agg(e) for e, _ in s.projections):
        # an ungrouped aggregate subquery yields exactly one row even
        # over zero input rows, so EXISTS is true for EVERY outer row
        # (group_by shapes never reach here: _corr_shape_guard rejects)
        return Lit(True)
    keys, cmp_keys, residual = _corr_split(s, outer_tables,
                                           allow_cmp=True)
    if cmp_keys:
        # min/max reduction: EXISTS(... inner_e OP t.col AND eq-keys)
        # <=> the per-eq-group extreme of inner_e satisfies OP against
        # the outer value. Sound only for ONE comparison conjunct —
        # two comparisons must hold on the SAME inner row, which
        # min/max cannot witness
        if len(cmp_keys) > 1:
            raise FallbackError(
                "correlated EXISTS: at most one comparison-correlation "
                "conjunct is decorrelated")
        ce, op, oe_cmp = cmp_keys[0]
        inner = _dc.replace(
            s, projections=[(ie, f"__ck{i}")
                            for i, (ie, _) in enumerate(keys)]
            + [(FuncCall("min", (ce,)), "__lo"),
               (FuncCall("max", (ce,)), "__hi")],
            distinct=False, group_by=[ie for ie, _ in keys],
            where=_and_all(residual), order_by=[], limit=None, offset=0)
        sub = run(inner)
        kcols = [sub[f"__ck{j}"] for j in range(len(keys))]
        items = []
        for kt, lo, hi in zip(
                _key_rows(kcols) if keys else ((),) * len(sub),
                (_plain(v) for v in sub["__lo"].tolist()),
                (_plain(v) for v in sub["__hi"].tolist())):
            if any(k is None for k in kt) or lo is None:
                continue  # NULL key never matches; all-NULL group: no
            items.append((kt, (lo, hi)))   # non-null value to witness
        return FuncCall(
            "corr_exists_cmp_map",
            (Lit(tuple(items)), Lit(op), oe_cmp)
            + tuple(oe for _, oe in keys))
    inner = _dc.replace(
        s, projections=[(ie, f"__ck{i}") for i, (ie, _) in enumerate(keys)],
        distinct=True, group_by=[], where=_and_all(residual),
        order_by=[], limit=None, offset=0)
    sub = run(inner)
    kcols = [sub[f"__ck{j}"] for j in range(len(keys))]
    keyset = {kt for kt in _key_rows(kcols)
              if not any(k is None for k in kt)}
    return FuncCall("corr_exists_map",
                    (Lit(tuple(keyset)),) + tuple(oe for _, oe in keys))


def _decorrelate_in(lhs, s, outer_tables, catalog, config, run):
    """x IN (SELECT y FROM u WHERE u.k = t.k AND residual) -> membership
    over (key..., y) tuples; NULL x or NULL y never match (the engine's
    comparisons-with-NULL-are-False rule)."""
    import dataclasses as _dc
    _corr_shape_guard(s, "IN subquery")
    if len(s.projections) != 1:
        raise FallbackError("IN subquery must project exactly one column")
    keys, _cmp, residual = _corr_split(s, outer_tables)
    ve = s.projections[0][0]
    inner = _dc.replace(
        s, projections=[(ie, f"__ck{i}")
                        for i, (ie, _) in enumerate(keys)] + [(ve, "__v")],
        distinct=True, group_by=[], where=_and_all(residual),
        order_by=[], limit=None, offset=0)
    sub = run(inner)
    if len(sub) > config.fallback_scan_row_cap:
        raise FallbackError(
            "IN subquery result exceeds fallback_scan_row_cap")
    kcols = [sub[f"__ck{j}"] for j in range(len(keys))] + [sub["__v"]]
    pairs = {kt for kt in _key_rows(kcols)
             if not any(k is None for k in kt)}
    return FuncCall("corr_in_map",
                    (Lit(tuple(pairs)), lhs) + tuple(oe for _, oe in keys))


_JOIN_HOW = {"inner": "inner", "left": "left", "right": "right",
             "full": "outer"}


def _merge_one(df, other, j, lcol, rcol, extras, time_col):
    """One join step. Extra ON conjuncts participate in the MATCH for
    outer kinds (SQL: an unmatched preserved row keeps NULLs — it is not
    re-filtered by the ON condition), so those kinds take an inner match
    + add-back-unmatched construction; a plain post-merge filter would
    silently turn LEFT JOIN ... ON a=b AND extra into an inner join."""
    sfx = ("", f"__{j.table}")
    if j.kind == "inner" or not extras:
        out = df.merge(other, left_on=lcol, right_on=rcol,
                       how=_JOIN_HOW[j.kind], suffixes=sfx)
        for c in extras:  # inner only: filtering == matching
            out = out[_eval_bool(c, out, time_col)]
        return out
    ldf = df.reset_index(drop=True).copy()
    ldf["__lid"] = np.arange(len(ldf))
    rdf = other.reset_index(drop=True).copy()
    rdf["__rid"] = np.arange(len(rdf))
    m = ldf.merge(rdf, left_on=lcol, right_on=rcol, how="inner",
                  suffixes=sfx)
    for c in extras:
        m = m[_eval_bool(c, m, time_col)]
    parts = [m]
    if j.kind in ("left", "full"):
        parts.append(ldf[~ldf["__lid"].isin(m["__lid"])])
    if j.kind in ("right", "full"):
        un = rdf[~rdf["__rid"].isin(m["__rid"])]
        collide = [c for c in un.columns if c in ldf.columns]
        # same-named join keys coalesce into ONE output column in the
        # merged frame; keep the unmatched right rows' key under that
        # coalesced name instead of suffixing it away (else every
        # preserved-but-unmatched row reads NULL for its own key)
        ren = {c: c + sfx[1] for c in collide
               if not (c == rcol and rcol == lcol)}
        parts.append(un.rename(columns=ren))
    out = pd.concat(parts, ignore_index=True)
    return out.drop(columns=[c for c in ("__lid", "__rid")
                             if c in out.columns])


def _join_and_filter(stmt, df, catalog, time_col, config,
                     derived_cache=None):
    """Apply the statement's joins (equi-joins; conditions from ON or
    WHERE) and residual WHERE conjuncts to one frame. Fixed point over
    the join list: a snowflake chain's parent may be listed after its
    child, and the link column only appears once the parent merges.
    RIGHT/FULL OUTER joins are order-sensitive, so their presence pins
    strict listed-order processing (no deferral). The chunked drivers
    pass a shared `derived_cache` so a derived-join subquery executes
    once per query, not once per chunk."""
    derived_frames = derived_cache if derived_cache is not None else {}

    def frame_of(j):
        if j.derived is not None:
            # JOIN (SELECT ...) alias / JOIN-position CTE: its scope is
            # its own — an outer-table qualifier inside the body would
            # be silently stripped onto the inner frame by the
            # evaluator, so reject correlation up front (non-LATERAL
            # derived tables cannot see the outer row in standard SQL)
            if id(j) not in derived_frames:
                _check_uncorrelated(j.derived)
                derived_frames[id(j)] = _run_inner_stmt(
                    j.derived, catalog, config)
            return derived_frames[id(j)]
        return catalog.get(j.table).frame

    if stmt.joins and (stmt.table_alias is not None
                       or stmt.derived is not None
                       or any(j.alias is not None or j.derived is not None
                              for j in stmt.joins)):
        # the evaluator resolves qualified refs by STRIPPING the
        # qualifier, which is only sound when every qualifier maps to
        # distinctly-named columns — in an aliased multi-table scope with
        # same-named columns (e.g. a self-join `t a JOIN t b`) a stripped
        # ref would silently read the wrong frame. Allow the scope when
        # column names are pairwise disjoint (USING keys coalesce, so
        # they are exempt); reject the ambiguous remainder legibly.
        seen = set(df.columns)
        clash = set()
        for j in stmt.joins:
            cols = set(frame_of(j).columns) - set(j.using or ())
            clash |= cols & seen
            seen |= cols
        if clash:
            raise FallbackError(
                "aliased multi-table FROM with same-named columns is not "
                "supported (qualified refs would not disambiguate "
                f"{sorted(clash)[:5]})")
    where_conjs = _split_and(stmt.where)
    pending = list(stmt.joins)
    strict = any(j.kind in ("right", "full") for j in pending)
    while pending:
        still = []
        for j in pending:
            other = frame_of(j)
            if j.kind == "cross":
                df = df.merge(other, how="cross",
                              suffixes=("", f"__{j.table}"))
                continue
            if j.using is not None:
                missing = [c for c in j.using
                           if c not in df.columns or c not in other.columns]
                if missing:
                    raise FallbackError(
                        f"USING column(s) {missing} not on both sides of "
                        f"the join with {j.table!r}")
                # merge on the full column list: pandas coalesces the
                # same-named keys, matching SQL USING output
                df = df.merge(other, on=list(j.using),
                              how=_JOIN_HOW[j.kind],
                              suffixes=("", f"__{j.table}"))
                continue
            conds = _split_and(j.on) if j.on is not None else where_conjs
            pair = None
            for c in conds:
                p = _equi_pair(c, df.columns, other.columns)
                if p:
                    pair = (c, p)
                    break
            if pair is None:
                if strict:
                    raise FallbackError(
                        f"no join condition for {j.table!r} at its "
                        "position (RIGHT/FULL joins run in listed order)")
                still.append(j)
                continue
            cond, (lcol, rcol) = pair
            if j.on is None:
                where_conjs.remove(cond)
            extras = [c for c in _split_and(j.on) if c is not cond] \
                if j.on is not None else []
            df = _merge_one(df, other, j, lcol, rcol, extras, time_col)
        if len(still) == len(pending):
            raise FallbackError(
                f"no join condition for {still[0].table!r}")
        pending = still

    for c in where_conjs:
        m = _eval_bool(c, df, time_col)
        if isinstance(m, bool):  # constant predicate, e.g. EXISTS(...)
            if not m:
                df = df.iloc[0:0]
            continue
        df = df[m]
    return df


def _gset_expr(e, gkeys, full_keys):
    """Projection expr for one grouping set: absent group keys become
    NULL literals, GROUPING(key) becomes 0/1. Shared by the fallback
    union below and the device-union leg builder (grouping_set_legs)."""
    if isinstance(e, FuncCall) and e.name == "grouping" \
            and len(e.args) == 1:
        return Lit(0 if _k(e.args[0]) in gkeys else 1)
    if _k(e) in full_keys and _k(e) not in gkeys:
        return Lit(None)
    if isinstance(e, BinOp):
        return BinOp(e.op, _gset_expr(e.left, gkeys, full_keys),
                     _gset_expr(e.right, gkeys, full_keys))
    if isinstance(e, FuncCall) and e.name not in AGG_FUNCS:
        return FuncCall(e.name, tuple(_gset_expr(a, gkeys, full_keys)
                                      for a in e.args))
    return e


def grouping_set_legs(stmt):
    """Decompose a GROUPING SETS/ROLLUP/CUBE statement into one ordinary
    GROUP BY statement per set, for the DEVICE union path (VERDICT r4
    missing #4: every leg is an already-device-eligible GROUP BY, so a
    union of cached-template dispatches serves the construct at device
    speed). Returns (out_names, legs); each leg is (leg_stmt, consts)
    where consts maps output columns this set does not compute (absent
    group keys -> None, GROUPING(k) -> 0/1) for post-hoc reattachment —
    keeping constant projections OUT of the leg SQL keeps every leg on
    the same compiled template family as its plain-GROUP BY twin.
    Output aliases are pinned from the ORIGINAL exprs so every leg
    yields the same column names. ORDER BY/LIMIT are stripped (the
    caller applies them over the union). HAVING is left untouched: a
    leg whose HAVING references columns outside its set simply fails
    rewrite and runs on the fallback, which evaluates it exactly as the
    whole-statement fallback would (_aggregate receives the same
    group_exprs + untransformed HAVING either way)."""
    import dataclasses as _dc
    if any(isinstance(e, Col) and e.name == "*"
           for e, _ in stmt.projections):
        raise FallbackError("SELECT * with GROUPING SETS is fallback-only")
    full_keys = {_k(g) for g in stmt.group_by}
    out_names = [a or _auto_name(e) for e, a in stmt.projections]
    legs = []
    for gset in stmt.grouping_sets:
        gkeys = {_k(g) for g in gset}
        projs, consts = [], {}
        for (e, _a), name in zip(stmt.projections, out_names):
            t = _gset_expr(e, gkeys, full_keys)
            if isinstance(t, Lit) and not isinstance(e, Lit):
                consts[name] = t.value
                continue
            projs.append((t, name))
        if not projs:
            # all projections folded to constants (pure-dimension set):
            # the leg must still yield one row PER GROUP of this set
            # (one row for the () set), so probe with a count the caller
            # reindexes away — without it the degenerate SELECT returns
            # zero rows and the set's rows vanish from the union
            projs.append((FuncCall("count", ()), "__gsrows"))
        legs.append((_dc.replace(
            stmt, projections=list(projs), group_by=list(gset),
            grouping_sets=None, order_by=[], limit=None, offset=0),
            consts))
    return out_names, legs


def union_order_keys(stmt, out_names):
    """ORDER BY key names over a grouping-set union: each item must
    reference an output column — by its spelled name or structurally
    (the parser resolves output aliases to their exprs, so ORDER BY s
    arrives as the sum(v) tree and must map back to 's'). None when an
    item references anything else (per-row exprs are meaningless over a
    union of differently-grouped rows)."""
    key_of = {_k(e): n
              for (e, _a), n in zip(stmt.projections, out_names)}
    keys = []
    for item in stmt.order_by:
        name = _auto_name(item.expr)
        if name not in out_names:
            name = key_of.get(_k(item.expr))
        if name is None:
            return None
        keys.append(name)
    return keys


def _grouping_sets_aggregate(df, exprs, out_names, stmt, time_col):
    """GROUP BY ROLLUP/CUBE/GROUPING SETS (the reference served these
    via full Spark SQL, SURVEY.md §3.1): one _aggregate pass per
    grouping set with the ABSENT group keys projected as NULL literals,
    results unioned, then ORDER BY/LIMIT over the union (applied here,
    not per set — standard SQL). HAVING filters inside each pass."""
    import dataclasses as _dc
    full_keys = {_k(g) for g in stmt.group_by}
    inner = _dc.replace(stmt, order_by=[], limit=None, offset=0)

    parts = []
    for gset in stmt.grouping_sets:
        gkeys = {_k(g) for g in gset}
        sub_exprs = [_gset_expr(e, gkeys, full_keys) for e in exprs]
        parts.append(_aggregate(df, sub_exprs, out_names, list(gset),
                                inner, time_col))
    out = pd.concat(parts, ignore_index=True) if parts \
        else pd.DataFrame(columns=out_names)
    if stmt.order_by:
        keys = union_order_keys(stmt, out_names)
        if keys is None:
            raise FallbackError(
                "ORDER BY over GROUPING SETS must reference output "
                "columns")
        out = _sort_order_items(out, keys, stmt.order_by)
    return out.reset_index(drop=True)


def _aggregate(df, exprs, out_names, group_exprs, stmt, time_col):
    gkeys = {}
    gname_of = {}
    for i, g in enumerate(group_exprs):
        name = f"__g{i}"
        gkeys[name] = _eval(g, df, time_col)
        gname_of[_k(g)] = name
    kdf = pd.DataFrame(gkeys) if gkeys else None

    def _filtered(sub, cond):
        m = _eval(cond, sub, time_col)
        m = pd.Series(m, index=sub.index).fillna(False).astype(bool)
        return sub[m]

    def agg_series(e, sub):
        if isinstance(e, FuncCall) and e.name in AGG_FUNCS:
            if e.name == "agg_filter":
                inner, cond = e.args
                return agg_series(inner, _filtered(sub, cond))
            if e.name == "count" and not e.args:
                return len(sub)
            if e.name == "count":
                return _eval_agg_input(e.args[0], sub, time_col) \
                    .notna().sum()
            if e.name in ("count_distinct", "approx_count_distinct",
                          "theta_sketch"):
                if e.name == "theta_sketch" and len(e.args) != 1:
                    # single-field, like the device aggregator
                    raise FallbackError("theta_sketch takes one column")
                vals = [_eval_agg_input(a, sub, time_col) for a in e.args]
                if len(vals) == 1:
                    return vals[0].dropna().nunique()
                tup = pd.concat(vals, axis=1).dropna()
                return len(tup.drop_duplicates())
            if e.name in ("sum_distinct", "avg_distinct"):
                v = _eval_agg_input(e.args[0], sub, time_col) \
                    .dropna().drop_duplicates()
                if e.name == "sum_distinct":
                    return v.sum() if len(v) else np.nan
                return v.sum() / len(v) if len(v) else np.nan
            v = _eval_agg_input(e.args[0], sub, time_col)
            if e.name == "sum":
                return v.sum()
            if e.name == "min":
                return v.min()
            if e.name == "max":
                return v.max()
            if e.name == "avg":
                return v.sum() / len(sub) if len(sub) else np.nan
            raise FallbackError(f"unknown aggregate {e.name!r}")
        if isinstance(e, FuncCall) and e.name in _THETA_SET_FNS:
            return float(len(_theta_set(e, sub)))
        if isinstance(e, FuncCall) and e.name == "theta_sketch_estimate" \
                and len(e.args) == 1:
            # _theta_set validates the argument IS a sketch (a plain
            # aggregate must error, not pass through as an "estimate")
            return float(len(_theta_set(e.args[0], sub)))
        if isinstance(e, BinOp):
            l_val = agg_series(e.left, sub)
            r_val = agg_series(e.right, sub)
            if e.op == "/":
                # NULL operand -> NULL (device: NaN propagates through
                # the post-agg); else ArithmeticPostAgg rule x/0 -> 0
                if pd.isna(l_val) or pd.isna(r_val):
                    return np.nan
                return float(l_val) / r_val if r_val else 0.0
            return _APPLY[e.op](l_val, r_val)
        if isinstance(e, Lit):
            return e.value
        raise FallbackError(f"non-aggregate projection {e!r} with GROUP BY")

    def _theta_set(e, sub) -> set:
        """Exact value set for a theta set-op tree (the fallback's exact
        analog of the device's KMV set operations)."""
        if isinstance(e, FuncCall) and e.name in _THETA_SET_FNS:
            if len(e.args) < 2:  # arity parity with the device rewrite
                raise FallbackError(
                    f"{e.name} takes at least two arguments")
            parts = [_theta_set(a, sub) for a in e.args]
            if e.name == "theta_sketch_union":
                return set().union(*parts)
            if e.name == "theta_sketch_intersect":
                out = parts[0]
                for p in parts[1:]:
                    out = out & p
                return out
            out = parts[0]
            for p in parts[1:]:
                out = out - p
            return out
        inner, sub2 = e, sub
        if isinstance(e, FuncCall) and e.name == "agg_filter":
            inner = e.args[0]
            sub2 = _filtered(sub, e.args[1])
        if not (isinstance(inner, FuncCall)
                and inner.name == "theta_sketch"):
            raise FallbackError(
                "theta sketch functions take theta_sketch(...) arguments "
                f"(optionally with FILTER), got {inner!r}")
        return set(_eval_agg_input(inner.args[0], sub2, time_col)
                   .dropna())

    rows = []
    if kdf is None:
        rec = {}
        for n, e in zip(out_names, exprs):
            rec[n] = agg_series(e, df)
        having = stmt.having
        if having is not None and not _having_ok(having, df, rec, time_col,
                                                 agg_series):
            return pd.DataFrame(columns=out_names)
        rows.append(rec)
        return pd.DataFrame(rows, columns=out_names)

    fill = "\0null"
    filled = kdf.copy()
    for c in filled.columns:
        if filled[c].dtype == object or str(filled[c].dtype).startswith(
                ("str", "category")):
            filled[c] = filled[c].fillna(fill)
    # pre-resolve ORDER BY items to either an output column or an
    # extra computed key evaluated per group
    order_cols, order_exprs, ascending = [], {}, []
    for i, item in enumerate(stmt.order_by):
        name = _auto_name(item.expr)
        if name in out_names:
            order_cols.append(name)
        else:
            col = f"__s{i}"
            order_cols.append(col)
            order_exprs[col] = item.expr
        ascending.append(not item.descending)

    grouped = df.groupby([filled[c] for c in filled.columns], sort=True,
                         dropna=False)
    for key, sub in grouped:
        if not isinstance(key, tuple):
            key = (key,)
        rec = {}
        for n, e in zip(out_names, exprs):
            gk = _k(e)
            if gk in gname_of:
                pos = list(kdf.columns).index(gname_of[gk])
                v = key[pos]
                rec[n] = None if (isinstance(v, str) and v == fill) else v
            else:
                rec[n] = agg_series(e, sub)
        if stmt.having is not None and not _having_ok(
                stmt.having, sub, rec, time_col, agg_series):
            continue
        for col, e in order_exprs.items():
            rec[col] = agg_series(e, sub) if _contains_agg(e) else \
                _eval(e, sub, time_col).iloc[0]
        rows.append(rec)
    out = pd.DataFrame(rows, columns=out_names + list(order_exprs))

    if order_cols:
        out = _sort_order_items(out, order_cols, stmt.order_by)
    return out[out_names].reset_index(drop=True)


# ---------------------------------------------------------------------------
# Chunked (streamed) fallback — bounded resident rows at SF scale.

_FILL = "\0null"


def _collect_agg_calls(e, into: dict):
    if isinstance(e, FuncCall) and e.name in AGG_FUNCS:
        into[_k(e)] = e
        return
    if isinstance(e, BinOp):
        _collect_agg_calls(e.left, into)
        _collect_agg_calls(e.right, into)
    elif isinstance(e, FuncCall):
        for a in e.args:
            _collect_agg_calls(a, into)


def _fill_strings(s: pd.Series) -> pd.Series:
    if s.dtype == object or str(s.dtype).startswith(("str", "category")):
        return s.fillna(_FILL)
    return s


def _execute_chunked(stmt: SelectStmt, entry, catalog, config):
    """Execute the fallback over streamed parquet row-group chunks:
    partial aggregation per chunk + pandas merge of decomposable partial
    states (sum/min/max/count as themselves, AVG as sum+rows, DISTINCT as
    deduplicated (group, value) pairs) — the host-side mirror of the
    device path's partial/final aggregate split (SURVEY.md §3.5 P2). A
    non-aggregate result larger than fallback_scan_row_cap refuses with a
    clear error instead of exhausting host RAM."""
    time_col = entry.time_column
    if stmt.grouping_sets is not None:
        raise FallbackError(
            "GROUPING SETS/ROLLUP/CUBE over a chunked-scale table is not "
            "supported yet; aggregate per set explicitly or reduce the "
            "table")
    if any(j.kind in ("right", "full") for j in stmt.joins):
        # per-chunk outer joins would re-emit every unmatched right row
        # once per chunk; correct chunked outer joins need global match
        # tracking, which the whole-frame path provides below the
        # chunking threshold
        raise FallbackError(
            "RIGHT/FULL OUTER join over a chunked-scale table is not "
            "supported; reduce the table or flip the join around the "
            "smaller side")
    batch = config.fallback_chunk_batch_rows
    chunks = entry.iter_chunks(batch)

    out_names, exprs = [], []
    star_expand = any(isinstance(e, Col) and e.name == "*"
                      for e, _ in stmt.projections)
    first = None
    dcache: dict = {}  # derived-join frames execute once per query,
    # shared across the schema probe and the chunk loops
    if star_expand:
        first = next(chunks, None)
        if first is None:
            return pd.DataFrame()
    for e, alias in stmt.projections:
        if isinstance(e, Col) and e.name == "*":
            base = _join_and_filter(stmt, first.iloc[:0], catalog,
                                    time_col, config,
                                    derived_cache=dcache)
            for c in base.columns:
                out_names.append(c)
                exprs.append(Col(c))
            continue
        out_names.append(alias or _auto_name(e))
        exprs.append(e)
    if first is not None:
        import itertools
        chunks = itertools.chain([first], chunks)

    has_agg = any(_contains_agg(e) for e in exprs)
    group_exprs = list(stmt.group_by)
    if stmt.distinct and not has_agg and not group_exprs:
        group_exprs = list(exprs)

    from tpu_olap.planner.exprutil import contains_window

    if any(contains_window(x) for x in exprs) or \
            any(contains_window(o.expr) for o in stmt.order_by):
        # per-chunk window evaluation would silently restart partitions
        # at every chunk boundary; requiring the whole frame here would
        # be the OOM the chunked path exists to avoid
        raise FallbackError(
            "window functions need the whole partition resident; over a "
            "chunked-scale table, aggregate first in a derived table "
            "(FROM (SELECT ... GROUP BY ...)) and window over that")

    if group_exprs or has_agg:
        return _chunked_aggregate(stmt, chunks, exprs, out_names,
                                  group_exprs, catalog, time_col, config,
                                  pair_cap=config.fallback_scan_row_cap,
                                  derived_cache=dcache, entry=entry)
    return _chunked_scan(stmt, chunks, exprs, out_names, catalog,
                         time_col, config, derived_cache=dcache)


def _chunked_scan(stmt, chunks, exprs, out_names, catalog, time_col,
                  config, derived_cache=None):
    order_exprs = {}
    for i, item in enumerate(stmt.order_by):
        name = _auto_name(item.expr)
        if name not in out_names:
            order_exprs[f"__s{i}"] = item.expr
    need = None
    if stmt.limit is not None and not stmt.order_by:
        need = stmt.offset + stmt.limit
    # unordered LIMIT: SQL allows any rows, but keep determinism within
    # the streamed window by sorting it on time (the whole-frame path
    # sorts the WHOLE table on time — streaming the whole table to honor
    # that exactly would defeat the early stop, so the guarantee here is
    # "time-sorted within the first chunks that satisfy the limit")
    time_sort = need is not None and time_col is not None
    parts, total = [], 0
    dcache = derived_cache if derived_cache is not None else {}
    for chunk in chunks:
        df = _join_and_filter(stmt, chunk, catalog, time_col, config,
                              derived_cache=dcache)
        if not len(df):
            continue
        part = pd.DataFrame(
            {n: _eval(e, df, time_col) for n, e in zip(out_names, exprs)})
        for col, e in order_exprs.items():
            part[col] = _eval(e, df, time_col).to_numpy()
        if time_sort and time_col in df.columns:
            part["__t"] = df[time_col].to_numpy()
        parts.append(part.reset_index(drop=True))
        total += len(part)
        if need is not None and total >= need:
            break
        if total > config.fallback_scan_row_cap:
            raise FallbackError(
                f"chunked fallback result exceeds fallback_scan_row_cap="
                f"{config.fallback_scan_row_cap} rows; narrow the query "
                "or raise the cap")
    if not parts:
        return pd.DataFrame(columns=out_names)
    out = pd.concat(parts, ignore_index=True)
    if stmt.order_by:
        keys = [(_auto_name(i.expr) if _auto_name(i.expr) in out_names
                 else f"__s{j}") for j, i in enumerate(stmt.order_by)]
        out = _sort_order_items(out, keys, stmt.order_by,
                                default_low=False)
    elif time_sort and "__t" in out.columns:
        out = out.sort_values("__t", kind="stable")
    lo = stmt.offset
    hi = None if stmt.limit is None else lo + stmt.limit
    return out[out_names].iloc[lo:hi].reset_index(drop=True)


# Fork-inherited context for the parallel chunked fallback: the worker
# function must be module-level (Pool pickles it by reference), but the
# closures/frames it needs are NOT picklable — they are handed over via
# this global, which the fork()ed children inherit by memory snapshot.
# The lock serializes concurrent parallel fallbacks (the BI server is a
# ThreadingHTTPServer and the fallback path takes no device lock): the
# global must not be overwritten between set and fork, or query A's
# workers would compute with query B's closures.
_PFORK_CTX = None
_PFORK_LOCK = threading.Lock()


def _pair_cap_refuse(name: str, pair_cap: int):
    """A high-cardinality DISTINCT aggregate needs the full value set;
    refusing with a clear error beats an OOM (the "never an error"
    property is already forfeit either way — this makes the failure
    legible/bounded). Shared by the sequential compact() and the fork
    workers so both paths refuse identically."""
    remedy = (
        "use approx_count_distinct on the device path or raise the cap"
        if name in ("count_distinct", "approx_count_distinct",
                    "theta_sketch") else "raise the cap")
    raise FallbackError(
        f"chunked fallback {name} exceeds "
        f"fallback_scan_row_cap={pair_cap} distinct pairs; {remedy}")


def _compact_pairs(pairs, distinct_specs, pair_cap):
    """Dedup each key's accumulated pair frames down to one and enforce
    the pair cap. Returns total retained pair rows."""
    total = 0
    for k, fs in pairs.items():
        if len(fs) > 1:
            pairs[k] = [pd.concat(fs, ignore_index=True)
                        .drop_duplicates()]
        if pairs[k] and len(pairs[k][0]) > pair_cap:
            _pair_cap_refuse(distinct_specs[k], pair_cap)
        total += len(pairs[k][0]) if pairs[k] else 0
    return total


def _pfork_worker(units):
    """One worker: stream assigned (path, row-group) units via the
    entry's iter_chunks (single source of the parquet read conventions),
    join+filter each chunk, compute partial aggregates, locally compact,
    and return (partial frames, {agg key: distinct-pair frames}).
    Distinct pairs are compacted and cap-checked incrementally (same
    ~1M-NEW-row trigger as the sequential loop) so a high-cardinality
    DISTINCT refuses legibly from inside the worker instead of
    accumulating toward an OOM."""
    (entry, chunk_partial, join, batch, gcols,
     merge_ops, distinct_specs, pair_cap) = _PFORK_CTX
    partials, pairs = [], {}
    pending_pairs = 0
    for chunk in entry.iter_chunks(batch_rows=batch, units=units):
        df = join(chunk)
        if not len(df):
            continue
        part, dp = chunk_partial(df)
        partials.append(part)
        for k, p in dp.items():
            pairs.setdefault(k, []).append(p)
            pending_pairs += len(p)
        if pending_pairs > (1 << 20):
            _compact_pairs(pairs, distinct_specs, pair_cap)
            pending_pairs = 0  # counts NEW pairs since last compaction
    if len(partials) > 1:  # bound the IPC payload
        cat = pd.concat(partials, ignore_index=True)
        if gcols:
            partials = [cat.groupby(gcols, sort=False, dropna=False)
                           .agg(merge_ops).reset_index()]
        else:
            partials = [cat.agg(merge_ops).to_frame().T]
    _compact_pairs(pairs, distinct_specs, pair_cap)
    return partials, pairs


def _parallel_timeout_s(config, entry) -> float:
    """Bound on the fork pool's map (ADVICE round 5): a deadlocked child
    must trigger the safe sequential retry interactively (the 45 s
    default), not after 15 min — but a legitimately huge parallel
    aggregate must not be cut off either, so the bound scales with the
    estimated scan size once the table passes ~200M rows (the default
    then grows proportionally: 2B rows -> 450 s)."""
    t = float(config.fallback_parallel_timeout_s)
    rows = (getattr(entry, "parquet_rows", None) or 0) \
        if entry is not None else 0
    return max(t, t * rows / 200_000_000.0)


def _parallel_chunk_partials(stmt, entry, catalog, config, time_col,
                             chunk_partial, gcols, merge_ops,
                             distinct_specs, pair_cap, dcache):
    """Fan the chunk loop over a fork Pool of row-group readers (VERDICT
    r4 missing #3: the reference's slow path was distributed Spark; a
    single-core pandas loop at SF100 is minutes per query, and the chunk
    loop is embarrassingly parallel for decomposable partials). Returns
    (partials, pair_parts, empty_proto) or None when the parallel path
    does not apply (sequential caller takes over): no parquet paths,
    fewer than two row groups, one worker, or no fork on this platform.
    The derived-join cache is pre-populated by the 0-row schema probe
    BEFORE forking, so every worker inherits the executed derived frames
    instead of re-running them per process."""
    import multiprocessing as mp
    import os as _os

    global _PFORK_CTX
    paths = entry.parquet_paths if entry is not None else None
    if not paths:
        return None
    ds = getattr(entry, "delta_source", None)
    if ds is not None and ds()[1]:
        # appended delta rows (docs/INGEST.md) ride only the sequential
        # iter_chunks tail; per-worker row-group units would miss them
        # (or the leader would double-count) — take the sequential path
        return None
    workers = config.fallback_parallel_workers
    if workers == 0:
        workers = min(8, _os.cpu_count() or 1)
    try:
        ctx = mp.get_context("fork")
    except ValueError:
        return None
    import pyarrow.parquet as pq
    units = []  # (path, row-group index)
    for path in paths:
        pf = pq.ParquetFile(path)
        try:
            units.extend((path, rg)
                         for rg in range(pf.metadata.num_row_groups))
        finally:
            pf.close()
    workers = min(workers, len(units))
    if workers < 2:
        return None

    # 0-row schema probe: the real joined schema for the empty-result
    # path, and it executes any derived-table joins once into dcache
    empty_proto = _join_and_filter(stmt, entry.parquet_empty_frame(),
                                   catalog, time_col, config,
                                   derived_cache=dcache)

    def join(chunk):
        return _join_and_filter(stmt, chunk, catalog, time_col, config,
                                derived_cache=dcache)

    # interleave row groups across workers (adjacent groups tend to have
    # correlated sizes); group back into per-worker (path, [rgs]) lists
    per_worker = []
    for w in range(workers):
        mine = units[w::workers]
        by_path: dict = {}
        for path, rg in mine:
            by_path.setdefault(path, []).append(rg)
        per_worker.append(sorted(by_path.items()))

    # the lock covers only ctx-set -> fork: Pool() forks its workers at
    # construction, each child snapshotting _PFORK_CTX by fork memory
    # copy, so the global can be cleared (and the lock released) before
    # the map runs — concurrent queries' parallel fallbacks overlap
    # instead of serializing behind the slowest pool
    with _PFORK_LOCK:
        # each worker gets pair_cap // workers: the workers' in-flight
        # distinct-pair sets coexist, so per-worker caps must SUM to the
        # configured cap — with the full cap per worker, total in-flight
        # pairs could transiently reach workers x pair_cap before the
        # parent-side merge re-checks the real cap
        _PFORK_CTX = (entry, chunk_partial, join,
                      config.fallback_chunk_batch_rows,
                      gcols, merge_ops, distinct_specs,
                      max(1, pair_cap // workers))
        try:
            pool = ctx.Pool(workers)
        except Exception:  # noqa: BLE001 — sequential retry is sound
            return None
        finally:
            _PFORK_CTX = None
    try:
        # the parent process has live JAX/XLA threads, so fork carries a
        # lock-inheritance hazard (workers never call jax, and pyarrow
        # re-inits its pools atfork, but belt-and-braces): any worker
        # failure OR a stuck pool degrades to the sequential loop — the
        # chunk generator is still unconsumed at this point, and the
        # bounded timeout keeps a deadlocked child from stalling the
        # query for more than fallback_parallel_timeout_s
        with pool:
            results = pool.map_async(_pfork_worker, per_worker) \
                .get(timeout=_parallel_timeout_s(config, entry))
    except FallbackError:
        # a worker's pair-cap refusal fired at the DIVIDED cap
        # (pair_cap // workers) — ambiguous about the real cap, because
        # interleaved row groups make each worker's distinct set nearly
        # duplicate the global universe rather than partition it. The
        # sequential loop enforces the configured cap exactly: it either
        # succeeds (the refusal was false) or refuses legibly at the
        # true cap.
        return None
    except Exception:  # noqa: BLE001 — sequential retry is sound
        return None
    partials = []
    pair_parts = {k: [] for k in distinct_specs}
    for parts, pairs in results:
        partials.extend(parts)
        for k, fs in pairs.items():
            pair_parts[k].extend(fs)
    return partials, pair_parts, empty_proto


def _chunked_aggregate(stmt, chunks, exprs, out_names, group_exprs,
                       catalog, time_col, config,
                       pair_cap=20_000_000, derived_cache=None,
                       entry=None):
    # every aggregate call reachable from projections / HAVING / ORDER BY
    agg_calls: dict = {}
    for e in exprs:
        _collect_agg_calls(e, agg_calls)
    if stmt.having is not None:
        _collect_agg_calls(stmt.having, agg_calls)
    for item in stmt.order_by:
        _collect_agg_calls(item.expr, agg_calls)
    specs = list(agg_calls.items())  # [(key, FuncCall)]

    gcols = [f"__g{i}" for i in range(len(group_exprs))]
    gname_of = {_k(g): n for g, n in zip(group_exprs, gcols)}
    merge_ops: dict = {"__rows": "sum"}

    def _unwrap(e):
        """agg_filter(inner, cond) -> (inner, cond); plain -> (e, None)."""
        if e.name == "agg_filter":
            return e.args[0], e.args[1]
        return e, None

    # every aggregate needing the full per-group distinct value set rides
    # the same deduped (group, value)-pairs accumulation across chunks
    distinct_specs = {k: _unwrap(e)[0].name for k, e in specs
                      if _unwrap(e)[0].name in (
                          "count_distinct", "approx_count_distinct",
                          "theta_sketch", "sum_distinct", "avg_distinct")}
    distinct_keys = list(distinct_specs)

    # merge_ops is complete BEFORE any chunk runs (mirrors the per-spec
    # branches of chunk_partial): the parallel path's parent process
    # merges worker partials without ever executing a chunk itself, and
    # an unsupported aggregate errors before any IO is spent
    for i, (k, e0) in enumerate(specs):
        e, cond = _unwrap(e0)
        if k in distinct_specs:
            continue
        if e.name == "count" and not e.args:
            if cond is not None:
                merge_ops[f"p{i}"] = "sum"
            continue
        if e.name == "count":
            merge_ops[f"p{i}"] = "sum"
        elif e.name in ("sum", "avg"):
            merge_ops[f"p{i}"] = "sum"
            if e.name == "avg" and cond is not None:
                merge_ops[f"p{i}n"] = "sum"
        elif e.name in ("min", "max"):
            merge_ops[f"p{i}"] = e.name
        else:
            raise FallbackError(
                f"aggregate {e.name!r} has no chunked fallback")

    def chunk_partial(df):
        """One chunk -> (partials frame, {agg key: distinct-pairs frame})."""
        work = {}
        for g, n in zip(group_exprs, gcols):
            work[n] = _fill_strings(_eval(g, df, time_col))
        work["__rows"] = np.ones(len(df), np.int64)
        dpairs = {}
        for i, (k, e) in enumerate(specs):
            e, cond = _unwrap(e)
            mask = None
            if cond is not None:
                mask = pd.Series(_eval(cond, df, time_col),
                                 index=df.index).fillna(False).astype(bool)
            if e.name in ("count_distinct", "approx_count_distinct",
                          "theta_sketch", "sum_distinct", "avg_distinct"):
                if e.name == "theta_sketch" and len(e.args) != 1:
                    raise FallbackError("theta_sketch takes one column")
                sub = df if mask is None else df[mask]
                gsub = {n: (work[n] if mask is None else work[n][mask])
                        for n in gcols}
                cols = dict(
                    gsub,
                    **{f"v{j}": _eval_agg_input(a, sub, time_col)
                       for j, a in enumerate(e.args)})
                p = pd.DataFrame(cols).dropna(
                    subset=[f"v{j}" for j in range(len(e.args))])
                dpairs[k] = p.drop_duplicates()
                continue
            # merge_ops is pre-computed above (single source of truth);
            # this function only materializes the matching work columns
            if e.name == "count" and not e.args:
                if mask is not None:  # filtered row count
                    work[f"p{i}"] = mask.astype(np.int64)
                continue  # unfiltered: __rows covers it
            v = _eval_agg_input(e.args[0], df, time_col)
            if mask is not None:
                v = v.where(mask)
            if e.name == "count":
                # v.where(mask) above already nulled masked-out rows
                work[f"p{i}"] = v.notna().astype(np.int64)
            elif e.name in ("sum", "avg"):
                work[f"p{i}"] = v
                if e.name == "avg" and mask is not None:
                    # filtered avg denominator: filtered row count
                    work[f"p{i}n"] = mask.astype(np.int64)
            elif e.name in ("min", "max"):
                work[f"p{i}"] = v
            else:
                raise FallbackError(
                    f"aggregate {e.name!r} has no chunked fallback")
        wf = pd.DataFrame(work, index=df.index)
        if gcols:
            return (wf.groupby(gcols, sort=False, dropna=False)
                      .agg(merge_ops).reset_index(), dpairs)
        return wf.agg(merge_ops).to_frame().T, dpairs

    partials: list = []
    pair_parts: dict = {k: [] for k in distinct_keys}

    def compact():
        nonlocal partials
        if len(partials) > 1:
            cat = pd.concat(partials, ignore_index=True)
            if gcols:
                partials = [cat.groupby(gcols, sort=False, dropna=False)
                               .agg(merge_ops).reset_index()]
            else:
                partials = [cat.agg(merge_ops).to_frame().T]
        _compact_pairs(pair_parts, distinct_specs, pair_cap)

    pending_rows = 0
    empty_proto = None   # 0-row joined frame with the real schema
    dcache = derived_cache if derived_cache is not None else {}
    par = _parallel_chunk_partials(stmt, entry, catalog, config, time_col,
                                   chunk_partial, gcols, merge_ops,
                                   distinct_specs, pair_cap, dcache)
    if par is not None:
        partials, pp, empty_proto = par
        for k, frames in pp.items():
            pair_parts[k].extend(frames)
        compact()
    else:
        for chunk in chunks:
            df = _join_and_filter(stmt, chunk, catalog, time_col, config,
                                  derived_cache=dcache)
            if empty_proto is None:
                empty_proto = df.iloc[0:0]
            if not len(df):
                continue
            part, dpairs = chunk_partial(df)
            partials.append(part)
            for k, p in dpairs.items():
                pair_parts[k].append(p)
            # distinct pairs count toward the compaction trigger too — a
            # high-cardinality DISTINCT grows pairs by up to a whole
            # chunk while adding one partial row, and the pair cap is
            # enforced inside compact()
            pending_rows += len(part) + sum(len(p) for p in dpairs.values())
            if pending_rows > (1 << 20):
                compact()
                pending_rows = 0
    if not partials:
        if gcols:
            return pd.DataFrame(columns=out_names)
        # global aggregate over zero matching rows: delegate to the
        # in-memory aggregator on a 0-row frame CARRYING THE REAL SCHEMA
        # so column references resolve (count->0, sum->0, min->NA)
        if empty_proto is None:
            empty_proto = pd.DataFrame(columns=out_names)
        return _aggregate(empty_proto, exprs, out_names, [], stmt,
                          time_col)
    compact()
    merged = partials[0]

    def _norm_key(t):
        """NaN group-key slots normalize to the string fill so dict
        lookups hit (nan != nan would always miss)."""
        return tuple(_FILL if (not isinstance(v, str) and pd.isna(v))
                     else v for v in t)

    # distinct counts per group: {agg key: {group tuple: count}};
    # sum/avg over distinct values: {agg key: {group tuple: (sum, n)}}
    dcounts: dict = {}
    dstats: dict = {}
    for k in distinct_keys:
        pairs = pair_parts[k][0] if pair_parts[k] else \
            pd.DataFrame(columns=gcols + ["v0"])
        if distinct_specs[k] in ("sum_distinct", "avg_distinct"):
            if gcols:
                grp = pairs.groupby(gcols, sort=False, dropna=False)["v0"]
                sizes = grp.size()
                dstats[k] = {
                    _norm_key(kk if isinstance(kk, tuple) else (kk,)):
                        (sv, int(nv))
                    for (kk, sv), nv in zip(grp.sum().items(), sizes)}
            else:
                v = pairs["v0"]
                dstats[k] = {(): (v.sum() if len(v) else np.nan, len(v))}
            continue
        if gcols:
            sizes = pairs.groupby(gcols, sort=False, dropna=False).size()
            dcounts[k] = {_norm_key(kk if isinstance(kk, tuple)
                                    else (kk,)): int(v)
                          for kk, v in sizes.items()}
        else:
            dcounts[k] = {(): len(pairs)}

    spec_col = {k: f"p{i}" for i, (k, _) in enumerate(specs)}

    # ---- theta set ops over the distinct-pair frames (SF-scale analog
    # of the in-memory exact sets): each sketch argument's (group, value)
    # pairs are already accumulated; set algebra is frame algebra.
    def _norm_pairs(f: pd.DataFrame) -> pd.DataFrame:
        # pandas merges do not match NaN keys: normalize numeric
        # group-key NaNs to the string fill (strings already carry it).
        # __v is object-typed so differently-typed sketches merge to the
        # empty set (like the in-memory path) instead of raising, and
        # only the FIRST value column counts (theta is single-field;
        # extra pair columns would explode the joins many-to-many).
        out = {c: _norm_gcol(f[c]) for c in gcols}
        out["__v"] = f[f.columns[len(gcols)]].astype(object)
        return pd.DataFrame(out).drop_duplicates(ignore_index=True)

    def _setop_frame(e) -> pd.DataFrame:
        if isinstance(e, FuncCall) and e.name in _THETA_SET_FNS:
            if len(e.args) < 2:
                raise FallbackError(
                    f"{e.name} takes at least two arguments")
            parts = [_setop_frame(a) for a in e.args]
            on = gcols + ["__v"]
            if e.name == "theta_sketch_union":
                return pd.concat(parts, ignore_index=True) \
                    .drop_duplicates(ignore_index=True)
            if e.name == "theta_sketch_intersect":
                out = parts[0]
                for p in parts[1:]:
                    out = out.merge(p, on=on)
                return out
            out = parts[0]
            for p in parts[1:]:
                m = out.merge(p, on=on, how="left", indicator=True)
                out = m[m["_merge"] == "left_only"].drop(columns="_merge")
            return out
        inner = e.args[0] if isinstance(e, FuncCall) \
            and e.name == "agg_filter" else e
        if not (isinstance(inner, FuncCall)
                and inner.name == "theta_sketch"):
            raise FallbackError(
                "theta sketch functions take theta_sketch(...) arguments "
                f"(optionally with FILTER), got {inner!r}")
        ka = _k(e)
        cached = norm_pairs_cache.get(ka)
        if cached is None:
            cached = _norm_pairs(pair_parts[ka][0]) if pair_parts.get(ka) \
                else pd.DataFrame(columns=gcols + ["__v"])
            norm_pairs_cache[ka] = cached
        return cached

    setop_counts: dict = {}
    norm_pairs_cache: dict = {}

    def _setop_count_dict(e) -> dict:
        k = _k(e)
        if k not in setop_counts:
            f = _setop_frame(e)
            if gcols:
                sizes = f.groupby(gcols, sort=False, dropna=False).size()
                setop_counts[k] = {
                    _norm_key(kk if isinstance(kk, tuple) else (kk,)):
                    int(v) for kk, v in sizes.items()}
            else:
                setop_counts[k] = {(): len(f)}
        return setop_counts[k]

    def _estimate_arg(e):
        """theta_sketch_estimate argument: a setop node, or a validated
        leaf sketch (a non-sketch aggregate must error, not pass
        through)."""
        a = e.args[0]
        if isinstance(a, FuncCall) and a.name in _THETA_SET_FNS:
            return a, True
        inner = a.args[0] if isinstance(a, FuncCall) \
            and a.name == "agg_filter" else a
        if not (isinstance(inner, FuncCall)
                and inner.name == "theta_sketch"):
            raise FallbackError(
                "theta sketch functions take theta_sketch(...) arguments "
                f"(optionally with FILTER), got {inner!r}")
        return a, False

    def merged_agg(e, row, gkey):
        k = _k(e)
        inner, cond = _unwrap(e)
        if inner.name in ("count_distinct", "approx_count_distinct",
                          "theta_sketch"):
            return dcounts[k].get(_norm_key(gkey), 0)
        if inner.name in ("sum_distinct", "avg_distinct"):
            s, c = dstats[k].get(_norm_key(gkey), (np.nan, 0))
            if not c:
                return np.nan
            return s if inner.name == "sum_distinct" else s / c
        if inner.name == "count" and not inner.args:
            return int(row[spec_col[k]] if cond is not None
                       else row["__rows"])
        if inner.name == "count":
            return int(row[spec_col[k]])
        if inner.name == "avg":
            r = int(row[spec_col[k] + "n"] if cond is not None
                    else row["__rows"])
            return row[spec_col[k]] / r if r else np.nan
        return row[spec_col[k]]

    def ev_merged(e, row, gkey):
        if isinstance(e, Lit):
            return e.value
        if isinstance(e, FuncCall) and e.name in _THETA_SET_FNS:
            return float(_setop_count_dict(e).get(_norm_key(gkey), 0))
        if isinstance(e, FuncCall) and e.name == "theta_sketch_estimate" \
                and len(e.args) == 1:
            a, is_setop = _estimate_arg(e)
            if is_setop:
                return float(_setop_count_dict(a).get(_norm_key(gkey), 0))
            return float(merged_agg(a, row, gkey))
        if isinstance(e, FuncCall) and e.name in AGG_FUNCS:
            return merged_agg(e, row, gkey)
        k = _k(e)
        if k in gname_of:
            v = row[gname_of[k]]
            return None if (isinstance(v, str) and v == _FILL) else v
        if isinstance(e, BinOp):
            l_val = ev_merged(e.left, row, gkey)
            r_val = ev_merged(e.right, row, gkey)
            if e.op == "/":
                # NULL operand -> NULL (device: NaN propagates through
                # the post-agg); else ArithmeticPostAgg rule x/0 -> 0
                if pd.isna(l_val) or pd.isna(r_val):
                    return np.nan
                return float(l_val) / r_val if r_val else 0.0
            return _APPLY[e.op](l_val, r_val)
        raise FallbackError(
            f"non-aggregate projection {e!r} with GROUP BY")

    order_cols, order_exprs, ascending = [], {}, []
    for i, item in enumerate(stmt.order_by):
        name = _auto_name(item.expr)
        if name in out_names:
            order_cols.append(name)
        else:
            col = f"__s{i}"
            order_cols.append(col)
            order_exprs[col] = item.expr
        ascending.append(not item.descending)

    def _vec_count_lookup(d: dict, fill=0, dtype="int64") -> pd.Series:
        """{group tuple: value} -> Series aligned to merged's rows:
        normalize NaN group-key slots to the string fill exactly like
        _norm_key, then reindex. fill/dtype support the float-valued
        sum_distinct lookups (absent group -> NaN)."""
        if not gcols:
            return pd.Series([d.get((), fill)] * len(merged),
                             index=merged.index)
        mi = pd.MultiIndex.from_frame(
            pd.DataFrame({c: _norm_gcol(merged[c]) for c in gcols}))
        if d:
            # dtype at construction: Int64 luts must not round-trip
            # through the float64 promotion reindex would otherwise do
            lut = pd.Series(list(d.values()), dtype=dtype,
                            index=pd.MultiIndex.from_tuples(d))
            vals = lut.reindex(mi)
            vals = vals.fillna(fill) if not pd.isna(fill) else vals
            vals = vals.astype(dtype)
        else:
            vals = pd.Series(fill, index=mi, dtype=dtype)
        if str(vals.dtype) == "Int64":
            # keep the extension array: to_numpy() would degrade Int64
            # to an object array of pd.NA-mixed Python ints
            return pd.Series(vals.array, index=merged.index)
        return pd.Series(vals.to_numpy(), index=merged.index)

    def vec_merged(e) -> pd.Series:
        """Vectorized ev_merged over the whole merged frame — the emit
        is O(groups) and a per-row Python loop dominates at-scale
        fallback time (200k groups ≈ seconds)."""
        if isinstance(e, Lit):
            return pd.Series([e.value] * len(merged), index=merged.index)
        if isinstance(e, FuncCall) and e.name in _THETA_SET_FNS:
            return _vec_count_lookup(_setop_count_dict(e)).astype(float)
        if isinstance(e, FuncCall) and e.name == "theta_sketch_estimate" \
                and len(e.args) == 1:
            a, is_setop = _estimate_arg(e)
            if is_setop:
                return _vec_count_lookup(_setop_count_dict(a)) \
                    .astype(float)
            return vec_merged(a).astype(float)
        if isinstance(e, FuncCall) and e.name in AGG_FUNCS:
            k = _k(e)
            inner, cond = _unwrap(e)
            if inner.name in ("count_distinct", "approx_count_distinct",
                              "theta_sketch"):
                return _vec_count_lookup(dcounts[k])
            if inner.name in ("sum_distinct", "avg_distinct"):
                vals = {g: v[0] for g, v in dstats[k].items()}
                # integer sums stay exact via the nullable Int64 dtype
                # (a float64 cast would round past 2^53, diverging from
                # the whole-frame path); floats keep NaN semantics
                int_exact = all(isinstance(x, (int, np.integer))
                                for x in vals.values())
                s = _vec_count_lookup(
                    vals, fill=pd.NA if int_exact else np.nan,
                    dtype="Int64" if int_exact else "float64")
                if inner.name == "sum_distinct":
                    return s
                n = _vec_count_lookup(
                    {g: v[1] for g, v in dstats[k].items()},
                    fill=np.nan, dtype="float64")
                return s.astype("float64") / n.where(n != 0, np.nan)
            if inner.name == "count" and not inner.args:
                s = merged[spec_col[k]] if cond is not None \
                    else merged["__rows"]
                return s.astype("int64")
            if inner.name == "count":
                return merged[spec_col[k]].astype("int64")
            if inner.name == "avg":
                r = (merged[spec_col[k] + "n"] if cond is not None
                     else merged["__rows"]).astype("float64")
                # r == 0 -> NaN, matching the scalar `if r else nan`
                return merged[spec_col[k]].astype("float64") / \
                    r.where(r != 0, np.nan)
            return merged[spec_col[k]]
        k = _k(e)
        if k in gname_of:
            s = merged[gname_of[k]]
            if s.dtype == object or \
                    str(s.dtype).startswith(("str", "category")):
                return s.where(s != _FILL, None)
            return s
        if isinstance(e, BinOp):
            l_val = vec_merged(e.left)
            r_val = vec_merged(e.right)
            if e.op == "/":
                lf = pd.to_numeric(l_val, errors="coerce") \
                    .astype("float64")
                rf = pd.to_numeric(r_val, errors="coerce") \
                    .astype("float64")
                out = (lf / rf.where(rf != 0, 1.0)).where(rf != 0, 0.0)
                return out.where(~(lf.isna() | rf.isna()), np.nan)
            return _APPLY[e.op](l_val, r_val)
        raise FallbackError(
            f"non-aggregate projection {e!r} with GROUP BY")

    if gcols:
        merged = merged.sort_values(gcols, kind="stable")
    if stmt.having is None:
        cols = {n: vec_merged(e) for n, e in zip(out_names, exprs)}
        for col, e in order_exprs.items():
            cols[col] = vec_merged(e)
        out = pd.DataFrame(cols).reset_index(drop=True)
    else:
        # HAVING keeps the scalar path: its NULL-comparison semantics
        # (_having_ok) are defined per row
        rows = []
        for _, row in merged.iterrows():
            gkey = tuple(row[c] for c in gcols)
            rec = {n: ev_merged(e, row, gkey)
                   for n, e in zip(out_names, exprs)}
            if not _having_ok(
                    stmt.having, None, rec, time_col,
                    lambda x, sub, _r=row, _g=gkey: ev_merged(x, _r, _g)):
                continue
            for col, e in order_exprs.items():
                rec[col] = ev_merged(e, row, gkey)
            rows.append(rec)
        out = pd.DataFrame(rows, columns=out_names + list(order_exprs))
    if order_cols:
        out = _sort_order_items(out, order_cols, stmt.order_by)
    out = out[out_names].reset_index(drop=True)
    lo = stmt.offset
    hi = None if stmt.limit is None else lo + stmt.limit
    return out.iloc[lo:hi].reset_index(drop=True)


def _sort_order_items(out: pd.DataFrame, cols: list, items: list,
                      default_low: bool = True) -> pd.DataFrame:
    """THE ORDER BY sorter for every fallback path: multi-key stable
    sort via successive stable single-key sorts (last key first),
    honoring per-key NULLS FIRST/LAST. A key without a spelling takes
    the site default: nulls-low (`default_low=True`, matching the device
    path's null placement) or pandas-plain (nulls last in both
    directions — the historical scan-path behavior). Keeping one helper
    prevents the per-site copies from drifting (a missed site silently
    ignored the spelling; split defaults flipped unspelled keys)."""
    for col, item in list(zip(cols, items))[::-1]:
        asc = not item.descending
        keyed = _null_low_key(out[col])
        out = out.loc[keyed.sort_values(ascending=asc,
                                        kind="stable").index]
        if item.nulls is not None:
            want_first = item.nulls == "first"
        elif default_low:
            want_first = asc       # nulls-low: already where they landed
        else:
            want_first = False     # pandas default: nulls last either way
        nulls_first_now = asc      # the nulls-low key put them here
        if want_first != nulls_first_now:
            m = pd.isna(out[col]).to_numpy()
            if m.any():
                parts = (out[m], out[~m]) if want_first \
                    else (out[~m], out[m])
                out = pd.concat(parts)
    return out


def _null_low_key(s: pd.Series) -> pd.Series:
    """Sort key matching the device path's null placement: null == ""
    for string dims (Druid's legacy null ordering) and -inf for numeric
    keys, i.e. nulls FIRST ascending — pandas defaults put them last.
    Aggregate outputs arrive as object dtype whenever a group's value is
    NULL, so object columns are re-typed by inspecting their values
    (stringifying numbers would sort them lexicographically)."""
    if pd.api.types.is_datetime64_any_dtype(s):
        return s.fillna(pd.Timestamp.min)
    if pd.api.types.is_extension_array_dtype(s.dtype) and \
            pd.api.types.is_numeric_dtype(s):
        return pd.Series(s.to_numpy(dtype=np.float64, na_value=-np.inf),
                         index=s.index)
    if s.dtype == object or str(s.dtype).startswith(("str", "category")):
        # explicit comprehensions, NOT Series.map: pandas 3 skips NA values
        # by default, which would leave nulls sorting last again
        non_null = [v for v in s if not pd.isna(v)]
        if non_null and all(
                isinstance(v, (int, float, np.integer, np.floating))
                and not isinstance(v, bool) for v in non_null):
            return pd.Series([-np.inf if pd.isna(v) else float(v)
                              for v in s], index=s.index)
        return pd.Series(["" if pd.isna(v) else str(v) for v in s],
                         index=s.index)
    if pd.api.types.is_float_dtype(s) and s.isna().any():
        return s.fillna(-np.inf)
    return s


_CMP_OPS = ("==", "!=", "<", "<=", ">", ">=")


def _having_ok(having, sub, rec, time_col, agg_series) -> bool:
    """NULL-aggregate semantics match the device path (results.eval_having):
    NULL aggregates surface there as NaN in float64 arrays, so every
    comparison against them is False and NOT flips that to True. Here the
    NULL may be pd.NA instead of NaN, so comparisons collapse an NA operand
    to False explicitly; arithmetic propagates NA; a bare NA truth value at
    the top is False."""
    e = having

    def ev(x):
        if isinstance(x, Lit):
            return x.value
        if isinstance(x, BinOp) and (
                x.op in _CMP_OPS or x.op in ("&&", "||")):
            lv, rv = ev(x.left), ev(x.right)
            if x.op in _CMP_OPS and (pd.isna(lv) or pd.isna(rv)):
                return False
            if x.op in ("&&", "||"):
                lv = False if pd.isna(lv) else bool(lv)
                rv = False if pd.isna(rv) else bool(rv)
            return _APPLY[x.op](lv, rv)
        if isinstance(x, FuncCall) and x.name == "not":
            v = ev(x.args[0])
            return True if pd.isna(v) else not v
        if _contains_agg(x):
            return agg_series(x, sub)
        if isinstance(x, Col):
            return rec.get(x.name)
        if isinstance(x, BinOp):
            return _APPLY[x.op](ev(x.left), ev(x.right))
        raise FallbackError(f"cannot evaluate HAVING {x!r}")
    v = ev(e)
    return False if pd.isna(v) else bool(v)


# ---------------------------------------------------------------------------

_APPLY = {
    "+": lambda a, b: a + b, "-": lambda a, b: a - b,
    "*": lambda a, b: a * b, "/": lambda a, b: a / b,
    "%": lambda a, b: a % b,
    "==": lambda a, b: a == b, "!=": lambda a, b: a != b,
    "<": lambda a, b: a < b, "<=": lambda a, b: a <= b,
    ">": lambda a, b: a > b, ">=": lambda a, b: a >= b,
    "&&": lambda a, b: a & b, "||": lambda a, b: a | b,
}


def _ts(series, time_col):
    if pd.api.types.is_datetime64_any_dtype(series):
        return series
    return pd.to_datetime(series, unit="ms")


def _eval(e, df, time_col):
    """Expression -> Series aligned with df (scalar for Lit)."""
    if isinstance(e, Lit):
        n = len(df)
        if not n:
            return pd.Series([], dtype=object)
        v = e.value
        # np.full instead of a python list: a literal operand over a
        # wide frame must not cost O(n) list construction + inference
        # (it dominated simple-WHERE fallback profiles). Exact-dtype
        # parity with the list path: bool stays bool, int64-range ints
        # stay int64, floats float64, everything else object.
        if type(v) is bool or type(v) is float:
            arr = np.full(n, v)
        elif type(v) is int and -(2 ** 63) <= v < 2 ** 63:
            arr = np.full(n, v, dtype=np.int64)
        elif isinstance(v, (list, tuple, set, dict)):
            return pd.Series([v] * n, index=df.index)
        else:
            arr = np.full(n, v, dtype=object)
        return pd.Series(arr, index=df.index)
    if isinstance(e, Col):
        name = e.name.split(".")[-1]
        if name not in df.columns:
            raise FallbackError(f"unknown column {name!r}")
        return df[name]
    if isinstance(e, BinOp):
        if e.op in ("==", "!=", "<", "<=", ">", ">=") and (
                (isinstance(e.left, Lit) and e.left.value is None)
                or (isinstance(e.right, Lit) and e.right.value is None)):
            # comparison against a NULL literal (e.g. an empty scalar
            # subquery inlined as Lit(None)) matches no rows — pandas
            # would raise a TypeError on `series > None`
            return pd.Series(np.zeros(len(df), bool), index=df.index)
        if e.op == "!=":
            # a <> b IS NOT(a = b) engine-wide (the planner lowers it
            # that way; NULL-operand rows match). Direct pandas `!=`
            # would depend on the dtype representation: float-NaN
            # comparisons yield True while nullable-dtype NA yields NA
            # -> fillna(False) — opposite answers for the same data.
            return ~_eval(BinOp("==", e.left, e.right), df, time_col)
        left = _eval(e.left, df, time_col)
        right = _eval(e.right, df, time_col)
        if e.op == "/":
            left = left.astype(float) if hasattr(left, "astype") else left
        out = _APPLY[e.op](left, right)
        if e.op in ("==", "!=", "<", "<=", ">", ">=", "&&", "||") and \
                hasattr(out, "fillna"):
            # filter-context semantics: a comparison with a NULL operand is
            # False at the leaf (matches the device's filtereval rule).
            # Aggregation inputs instead mask whole-expression nulls via
            # _expr_null_mask — matching kernels.exprs.virtual_null_mask.
            out = out.fillna(False).astype(bool)
        return out
    if isinstance(e, WindowCall):
        return _eval_window(e, df, time_col)
    if isinstance(e, FuncCall):
        fn = e.name
        if fn in _TIME_FUNCS:
            t = _ts(_eval(e.args[0], df, time_col), time_col)
            return getattr(t.dt, {"day": "day", "dayofmonth": "day"}
                           .get(fn, fn))
        if fn == "date_trunc":
            unit = str(e.args[0].value).lower()
            t = _ts(_eval(e.args[1], df, time_col), time_col)
            freq = {"second": "s", "minute": "min", "hour": "h", "day": "D",
                    "week": "W", "month": "MS", "quarter": "QS",
                    "year": "YS"}[unit]
            if unit in ("month", "quarter", "year", "week"):
                return t.dt.to_period(
                    {"month": "M", "quarter": "Q", "year": "Y",
                     "week": "W-SUN"}[unit]).dt.start_time
            return t.dt.floor(freq)
        if fn == "coalesce":
            out = None
            for a in e.args:
                v = _eval(a, df, time_col)
                if not isinstance(v, pd.Series):
                    v = pd.Series([v] * len(df), index=df.index)
                out = v if out is None else out.where(out.notna(), v)
            return out
        if fn == "nullif":
            a = _eval(e.args[0], df, time_col)
            b = _eval(e.args[1], df, time_col)
            if not isinstance(a, pd.Series):
                a = pd.Series([a] * len(df), index=df.index)
            return a.mask(pd.Series(a == b, index=a.index).fillna(False))
        if fn in ("length", "char_length"):
            s = _as_str_series(_eval(e.args[0], df, time_col), df, fn)
            return s.str.len()
        if fn == "replace":
            if not (len(e.args) == 3 and isinstance(e.args[1], Lit)
                    and isinstance(e.args[2], Lit)):
                raise FallbackError(
                    "replace() needs literal search/replacement strings")
            s = _as_str_series(_eval(e.args[0], df, time_col), df, fn)
            return s.str.replace(str(e.args[1].value),
                                 str(e.args[2].value), regex=False)
        if fn in ("upper", "lower", "trim"):
            s = _as_str_series(_eval(e.args[0], df, time_col), df, fn)
            if fn == "upper":
                return s.str.upper()
            if fn == "lower":
                return s.str.lower()
            # SQL/Druid TRIM strips space characters only by default
            return s.str.strip(" ")
        if fn == "concat":
            parts = [_eval(a, df, time_col) for a in e.args]
            out = None
            for p in parts:
                s = p.astype("string") if hasattr(p, "astype") else \
                    pd.Series(str(p), index=df.index, dtype="string")
                out = s if out is None else out + s
            return out
        if fn == "not":
            v = _eval(e.args[0], df, time_col)
            return (~v.astype(bool)) if hasattr(v, "astype") else (not v)
        if fn == "is_null":
            return _eval(e.args[0], df, time_col).isna()
        if fn in ("in_list", "in_list_packed"):
            v = _eval(e.args[0], df, time_col)
            vals = list(e.args[1].value) if fn == "in_list_packed" \
                else [a.value for a in e.args[1:]]
            has_null = any(x is None for x in vals)
            m = v.isin([x for x in vals if x is not None])
            if has_null:
                m = m | v.isna()
            return m
        if fn == "like":
            v = _eval(e.args[0], df, time_col)
            rx = re.compile(_like_to_regex(e.args[1].value))
            return v.map(lambda x: x is not None and not pd.isna(x)
                         and rx.fullmatch(str(x)) is not None)
        if fn == "abs":
            return _eval(e.args[0], df, time_col).abs()
        if fn == "if":
            c = _eval(e.args[0], df, time_col)
            if hasattr(c, "fillna"):
                c = c.fillna(False).astype(bool)
            a = _eval(e.args[1], df, time_col)
            b = _eval(e.args[2], df, time_col)
            if not hasattr(a, "where"):
                a = pd.Series([a] * len(df), index=df.index)
            return a.where(c, b)
        if fn == "cast_double":
            v = _eval(e.args[0], df, time_col)
            return pd.to_numeric(v, errors="raise").astype("Float64")
        if fn == "cast_long":
            v = pd.to_numeric(_eval(e.args[0], df, time_col),
                              errors="raise")
            arr = v.to_numpy(dtype="float64", na_value=np.nan)
            tr = np.trunc(arr)  # SQL casts truncate toward zero
            out = pd.array([pd.NA if np.isnan(x) else int(x) for x in tr],
                           dtype="Int64")
            return pd.Series(out, index=v.index)
        if fn == "cast_string":
            v = _eval(e.args[0], df, time_col)
            return v.map(lambda x: None if pd.isna(x) else str(x))
        if fn in ("substr", "substring"):
            v = _eval(e.args[0], df, time_col)
            start = int(e.args[1].value) - 1  # SQL 1-based
            ln = int(e.args[2].value) if len(e.args) == 3 else None
            end = None if ln is None else start + ln
            return v.map(lambda x: None if pd.isna(x)
                         else str(x)[start:end])
        if fn == "corr_scalar_map":
            items = dict(e.args[0].value)
            default = e.args[1].value
            kser = [_eval(a, df, time_col) for a in e.args[2:]]
            if not len(df):
                return pd.Series([], dtype=object)
            vals = [items.get(kt, default) for kt in _key_rows(kser)]
            return pd.Series([np.nan if v is None else v for v in vals],
                             index=df.index)
        if fn == "corr_exists_map":
            keyset = set(e.args[0].value)
            kser = [_eval(a, df, time_col) for a in e.args[1:]]
            if not len(df):
                return pd.Series([], dtype=bool)
            return pd.Series([kt in keyset for kt in _key_rows(kser)],
                             index=df.index)
        if fn == "corr_exists_cmp_map":
            items = dict(e.args[0].value)
            op = e.args[1].value
            vser = _eval(e.args[2], df, time_col)
            kser = [_eval(a, df, time_col) for a in e.args[3:]]
            if not len(df):
                return pd.Series([], dtype=bool)

            def hit(kt, v):
                rng = items.get(kt)
                if rng is None or v is None or pd.isna(v):
                    return False  # empty group / NULL comparand: UNKNOWN
                lo, hi = rng
                if op == ">":
                    return hi > v
                if op == ">=":
                    return hi >= v
                if op == "<":
                    return lo < v
                if op == "<=":
                    return lo <= v
                return lo != v or hi != v  # "!=": any differing value

            kt_rows = _key_rows(kser) if kser else ((),) * len(df)
            return pd.Series([hit(kt, v) for kt, v
                              in zip(kt_rows, vser.tolist())],
                             index=df.index)
        if fn == "corr_in_map":
            pairs = set(e.args[0].value)
            lhs = _eval(e.args[1], df, time_col)
            kser = [_eval(a, df, time_col) for a in e.args[2:]]
            if not len(df):
                return pd.Series([], dtype=bool)
            return pd.Series([kt in pairs
                              for kt in _key_rows(kser + [lhs])],
                             index=df.index)
        if fn == "lookup_map":
            v = _eval(e.args[0], df, time_col)
            m = dict(e.args[1].value)
            # Druid lookup semantics (retainMissingValue=false): values
            # absent from the map (and nulls) become null
            return v.map(lambda x: None if pd.isna(x)
                         else m.get(str(x)))
        if fn == "regexp_extract":
            v = _eval(e.args[0], df, time_col)
            rx = re.compile(str(e.args[1].value))

            def ex(x):
                if pd.isna(x):
                    return None
                m = rx.search(str(x))
                if m is None:
                    return None
                return m.group(1) if rx.groups else m.group(0)
            return v.map(ex)
        if fn in ("floor", "ceil", "sqrt", "log", "exp"):
            v = _eval(e.args[0], df, time_col)
            npf = {"floor": np.floor, "ceil": np.ceil, "sqrt": np.sqrt,
                   "log": np.log, "exp": np.exp}[fn]
            return pd.Series(npf(v.astype(float)), index=v.index)
        if fn == "pow":
            a = _eval(e.args[0], df, time_col)
            b = _eval(e.args[1], df, time_col)
            return a.astype(float) ** (b if not hasattr(b, "astype")
                                       else b.astype(float))
        if fn in ("min", "least", "max", "greatest"):
            a = _eval(e.args[0], df, time_col)
            b = _eval(e.args[1], df, time_col)
            f = np.minimum if fn in ("min", "least") else np.maximum
            return pd.Series(f(a, b), index=getattr(a, "index", df.index))
        raise FallbackError(f"unknown function {fn!r}")
    raise FallbackError(f"cannot evaluate {e!r}")


_RANK_FNS = {"row_number", "rank", "dense_rank"}
_WINDOW_AGGS = {"sum", "min", "max", "count", "avg"}
_SHIFT_FNS = {"lag", "lead"}


def _eval_window(e: WindowCall, df, time_col) -> pd.Series:
    """fn() OVER (PARTITION BY ... ORDER BY ...) -> Series aligned with
    df. Rank functions need ORDER BY; aggregates compute over the whole
    partition without it and as running (cumulative) aggregates with it
    (the standard's default RANGE UNBOUNDED PRECEDING frame, approximated
    row-wise)."""
    if e.name not in _RANK_FNS | _WINDOW_AGGS | _SHIFT_FNS:
        raise FallbackError(f"unsupported window function {e.name!r}")

    # NULL partition keys form their own partition: string keys fill
    # with the sentinel, non-string keys rely on dropna=False groupbys
    keys = [_fill_strings(_eval(p, df, time_col)) for p in e.partition_by]
    grouped_keys = keys if keys else [pd.Series(0, index=df.index)]

    def by(series):
        return series.groupby(grouped_keys, dropna=False)

    order_cols = []
    ascending = []
    work = pd.DataFrame(index=df.index)
    for i, (oe, desc) in enumerate(e.order_by):
        work[f"__o{i}"] = _eval(oe, df, time_col)
        order_cols.append(f"__o{i}")
        ascending.append(not desc)

    if e.name in _RANK_FNS:
        if not e.order_by:
            raise FallbackError(f"{e.name}() requires ORDER BY")
        # global sorted position handles any mix of directions; ties
        # collapse through the tuple of ORDER BY values
        order = work.sort_values(order_cols, ascending=ascending,
                                 kind="stable", key=_null_low_key).index
        pos = pd.Series(np.arange(len(df)), index=order).reindex(df.index)
        rn = by(pos).rank(method="first")
        if e.name == "row_number":
            return rn.astype(np.int64)
        tie = work[order_cols].apply(tuple, axis=1)
        min_rn = rn.groupby(grouped_keys + [tie],
                            dropna=False).transform("min")
        if e.name == "rank":
            return min_rn.astype(np.int64)
        return by(min_rn).rank(method="dense").astype(np.int64)

    if e.name in ("lag", "lead"):
        if not e.order_by:
            raise FallbackError(f"{e.name}() requires ORDER BY")
        v = _eval(e.args[0], df, time_col)

        def const_arg(i, what):
            if len(e.args) <= i:
                return None
            from tpu_olap.planner.exprutil import simplify
            a = simplify(e.args[i])
            if not isinstance(a, Lit):
                raise FallbackError(
                    f"{e.name}() {what} must be a constant")
            return a.value

        off = const_arg(1, "offset")
        off = 1 if off is None else int(off)  # 0 is a valid offset
        default = const_arg(2, "default")
        order = work.sort_values(order_cols, ascending=ascending,
                                 kind="stable", key=_null_low_key).index
        vo = v.reindex(order)
        gk = [k.reindex(order) for k in grouped_keys]
        shift = off if e.name == "lag" else -off
        shifted = vo.groupby(gk, dropna=False).shift(shift)
        if default is not None:
            # the default applies only BEYOND the partition boundary,
            # not to genuine NULL data values that were shifted in
            marker = pd.Series(1, index=vo.index) \
                .groupby(gk, dropna=False).shift(shift)
            shifted = shifted.mask(marker.isna(), default)
        return shifted.reindex(df.index)

    v = _eval_agg_input(e.args[0], df, time_col) if e.args else \
        pd.Series(1, index=df.index)
    if e.frame is not None:
        # explicit ROWS BETWEEN frame: sliding aggregate over the sorted
        # partition. cumsum prefix differences serve sum/count/avg;
        # min/max slice per row (fallback tier — partitions are small)
        if not e.order_by:
            raise FallbackError("a ROWS frame requires a window ORDER BY")
        lo, hi = e.frame
        if lo is not None and hi is not None and lo > hi:
            raise FallbackError("empty ROWS frame (start after end)")
        order = work.sort_values(order_cols, ascending=ascending,
                                 kind="stable", key=_null_low_key).index
        vs = v.reindex(order)
        gk = [k.reindex(order) for k in grouped_keys]

        def slide(s):
            arr = s.to_numpy()
            m = len(arr)
            idx = np.arange(m)
            notna = ~pd.isna(arr)
            a = np.zeros(m, np.int64) if lo is None else \
                np.clip(idx + lo, 0, m)
            b = np.full(m, m, dtype=np.int64) if hi is None else \
                np.clip(idx + hi + 1, 0, m)
            b = np.maximum(a, b)
            cn = np.concatenate([[0], np.cumsum(notna.astype(np.int64))])
            cnt = cn[b] - cn[a]
            if e.name == "count":
                return pd.Series(cnt, index=s.index)
            if e.name in ("sum", "avg"):
                vals = np.where(notna, arr, 0).astype("float64")
                cs = np.concatenate([[0.0], np.cumsum(vals)])
                out = np.where(cnt > 0, cs[b] - cs[a], np.nan)
                if e.name == "avg":
                    out = out / np.where(cnt > 0, cnt, 1)
                return pd.Series(out, index=s.index)
            out = np.full(m, np.nan)
            for i in range(m):
                wv = arr[a[i]:b[i]]
                wv = wv[~pd.isna(wv)]
                if len(wv):
                    out[i] = wv.min() if e.name == "min" else wv.max()
            return pd.Series(out, index=s.index)

        res = vs.groupby(gk, dropna=False, group_keys=False).apply(slide)
        return res.reindex(df.index)
    if not e.order_by:
        g = by(v)
        if e.name == "count":
            out = g.transform("count") if e.args else \
                g.transform("size")
        elif e.name == "avg":
            out = g.transform("sum") / g.transform("count")
        else:
            out = g.transform(e.name)
        return out
    # running aggregates in ORDER BY order, mapped back to row order.
    # SQL frame semantics over NULL values: the frame aggregate skips
    # NULLs, so at a NULL-value row the running value CARRIES (it is the
    # aggregate of the prior frame), and it is NULL only while the frame
    # has seen no non-null value yet.
    order = work.sort_values(order_cols, ascending=ascending,
                             kind="stable", key=_null_low_key).index
    vs = v.reindex(order)
    gk = [k.reindex(order) for k in grouped_keys]

    def gby(s):
        return s.groupby(gk, dropna=False)

    nn_cum = gby(vs.notna().astype(np.int64)).cumsum()
    if e.name == "count":
        run = nn_cum if e.args else \
            gby(pd.Series(1, index=vs.index)).cumsum()
    elif e.name in ("sum", "avg"):
        s_run = gby(vs.fillna(0)).cumsum()
        run = s_run.where(nn_cum > 0)
        if e.name == "avg":
            run = run / nn_cum.where(nn_cum > 0)
    else:
        run = gby(vs).cummin() if e.name == "min" else gby(vs).cummax()
        run = gby(run).ffill()  # carry over NULL-value rows
    return run.reindex(df.index)


def _expr_null_mask(e, df, time_col):
    """SQL null propagation for an expression used as an AGGREGATION
    input: the value is null wherever any referenced column is null
    (the fallback mirror of kernels.exprs.virtual_null_mask)."""
    mask = None
    for col in e.columns():
        name = col.split(".")[-1]
        if name in df.columns:
            na = df[name].isna()
            mask = na if mask is None else (mask | na)
    return mask


def _eval_agg_input(e, df, time_col):
    """Evaluate an aggregation-input expression with whole-expression
    null masking (NULL if any referenced input is NULL)."""
    v = _eval(e, df, time_col)
    mask = _expr_null_mask(e, df, time_col)
    if mask is not None and hasattr(v, "mask") and mask.any():
        v = v.mask(mask)
    return v


def _eval_bool(e, df, time_col):
    v = _eval(e, df, time_col)
    if hasattr(v, "fillna"):
        return v.fillna(False).astype(bool)
    return bool(v)


def _equi_pair(c, left_cols, right_cols):
    if isinstance(c, BinOp) and c.op == "==" and \
            isinstance(c.left, Col) and isinstance(c.right, Col):
        a = c.left.name.split(".")[-1]
        b = c.right.name.split(".")[-1]
        if a in left_cols and b in right_cols:
            return (a, b)
        if b in left_cols and a in right_cols:
            return (b, a)
    return None


