"""Planner — the analog of the reference's L6 rewrite layer (SURVEY.md
§3.2): SQL text parses to a logical SELECT tree; rewrite rules in the
reference's order (join collapse → project/filter pushdown + interval
extraction → aggregate translation → limit/topN selection) compile it into
a QuerySpec via the QueryBuilder accumulator; anything non-rewritable runs
on the pandas fallback interpreter instead of erroring (SURVEY.md §2
property 2: "fallback is structural").
"""

from tpu_olap.planner.sqlparse import parse_sql  # noqa: F401
from tpu_olap.planner.plan import DruidPlanner, PlanResult, RewriteError  # noqa: F401
