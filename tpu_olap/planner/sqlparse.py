"""Hand-rolled SQL parser for the BI subset the reference accelerates.

The analog of the reference's SparklineDataParser + Spark's own SQL parser
(SURVEY.md §3.1) — scoped to the SELECT shape the rewrite rules understand:

  SELECT expr [AS alias], ...
  FROM t1 [, t2 ...] [[INNER|LEFT] JOIN t3 ON cond]*
  [WHERE cond] [GROUP BY exprs] [HAVING cond]
  [ORDER BY expr [ASC|DESC], ...] [LIMIT n [OFFSET m]]

Scalar/boolean expressions reuse the IR expression AST (tpu_olap.ir.expr);
aggregates parse to FuncCall nodes (count/sum/min/max/avg, COUNT(DISTINCT
x) -> count_distinct, approx_count_distinct, theta_sketch). BETWEEN, IN,
LIKE, IS [NOT] NULL, NOT/AND/OR are normalized into the same AST using
comparison/logical BinOps plus marker FuncCalls (in_list, like, is_null).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

from tpu_olap.ir.expr import (BinOp, Col, Expr, FuncCall, Lit,
                              Subquery, WindowCall)

AGG_FUNCS = {"count", "sum", "min", "max", "avg", "count_distinct",
             "sum_distinct", "avg_distinct",
             "approx_count_distinct", "theta_sketch",
             # agg(...) FILTER (WHERE cond) wrapper node
             "agg_filter"}

_TOKEN = re.compile(r"""
    \s*(?:
      (?P<num>\d+(?:\.\d+)?(?:[eE][+-]?\d+)?)
    | (?P<name>[^\W\d]\w*(?:\.[^\W\d]\w*)?)
    | (?P<str>'(?:[^']|'')*')
    | (?P<op>\|\||<>|!=|<=|>=|=|<|>|\(|\)|,|\*|\+|-|/|%)
    )""", re.VERBOSE)

_KEYWORDS = {
    "select", "from", "where", "group", "by", "having", "order", "limit",
    "offset", "as", "and", "or", "not", "between", "in", "like", "is",
    "null", "asc", "desc", "join", "inner", "left", "right", "full",
    "outer", "cross", "on", "using", "nulls", "distinct",
    "case", "when", "then", "else", "end", "cast", "union", "all", "with",
    "intersect", "except", "exists",
}

# CAST target type -> internal conversion function (kernels.exprs)
_CAST_FNS = {
    "double": "cast_double", "float": "cast_double", "real": "cast_double",
    "long": "cast_long", "int": "cast_long", "integer": "cast_long",
    "bigint": "cast_long", "smallint": "cast_long", "tinyint": "cast_long",
    "varchar": "cast_string", "string": "cast_string", "char": "cast_string",
    "text": "cast_string",
}


class SqlError(ValueError):
    pass


def _tokenize(s: str):
    out, pos = [], 0
    while pos < len(s):
        if s[pos] == ";":
            pos += 1
            continue
        m = _TOKEN.match(s, pos)
        if not m:
            if s[pos:].strip() == "":
                break
            raise SqlError(f"bad token near {s[pos:pos + 20]!r}")
        pos = m.end()
        if m.lastgroup == "num":
            t = m.group("num")
            out.append(("num",
                        float(t) if "." in t or "e" in t.lower() else int(t)))
        elif m.lastgroup == "name":
            w = m.group("name")
            if w.lower() in _KEYWORDS:
                out.append(("kw", w.lower()))
            else:
                out.append(("name", w))
        elif m.lastgroup == "str":
            out.append(("str", m.group("str")[1:-1].replace("''", "'")))
        else:
            out.append(("op", m.group("op")))
    out.append(("eof", None))
    return out


@dataclass
class JoinClause:
    table: str
    on: Expr | None  # None for comma joins (condition lives in WHERE)
    kind: str = "inner"
    # [AS] alias — when set, it HIDES the base table name in this scope
    # (standard SQL): qualified refs resolve via `alias`, not `table`
    alias: str | None = None
    # JOIN ... USING (a, b): same-named multi-column equi keys. Kept as
    # a column tuple, NOT synthesized `Col(a)==Col(a)` conditions — an
    # unqualified self-equality is a tautology after qualifier stripping
    # (it would silently join on nothing)
    using: tuple | None = None
    # JOIN (SELECT ...) alias — the derived statement; `table` holds the
    # alias (like SelectStmt.derived). Also set by _inline_ctes for a
    # CTE referenced in JOIN position. Fallback-only.
    derived: object = None


@dataclass
class OrderItem:
    expr: Expr
    descending: bool = False
    # NULLS FIRST|LAST (None = the engine default: nulls sort low).
    # Honored by the fallback sorter; the device rewriter declines
    # non-default spellings so they fall back rather than mis-sort.
    nulls: str | None = None


@dataclass
class SelectStmt:
    projections: list            # [(Expr, alias|None)]
    table: str = ""
    joins: list = field(default_factory=list)
    where: Expr | None = None
    group_by: list = field(default_factory=list)
    having: Expr | None = None
    order_by: list = field(default_factory=list)
    limit: int | None = None
    offset: int = 0
    distinct: bool = False
    # FROM (SELECT ...) alias — the derived statement; `table` holds the
    # alias. Fallback-only (the planner declines derived tables).
    derived: object = None
    # FROM <table> [AS] alias — hides the base name in this scope
    table_alias: str | None = None
    # GROUP BY ROLLUP/CUBE/GROUPING SETS: list of group-expr lists
    # (None = plain GROUP BY). group_by still holds the full detail
    # list; the device rewriter declines, the fallback unions the sets.
    grouping_sets: list | None = None


@dataclass
class UnionStmt:
    """SELECT ... {UNION [ALL] | INTERSECT | EXCEPT} SELECT ... —
    fallback-only (the reference ran these through full Spark SQL; here
    the pandas interpreter executes each branch and combines).
    ORDER/LIMIT/OFFSET written after the last branch apply to the whole
    compound, per standard SQL. One operator kind per chain — mixing
    UNION with INTERSECT/EXCEPT needs explicit derived-table parens (no
    silent precedence surprises)."""
    parts: list                  # [SelectStmt]
    all: bool = False
    order_by: list = field(default_factory=list)
    limit: int | None = None
    offset: int = 0
    op: str = "union"            # "union" | "intersect" | "except"

    @property
    def table(self) -> str:
        return self.parts[0].table


class _Parser:
    def __init__(self, toks):
        self.toks = toks
        self.i = 0

    def peek(self):
        return self.toks[self.i]

    def at_kw(self, *kws):
        k, v = self.peek()
        return k == "kw" and v in kws

    def take(self, kind=None, val=None):
        k, v = self.toks[self.i]
        if (kind and k != kind) or (val is not None and v != val):
            raise SqlError(f"expected {val or kind}, got {v!r}")
        self.i += 1
        return v

    def take_kw(self, kw):
        return self.take("kw", kw)

    def _table_alias(self):
        """[AS] alias after a FROM/JOIN table name (a bare name token —
        keywords like WHERE/JOIN/ON end the reference, so no ambiguity).
        Dotted names are column refs, never aliases."""
        if self.at_kw("as"):
            self.take()
            return self.take("name")
        if self.peek()[0] == "name" and "." not in self.peek()[1]:
            return self.take("name")
        return None

    def _join_target(self):
        """Join target: a table name, or a derived table
        `(SELECT ...) [AS] alias` (the reference served these through
        full Spark SQL, SURVEY.md §3.1). Returns (name, derived, alias);
        for a derived target `name` holds the alias and `alias` is None,
        mirroring how FROM-position derived tables are represented."""
        if self.peek() == ("op", "("):
            self.take()
            sub = self.statement_in_parens()
            self.take("op", ")")
            if self.at_kw("as"):
                self.take()
            name = self.take("name") if self.peek()[0] == "name" \
                else "__derived"
            return name, sub, None
        return self.take("name"), None, self._table_alias()

    # ---- statement -------------------------------------------------------

    def statement(self):
        """select [UNION [ALL] select]* — trailing ORDER/LIMIT/OFFSET
        written after the last branch belong to the union."""
        out = self.statement_in_parens()
        if self.peek()[0] != "eof":
            k, v = self.peek()
            raise SqlError(f"unexpected {v!r} after statement")
        return out

    def statement_in_parens(self):
        """Like statement() but stops at the enclosing context's
        terminator (')' or eof) instead of requiring eof. An optional
        WITH prefix defines CTEs, inlined as derived tables (the
        reference ran CTEs through full Spark SQL; here every reference
        in FROM position becomes the equivalent subquery)."""
        ctes = {}
        if self.at_kw("with"):
            self.take()
            while True:
                name = self.take("name")
                self.take_kw("as")
                self.take("op", "(")
                body = self.statement_in_parens()
                # later CTEs may reference earlier ones (standard SQL)
                ctes[name] = _inline_ctes(body, ctes) if ctes else body
                self.take("op", ")")
                if self.peek() == ("op", ","):
                    self.take()
                    continue
                break
        parts = [self.select()]
        all_flags = []
        ops = []
        while self.at_kw("union", "intersect", "except"):
            ops.append(self.take())
            is_all = False
            if self.at_kw("all"):
                if ops[-1] != "union":
                    raise SqlError(f"{ops[-1].upper()} ALL not supported")
                self.take()
                is_all = True
            all_flags.append(is_all)
            parts.append(self.select())
        if len(parts) == 1:
            return _inline_ctes(parts[0], ctes) if ctes else parts[0]
        if len(set(ops)) > 1:
            raise SqlError(
                "mixed set operators in one chain — parenthesize as a "
                "derived table to make precedence explicit")
        if ops[0] == "union" and len(set(all_flags)) > 1:
            raise SqlError("mixed UNION and UNION ALL are not supported")
        last = parts[-1]
        u = UnionStmt(parts, all=all_flags[0], order_by=last.order_by,
                      limit=last.limit, offset=last.offset, op=ops[0])
        last.order_by, last.limit, last.offset = [], None, 0
        for p in parts[:-1]:
            if p.order_by or p.limit is not None or p.offset:
                raise SqlError(
                    "ORDER BY / LIMIT inside a set-operator branch is "
                    "not supported (write it after the last branch)")
        return _inline_ctes(u, ctes) if ctes else u

    def select(self) -> SelectStmt:
        self.take_kw("select")
        stmt = SelectStmt(projections=[])
        if self.at_kw("distinct"):
            self.take()
            stmt.distinct = True
        while True:
            if self.peek() == ("op", "*"):
                self.take()
                stmt.projections.append((Col("*"), None))
            else:
                e = self.expr()
                alias = None
                if self.at_kw("as"):
                    self.take()
                    alias = self.take("name")
                elif self.peek()[0] == "name":
                    alias = self.take("name")
                stmt.projections.append((e, alias))
            if self.peek() == ("op", ","):
                self.take()
                continue
            break
        self.take_kw("from")
        if self.peek() == ("op", "("):
            # derived table: FROM (SELECT ...) [AS] alias
            self.take()
            stmt.derived = self.statement_in_parens()
            self.take("op", ")")
            if self.at_kw("as"):
                self.take()
            stmt.table = self.take("name") if self.peek()[0] == "name" \
                else "__derived"
        else:
            stmt.table = self.take("name")
            stmt.table_alias = self._table_alias()
        while True:
            if self.peek() == ("op", ","):
                self.take()
                tname, tderived, talias = self._join_target()
                stmt.joins.append(JoinClause(tname, None, alias=talias,
                                             derived=tderived))
                continue
            if self.at_kw("cross"):
                self.take()
                self.take_kw("join")
                tname, tderived, talias = self._join_target()
                stmt.joins.append(JoinClause(tname, None, "cross",
                                             alias=talias,
                                             derived=tderived))
                continue
            if self.at_kw("join", "inner", "left", "right", "full"):
                kind = "inner"
                if self.at_kw("left", "right", "full"):
                    kind = self.take()
                    if self.at_kw("outer"):
                        self.take()
                elif self.at_kw("inner"):
                    self.take()
                self.take_kw("join")
                tname, tderived, talias = self._join_target()
                if self.at_kw("using"):
                    self.take()
                    self.take("op", "(")
                    ucols = [self.take("name")]
                    while self.peek() == ("op", ","):
                        self.take()
                        ucols.append(self.take("name"))
                    self.take("op", ")")
                    stmt.joins.append(JoinClause(
                        tname, None, kind, alias=talias,
                        using=tuple(ucols), derived=tderived))
                    continue
                self.take_kw("on")
                cond = self.expr()
                stmt.joins.append(JoinClause(tname, cond, kind,
                                             alias=talias,
                                             derived=tderived))
                continue
            break
        if self.at_kw("where"):
            self.take()
            stmt.where = self.expr()
        if self.at_kw("group"):
            self.take()
            self.take_kw("by")
            w = self.peek()
            nxt = self.toks[self.i + 1] if self.i + 1 < len(self.toks) \
                else ("eof", None)
            # only the construct spellings: a plain column named
            # rollup/cube/grouping must still GROUP BY normally
            is_construct = w[0] == "name" and (
                (w[1].lower() in ("rollup", "cube")
                 and nxt == ("op", "("))
                or (w[1].lower() == "grouping" and nxt[0] == "name"
                    and str(nxt[1]).lower() == "sets"))
            if is_construct:
                self._grouping_sets(stmt)
            else:
                stmt.group_by.append(self.expr())
                while self.peek() == ("op", ","):
                    self.take()
                    stmt.group_by.append(self.expr())
        if self.at_kw("having"):
            self.take()
            stmt.having = self.expr()
        if self.at_kw("order"):
            self.take()
            self.take_kw("by")
            stmt.order_by = [OrderItem(e, d, n) for e, d, n in
                             self._order_items()]
        if self.at_kw("limit"):
            self.take()
            stmt.limit = int(self.take("num"))
        if self.at_kw("offset"):
            self.take()
            stmt.offset = int(self.take("num"))
        # standard SQL: a bare integer in GROUP BY / ORDER BY is a
        # 1-based projection ordinal, never a constant (sorting by a
        # constant would silently return unordered results)
        stmt.group_by = [_resolve_ordinal(e, stmt) for e in stmt.group_by]
        if stmt.grouping_sets is not None:
            stmt.grouping_sets = [[_resolve_ordinal(e, stmt) for e in s]
                                  for s in stmt.grouping_sets]
        for oi in stmt.order_by:
            oi.expr = _resolve_ordinal(oi.expr, stmt)
        # end-of-input is checked by statement(): a select may also end
        # at ')' (subquery/derived table) or UNION
        return stmt

    # ---- expressions -----------------------------------------------------

    def expr(self) -> Expr:
        return self.or_()

    def or_(self):
        e = self.and_()
        while self.at_kw("or"):
            self.take()
            e = BinOp("||", e, self.and_())
        return e

    def and_(self):
        e = self.not_()
        while self.at_kw("and"):
            self.take()
            e = BinOp("&&", e, self.not_())
        return e

    def not_(self):
        if self.at_kw("not"):
            self.take()
            return FuncCall("not", (self.not_(),))
        return self.cmp()

    def cmp(self):
        e = self.add()
        k, v = self.peek()
        if k == "op" and v in ("=", "<>", "!=", "<", "<=", ">", ">="):
            self.take()
            op = {"=": "==", "<>": "!="}.get(v, v)
            return BinOp(op, e, self.add())
        if self.at_kw("between"):
            self.take()
            lo = self.add()
            self.take_kw("and")
            hi = self.add()
            return BinOp("&&", BinOp(">=", e, lo), BinOp("<=", e, hi))
        if self.at_kw("in"):
            self.take()
            self.take("op", "(")
            if self.at_kw("select"):
                sub = self.statement_in_parens()
                self.take("op", ")")
                return FuncCall("in_subquery", (e, Subquery(sub)))
            vals = [self.add()]
            while self.peek() == ("op", ","):
                self.take()
                vals.append(self.add())
            self.take("op", ")")
            return _expand_tuple_in(e, vals)
        if self.at_kw("like"):
            self.take()
            pat = self.add()
            return FuncCall("like", (e, pat))
        if self.at_kw("not"):
            # e NOT IN (...) / e NOT LIKE / e NOT BETWEEN
            self.take()
            inner = self._negatable(e)
            return FuncCall("not", (inner,))
        if self.at_kw("is"):
            self.take()
            neg = False
            if self.at_kw("not"):
                self.take()
                neg = True
            self.take_kw("null")
            isnull = FuncCall("is_null", (e,))
            return FuncCall("not", (isnull,)) if neg else isnull
        return e

    def _negatable(self, e):
        if self.at_kw("in"):
            self.take()
            self.take("op", "(")
            if self.at_kw("select"):
                sub = self.statement_in_parens()
                self.take("op", ")")
                return FuncCall("in_subquery", (e, Subquery(sub)))
            vals = [self.add()]
            while self.peek() == ("op", ","):
                self.take()
                vals.append(self.add())
            self.take("op", ")")
            return _expand_tuple_in(e, vals)
        if self.at_kw("like"):
            self.take()
            return FuncCall("like", (e, self.add()))
        if self.at_kw("between"):
            self.take()
            lo = self.add()
            self.take_kw("and")
            hi = self.add()
            return BinOp("&&", BinOp(">=", e, lo), BinOp("<=", e, hi))
        raise SqlError("expected IN/LIKE/BETWEEN after NOT")

    def add(self):
        e = self.mul()
        while self.peek()[0] == "op" and self.peek()[1] in ("+", "-",
                                                            "||"):
            op = self.take()
            if op == "||":  # SQL string concatenation
                e = FuncCall("concat", (e, self.mul()))
                continue
            rhs = self.mul()
            l_iv = isinstance(e, FuncCall) and e.name == "__interval"
            r_iv = isinstance(rhs, FuncCall) and rhs.name == "__interval"
            if l_iv and r_iv:
                raise SqlError("INTERVAL +/- INTERVAL is not supported")
            if r_iv:
                e = _fold_interval(e, op, rhs)
            elif l_iv:
                # commuted form: INTERVAL + TIMESTAMP (subtraction from
                # an interval has no meaning)
                if op != "+":
                    raise SqlError(
                        "INTERVAL may only be subtracted FROM a "
                        "timestamp, not the reverse")
                e = _fold_interval(rhs, "+", e)
            else:
                e = BinOp(op, e, rhs)
        if isinstance(e, FuncCall) and e.name == "__interval":
            raise SqlError(
                "INTERVAL literal is only valid in +/- timestamp "
                "arithmetic")
        return e

    def mul(self):
        e = self.unary()
        while self.peek()[0] == "op" and self.peek()[1] in ("*", "/", "%"):
            op = self.take()
            e = BinOp(op, e, self.unary())
        return e

    def unary(self):
        if self.peek() == ("op", "-"):
            self.take()
            return BinOp("-", Lit(0), self.unary())
        return self.atom()

    def atom(self):
        k, v = self.peek()
        if k == "num":
            self.take()
            return Lit(v)
        if k == "str":
            self.take()
            return Lit(v)
        if k == "kw" and v == "null":
            self.take()
            return Lit(None)
        if k == "kw" and v == "case":
            return self._case()
        if k == "kw" and v == "exists":
            # EXISTS (SELECT ...) -> true iff the subquery has rows;
            # non-correlated only (resolved by the fallback interpreter)
            self.take()
            self.take("op", "(")
            sub = self.statement_in_parens()
            self.take("op", ")")
            return FuncCall("exists", (Subquery(sub),))
        if k == "kw" and v == "cast":
            self.take()
            self.take("op", "(")
            e = self.expr()
            self.take_kw("as")
            tname = self.take("name").lower()
            self.take("op", ")")
            fn = _CAST_FNS.get(tname)
            if fn is None:
                raise SqlError(f"unknown CAST type {tname!r}")
            return FuncCall(fn, (e,))
        if k == "name":
            self.take()
            vl = v.lower()
            # typed literals: TIMESTAMP '...' / DATE '...' are plain
            # string literals to the engine (every time comparison path
            # parses ISO-ish strings); INTERVAL '...' UNIT is a marker
            # the additive parser folds into timestamp arithmetic
            if vl in ("timestamp", "date") and self.peek()[0] == "str":
                return Lit(self.take("str"))
            if vl == "interval" and self.peek()[0] in ("str", "num"):
                amt = self.take()
                unit = str(self.take("name")).lower().rstrip("s")
                if unit not in ("year", "month", "week", "day", "hour",
                                "minute", "second"):
                    raise SqlError(f"unknown INTERVAL unit {unit!r}")
                return FuncCall("__interval", (Lit(str(amt)), Lit(unit)))
            if self.peek() == ("op", "("):
                self.take()
                fname = vl
                if fname == "extract":
                    # EXTRACT(YEAR FROM ts) -> year(ts) etc.
                    unit = str(self.take("name")).lower()
                    if unit not in ("year", "quarter", "month", "day",
                                    "hour", "minute", "second"):
                        raise SqlError(f"EXTRACT unit {unit!r}")
                    self.take_kw("from")
                    arg = self.expr()
                    self.take("op", ")")
                    return FuncCall(unit, (arg,))
                distinct = False
                if self.at_kw("distinct"):
                    self.take()
                    distinct = True
                args = []
                if self.peek() == ("op", "*"):
                    self.take()
                elif self.peek() != ("op", ")"):
                    args.append(self.expr())
                    while self.peek() == ("op", ","):
                        self.take()
                        args.append(self.expr())
                self.take("op", ")")
                if distinct:
                    if fname == "count":
                        fname = "count_distinct"
                    elif fname in ("sum", "avg"):
                        # fallback-path aggregates (the device planner
                        # declines them legibly; the reference served
                        # them via full Spark SQL, SURVEY.md §3.1)
                        if len(args) != 1:
                            raise SqlError(
                                f"{fname}(DISTINCT ...) takes exactly "
                                "one argument")
                        fname += "_distinct"
                    elif fname in ("min", "max"):
                        # DISTINCT is a no-op for min/max, but only the
                        # single-argument form is well-defined
                        if len(args) != 1:
                            raise SqlError(
                                f"{fname}(DISTINCT ...) takes exactly "
                                "one argument")
                    else:
                        raise SqlError(
                            "DISTINCT only inside COUNT/SUM/AVG/MIN/MAX")
                k2, v2 = self.peek()
                if k2 == "name" and v2.lower() == "over":
                    return self._window(fname, tuple(args))
                call = FuncCall(fname, tuple(args))
                k2, v2 = self.peek()
                if k2 == "name" and v2.lower() == "filter":
                    # standard SQL: agg(...) FILTER (WHERE cond)
                    if fname not in AGG_FUNCS:
                        raise SqlError(
                            f"FILTER only follows an aggregate, not "
                            f"{fname!r}")
                    self.take()
                    self.take("op", "(")
                    self.take_kw("where")
                    cond = self.expr()
                    self.take("op", ")")
                    return FuncCall("agg_filter", (call, cond))
                return call
            return Col(v)
        if (k, v) == ("op", "("):
            self.take()
            if self.at_kw("select"):  # scalar subquery
                sub = self.statement_in_parens()
                self.take("op", ")")
                return Subquery(sub)
            e = self.expr()
            if self.peek() == ("op", ","):
                # (a, b, ...) row constructor — only meaningful as the
                # LHS/elements of a tuple IN, which expands it away;
                # anywhere else the unknown "row" function errs legibly
                parts = [e]
                while self.peek() == ("op", ","):
                    self.take()
                    parts.append(self.expr())
                self.take("op", ")")
                return FuncCall("row", tuple(parts))
            self.take("op", ")")
            return e
        raise SqlError(f"unexpected token {v!r}")

    def _window(self, fname: str, args: tuple):
        """fn(...) OVER ([PARTITION BY e, ...] [ORDER BY e [DESC], ...])"""
        self.take("name")  # 'over'
        self.take("op", "(")
        partition: list = []
        order: list = []
        k, v = self.peek()
        if k == "name" and v.lower() == "partition":
            self.take()
            self.take_kw("by")
            partition.append(self.expr())
            while self.peek() == ("op", ","):
                self.take()
                partition.append(self.expr())
        if self.at_kw("order"):
            self.take()
            self.take_kw("by")
            items = self._order_items()
            if any(n for _, _, n in items):
                raise SqlError(
                    "NULLS FIRST/LAST in a window ORDER BY is not "
                    "supported")
            order = [(e, d) for e, d, _ in items]
        frame = None
        k, v = self.peek()
        if k == "name" and v.lower() in ("rows", "range"):
            if v.lower() == "range":
                raise SqlError(
                    "RANGE frames are not supported; use ROWS")
            self.take()

            def bound(is_start):
                """One frame bound; UNBOUNDED must point OUTWARD from
                the current row (PRECEDING as a start, FOLLOWING as an
                end) — the inward spellings are invalid SQL and would
                otherwise silently flip the frame's meaning."""
                bk, bv = self.peek()
                if bk == "name" and bv.lower() == "unbounded":
                    self.take()
                    d = str(self.take("name")).lower()
                    want = "preceding" if is_start else "following"
                    if d != want:
                        raise SqlError(
                            f"UNBOUNDED {d.upper()} is not a valid "
                            f"frame {'start' if is_start else 'end'}")
                    return None
                if bk == "name" and bv.lower() == "current":
                    self.take()
                    d = str(self.take("name")).lower()
                    if d != "row":
                        raise SqlError(f"expected CURRENT ROW, got "
                                       f"CURRENT {d.upper()}")
                    return 0
                raw = self.take("num")
                if float(raw) != int(raw):
                    raise SqlError(
                        f"ROWS frame bound must be an integer, "
                        f"got {raw!r}")
                n = int(raw)
                d = str(self.take("name")).lower()
                if d not in ("preceding", "following"):
                    raise SqlError(f"expected PRECEDING/FOLLOWING, "
                                   f"got {d!r}")
                return -n if d == "preceding" else n

            if self.at_kw("between"):
                self.take()
                lo = bound(True)
                self.take_kw("and")
                hi = bound(False)
            else:
                lo, hi = bound(True), 0  # ROWS n PRECEDING
            frame = (lo, hi)
        self.take("op", ")")
        return WindowCall(fname, args, tuple(partition), tuple(order),
                          frame)

    def _grouping_sets(self, stmt):
        """GROUP BY ROLLUP(a, b) | CUBE(a, b) | GROUPING SETS((a,b),(a),())
        -> stmt.grouping_sets = [[expr, ...], ...] (fallback-only; the
        rewriter declines). stmt.group_by holds the full detail list so
        projections/ordinals resolve normally."""
        word = self.take("name").lower()
        if word == "grouping":
            nxt = self.take("name")
            if nxt.lower() != "sets":
                raise SqlError(f"expected SETS after GROUPING, got {nxt!r}")
            self.take("op", "(")
            sets = []
            while True:
                if self.peek() == ("op", "("):
                    self.take()
                    s = []
                    if self.peek() != ("op", ")"):
                        s.append(self.expr())
                        while self.peek() == ("op", ","):
                            self.take()
                            s.append(self.expr())
                    self.take("op", ")")
                else:
                    s = [self.expr()]
                sets.append(s)
                if self.peek() == ("op", ","):
                    self.take()
                    continue
                break
            self.take("op", ")")
        else:
            self.take("op", "(")
            exprs = [self.expr()]
            while self.peek() == ("op", ","):
                self.take()
                exprs.append(self.expr())
            self.take("op", ")")
            if word == "rollup":
                sets = [exprs[:i] for i in range(len(exprs), -1, -1)]
            else:  # cube: every subset, detail-first
                from itertools import combinations
                sets = [list(c) for r in range(len(exprs), -1, -1)
                        for c in combinations(exprs, r)]
        # the full detail list: first-seen order over all sets
        seen, full = set(), []
        for s in sets:
            for e in s:
                k = repr(e)
                if k not in seen:
                    seen.add(k)
                    full.append(e)
        stmt.group_by = full
        stmt.grouping_sets = sets

    def _order_items(self) -> list:
        """Comma list of `expr [ASC|DESC] [NULLS FIRST|LAST]` ->
        [(expr, descending, nulls|None)]."""
        out = []
        while True:
            e = self.expr()
            desc = False
            if self.at_kw("asc"):
                self.take()
            elif self.at_kw("desc"):
                self.take()
                desc = True
            nulls = None
            if self.at_kw("nulls"):
                self.take()
                nulls = str(self.take("name")).lower()
                if nulls not in ("first", "last"):
                    raise SqlError(f"NULLS {nulls!r}: expected FIRST|LAST")
            out.append((e, desc, nulls))
            if self.peek() == ("op", ","):
                self.take()
                continue
            break
        return out

    def _case(self):
        """CASE [operand] WHEN c THEN v ... [ELSE d] END -> nested if()."""
        self.take_kw("case")
        operand = None
        if not self.at_kw("when"):
            operand = self.expr()  # simple CASE: compare operand = value
        branches = []
        while self.at_kw("when"):
            self.take()
            cond = self.expr()
            if operand is not None:
                cond = BinOp("==", operand, cond)
            self.take_kw("then")
            branches.append((cond, self.expr()))
        if not branches:
            raise SqlError("CASE without WHEN")
        default = Lit(None)
        if self.at_kw("else"):
            self.take()
            default = self.expr()
        self.take_kw("end")
        e = default
        for cond, val in reversed(branches):
            e = FuncCall("if", (cond, val, e))
        return e


def _resolve_ordinal(e, stmt):
    """GROUP BY 2 / ORDER BY 2 -> the 2nd projection's expression."""
    if not (isinstance(e, Lit) and type(e.value) is int):
        return e
    n = e.value
    if any(isinstance(p, Col) and p.name == "*"
           for p, _ in stmt.projections):
        # positions are unknowable before schema expansion; erroring
        # beats silently sorting by the constant
        raise SqlError(
            f"ordinal {n} cannot be resolved with SELECT * — name the "
            "column instead")
    if not 1 <= n <= len(stmt.projections):
        raise SqlError(
            f"ordinal {n} out of range (select list has "
            f"{len(stmt.projections)} items)")
    return stmt.projections[n - 1][0]


def _fold_interval(e, op, interval):
    """TIMESTAMP '...' +/- INTERVAL 'n' UNIT folds to a literal
    timestamp string at parse time (the shape BI date-window predicates
    take). Non-literal operands reject legibly — column +/- INTERVAL has
    no engine spelling yet."""
    import pandas as pd
    amt, unit = interval.args[0].value, interval.args[1].value
    if not (isinstance(e, Lit) and isinstance(e.value, str)):
        raise SqlError(
            "INTERVAL arithmetic needs a TIMESTAMP/DATE literal operand")
    try:
        n = float(amt)
        base = pd.Timestamp(e.value)
    except ValueError as err:
        raise SqlError(f"bad INTERVAL arithmetic operand: {err}") from None
    if unit in ("year", "month"):
        if n != int(n):
            raise SqlError(f"fractional INTERVAL {unit} not supported")
        delta = pd.DateOffset(**{unit + "s": int(n)})
    else:
        delta = pd.Timedelta(**{unit + "s": n})
    out = base + delta if op == "+" else base - delta
    return Lit(str(out))


def _expand_tuple_in(e, vals):
    """(a, b) IN ((x, y), ...) -> OR of per-row AND equalities — runs on
    both execution paths with no new IR (selector/and/or filters)."""
    if not (isinstance(e, FuncCall) and e.name == "row"):
        if any(isinstance(v, FuncCall) and v.name == "row"
               for v in vals):
            raise SqlError(
                "IN list contains a (…, …) row literal but the "
                "left-hand side is not a row")
        return FuncCall("in_list", (e, *vals))
    ors = None
    for vrow in vals:
        if not (isinstance(vrow, FuncCall) and vrow.name == "row"
                and len(vrow.args) == len(e.args)):
            raise SqlError("tuple IN needs matching-arity row literals")
        ands = None
        for a, b in zip(e.args, vrow.args):
            c = BinOp("==", a, b)
            ands = c if ands is None else BinOp("&&", ands, c)
        ors = ands if ors is None else BinOp("||", ors, ands)
    return ors if ors is not None else Lit(False)


def _sub_names(e, sub: dict):
    """Rebuild expression `e` with every bare Col whose name is in `sub`
    replaced by the mapped expression. Subquery internals are an inner
    scope and stay untouched; window specs substitute like any other
    expression position."""
    from tpu_olap.ir.expr import map_expr
    return map_expr(e, lambda x: sub.get(x.name)
                    if isinstance(x, Col) else None)


def resolve_output_aliases(stmt, scope_columns: set):
    """Standard-SQL output-alias references: a bare name in GROUP BY /
    ORDER BY that names a projection alias AND does not shadow a source
    column resolves to the aliased expression (Spark/MySQL semantics —
    the reference served these through full Spark SQL, SURVEY.md §3.1).
    Source columns win on conflict, so existing queries are unchanged.
    Aliases may reference earlier aliases; substitution iterates to a
    bounded fixpoint (mutually-recursive aliases stop at the cap)."""
    from tpu_olap.ir.expr import WindowCall

    def non_substitutable(e):
        # window- and grouping()-valued aliases stay as output-column
        # references: the fallback sorter matches them by name, and
        # neither can be re-evaluated inside ORDER BY expressions
        if isinstance(e, WindowCall):
            return True
        if isinstance(e, BinOp):
            return non_substitutable(e.left) or non_substitutable(e.right)
        if isinstance(e, FuncCall):
            if e.name == "grouping":
                return True
            return any(non_substitutable(a) for a in e.args)
        return False

    sub = {}
    for p, alias in stmt.projections:
        if alias and alias not in scope_columns \
                and not (isinstance(p, Col) and p.name == alias) \
                and not non_substitutable(p):
            sub[alias] = p
    if not sub:
        return stmt

    def fix(e):
        for _ in range(5):
            new = _sub_names(e, sub)
            if new == e:
                return e
            e = new
        return e

    stmt.group_by = [fix(e) for e in stmt.group_by]
    if stmt.grouping_sets is not None:
        stmt.grouping_sets = [[fix(e) for e in s]
                              for s in stmt.grouping_sets]
    for oi in stmt.order_by:
        oi.expr = fix(oi.expr)
    return stmt


def _inline_ctes(stmt, ctes: dict):
    """Replace FROM-position references to WITH-defined names with the
    equivalent derived table (deep-copied: one CTE may be referenced
    from several places and later passes mutate statements in place)."""
    import copy

    def walk_stmt(s):
        if isinstance(s, UnionStmt):
            for p in s.parts:
                walk_stmt(p)
            return s
        if s.derived is not None:
            walk_stmt(s.derived)
        elif s.table in ctes:
            s.derived = copy.deepcopy(ctes[s.table])
        for j in s.joins:
            if j.derived is not None:
                walk_stmt(j.derived)
            elif j.table in ctes:
                # JOIN-position CTE reference: same inlining as FROM
                # position (bodies in `ctes` are already fully inlined)
                j.derived = copy.deepcopy(ctes[j.table])
            walk_expr(j.on)  # subqueries inside ON may reference CTEs
        for e, _ in s.projections:
            walk_expr(e)
        walk_expr(s.where)
        walk_expr(s.having)
        for e in s.group_by:
            walk_expr(e)
        for oi in s.order_by:
            walk_expr(oi.expr)
        return s

    def walk_expr(e):
        if e is None:
            return
        if isinstance(e, Subquery):
            walk_stmt(e.stmt)
        elif isinstance(e, BinOp):
            walk_expr(e.left)
            walk_expr(e.right)
        elif isinstance(e, (FuncCall, WindowCall)):
            for a in e.args:
                walk_expr(a)
            if isinstance(e, WindowCall):
                for p in e.partition_by:
                    walk_expr(p)
                for ex, _ in e.order_by:
                    walk_expr(ex)

    return walk_stmt(stmt)


def parse_sql(sql: str):
    """Parse a statement: SelectStmt, or UnionStmt for UNION [ALL]."""
    p = _Parser(_tokenize(sql))
    return p.statement()
