"""HyperLogLog per-group registers on device.

The TPU-native analog of Druid's HyperLogLogCollector (SURVEY.md §3.7):
per-group register arrays updated with scatter-max, merged with elementwise
max (which is exactly the cross-chip allreduce op), finalized host-side or
in a post-aggregation. log2m=11 (2048 registers) matches Druid's default;
estimates use the classic HLL formula with linear-counting small-range
correction, so estimates agree with Druid to within normal HLL tolerance
(~1.6% stddev) — the parity harness applies per-class tolerances
(SURVEY.md §8.4 #2).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

LOG2M = 11
NUM_REGISTERS = 1 << LOG2M  # 2048
_ALPHA = 0.7213 / (1 + 1.079 / NUM_REGISTERS)


def hll_update(h, valid, key, num_groups, xp):
    """h: [N] int32 hashes; valid: [N] bool; key: [N] int32 group ids.

    Returns [num_groups, NUM_REGISTERS] int32 rho registers.
    """
    u = h.astype(xp.uint32)
    reg = (u & xp.uint32(NUM_REGISTERS - 1)).astype(xp.int32)
    w = (u >> LOG2M).astype(xp.uint32)
    # rho = leading-zero count of the remaining (32-log2m) bits + 1
    if xp is np:
        # numpy: bit_length via log2; w==0 -> max rho
        nz = w != 0
        fl = np.zeros(w.shape, np.int32)
        fl[nz] = np.floor(np.log2(w[nz].astype(np.float64))).astype(np.int32)
        rho = np.where(nz, (32 - LOG2M) - fl, (32 - LOG2M) + 1).astype(np.int32)
    else:
        shifted = (w << LOG2M).astype(jnp.uint32)
        rho = jnp.where(w == 0, (32 - LOG2M) + 1,
                        jax.lax.clz(shifted.astype(jnp.int32)) + 1
                        ).astype(jnp.int32)
    rho = xp.where(valid, rho, 0)
    # index space is groups × 2048: compute in the widest int available so
    # group counts inside the dense budget can't overflow the flat index
    # (callers guard the x64-off case — see lowering's sketch radix check)
    idx_dtype = xp.int64 if _wide_ints(xp) else xp.int32
    flat = key.astype(idx_dtype) * idx_dtype(NUM_REGISTERS) \
        + reg.astype(idx_dtype)
    flat = xp.where(valid, flat, 0)
    if xp is np:
        regs = np.zeros(num_groups * NUM_REGISTERS, np.int32)
        np.maximum.at(regs, flat, rho)
        return regs.reshape(num_groups, NUM_REGISTERS)
    regs = jax.ops.segment_max(rho, flat,
                               num_segments=num_groups * NUM_REGISTERS)
    regs = jnp.maximum(regs, 0)  # empty slots: segment_max yields -inf/min
    return regs.reshape(num_groups, NUM_REGISTERS)


def hll_merge(a, b, xp):
    return xp.maximum(a, b)


def _wide_ints(xp) -> bool:
    from tpu_olap.kernels.hashing import has_x64
    return has_x64(xp)


def hll_estimate(registers, xp=np, float_dtype=np.float64):
    """[K, m] registers -> [K] float estimates. Runs host-side (xp=np) or
    on device inside the packed-result program (xp=jnp) — finalizing on
    device keeps the per-query host fetch to one small buffer."""
    ft = np.dtype(float_dtype).type
    regs = xp.asarray(registers).astype(float_dtype)
    # clamp to the valid register range: padding/absent-group slots can
    # carry negative sentinels (exchange-merge buffers), and 2^-(-x)
    # overflows float for large x — those slots are masked downstream,
    # but the warning (and inf) must not be produced at all
    regs = xp.clip(regs, 0.0, 64.0)
    m = NUM_REGISTERS
    inv = xp.power(ft(2.0), -regs).sum(axis=-1)
    est = ft(_ALPHA * m * m) / inv
    zeros = (regs == 0).sum(axis=-1)
    small = est <= 2.5 * m
    lc = m * xp.log(xp.where(zeros > 0,
                             m / xp.maximum(zeros, 1).astype(float_dtype),
                             ft(1.0)))
    est = xp.where(small & (zeros > 0), lc, est)
    return est
