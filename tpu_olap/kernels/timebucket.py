"""Granularity compilation: time column -> dense bucket ids.

Uniform periods (hour/day/... in UTC, duration) are integer floor-divide on
device; calendar periods (month/quarter/year, or any non-UTC tz) use a
host-computed boundary array + vectorized searchsorted (SURVEY.md §8.2
step 3 "time bucketing"). Either way the result is a dense id in
[0, n_buckets) suitable for the mixed-radix group key.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np

from tpu_olap.ir.granularity import (AllGranularity, DurationGranularity,
                                     Granularity, NoneGranularity,
                                     PeriodGranularity)
from tpu_olap.utils import timeutil


class UnsupportedGranularity(Exception):
    pass


@dataclass
class BucketPlan:
    """Host-side plan: how many buckets over [t_min, t_max] and their start
    timestamps; `ids(time, consts)` computes in-range dense ids on device
    (out-of-range rows clip into 0 / n-1 — callers must mask them)."""

    n_buckets: int
    starts: np.ndarray  # [n_buckets] epoch millis (bucket starts)
    kind: str           # "all" | "uniform" | "boundaries"
    origin_name: str | None = None
    step_name: str | None = None
    boundaries_name: str | None = None
    # The runner caches the bucket id stream as a device-resident
    # derived column keyed by this token (same machinery as remap
    # dims), saving both the per-dispatch id compute (searchsorted for
    # "boundaries") and the int64 __time read. "uniform" streams are
    # TABLE-anchored — token u:<phase>:<step> is independent of the
    # query's time range, so a sliding dashboard window re-uses one
    # resident stream; ids_from_cached() rebases to the query's origin.
    cache_token: str | None = None
    phase_name: str | None = None          # uniform: origin mod step
    origin_bucket_name: str | None = None  # uniform: (origin-phase)/step

    @property
    def derived_name(self) -> str | None:
        return None if self.cache_token is None else "\0b:" + self.cache_token

    def ids(self, time, consts):
        xp = jnp if not isinstance(time, np.ndarray) else np
        if self.kind == "all":
            return xp.zeros(time.shape, xp.int32)
        if self.kind == "uniform":
            origin = consts[self.origin_name]
            step = consts[self.step_name]
            i = (time - origin) // step
            return xp.clip(i, 0, self.n_buckets - 1).astype(xp.int32)
        bs = consts[self.boundaries_name]
        i = xp.searchsorted(bs, time, side="right") - 1
        return xp.clip(i, 0, self.n_buckets - 1).astype(xp.int32)

    def build_stream(self, time, consts):
        """The cacheable per-row stream [same shape as time], int32.
        "uniform": table-anchored bucket index (t - phase) // step;
        "boundaries": the query-range ids themselves (the boundary set
        is the token, so the stream is exact for that token)."""
        xp = jnp if not isinstance(time, np.ndarray) else np
        if self.kind == "uniform":
            return ((time - consts[self.phase_name])
                    // consts[self.step_name]).astype(xp.int32)
        return self.ids(time, consts)

    def ids_from_cached(self, cached, consts, xp):
        """Query-range ids from a resident stream: rebase the table-
        anchored uniform index to this plan's origin bucket and clip
        (same out-of-range clamp semantics as ids() — callers mask)."""
        if self.kind == "uniform":
            i = cached - consts[self.origin_bucket_name]
            return xp.clip(i, 0, self.n_buckets - 1).astype(xp.int32)
        return cached


def _uniform_plan(origin: int, step: int, n: int, pool,
                  table_bounds) -> BucketPlan:
    """Uniform BucketPlan with a TABLE-anchored cacheable stream: the
    token depends only on (phase, step) — phase = origin mod step is the
    same for every query range of this granularity — so a sliding
    dashboard window re-uses one resident stream instead of rebuilding a
    full-table id pass per distinct time range. Caching is skipped when
    the table-anchored index could overflow int32 (sub-second steps over
    decades) or the table bounds are unknown."""
    starts = origin + step * np.arange(n, dtype=np.int64)
    phase = origin % step
    token = None
    phase_name = origin_bucket_name = None
    if table_bounds is not None:
        t_lo, t_hi = table_bounds
        lo_idx = (t_lo - phase) // step
        hi_idx = (t_hi - phase) // step
        if -(2 ** 31) < lo_idx and hi_idx < 2 ** 31 - 1 \
                and -(2 ** 31) < (origin - phase) // step < 2 ** 31 - 1:
            token = f"u:{phase}:{step}"
            phase_name = pool.add(phase, np.int64)
            origin_bucket_name = pool.add(
                np.int32((origin - phase) // step), np.int32)
    return BucketPlan(n, starts, "uniform",
                      origin_name=pool.add(origin, np.int64),
                      step_name=pool.add(step, np.int64),
                      cache_token=token, phase_name=phase_name,
                      origin_bucket_name=origin_bucket_name)


def compile_granularity(gran: Granularity, t_min: int, t_max: int,
                        pool, table_bounds=None) -> BucketPlan:
    """t_min/t_max: inclusive millis range actually queried (intervals ∩
    table time boundary). pool: ConstPool for device constants."""
    if isinstance(gran, AllGranularity):
        return BucketPlan(1, np.array([t_min], np.int64), "all")
    if isinstance(gran, NoneGranularity):
        raise UnsupportedGranularity(
            "granularity 'none' (per-millisecond buckets) is not supported "
            "on the dense device path")
    if isinstance(gran, DurationGranularity):
        step = int(gran.duration)
        if step <= 0:
            raise UnsupportedGranularity("duration must be positive")
        origin = gran.origin + ((t_min - gran.origin) // step) * step
        n = int((t_max - origin) // step) + 1
        return _uniform_plan(origin, step, n, pool, table_bounds)
    if isinstance(gran, PeriodGranularity):
        if gran.origin is not None:
            # explicit origin pins alignment: pure epoch stepping, but only
            # meaningful for fixed-duration periods (sub-day in any tz,
            # day/week in UTC — elsewhere local midnight drifts off origin)
            if not gran.is_uniform():
                raise UnsupportedGranularity(
                    "custom origin requires a fixed-duration period "
                    "(calendar periods / day in a DST tz not supported)")
            step = timeutil.period_millis(gran.period)
            origin = gran.origin + ((t_min - gran.origin) // step) * step
            n = int((t_max - origin) // step) + 1
            return _uniform_plan(origin, step, n, pool, table_bounds)
        if gran.is_uniform():
            step = timeutil.period_millis(gran.period)
            # natural alignment: floor t_min to the local period start
            bs = timeutil.calendar_boundaries(gran.period, gran.time_zone,
                                              t_min, t_min)
            origin = bs[0]
            n = int((t_max - origin) // step) + 1
            # resident id stream like the boundaries kind: the id
            # arithmetic is trivial but caching it drops the __time
            # (int64) read from every dispatch that needs no other
            # raw-timestamp consumer (executor/lowering.py need_time)
            return _uniform_plan(origin, step, n, pool, table_bounds)
        bs = np.asarray(timeutil.calendar_boundaries(
            gran.period, gran.time_zone, t_min, t_max), np.int64)
        n = len(bs) - 1
        import hashlib
        return BucketPlan(n, bs[:-1], "boundaries",
                          boundaries_name=pool.add(bs),
                          cache_token=hashlib.sha1(
                              bs.tobytes()).hexdigest()[:16])
    raise UnsupportedGranularity(f"unknown granularity {gran!r}")


# ---------------------------------------------------------------------------
# Time-format extraction: bucket remap through host-formatted bucket starts.

_FORMAT_FINEST = (
    (("%S", "ss", "SS"), "PT1S"),
    (("%M", "mm"), "PT1M"),
    (("%H", "HH", "hh"), "PT1H"),
    (("%d", "dd", "DD", "%j"), "P1D"),
    (("%m", "MM", "%b", "%B"), "P1M"),
    (("%Y", "%y", "YYYY", "yyyy", "YY"), "P1Y"),
)

_SHORTHAND = {
    "YYYY": "%Y", "yyyy": "%Y", "YY": "%y",
    "MM": "%m", "dd": "%d", "DD": "%d",
    "HH": "%H", "hh": "%H", "mm": "%M", "ss": "%S", "SS": "%S",
}


def format_finest_period(fmt: str) -> str:
    for needles, period in _FORMAT_FINEST:
        if any(nd in fmt for nd in needles):
            return period
    return "P1Y"


def strftime_of(fmt: str) -> str:
    """Translate joda-ish shorthands (YYYY, MM, dd...) to strftime."""
    if "%" in fmt:
        return fmt
    out = fmt
    for k in sorted(_SHORTHAND, key=len, reverse=True):
        out = out.replace(k, _SHORTHAND[k])
    return out


def compile_time_format(fmt: str, tz: str, t_min: int, t_max: int, pool,
                        bucket_budget: int | None = None):
    """TimeFormatExtractionFn -> (BucketPlan over the finest needed period,
    remap const name, group value strings).

    Device work: fine bucket id -> gather remap -> dense group id. The
    formatted strings (group labels) are computed host-side only for the
    bucket *starts* — never per row (SURVEY.md §8.2's host/device split).
    bucket_budget bounds the fine-bucket count BEFORE materializing it:
    second(ts) over an unfiltered multi-year table would otherwise build
    tens of millions of bucket starts host-side (and a matching remap
    constant); exceeding the budget rejects into the fallback.
    """
    if bucket_budget is not None:
        period_est = format_finest_period(fmt)
        try:
            ms = timeutil.period_millis(period_est)
        except ValueError:
            ms = None  # calendar periods: bucket counts are small
        if ms is not None and (t_max - t_min) / ms + 1 > bucket_budget:
            raise UnsupportedGranularity(
                f"timeFormat {fmt!r} over this time span needs more than "
                f"{bucket_budget} fine buckets; narrow the intervals")
    import datetime as dt
    from zoneinfo import ZoneInfo

    if fmt == "Q":
        # quarter-of-year (1-4): no joda/strftime code exists, so the
        # label renders directly from the P3M bucket starts
        plan = compile_granularity(PeriodGranularity("P3M", tz), t_min,
                                   t_max, pool)
        zone = ZoneInfo(tz)
        labels = [
            str((dt.datetime.fromtimestamp(ms / 1000, tz=zone).month - 1)
                // 3 + 1)
            for ms in plan.starts]
        values = sorted(set(labels))
        index = {v: i for i, v in enumerate(values)}
        remap = np.asarray([index[x] for x in labels], np.int32)
        return plan, pool.add(remap), values
    period = format_finest_period(fmt)
    plan = compile_granularity(PeriodGranularity(period, tz), t_min, t_max,
                               pool)
    sf = strftime_of(fmt)
    zone = ZoneInfo(tz)
    labels = [dt.datetime.fromtimestamp(ms / 1000, tz=zone).strftime(sf)
              for ms in plan.starts]
    # distinct labels, sorted (Druid sorts extraction outputs lexically)
    values = sorted(set(labels))
    index = {v: i for i, v in enumerate(values)}
    remap = np.asarray([index[x] for x in labels], np.int32)
    remap_name = pool.add(remap)
    return plan, remap_name, values
