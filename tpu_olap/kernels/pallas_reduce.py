"""Pallas TPU kernel: fused filter -> group key -> one-hot MXU reduce.

The in-tree "native tier" replacing Druid's segment-engine hot loop
(SURVEY.md §3.7): where the jnp path lowers the grouped reduce to XLA
scatter-adds (`jax.ops.segment_sum`), this kernel rides the MXU instead —
a masked one-hot of the dense group key contracted against the aggregate
inputs — and fuses the whole per-row pipeline (validity/filter masks,
mixed-radix key build, virtual-column arithmetic, half-plane decomposition)
into one pass over VMEM-resident row chunks.

Exact int64 sums via fixed-point byte planes
--------------------------------------------
The MXU has no integer matmul wide enough for longSum semantics, and f32
accumulation is only exact below 2^24. Each int32 aggregate input v >= 0 is
decomposed into 8-bit planes  v = sum_j h_j * 256^j  (h_j in [0, 255] —
exact in bf16, whose 8 mantissa bits represent every integer up to 256).
The plane COUNT is sized per query from the column-metadata value span
(round-5 roofline fix: the round-4 kernel burned a fixed 8 planes of 4
bits on every sum; byte planes + span sizing cut the accumulator lane
count 2-4x and the one-hot FLOPs with it). Per grid step the kernel
computes

    partial[K, H] = onehotT[K, RB] . valsT[H, RB]^T      (bf16 x bf16 -> f32)

whose entries are integer-valued and bounded by RB * 255 < 2^24, so the
f32 result is exact; it is cast to int32 and accumulated across grid
steps in the int32 output. Accumulation overflow is handled by a CHUNK
axis instead of an eligibility row cap: the output carries one [K, H]
buffer per run of `steps_per_chunk` grid steps (sized so each chunk's
accumulated plane sums stay under 2^31), and the host recombines chunks
with an exact f64 sum (each chunk value < 2^31, totals < 2^53). Planes
then recombine as sum_j out[:, j] << 8j in two f64 half-sums. Counts
ride the same matmul as columns of ones.

Eligibility (checked by `eligible()`, anything else falls back to the XLA
scatter path — mirroring the planner's structural-fallback rule, SURVEY.md
§2 property 2): dims lowered to codes/numeric-offset/remap (compare +
small-table gather only), aggs are count / integer sums whose value bounds
fit int32 (interval arithmetic over virtual-column exprs), no DOUBLE
inputs, no float or over-int32 constants *read inside the kernel*.

Time handling (round-3 widening): granularity buckets and interval masks
are computed OUTSIDE the kernel (plain XLA over the int64 time column —
cheap elementwise work XLA fuses anyway) and enter the kernel as an int32
bucket-id input folded into the mixed-radix key / ANDed into the validity
mask. The int64-free kernel interior stays int32. Only a query that reads
__time *inside* the kernel (a filter or aggregate on raw time) is
ineligible. Group spaces past pallas_k_per_block tile over a second grid
axis (K-blocks × row-blocks), so K is bounded by pallas_group_cap, not by
one onehot tile.

Float sums stay on the XLA scatter path BY DESIGN: doubleSum's contract
is f64 accumulation (exact parity with the fallback), and no bf16-plane
decomposition keeps f32 dot-products exact once the accumulation inside
the MXU rounds — the half-plane trick works for ints only because plane
values are small integers whose partial sums stay below 2^24. With
filter-constrained dim domains every SSB query's sums are integer and
Pallas-eligible, so the float tier has no benchmark pressure; revisit
only with a tolerance-based parity contract.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from tpu_olap.ir import aggregations as A
from tpu_olap.ir import filters as F
from tpu_olap.ir.expr import BinOp, Col, Lit
from tpu_olap.kernels.exprs import materialize_virtuals
from tpu_olap.segments.segment import ColumnType, TIME_COLUMN

N_PLANE_BITS = 8
PLANE_MASK = (1 << N_PLANE_BITS) - 1
MAX_VALUE = (1 << 31) - 1           # aggregate inputs must fit int32
# The chunked accumulator removes the int32 per-chip row cap; what
# remains is f64 exactness of the host-side half-sum recombination:
# each half-sum is below n_rows * 255 * 257 and must stay under 2^53.
MAX_ROWS = (1 << 53) // (PLANE_MASK * (PLANE_MASK + 2))


def expr_int_bounds(expr, col_bounds):
    """Conservative integer interval of an expression, or None if unbounded
    / non-integer (division, functions, unknown columns) — or if ANY
    intermediate result can leave int32 (the kernel evaluates the whole
    tree in int32, so every node must fit, not just the root)."""
    def fits(b):
        return b if (b is not None and -MAX_VALUE <= b[0]
                     and b[1] <= MAX_VALUE) else None

    if isinstance(expr, Lit):
        v = expr.value
        if isinstance(v, (int, np.integer)) and not isinstance(v, bool):
            return fits((int(v), int(v)))
        return None
    if isinstance(expr, Col):
        return fits(col_bounds.get(expr.name))
    if isinstance(expr, BinOp) and expr.op in ("+", "-", "*", "%"):
        a = expr_int_bounds(expr.left, col_bounds)
        b = expr_int_bounds(expr.right, col_bounds)
        if a is None or b is None:
            return None
        if expr.op == "+":
            return fits((a[0] + b[0], a[1] + b[1]))
        if expr.op == "-":
            return fits((a[0] - b[1], a[1] - b[0]))
        if expr.op == "%":
            # floored modulo (Python/numpy/jnp/pandas all agree): with a
            # positive constant modulus the result is in [0, m-1] for
            # ANY lhs sign; other moduli stay off the device path
            if not (isinstance(expr.right, Lit) and b[0] == b[1]
                    and b[0] > 0):
                return None
            return fits((0, b[0] - 1))
        prods = [x * y for x in a for y in b]
        return fits((min(prods), max(prods)))
    return None


class _Ineligible(Exception):
    pass


# Dimension kinds whose ids are computed INSIDE the kernel (pure int32
# arithmetic). remap/timeformat ids need a dynamic gather, which Mosaic
# does not lower for 1-D operands ("Only 2D gather is supported", v5e) —
# the host wrapper precomputes those in fused XLA and streams the int32
# ids in like granularity buckets.
IN_KERNEL_DIM_KINDS = ("codes", "numeric")


def _kernel_refs(plan) -> set:
    """Column NAMES (physical or virtual) referenced inside the kernel:
    filter + agg + in-kernel dim inputs. Gather-needing dims
    (remap/timeformat) are precomputed on the host side; their source
    columns (possibly __time) never enter the kernel unless a filter/agg
    also reads them."""
    q = plan.query
    cols: set = set()
    if q.filter is not None:
        cols |= q.filter.columns()
    for p in plan.agg_plans:
        cols |= set(p.fields)
    for dp in plan.dim_plans:
        if dp.source_col and dp.kind in IN_KERNEL_DIM_KINDS:
            cols.add(dp.source_col)

    def agg_filter_cols(spec):
        if isinstance(spec, A.FilteredAggregation):
            return spec.filter.columns() | agg_filter_cols(spec.aggregator)
        return set()

    for a in q.aggregations:
        cols |= agg_filter_cols(a)
    return cols


def kernel_virtuals(plan) -> dict:
    """The subset of plan.virtual_exprs the kernel must materialize."""
    refs = _kernel_refs(plan)
    return {c: e for c, e in plan.virtual_exprs.items() if c in refs}


def kernel_columns(plan) -> tuple:
    """Physical columns read INSIDE the kernel: _kernel_refs expanded
    through virtual columns. If __time appears here, the query reads raw
    time in-kernel and is ineligible (the kernel interior is int32-only;
    host-precomputed bucket ids / interval masks / dim ids are not
    in-kernel reads)."""
    phys: set = set()
    for c in _kernel_refs(plan):
        phys |= (plan.virtual_exprs[c].columns()
                 if c in plan.virtual_exprs else {c})
    return tuple(sorted(phys))


class _ConstTracker:
    """consts-dict wrapper recording which ConstPool names the kernel's
    compiled closures actually read (filters, dim id maps, agg filters) —
    only those enter the Pallas kernel and must fit int32; host-side
    consts (interval edges, bucket origins: int64 epoch millis) do not."""

    def __init__(self, consts):
        self._c = consts
        self.used: set = set()

    def __getitem__(self, k):
        self.used.add(k)
        return self._c[k]


def traced_const_names(plan, table, filter_fn) -> list:
    """Names of pool consts the kernel closures read, discovered by running
    them once on a tiny all-zeros numpy environment (the closures are
    xp-generic and total on any int input). Memoized on the plan —
    eligible() and build_kernel() both need it for the same lowering."""
    cached = getattr(plan, "_pallas_const_names", None)
    if cached is not None:
        return cached
    n = 8
    kcols = kernel_columns(plan)
    cols = {c: np.zeros(n, np.int64) for c in kcols}
    # filter-derived streams are present in the real kernel env, so the
    # trace must offer them too — otherwise the columnComparison closure
    # would take its gather branch here and record a const the kernel
    # never reads at runtime
    for token, _, _ in plan.filter_streams:
        cols["\0d:" + token] = np.zeros(n, np.int32)
    nulls = {c: np.zeros(n, bool) for c in plan.null_cols if c in kcols}
    materialize_virtuals(kernel_virtuals(plan), cols, nulls, np,
                         wide_ints=False)
    env = {"cols": cols, "nulls": nulls}
    tc = _ConstTracker(plan.pool.consts)
    if filter_fn is not None:
        filter_fn(env, tc)
    for dp in plan.dim_plans:
        if dp.kind in IN_KERNEL_DIM_KINDS:
            dp.ids(env, tc, np)
    for p in plan.agg_plans:
        if p.filter_fn is not None:
            p.filter_fn(env, tc)
    plan._pallas_const_names = sorted(tc.used)
    return plan._pallas_const_names


def column_bounds(plan, table) -> dict:
    """Integer [min, max] of every numeric column the kernel reads; raises
    _Ineligible for DOUBLE columns or ranges that cannot load as int32.
    Memoized on the table (segments are immutable after ingest), so
    repeated queries over the same columns pay the metadata scan once."""
    cache = getattr(table, "_pallas_bounds_cache", None)
    if cache is None:
        cache = table._pallas_bounds_cache = {}
    key = kernel_columns(plan)
    cached = cache.get(key)
    if cached is not None:
        if isinstance(cached, _Ineligible):
            raise cached
        return cached
    if not key:  # e.g. count(*) grouped only by precomputed dims
        cache[key] = {}
        return {}
    md = table.column_metadata(set(key))
    bounds = {}
    for c in key:
        typ = table.schema[c]
        if typ is ColumnType.STRING:
            continue
        if typ is ColumnType.DOUBLE:
            err = _Ineligible(f"DOUBLE column {c!r}")
            cache[key] = err
            raise err
        m = md.get(c, {})
        if m.get("min") is None:
            bounds[c] = (0, 0)  # empty table
        else:
            lo, hi = int(m["min"]), int(m["max"])
            if lo < -MAX_VALUE or hi > MAX_VALUE:
                err = _Ineligible(f"column {c!r} range exceeds int32")
                cache[key] = err
                raise err
            bounds[c] = (lo, hi)
    cache[key] = bounds
    return bounds


def sum_bounds(plan, table) -> dict:
    """Per-sum-aggregation input bounds (post eligibility: always bounded)."""
    bounds = column_bounds(plan, table)
    out = {}
    for p in plan.agg_plans:
        if p.kind != "sum":
            continue
        f = p.fields[0]
        b = (expr_int_bounds(plan.virtual_exprs[f], bounds)
             if f in plan.virtual_exprs else bounds.get(f))
        out[p.name] = b
    return out


_SIMPLE_FILTERS = (F.SelectorFilter, F.BoundFilter, F.InFilter,
                   F.RegexFilter, F.LikeFilter, F.ColumnComparisonFilter)


def _colcmp_nodes(spec):
    """Every ColumnComparisonFilter in the tree."""
    if spec is None:
        return
    if isinstance(spec, F.ColumnComparisonFilter):
        yield spec
    elif isinstance(spec, (F.AndFilter, F.OrFilter)):
        for f in spec.fields:
            yield from _colcmp_nodes(f)
    elif isinstance(spec, F.NotFilter):
        yield from _colcmp_nodes(spec.field)


def _filter_ok(spec) -> bool:
    if spec is None or isinstance(spec, _SIMPLE_FILTERS):
        return True
    if isinstance(spec, (F.AndFilter, F.OrFilter)):
        return all(_filter_ok(f) for f in spec.fields)
    if isinstance(spec, F.NotFilter):
        return _filter_ok(spec.field)
    return False


@dataclass
class Factorization:
    """Large-K lane packing: the dense key splits into (key >> s,
    key & (k2 - 1)) and k2 groups' aggregate columns share one lane tile,
    so the MXU tile product tracks K*H instead of K*128 (the direct
    layout pads H to a full 128-lane tile — a ~12x FLOP waste at H ~ 10).
    k2 is a power of two >= 8 so every sublane concat stays 8-aligned
    (Mosaic relayouts on misaligned sublane offsets are the alternative).
    Output entry (k1, h*k2 + k2v) holds agg column h of group k1*k2+k2v."""
    k2: int        # groups packed per lane tile (power of two, >= 8)
    shift: int     # log2(k2)
    width: int     # lane-padded k2 * H
    k1_pad: int    # padded row count of the [k1, width] output
    kb: int        # K1 rows per grid block
    n_kb: int      # grid blocks over the k1 axis


def factorization(K, H, n_mm, config) -> Factorization | None:
    """Pick the lane packing minimizing the output tile product, or None
    when the direct layout is no worse (small K) or inapplicable: min/max
    aggs key their VPU buffer on the full K (n_mm > 0), and H > 32 would
    spill past two lane tiles per group batch."""
    if n_mm or K < 2 or H > 32:
        return None
    kb_d = min(K, config.pallas_k_per_block)
    direct = -(-K // kb_d) * kb_d * max(128, -(-H // 128) * 128)
    best = None
    for k2 in (8, 16, 32, 64):
        width = -(-k2 * H // 128) * 128
        k1 = -(-K // k2)
        kb = min(-(-k1 // 8) * 8, config.pallas_k_per_block)
        n_kb = -(-k1 // kb)
        k1_pad = n_kb * kb
        prod = k1_pad * width
        # tie -> larger k2: fewer k1 rows means fewer passes over the
        # row stream once K1 exceeds one grid block
        if best is None or prod <= best[0]:
            best = (prod, Factorization(k2, k2.bit_length() - 1, width,
                                        k1_pad, kb, n_kb))
    return best[1] if best and best[0] < direct else None


def _layout_for(plan, table) -> "PallasLayout":
    """plan_layout memoized on the plan (same pattern as
    traced_const_names): eligible(), the FLOP budget gate, and
    build_kernel all need the identical layout during one lowering."""
    cached = getattr(plan, "_pallas_layout", None)
    if cached is None:
        cached = plan._pallas_layout = plan_layout(
            plan.agg_plans, sum_bounds(plan, table))
    return cached


def tile_product(plan, table, config) -> int:
    """K_pad * lane_width of the accumulator the kernel would build —
    the one-hot reduce costs 2 * n_rows * tile_product FLOPs. Shared by
    build_kernel and the auto-policy FLOP budget gate in lowering."""
    layout = _layout_for(plan, table)
    K = plan.total_groups
    fact = factorization(K, layout.n_cols, layout.n_minmax, config)
    if fact is not None:
        return fact.k1_pad * fact.width
    kb = min(K, config.pallas_k_per_block)
    return -(-K // kb) * kb * max(128, -(-layout.n_cols // 128) * 128)


@dataclass
class PallasLayout:
    """Half-plane column layout of the [K, H] accumulator."""
    n_cols: int                   # H (before lane padding)
    rows_slot: int                # column index of the _rows count
    agg_slots: tuple              # per agg: (name, kind, start, n_planes,
    #                               bias) — bias < 0 means inputs are
    #                               shifted by -bias into [0, hi-lo] and an
    #                               extra per-agg row-count column sits at
    #                               start + n_planes for the un-shift.
    #                               min/max aggs use `start` for their
    #                               non-null COUNT column (riding the
    #                               matmul) and n_planes as the column
    #                               index into the second (VPU min)
    #                               output buffer
    n_minmax: int = 0             # columns of the second output buffer


def _sum_plane_spec(lo: int, hi: int) -> tuple:
    """(n_planes, bias) for a sum whose inputs lie in [lo, hi]: the
    minimal byte-plane count covering the value span. bias != 0 shifts
    inputs into [0, hi - lo] (mandatory for lo < 0, since planes are
    unsigned); a non-negative range is biased only when the shift saves
    more planes than the one extra row-count column the un-shift needs."""
    def planes(top):
        return max(1, -(-max(int(top), 1).bit_length() // N_PLANE_BITS))

    shifted = planes(hi - lo)
    if lo < 0:
        return shifted, lo
    if shifted + 1 < planes(hi):
        return shifted, lo
    return planes(hi), 0


def plan_layout(agg_plans, sum_bounds) -> PallasLayout:
    slots = []
    h = 1  # slot 0: _rows
    n_mm = 0
    for p in agg_plans:
        if p.kind == "count":
            slots.append((p.name, "count", h, 1, 0))
            h += 1
        elif p.kind in ("min", "max"):
            # non-null count column in the matmul buffer + one column in
            # the min-accumulated VPU buffer (max rides negated)
            slots.append((p.name, p.kind, h, n_mm, 0))
            h += 1
            n_mm += 1
        else:  # sum
            n, bias = _sum_plane_spec(*sum_bounds[p.name])
            slots.append((p.name, "sum", h, n, bias))
            h += n + (1 if bias else 0)
    return PallasLayout(h, 0, tuple(slots), n_minmax=n_mm)


def eligible(query, plan, table, config, filter_fn=None) -> str | None:
    """None if the plan can run on the Pallas kernel, else the reason."""
    if plan.kind != "agg":
        return "not an aggregate plan"
    kcols = kernel_columns(plan)
    if TIME_COLUMN in kcols:
        return "raw __time read inside the kernel"
    if plan.total_groups > config.pallas_group_cap:
        # past the direct cap, only the factorized lane packing keeps
        # the tile product (and the VPU compare cost) in the win regime
        # — and computing the layout needs the bounds scan, so do the
        # cheap hard-cap check first
        if plan.total_groups > config.pallas_group_cap_factorized:
            return (f"group space {plan.total_groups} exceeds pallas "
                    f"cap {config.pallas_group_cap_factorized}")
        bad = next((p.kind for p in plan.agg_plans
                    if p.kind not in ("count", "sum", "min", "max")), None)
        if bad is not None:  # plan_layout would KeyError on e.g. HLL
            return f"aggregation kind {bad!r}"
        try:
            # plan_layout subscripts sum-input bounds — an unboundable
            # sum stores None there (the under-cap path rejects it
            # later with its own reason), so probe the bounds first
            sb = sum_bounds(plan, table)
            missing = next((k for k, v in sb.items() if v is None), None)
            if missing is not None:
                return f"cannot bound sum input of {missing!r}"
            layout = _layout_for(plan, table)
        except _Ineligible as e:
            return str(e)
        if factorization(plan.total_groups, layout.n_cols,
                         layout.n_minmax, config) is None:
            return (f"group space {plan.total_groups} exceeds pallas "
                    f"cap {config.pallas_group_cap} and the layout "
                    "does not factorize")
    if table.block_rows % 128 != 0:
        return f"block_rows {table.block_rows} not a multiple of 128"
    rb = min(table.block_rows, config.pallas_rows_per_block)
    if table.block_rows % rb != 0:
        return (f"pallas_rows_per_block {rb} does not divide block_rows "
                f"{table.block_rows}")
    if rb * PLANE_MASK >= 1 << 24:
        # per-step f32 matmul partials must stay exact: byte planes bound
        # each lane's per-row worth at 255, so rb caps at 65792
        return f"rows-per-block {rb} breaks f32 plane-sum exactness"
    if table.num_rows > MAX_ROWS:
        return (f"row count {table.num_rows} exceeds f64 recombination "
                "headroom")
    for dp in plan.dim_plans:
        if dp.kind not in ("codes", "numeric", "remap", "timeformat"):
            return f"dimension kind {dp.kind!r}"
    if not _filter_ok(query.filter):
        return "filter tree has non-simple members"
    for cc in _colcmp_nodes(query.filter):
        # string pairs read the precomputed translation stream (int32 by
        # construction); numeric pairs compare loaded columns — both need
        # PHYSICAL columns (virtuals would evaluate un-bounded in-kernel)
        for c in cc.dimensions:
            if c not in table.schema:
                return f"columnComparison over virtual column {c!r}"

    try:
        bounds = column_bounds(plan, table)
    except _Ineligible as e:
        return str(e)

    specs = {a.name: a for a in query.aggregations}

    def base_spec(spec):
        if isinstance(spec, A.FilteredAggregation):
            if not _filter_ok(spec.filter):
                return None
            return base_spec(spec.aggregator)
        return spec

    for p in plan.agg_plans:
        spec = base_spec(specs[p.name])
        if spec is None:
            return f"aggregator {p.name!r} has a non-simple filter"
        if p.kind == "count":
            continue
        if p.kind not in ("sum", "min", "max"):
            return f"aggregation kind {p.kind!r}"
        if p.kind == "sum" and np.dtype(p.acc_dtype).kind != "i":
            return f"non-integer sum {p.name!r}"
        f = p.fields[0]
        if f in plan.virtual_exprs:
            b = expr_int_bounds(plan.virtual_exprs[f], bounds)
        else:
            b = bounds.get(f)
        if b is None:
            return f"cannot bound {p.kind} input {f!r}"
        if p.kind == "sum" and b[1] - b[0] > MAX_VALUE:
            return f"sum input {f!r} span {b} exceeds int32"

    for name in traced_const_names(plan, table, filter_fn):
        v = plan.pool.consts[name]
        if v.dtype.kind == "f":
            return f"float literal const {name}"
        if v.dtype.kind == "i" and v.size and (
                v.min() < -MAX_VALUE or v.max() > MAX_VALUE):
            return f"const {name} exceeds int32"
    return None


def build_kernel(plan, table, config, filter_fn, interpret: bool,
                 imask_fn=None):
    """The Pallas replacement for lowering's generic agg kernel closure.

    Same contract: fn(env, valid, seg_mask, consts) -> partial dict with
    "_rows" plus one int64 [K] array per aggregation. Interval masks and
    granularity bucket ids are evaluated on the int64 time column OUTSIDE
    the pallas_call (plain fused XLA) and enter as mask / int32 key input;
    group spaces wider than pallas_k_per_block tile over grid axis 0.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    layout = _layout_for(plan, table)
    K = plan.total_groups
    H = layout.n_cols
    H_pad = max(128, -(-H // 128) * 128)
    sizes = plan.sizes
    dim_plans = plan.dim_plans
    agg_plans = plan.agg_plans
    vexprs = kernel_virtuals(plan)
    bucket_plan = plan.bucket_plan
    has_buckets = bucket_plan.kind != "all"
    pre_dims = [dp.kind not in IN_KERNEL_DIM_KINDS for dp in dim_plans]
    n_pre = (1 if has_buckets else 0) + sum(pre_dims)
    block_rows = table.block_rows
    rb = min(block_rows, config.pallas_rows_per_block)
    fact = factorization(K, H, layout.n_minmax, config)
    if fact is not None:
        KB, n_kb, K_pad = fact.kb, fact.n_kb, fact.k1_pad
        W = fact.width
    else:
        KB = min(K, config.pallas_k_per_block)
        n_kb = -(-K // KB)
        K_pad = n_kb * KB
        W = H_pad

    const_names = traced_const_names(plan, table, filter_fn)
    col_names = [c for c in kernel_columns(plan) if c != TIME_COLUMN] \
        + ["\0d:" + t for t, _, _ in plan.filter_streams]
    n_mm = layout.n_minmax
    MM_pad = max(128, -(-n_mm // 128) * 128) if n_mm else 0
    # Chunked accumulation: the int32 output accumulates per-step f32
    # partials whose per-row worth is PLANE_MASK (byte planes) or 1
    # (count-only layouts). Every `spc` grid steps the output block index
    # advances, flushing a fresh [KB, W] chunk, so per-chunk sums stay
    # under 2^31 for ANY per-chip row count; the host recombines chunks
    # with an exact f64 sum. spc is static (rb is), n_chunks is shape-
    # derived inside fn.
    per_row = PLANE_MASK if any(
        s[1] == "sum" for s in layout.agg_slots) else 1
    spc = max(1, MAX_VALUE // (rb * per_row))

    def make_kernel_fn(null_names):
        def kernel_fn(*refs):
            (col_refs, pre_refs, null_refs, valid_ref, const_refs,
             outs) = _split_refs(refs, len(col_names), n_pre,
                                 len(null_names), len(const_names),
                                 n_outs=2 if n_mm else 1)
            out_ref = outs[0]
            mm_ref = outs[1] if n_mm else None
            kb = pl.program_id(0)
            step = pl.program_id(1)

            env = {"cols": {}, "nulls": {}}
            for name, r in zip(col_names, col_refs):
                # loads may be narrower than int32 (int8/int16 segment
                # storage); compute in int32 — eligibility bounded every
                # expression node to int32, narrower products would wrap
                v = r[0, :]
                if v.dtype != jnp.int32 and jnp.issubdtype(v.dtype,
                                                           jnp.integer):
                    v = v.astype(jnp.int32)
                env["cols"][name] = v
            for name, r in zip(null_names, null_refs):
                env["nulls"][name] = r[0, :]
            materialize_virtuals(vexprs, env["cols"], env["nulls"], jnp,
                                 wide_ints=False)
            consts = {n: r[0, :] for n, r in zip(const_names, const_refs)}

            mask = valid_ref[0, :]
            if filter_fn is not None:
                mask = mask & filter_fn(env, consts)

            # mixed-radix dense group key [rb]; the precomputed granularity
            # bucket id is the most-significant digit (radix sizes[0]);
            # gather-needing dim ids arrive precomputed in dim order
            pi = 0
            key = None
            if has_buckets:
                key = pre_refs[pi][0, :]
                pi += 1
            for dp, is_pre, size in zip(dim_plans, pre_dims, sizes[1:]):
                if is_pre:
                    i = pre_refs[pi][0, :]
                    pi += 1
                else:
                    i = dp.ids(env, consts, jnp).astype(jnp.int32)
                key = i if key is None else key * jnp.int32(size) + i
            if key is None:
                key = jnp.zeros((rb,), jnp.int32)

            # transposed masked one-hot [KB, rb] for this K-block — built
            # directly in K-major orientation so every op stays 2-D. Under
            # factorization the row axis indexes k1 = key >> s; garbage
            # keys on masked-out rows shift to negative k1 and never match
            kk = jax.lax.broadcasted_iota(jnp.int32, (KB, rb), 0) + kb * KB
            if fact is not None:
                k1 = jnp.right_shift(key, jnp.int32(fact.shift))
                k2v = jnp.bitwise_and(key, jnp.int32(fact.k2 - 1))
                onehot = ((kk == k1[None, :])
                          & mask[None, :]).astype(jnp.bfloat16)
            else:
                onehot = ((kk == key[None, :])
                          & mask[None, :]).astype(jnp.bfloat16)

            # value planes [H_pad, rb]
            rows = [mask.astype(jnp.bfloat16)[None, :]]
            mm_cols = []
            for p, (name, kind, start, n_planes, bias) in zip(
                    agg_plans, layout.agg_slots):
                m = mask if p.filter_fn is None else \
                    (mask & p.filter_fn(env, consts))
                if kind == "count":
                    rows.append(m.astype(jnp.bfloat16)[None, :])
                    continue
                f = p.fields[0]
                v = env["cols"][f].astype(jnp.int32)
                nm = env["nulls"].get(f)
                if nm is not None:
                    m = m & ~nm
                if kind in ("min", "max"):
                    # non-null count rides the matmul; the value is a
                    # masked VPU min over this K-block (max rides
                    # NEGATED so one minimum-accumulate serves both)
                    rows.append(m.astype(jnp.bfloat16)[None, :])
                    vv = -v if kind == "max" else v
                    sel = (kk == key[None, :]) & m[None, :]
                    mm_cols.append(jnp.min(
                        jnp.where(sel, vv[None, :], jnp.int32(MAX_VALUE)),
                        axis=1))
                    continue
                if bias:
                    v = v - jnp.int32(bias)  # shift into [0, hi-lo]
                # strongly-typed zero: under x64 a Python 0 enters the
                # where as a weak i64 scalar, and Mosaic's scalar i64->i32
                # conversion recurses forever (observed on v5e; the CPU
                # interpret path never lowers through Mosaic and hides it)
                v = jnp.where(m, v, jnp.int32(0))
                for j in range(n_planes):
                    h = (v >> (N_PLANE_BITS * j)) & PLANE_MASK
                    rows.append(h.astype(jnp.bfloat16)[None, :])
                if bias:  # per-agg masked row count for the un-shift
                    rows.append(m.astype(jnp.bfloat16)[None, :])
            if fact is not None:
                # pack k2 groups per lane tile: each [1, rb] agg row h
                # expands through onehot2 into rows [h*k2, (h+1)*k2) —
                # h-major so every concat part is k2 (>= 8) sublanes
                oh2 = (jax.lax.broadcasted_iota(
                    jnp.int32, (fact.k2, rb), 0)
                    == k2v[None, :]).astype(jnp.bfloat16)
                parts = [oh2 * r for r in rows]
                pad = W - fact.k2 * len(rows)
                if pad:
                    parts.append(jnp.zeros((pad, rb), jnp.bfloat16))
                vals = jnp.concatenate(parts, axis=0)
            else:
                pad = H_pad - len(rows)
                if pad:
                    rows.append(jnp.zeros((pad, rb), jnp.bfloat16))
                vals = jnp.concatenate(rows, axis=0)

            partial = jax.lax.dot_general(
                onehot, vals, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32).astype(jnp.int32)

            @pl.when(step % spc == 0)  # first step of this chunk
            def _():
                out_ref[0, :, :] = jnp.zeros((KB, W), jnp.int32)
            out_ref[0, :, :] += partial

            if mm_ref is not None:
                pad = MM_pad - len(mm_cols)
                cols2 = [c[:, None] for c in mm_cols]
                if pad:
                    cols2.append(jnp.full((KB, pad), jnp.int32(MAX_VALUE),
                                          jnp.int32))
                upd = jnp.concatenate(cols2, axis=1)

                @pl.when(step == 0)
                def _():
                    mm_ref[:, :] = jnp.full((KB, MM_pad),
                                            jnp.int32(MAX_VALUE),
                                            jnp.int32)
                mm_ref[:, :] = jnp.minimum(mm_ref[:, :], upd)
        return kernel_fn

    # index maps return strongly-typed int32 zeros: under x64 a literal 0
    # traces as i64, and Mosaic rejects the index-map func.return with
    # 64-bit operands ("failed to legalize func.return", v5e)
    _z = np.int32(0)

    def row_spec():
        return pl.BlockSpec((1, rb), lambda kb, i: (_z, i))

    def const_spec(n):
        return pl.BlockSpec((1, n), lambda kb, i: (_z, _z))

    def fn(env, valid, seg_mask, consts):
        n_segments = valid.shape[0]
        n = n_segments * block_rows
        grid_rows = n // rb
        cset = set(col_names)
        null_names = sorted(c for c in env["nulls"]
                            if c != TIME_COLUMN and c in cset)
        mask = (valid & seg_mask[:, None]).reshape(-1)
        pre_in = []
        if imask_fn is not None or n_pre:
            flat_env = {
                "cols": {c: a.reshape(-1) for c, a in env["cols"].items()},
                "nulls": {c: a.reshape(-1)
                          for c, a in env["nulls"].items()}}
            if imask_fn is not None:
                mask = mask & imask_fn(flat_env, consts)
            if has_buckets:
                b = flat_env["cols"].get(bucket_plan.derived_name) \
                    if bucket_plan.cache_token else None
                # cached uniform streams are TABLE-anchored; rebase to
                # this plan's origin bucket (timebucket.ids_from_cached)
                b = bucket_plan.ids(flat_env["cols"][TIME_COLUMN],
                                    consts) if b is None else \
                    bucket_plan.ids_from_cached(b, consts, jnp)
                pre_in.append(b.astype(jnp.int32).reshape(1, n))
            for dp, is_pre in zip(dim_plans, pre_dims):
                if is_pre:
                    ids = dp.ids(flat_env, consts, jnp)
                    pre_in.append(ids.astype(jnp.int32).reshape(1, n))
        mask2 = mask.reshape(1, n)
        col_in = [_narrow(env["cols"][c].reshape(1, n), jnp)
                  for c in col_names]
        null_in = [env["nulls"][c].reshape(1, n) for c in null_names]
        const_in = [_narrow(jnp.asarray(consts[c]).reshape(1, -1), jnp)
                    for c in const_names]

        n_chunks = -(-grid_rows // spc)
        _spc = np.int32(spc)
        out_specs = pl.BlockSpec((1, KB, W),
                                 lambda kb, i: (i // _spc, kb, _z))
        out_shape = jax.ShapeDtypeStruct((n_chunks, K_pad, W), jnp.int32)
        if n_mm:
            # the min/max VPU buffer accumulates a minimum — no overflow,
            # so it stays unchunked (one block per K-block, all steps)
            out_specs = [out_specs,
                         pl.BlockSpec((KB, MM_pad), lambda kb, i: (kb, _z))]
            out_shape = [out_shape,
                         jax.ShapeDtypeStruct((K_pad, MM_pad), jnp.int32)]
        out = pl.pallas_call(
            make_kernel_fn(null_names),
            grid=(n_kb, grid_rows),
            in_specs=([row_spec() for _ in col_in]
                      + [row_spec() for _ in pre_in]
                      + [row_spec() for _ in null_in]
                      + [row_spec()]
                      + [const_spec(c.shape[1]) for c in const_in]),
            out_specs=out_specs,
            out_shape=out_shape,
            interpret=interpret,
        )(*col_in, *pre_in, *null_in, mask2, *const_in)
        mm = None
        if n_mm:
            out, mm = out
            mm = mm[:K]
        if n_chunks > 1:
            # exact: each chunk entry < 2^31, chunk totals < 2^53 (the
            # MAX_ROWS eligibility bound); f64 keeps the consumer out of
            # the fused int pipeline (see the recombination note below)
            out = out.astype(jnp.float64).sum(axis=0)
        else:
            out = out[0]
        if fact is not None:
            # entry (k1, h*k2 + k2v) -> row k1*k2 + k2v == dense key,
            # column h: plain XLA reshuffle outside the pallas_call
            out = (out[:, :fact.k2 * H]
                   .reshape(K_pad, H, fact.k2)
                   .transpose(0, 2, 1)
                   .reshape(K_pad * fact.k2, H))
        out = out[:K]

        res = {"_rows": out[:, layout.rows_slot].astype(jnp.int64)}
        for p, (name, kind, start, n_planes, bias) in zip(agg_plans,
                                                          layout.agg_slots):
            if kind == "count":
                res[name] = out[:, start].astype(p.acc_dtype)
            elif kind in ("min", "max"):
                v = mm[:, n_planes]  # n_planes doubles as the mm column
                if kind == "max":
                    v = -v
                # empty groups carry the identity; finalize renders them
                # NULL via the non-null count
                res[name] = v.astype(p.acc_dtype)
                res[f"_nn_{name}"] = out[:, start].astype(jnp.int32)
            else:
                # Plane recombination rides f64, NOT int64 shifts: on the
                # v5e sandbox, a jit-fused  custom_call -> convert(i64) ->
                # shift/mul  chain miscompiles (the converted values read
                # as ZERO for a deterministic subset of rows; eager or
                # plain-array runs of the identical expression are
                # correct, and multiplies instead of shifts change
                # nothing). f64 math forces the consumer out of the fused
                # int pipeline and is exact here: each half-sum is below
                # 255*MAX_ROWS*(256+1) < 2^53 (the MAX_ROWS bound).
                half = (n_planes + 1) // 2  # [0, half) and [half, n)
                lo = jnp.zeros((K,), jnp.float64)
                hi = jnp.zeros((K,), jnp.float64)
                for j in range(n_planes):
                    w = float(1 << (N_PLANE_BITS *
                                    (j if j < half else j - half)))
                    v = out[:, start + j].astype(jnp.float64) * w
                    if j < half:
                        lo = lo + v
                    else:
                        hi = hi + v
                acc = lo.astype(jnp.int64) + (
                    hi.astype(jnp.int64) << (N_PLANE_BITS * half))
                if bias:
                    # same split for the bias un-shift: bias*n can exceed
                    # 2^53, so do it in 16-bit halves of |bias|. True sum
                    # = plane sum + n_masked * bias (inputs were shifted
                    # by -bias), so the adjustment adds for bias > 0 and
                    # subtracts for bias < 0.
                    n_masked = out[:, start + n_planes].astype(jnp.float64)
                    b = abs(bias)
                    b_lo, b_hi = b & 0xFFFF, b >> 16
                    adj = (n_masked * float(b_lo)).astype(jnp.int64) + (
                        (n_masked * float(b_hi)).astype(jnp.int64) << 16)
                    acc = acc + adj if bias > 0 else acc - adj
                res[name] = acc.astype(p.acc_dtype)
        return res

    return fn


def _split_refs(refs, n_cols, n_pre, n_nulls, n_consts, n_outs=1):
    """n_pre: host-precomputed int32 id streams — the granularity bucket
    (if any) followed by one stream per gather-needing dimension
    (remap/timeformat), in dimension order. n_outs: trailing output refs
    (the matmul accumulator, plus the min/max buffer when present)."""
    refs = list(refs)
    cols = refs[:n_cols]
    pre = refs[n_cols:n_cols + n_pre]
    nulls = refs[n_cols + n_pre:n_cols + n_pre + n_nulls]
    valid = refs[n_cols + n_pre + n_nulls]
    consts = refs[n_cols + n_pre + n_nulls + 1:
                  n_cols + n_pre + n_nulls + 1 + n_consts]
    outs = refs[-n_outs:]
    return cols, pre, nulls, valid, consts, outs


def _narrow(x, jnp):
    """i64 -> i32 (eligibility guarantees the values fit); bool stays."""
    if x.dtype == jnp.int64:
        return x.astype(jnp.int32)
    if x.dtype == jnp.float64:  # pragma: no cover — eligibility rejects
        return x.astype(jnp.float32)
    return x
