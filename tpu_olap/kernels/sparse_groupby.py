"""Sort-based sparse group-by: high-cardinality GROUP BY on device.

SURVEY.md §8.4 hard part #1: static shapes force a choice of group-table
size. The dense path (kernels.groupby) materializes the full mixed-radix
space [K] and stops at the dense budget; beyond it the reference-shaped
answer would be a hash exchange, but sorting is the TPU-idiomatic move —
XLA's sort is fast on TPU and everything stays static-shaped:

  1. mixed-radix key in int64 (the radix product may exceed int32);
     masked rows get the +inf sentinel so they sort to the tail;
  2. one multi-operand `lax.sort` carries the key and every aggregate
     input along;
  3. group boundaries (key[i] != key[i-1]) -> cumsum -> dense ids in
     [0, n_unique); ids clip to a `cap` slot table (+1 overflow slot that
     also swallows the sentinel tail);
  4. segment reduces into [cap] arrays; slot i holds the i-th smallest
     present group key, so results are already compact AND sorted;
  5. "_count" reports the true unique count — if it exceeds cap the
     runner re-runs with the next power of two (same adaptive-cap pattern
     as executor.packing).

Multi-chip merge (P2, SURVEY.md §3.5): each chip's compacted [cap] table
all-gathers over ICI ([D, cap] is small) and the SAME sort+reduce runs on
the concatenation — partial sums re-sum, mins re-min, HLL registers
re-max, theta tables re-merge.
"""

from __future__ import annotations

import numpy as np

from tpu_olap.kernels import hll as hll_mod
from tpu_olap.kernels import theta as theta_mod
from tpu_olap.kernels.groupby import (UnsupportedAggregation, _hash_fields,
                                      _ident)

SENTINEL = np.int64(np.iinfo(np.int64).max)


def build_group_key64(ids, sizes, xp):
    """Mixed-radix combine into int64. Callers guard product < 2^62."""
    total = 1
    for s in sizes:
        total *= int(s)
    if total >= (1 << 62):
        raise UnsupportedAggregation(
            f"group space {total} overflows the int64 key")
    key = None
    for i, s in zip(ids, sizes):
        i = i.astype(xp.int64)
        key = i if key is None else key * xp.int64(s) + i
    if key is None:
        key = xp.zeros((), xp.int64)
    return key, total


def _sorted_segments(skey, cap, xp):
    """boundary/gid/count core shared by row reduction and table merge:
    gid clips into the dropped overflow+sentinel slot `cap`."""
    boundary = xp.concatenate([
        xp.ones((1,), bool),
        skey[1:] != skey[:-1],
    ])
    gid = xp.cumsum(boundary.astype(xp.int32)) - 1
    count = (boundary & (skey != SENTINEL)).sum(dtype=xp.int32)
    gid = xp.where((gid < cap) & (skey != SENTINEL), gid, cap)
    return gid, count


def _seg_sum(v, gid, cap, xp):
    if xp is np:
        out = np.zeros((cap + 1,) + v.shape[1:], v.dtype)
        np.add.at(out, gid, v)
        return out[:cap]
    import jax
    return jax.ops.segment_sum(v, gid, num_segments=cap + 1)[:cap]


def _seg_ext(v, gid, cap, kind, xp):
    if xp is np:
        out = np.full((cap + 1,) + v.shape[1:], _ident(v.dtype, kind),
                      v.dtype)
        (np.minimum if kind == "min" else np.maximum).at(out, gid, v)
        return out[:cap]
    import jax
    f = jax.ops.segment_min if kind == "min" else jax.ops.segment_max
    return f(v, gid, num_segments=cap + 1)[:cap]


def sparse_group_reduce(key, mask, env, plans, cap, consts, xp):
    """[N] int64 keys + mask -> compacted per-group partials.

    Returns {"_keys": [cap] int64 (SENTINEL marks empty slots),
             "_count": [] int32 true unique count,
             "_rows": [cap], <agg name>: [cap] or [cap, m], ...}.
    """
    import jax

    key = xp.where(mask, key, SENTINEL)

    operands = [key, mask]
    slots = {}

    def carry(name, arr):
        slots[name] = len(operands)
        operands.append(arr)

    for p in plans:
        m = mask if p.filter_fn is None else (mask & p.filter_fn(env, consts))
        if p.filter_fn is not None:
            carry(f"m:{p.name}", m)
        if p.kind == "count":
            continue
        if p.kind in ("sum", "min", "max"):
            x = env["cols"][p.fields[0]]
            nulls = env["nulls"].get(p.fields[0])
            mm = m & ~nulls if nulls is not None else m
            if p.kind == "sum":
                carry(f"v:{p.name}", xp.where(mm, x, 0).astype(p.acc_dtype))
            else:
                ident = _ident(p.acc_dtype, p.kind)
                carry(f"v:{p.name}",
                      xp.where(mm, x.astype(p.acc_dtype), ident))
                if p.filter_fn is not None or nulls is not None:
                    # mm == mask otherwise: the non-null count IS _rows,
                    # so skip both the sort operand and the reduction
                    carry(f"nn:{p.name}", mm)
        elif p.kind in ("hll", "theta"):
            h, valid = _hash_fields(env, p, m, xp, consts)
            carry(f"h:{p.name}", h)
            carry(f"hv:{p.name}", valid)
        else:
            raise UnsupportedAggregation(
                f"sparse group-by does not support {p.kind!r}")

    if xp is np:
        order = np.argsort(operands[0], kind="stable")
        sorted_ops = [o[order] for o in operands]
    else:
        sorted_ops = list(jax.lax.sort(tuple(operands), num_keys=1))

    skey = sorted_ops[0]
    smask = sorted_ops[1]

    gid, count = _sorted_segments(skey, cap, xp)

    def seg_sum(v):
        return _seg_sum(v, gid, cap, xp)

    def seg_ext(v, kind):
        return _seg_ext(v, gid, cap, kind, xp)

    out = {"_count": count, "_rows": seg_sum(smask.astype(np.int32))}
    out["_keys"] = seg_ext(skey, "min")  # all equal per group; SENTINEL fills

    for p in plans:
        m = smask if p.filter_fn is None else sorted_ops[slots[f"m:{p.name}"]]
        if p.kind == "count":
            # unfiltered COUNT(*) is the _rows reduction, already done
            out[p.name] = out["_rows"].astype(p.acc_dtype) \
                if p.filter_fn is None else seg_sum(m.astype(p.acc_dtype))
            continue
        if p.kind == "sum":
            out[p.name] = seg_sum(sorted_ops[slots[f"v:{p.name}"]])
            continue
        if p.kind in ("min", "max"):
            out[p.name] = seg_ext(sorted_ops[slots[f"v:{p.name}"]], p.kind)
            out[f"_nn_{p.name}"] = seg_sum(
                sorted_ops[slots[f"nn:{p.name}"]].astype(np.int32)) \
                if f"nn:{p.name}" in slots else out["_rows"]
            continue
        if p.kind == "hll":
            h = sorted_ops[slots[f"h:{p.name}"]]
            valid = sorted_ops[slots[f"hv:{p.name}"]]
            regs = hll_mod.hll_update(h, valid, xp.where(valid, gid, 0),
                                      cap + 1, xp)
            out[p.name] = regs[:cap]
            continue
        if p.kind == "theta":
            h = sorted_ops[slots[f"h:{p.name}"]]
            valid = sorted_ops[slots[f"hv:{p.name}"]]
            # theta_update routes invalid rows to the num_groups pad row
            # itself; gid==cap (overflow/sentinel) rows land in the pad
            # row and are sliced off
            t = theta_mod.theta_update(h, valid, gid, cap + 1,
                                       p.theta_k, xp)
            out[p.name] = t[:cap]
            continue
    return out


def merge_sparse(parts: list, plans, cap, xp):
    """Merge compacted tables (e.g. the [D, cap] slices of an all_gather):
    concatenate and re-reduce by key. Values are already partial
    aggregates, so the merge semantics differ from row reduction — sums
    and counts re-sum, min/max re-extremize, HLL registers re-max, theta
    re-merges pairwise."""
    import jax

    keys = xp.concatenate([p["_keys"] for p in parts])

    if xp is np:
        order = np.argsort(keys, kind="stable")
    else:
        (_, order) = jax.lax.sort(
            (keys, xp.arange(keys.shape[0], dtype=xp.int32)), num_keys=1)
        order = order.astype(xp.int32)
    skey = keys[order]
    gid, count = _sorted_segments(skey, cap, xp)
    # a chip whose LOCAL table overflowed already dropped groups; the
    # merged distinct count alone cannot see them, so take the max with
    # every per-part count — the runner then retries with a larger cap
    for p in parts:
        if "_count" in p:
            count = xp.maximum(count, p["_count"].astype(xp.int32))

    def gathered(name):
        return xp.concatenate([p[name] for p in parts])[order]

    def seg_sum(v):
        return _seg_sum(v, gid, cap, xp)

    def seg_ext(v, kind):
        return _seg_ext(v, gid, cap, kind, xp)

    out = {"_count": count, "_rows": seg_sum(gathered("_rows"))}
    out["_keys"] = seg_ext(skey, "min")
    for p in plans:
        if p.kind in ("count", "sum"):
            out[p.name] = seg_sum(gathered(p.name))
        elif p.kind in ("min", "max"):
            out[p.name] = seg_ext(gathered(p.name), p.kind)
            out[f"_nn_{p.name}"] = seg_sum(gathered(f"_nn_{p.name}"))
        elif p.kind == "hll":
            out[p.name] = seg_ext(gathered(p.name), "max")
        elif p.kind == "theta":
            out[p.name] = _seg_theta_union(gathered(p.name), gid, cap,
                                           len(parts), xp)
        else:
            raise UnsupportedAggregation(p.kind)
    return out


def _seg_theta_union(rows, gid, cap, n_parts, xp):
    """Segmented theta union: [n, k] row-sorted tables with group ids
    `gid` (sorted; cap = dropped pad slot) -> [cap, k] merged tables of
    the k smallest distinct per group. Each part contributes at most one
    row per key, so within-group rank < n_parts; rows rank-scatter into
    a [cap, n_parts*k] wide buffer which sorts, dedupes, and truncates.
    Transient memory is cap * n_parts * k * 8B — sparse_theta_k_cap
    keeps that modest."""
    import jax

    n, k = rows.shape
    idx = xp.arange(n, dtype=xp.int32)
    boundary = xp.concatenate([xp.ones((1,), bool), gid[1:] != gid[:-1]])
    starts = xp.where(boundary, idx, 0)
    if xp is np:
        seg_start = np.maximum.accumulate(starts)
    else:
        seg_start = jax.lax.cummax(starts)
    rank = xp.minimum(idx - seg_start, n_parts - 1)
    slot = gid.astype(xp.int64) * n_parts + rank
    shape = ((cap + 1) * n_parts, k)
    if xp is np:
        buf = np.full(shape, theta_mod.EMPTY, rows.dtype)
        buf[slot] = rows
    else:
        buf = xp.full(shape, theta_mod.EMPTY, rows.dtype) \
            .at[slot].set(rows, mode="drop")
    wide = buf[:cap * n_parts].reshape(cap, n_parts * k)
    wide = xp.sort(wide, axis=-1)
    dup = xp.concatenate(
        [xp.zeros((cap, 1), bool), wide[:, 1:] == wide[:, :-1]], axis=-1)
    wide = xp.sort(xp.where(dup, theta_mod.EMPTY, wide), axis=-1)
    return wide[:, :k]
