"""Theta (KMV) sketch count-distinct per group, sort-based, static shapes.

The datasketches-extension analog (SURVEY.md §3.3 Theta-sketch aggregator),
re-designed for XLA: per group keep the k smallest *distinct* 32-bit hash
values. Update is a lexsort + within-group rank + scatter (no dynamic
shapes); merge concatenates two [K, k] tables and re-selects k minimums —
both jittable, so merge also rides the ICI collective path.

State: float64 table [K, k] of hash values mapped to [0,1) (1.0 = empty
slot), plus implicit count = #slots < 1.0. Estimate: if the table is not
full, the count is exact; else (k-1)/theta with theta = k-th smallest.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from tpu_olap.kernels.hashing import to_unit_float

EMPTY = 1.0  # sentinel: empty slot (hashes are in [0, 1))


def theta_update(h, valid, key, num_groups, k, xp):
    """h: [N] int32 hashes; -> [K, k] sorted unit-hash table."""
    u = to_unit_float(h, xp)
    u = xp.where(valid, u, EMPTY)
    g = xp.where(valid, key.astype(xp.int32), num_groups)  # invalid -> end
    if xp is np:
        order = np.lexsort((u, g))
    else:
        order = jnp.lexsort((u, g))
    gs, us = g[order], u[order]
    first = xp.ones(gs.shape, bool)
    if gs.shape[0] > 1:
        dup = (gs[1:] == gs[:-1]) & (us[1:] == us[:-1])
        first = xp.concatenate([first[:1], ~dup])
    kept = first & (gs < num_groups) & (us < EMPTY)
    # rank of each kept row within its group
    prefix = xp.cumsum(kept.astype(xp.int32)) - kept.astype(xp.int32)
    start = _seg_min(xp.where(kept, prefix, np.int32(2**31 - 1)), gs,
                     num_groups + 1, xp)
    rank = prefix - start[gs]
    ok = kept & (rank < k)
    from tpu_olap.kernels.hashing import has_x64
    idt = xp.int64 if has_x64(xp) else xp.int32
    flat = xp.where(ok, gs.astype(idt) * idt(k) + rank.astype(idt), 0)
    vals = xp.where(ok, us, EMPTY)
    table = _scatter_min(vals, flat, num_groups * k, xp)
    return table.reshape(num_groups, k)


def theta_merge(a, b, xp):
    """[K, k] + [K, k] -> [K, k]: keep k smallest distinct of the union."""
    k = a.shape[-1]
    both = xp.concatenate([a, b], axis=-1)
    both = xp.sort(both, axis=-1)
    # dedupe equal neighbors (same hash from both sides)
    dup = xp.concatenate(
        [xp.zeros(both.shape[:-1] + (1,), bool), both[..., 1:] == both[..., :-1]],
        axis=-1)
    both = xp.where(dup, EMPTY, both)
    both = xp.sort(both, axis=-1)
    return both[..., :k]


def theta_estimate(table, xp=np, float_dtype=np.float64):
    """[K, k] sorted unit-hash table -> [K] float estimates. Host (xp=np)
    or on-device finalize for the packed-result path (xp=jnp)."""
    ft = np.dtype(float_dtype).type
    t = xp.asarray(table).astype(float_dtype)
    k = t.shape[-1]
    count = (t < EMPTY).sum(axis=-1)
    full = count >= k
    theta = t[..., -1]
    est_full = ft(k - 1) / xp.maximum(theta, ft(1e-30))
    return xp.where(full, est_full, count.astype(float_dtype))


def _seg_min(v, key, n, xp):
    if xp is np:
        out = np.full(n, 2**31 - 1, np.int32)
        np.minimum.at(out, key, v.astype(np.int32))
        return out
    import jax
    return jax.ops.segment_min(v.astype(jnp.int32), key, num_segments=n)


def _scatter_min(v, flat, n, xp):
    if xp is np:
        out = np.full(n, EMPTY, np.float64)
        np.minimum.at(out, flat, v)
        return out
    import jax
    return jnp.minimum(
        jax.ops.segment_min(v, flat, num_segments=n), EMPTY)
