"""Filter compilation: FilterSpec -> device mask function + constant pool.

The analog of Druid's filter evaluation over bitmap indexes (SURVEY.md
§3.7), redesigned for TPU: no bitmaps — predicates become vectorized mask
math over dictionary codes / numeric values. Literals go into a ConstPool
and are passed as device arrays, so the jitted program is reusable across
queries that differ only in literal values (compile-cache, §8.4 #3).

Boolean semantics (not SQL 3VL): any comparison with a NULL operand is
False; NOT inverts the boolean result. The pandas fallback implements the
same rule so the parity harness compares like with like.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from tpu_olap.ir import filters as F
from tpu_olap.ir.dimensions import (CaseExtractionFn, LookupExtractionFn,
                                    RegexExtractionFn,
                                    SubstringExtractionFn,
                                    TimeFormatExtractionFn)
from tpu_olap.kernels.exprs import eval_expr
from tpu_olap.segments.segment import ColumnType, TIME_COLUMN


class ConstPool:
    """Named host constants shipped to the device as a dict pytree.

    `tags` record literal-dependent *structural* choices made while
    compiling closures (e.g. "selector is-null", "IN list contains null",
    "unparseable literal -> match-nothing"). The compile cache must key on
    tags + const layout: two queries with the same stripped template but
    different closure structure would otherwise share a jitted program and
    silently return wrong results.
    """

    def __init__(self):
        self.consts: dict[str, np.ndarray] = {}
        self.tags: list[str] = []
        # (token, source_col, const_name) derived-stream requests from
        # filter compilation (columnComparison code translation): the
        # runner materializes consts[const_name][codes(source_col)] once
        # per content token as a device-resident "\0d:<token>" env column
        self.streams: list[tuple[str, str, str]] = []
        self._n = 0

    def add(self, value, dtype=None) -> str:
        name = f"c{self._n}"
        self._n += 1
        self.consts[name] = np.asarray(value, dtype=dtype)
        return name

    def tag(self, s: str) -> None:
        self.tags.append(s)

    def signature(self) -> tuple:
        """Structure-identifying key fragment: tags + const layout."""
        layout = tuple((k, v.shape, str(v.dtype))
                       for k, v in self.consts.items())
        return (tuple(self.tags), layout)


class UnsupportedFilter(Exception):
    """Raised when a filter can't lower to the device path; the planner
    treats this as 'not rewritable' and falls back (SURVEY.md §2 prop 2)."""


def compile_filter(spec, table, pool: ConstPool, virtual_exprs=None):
    """Compile a FilterSpec to fn(env, consts) -> bool mask.

    env: {"cols": {name: array}, "nulls": {name: bool array}}, where STRING
    columns hold dictionary codes and numeric columns hold values.
    virtual_exprs: name -> Expr for virtual columns referenced by filters.
    """
    virtual_exprs = virtual_exprs or {}

    def col_type(col):
        if col in virtual_exprs:
            return ColumnType.DOUBLE
        if col not in table.schema:
            raise UnsupportedFilter(f"unknown column {col!r}")
        return table.schema[col]

    def numeric_env(env):
        from tpu_olap.kernels.exprs import widen_int_env
        xp = jnp if _is_jax(env) else np
        out = dict(env["cols"])
        for name, ex in virtual_exprs.items():
            out[name] = eval_expr(ex, widen_int_env(ex, out, xp), xp)
        return out

    def lower(s):
        if isinstance(s, F.SelectorFilter):
            return _selector(s, col_type(s.dimension))
        if isinstance(s, F.BoundFilter):
            return _bound(s, col_type(s.dimension))
        if isinstance(s, F.InFilter):
            return _in(s, col_type(s.dimension))
        if isinstance(s, F.RegexFilter):
            return _table_filter(s.dimension, col_type(s.dimension),
                                 lambda d: d.regex_table(s.pattern))
        if isinstance(s, F.LikeFilter):
            return _table_filter(s.dimension, col_type(s.dimension),
                                 lambda d: d.like_table(s.pattern))
        if isinstance(s, F.AndFilter):
            fns = [lower(f) for f in s.fields]
            return lambda env, c: _fold(fns, env, c, True)
        if isinstance(s, F.OrFilter):
            fns = [lower(f) for f in s.fields]
            return lambda env, c: _fold(fns, env, c, False)
        if isinstance(s, F.NotFilter):
            fn = lower(s.field)
            return lambda env, c: ~fn(env, c)
        if isinstance(s, F.ColumnComparisonFilter):
            if len(s.dimensions) < 2:
                raise UnsupportedFilter(
                    "columnComparison needs >= 2 dimensions")
            pairs = [_colcmp_pair(a, b)
                     for a, b in zip(s.dimensions, s.dimensions[1:])]
            return lambda env, c: _fold_direct(pairs, env, c)
        if isinstance(s, F.ExpressionFilter):
            expr = s.expression
            phys = set()
            for col in expr.columns():
                if col_type(col) is ColumnType.STRING:
                    raise UnsupportedFilter(
                        f"expression filter over string column {col!r}")
                phys |= (virtual_exprs[col].columns()
                         if col in virtual_exprs else {col})

            def fn(env, c):
                from tpu_olap.kernels.exprs import widen_int_env
                xp = jnp if _is_jax(env) else np
                ne = numeric_env(env)
                m = eval_expr(expr, widen_int_env(expr, ne, xp), xp) != 0
                # NULL in any referenced input -> no match (boolean, not 3VL)
                for col in phys:
                    m = m & ~_null_mask(env, col)
                return m
            return fn
        raise UnsupportedFilter(f"cannot lower filter {type(s).__name__}")

    # ---- leaf lowerers ---------------------------------------------------

    def _selector(s, typ):
        col = s.dimension
        if s.extraction_fn is not None:
            if typ is not ColumnType.STRING:
                raise UnsupportedFilter(
                    "extractionFn filter on non-string column "
                    f"{col!r} (use intervals/granularity for __time)")
            d = table.dictionaries[col]
            ex = _extraction_callable(s.extraction_fn)
            tbl = d.predicate_table(lambda v: ex(v) == s.value)
            cname = pool.add(tbl)
            return lambda env, c: c[cname][env["cols"][col]]
        if typ is ColumnType.STRING:
            d = table.dictionaries[col]
            cid = pool.add(d.id_of(s.value), np.int32)
            return lambda env, c: env["cols"][col] == c[cid]
        # numeric
        if s.value is None:
            pool.tag(f"sel-null:{col}")
            return lambda env, c: _null_mask(env, col)
        val = _parse_num(s.value, typ)
        if val is None:
            pool.tag(f"sel-never:{col}")
            return _never(col)  # Druid: unparseable literal matches nothing
        cval = pool.add(val)
        return lambda env, c: ((env["cols"][col] == c[cval])
                               & ~_null_mask(env, col))

    def _bound(s, typ):
        col = s.dimension
        if s.extraction_fn is not None:
            if typ is not ColumnType.STRING:
                raise UnsupportedFilter(
                    f"extractionFn bound on non-string column {col!r}")
            if s.ordering == "numeric":
                raise UnsupportedFilter(
                    "extractionFn bound supports lexicographic ordering "
                    "only (extracted values are strings)")
            for b in (s.lower, s.upper):
                if b is not None and not isinstance(b, str):
                    raise UnsupportedFilter(
                        f"extractionFn bound needs string bounds, got "
                        f"{b!r}")
            d = table.dictionaries[col]
            ex = _extraction_callable(s.extraction_fn)

            def in_range(v):
                e = ex(v)
                if e is None:
                    return False
                if s.lower is not None and (
                        e < s.lower or (s.lower_strict and e == s.lower)):
                    return False
                if s.upper is not None and (
                        e > s.upper or (s.upper_strict and e == s.upper)):
                    return False
                return True

            cname = pool.add(d.predicate_table(in_range))
            return lambda env, c: c[cname][env["cols"][col]]
        if s.ordering == "numeric" or typ is not ColumnType.STRING \
                or col == TIME_COLUMN:
            if typ is ColumnType.STRING:
                # numeric ordering over a string dim: parse dict host-side
                d = table.dictionaries[col]
                tbl = d.predicate_table(
                    lambda v: _numeric_in_bound(v, s))
                cname = pool.add(tbl)
                return lambda env, c: c[cname][env["cols"][col]]
            parts = []
            if s.lower is not None:
                lo = _parse_num(s.lower, typ)
                if lo is None:
                    raise UnsupportedFilter(
                        f"non-numeric bound literal {s.lower!r} on {col!r}")
                clo = pool.add(lo)
                if s.lower_strict:
                    parts.append(lambda env, c: env["cols"][col] > c[clo])
                else:
                    parts.append(lambda env, c: env["cols"][col] >= c[clo])
            if s.upper is not None:
                hi = _parse_num(s.upper, typ)
                if hi is None:
                    raise UnsupportedFilter(
                        f"non-numeric bound literal {s.upper!r} on {col!r}")
                chi = pool.add(hi)
                if s.upper_strict:
                    parts.append(lambda env, c: env["cols"][col] < c[chi])
                else:
                    parts.append(lambda env, c: env["cols"][col] <= c[chi])
            return lambda env, c: _fold_direct(parts, env, c) \
                & ~_null_mask(env, col)
        # lexicographic bound over dictionary codes
        d = table.dictionaries[col]
        if not getattr(d, "is_sorted", True):
            # append-extended dictionary (unsorted tail, docs/INGEST.md):
            # code order no longer tracks value order, so the bound
            # lowers as a predicate table instead of a code-range
            # compare — O(|dict|) host work, exact either way
            def _in_bound(v, _s=s):
                if _s.lower is not None and (
                        v < _s.lower
                        or (_s.lower_strict and v == _s.lower)):
                    return False
                if _s.upper is not None and (
                        v > _s.upper
                        or (_s.upper_strict and v == _s.upper)):
                    return False
                return True

            cname = pool.add(d.predicate_table(_in_bound))
            return lambda env, c: c[cname][env["cols"][col]]
        lo, hi = d.bound_code_range(s.lower, s.upper, s.lower_strict,
                                    s.upper_strict)
        clo = pool.add(lo, np.int32)
        chi = pool.add(hi, np.int32)
        return lambda env, c: ((env["cols"][col] >= c[clo])
                               & (env["cols"][col] <= c[chi]))

    def _in(s, typ):
        col = s.dimension
        if s.extraction_fn is not None:
            if typ is not ColumnType.STRING:
                raise UnsupportedFilter(
                    f"extractionFn in filter on non-string column {col!r}")
            d = table.dictionaries[col]
            ex = _extraction_callable(s.extraction_fn)
            vset = set(s.values)
            tbl = d.predicate_table(lambda v: ex(v) in vset)
            # null rows match iff the list carries null (ex(null) is
            # null, mirroring the fallback's `... | isna()` semantics)
            tbl[0] = None in vset
            cname = pool.add(tbl)
            return lambda env, c: c[cname][env["cols"][col]]
        if typ is ColumnType.STRING:
            d = table.dictionaries[col]
            cname = pool.add(d.in_table(s.values))
            return lambda env, c: c[cname][env["cols"][col]]
        parsed = [_parse_num(v, typ) for v in s.values if v is not None]
        parsed = [v for v in parsed if v is not None]
        any_float = any(isinstance(v, np.floating) for v in parsed)
        vals = pool.add(np.asarray(
            parsed, dtype=np.float64 if any_float or typ is ColumnType.DOUBLE
            else np.int64))
        has_null = any(v is None for v in s.values)
        if has_null:
            pool.tag(f"in-null:{col}")

        def fn(env, c):
            x = env["cols"][col]
            m = (x[..., None] == c[vals]).any(axis=-1) & ~_null_mask(env, col)
            if has_null:
                m = m | _null_mask(env, col)
            return m
        return fn

    colcmp_cache: dict = {}

    def _colcmp_pair(a, b):
        """One (a, b) equality leg of a columnComparison filter. NULL
        operands never match (module-docstring boolean rule; NotFilter
        inversion gives the null-matches semantics SQL `<>` needs).
        Memoized per pair: the same comparison in several conjuncts must
        not ship duplicate dictionary-sized consts."""
        hit = colcmp_cache.get((a, b))
        if hit is not None:
            return hit
        ta, tb = col_type(a), col_type(b)
        a_str = ta is ColumnType.STRING
        b_str = tb is ColumnType.STRING
        if a_str != b_str:
            raise UnsupportedFilter(
                f"columnComparison across string and numeric columns "
                f"({a!r}, {b!r})")
        if not a_str:
            # numeric (incl. __time / virtual): elementwise compare;
            # int-vs-float promotes. Virtuals are materialized into the
            # env (with their null masks) before any filter fn runs.
            fn = lambda env, c: ((env["cols"][a] == env["cols"][b])  # noqa: E731
                                 & ~_null_mask(env, a)
                                 & ~_null_mask(env, b))
            colcmp_cache[(a, b)] = fn
            return fn
        # string/string: translate a's codes into b's dictionary space.
        # xmap[0] = -1 (null never matches); values absent from b's
        # dictionary map to -1 (id_of). b's codes are 0 (null) or >= 1,
        # so `xmap[code_a] == code_b` alone is the non-null equality.
        da, db = table.dictionaries[a], table.dictionaries[b]
        xmap = np.full(da.size + 1, -1, np.int32)
        for i, v in enumerate(da.values):
            xmap[i + 1] = db.id_of(v)
        cname = pool.add(xmap, np.int32)
        token = _stream_token("cc", a, b, xmap)
        pool.streams.append((token, a, cname))
        pool.tag(f"cc:{token}")  # closure structure depends on the stream
        dname = "\0d:" + token

        def fn(env, c):
            hit = env["cols"].get(dname)
            ta_ids = hit if hit is not None else c[cname][env["cols"][a]]
            return ta_ids == env["cols"][b]
        colcmp_cache[(a, b)] = fn
        return fn

    def _table_filter(col, typ, make_table):
        if typ is not ColumnType.STRING:
            raise UnsupportedFilter(
                f"regex/like over non-string column {col!r}")
        d = table.dictionaries[col]
        cname = pool.add(make_table(d))
        return lambda env, c: c[cname][env["cols"][col]]

    return lower(spec)


def compile_predicates(specs, table, pool: ConstPool, virtual_exprs=None):
    """Compile SEVERAL FilterSpecs against one shared ConstPool/env:
    every returned mask fn reads the same materialized column env, so N
    queries' predicates cost one scan of the shared inputs plus N
    vectorized mask combines, not N column reads. This is the
    kernel-level standalone spelling of the shared-scan contract — the
    batch executor itself reaches it through PhysicalPlan.key_fn (each
    lowered leg embeds its compiled filter over the shared env); use
    this API to compose predicates over one env by hand. None entries
    (unfiltered legs) pass through as None; raises UnsupportedFilter on
    the first spec that cannot lower."""
    virtual_exprs = virtual_exprs or {}
    return [None if s is None
            else compile_filter(s, table, pool, virtual_exprs)
            for s in specs]


def eval_predicates(fns, env, consts) -> list:
    """Evaluate compiled predicate fns over one shared env: a list of
    bool masks (None for unfiltered legs), all from the same pass."""
    return [None if fn is None else fn(env, consts) for fn in fns]


# ---------------------------------------------------------------------------


def _stream_token(*parts) -> str:
    """Content hash over everything a filter-derived id stream depends on
    (mirrors executor.dimplan._dim_token)."""
    import hashlib
    h = hashlib.sha1()
    for p in parts:
        h.update(p.tobytes() if isinstance(p, np.ndarray)
                 else repr(p).encode())
        h.update(b"\x1f")
    return h.hexdigest()[:16]


def _parse_num(value, typ):
    """Literal -> numeric scalar in the column's natural width, widening to
    float64 for fractional literals on LONG columns (comparison promotes);
    None if the literal isn't numeric at all (Druid: matches nothing)."""
    if typ is ColumnType.DOUBLE:
        try:
            return np.float64(value)
        except (TypeError, ValueError):
            return None
    if isinstance(value, (float, np.floating)):
        return np.int64(value) if float(value).is_integer() \
            else np.float64(value)
    try:
        return np.int64(value)
    except (TypeError, ValueError, OverflowError):
        try:
            return np.float64(value)
        except (TypeError, ValueError):
            return None


def _never(col):
    def fn(env, c):
        x = env["cols"][col]
        xp = np if isinstance(x, np.ndarray) else jnp
        return xp.zeros(x.shape, bool)
    return fn


def _null_mask(env, col):
    m = env["nulls"].get(col)
    if m is None:
        x = env["cols"][col]
        xp = np if isinstance(x, np.ndarray) else jnp
        return xp.zeros(x.shape, bool)
    return m


def _fold(fns, env, c, is_and):
    out = None
    for fn in fns:
        m = fn(env, c)
        out = m if out is None else ((out & m) if is_and else (out | m))
    if out is None:
        raise UnsupportedFilter("empty and/or filter")
    return out


def _fold_direct(parts, env, c):
    out = None
    for fn in parts:
        m = fn(env, c)
        out = m if out is None else (out & m)
    if out is None:
        raise UnsupportedFilter("bound filter with no bounds")
    return out


def _numeric_in_bound(v: str, s) -> bool:
    try:
        x = float(v)
    except (TypeError, ValueError):
        return False
    if s.lower is not None:
        lo = float(s.lower)
        if x < lo or (s.lower_strict and x == lo):
            return False
    if s.upper is not None:
        hi = float(s.upper)
        if x > hi or (s.upper_strict and x == hi):
            return False
    return True


def _extraction_callable(ex):
    """Host-side string->string extraction for predicate-table building."""
    if isinstance(ex, SubstringExtractionFn):
        def f(v):
            end = None if ex.length is None else ex.index + ex.length
            return v[ex.index:end]
        return f
    if isinstance(ex, RegexExtractionFn):
        import re
        rx = re.compile(ex.expr)

        def f(v):
            m = rx.search(v)
            if not m:
                return ex.replace_missing_value
            return m.group(1) if m.groups() else m.group(0)
        return f
    if isinstance(ex, LookupExtractionFn):
        table = dict(ex.lookup)

        def f(v):
            if v in table:
                return table[v]
            return v if ex.retain_missing_value else ex.replace_missing_value
        return f
    if isinstance(ex, CaseExtractionFn):
        return str.upper if ex.mode == "upper" else str.lower
    if isinstance(ex, TimeFormatExtractionFn):
        raise UnsupportedFilter(
            "timeFormat extraction in filters: use intervals instead")
    raise UnsupportedFilter(f"unsupported extractionFn {type(ex).__name__}")


def _is_jax(env):
    x = next(iter(env["cols"].values()))
    return not isinstance(x, np.ndarray)
