"""Dense group-by: mixed-radix key + XLA segmented reduces.

The TPU-first replacement for Druid's per-segment hash aggregation + broker
merge (SURVEY.md §3.5 P2/P3): group keys are dense ids (dictionary codes ×
time buckets), the group table is a static-shape [K] (or [K, m]) array, and
partial tables from different segments/chips merge with add/min/max — i.e.
an allreduce, never a hash exchange, as long as K fits the dense budget
(SURVEY.md §8.4 #1; the planner's cost model guards the budget).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from tpu_olap.ir import aggregations as A
from tpu_olap.kernels import hll as hll_mod
from tpu_olap.kernels import theta as theta_mod
from tpu_olap.kernels.exprs import eval_expr
from tpu_olap.kernels.filtereval import UnsupportedFilter, compile_filter
from tpu_olap.segments.segment import ColumnType


class UnsupportedAggregation(Exception):
    pass


@dataclass
class AggPlan:
    name: str
    kind: str            # sum | min | max | count | hll | theta
    fields: tuple        # input column/virtual names ((), for count)
    acc_dtype: object    # accumulator dtype (sum/min/max/count)
    filter_fn: object = None   # compiled FilterSpec for filtered aggs
    theta_k: int = 0
    is_string_input: tuple = ()  # per-field: True if dict codes
    by_row: bool = True  # multi-field HLL: distinct tuples (True) vs
    #                      union of per-field value sets (Druid byRow=False)
    hash_tables: tuple = ()  # per-field ConstPool name of the value-hash
    #                          table for string fields (None for numeric):
    #                          hashing VALUES (not codes) keeps hashes
    #                          consistent across dictionaries/fields


def compile_aggregations(aggs, table, pool, virtual_exprs=None,
                         long_dtype=np.int64, double_dtype=np.float64,
                         theta_k_cap=1 << 14):
    """AggregationSpec tuple -> list[AggPlan]. Raises Unsupported* for specs
    the device path can't run (planner then falls back)."""
    virtual_exprs = virtual_exprs or {}
    plans = []

    def field_type(f):
        if f in virtual_exprs:
            return ColumnType.DOUBLE
        if f not in table.schema:
            raise UnsupportedAggregation(f"unknown field {f!r}")
        return table.schema[f]

    def acc_dtype_for(spec):
        return long_dtype if spec.value_type == "long" else double_dtype

    def lower(spec, filter_fn=None):
        if isinstance(spec, A.FilteredAggregation):
            if filter_fn is not None:
                raise UnsupportedAggregation("nested filtered aggregator")
            try:
                ffn = compile_filter(spec.filter, table, pool, virtual_exprs)
            except UnsupportedFilter as e:
                raise UnsupportedAggregation(str(e)) from e
            return lower(spec.aggregator, ffn)
        if isinstance(spec, A.CountAggregation):
            return AggPlan(spec.name, "count", (), long_dtype, filter_fn)
        if isinstance(spec, (A.SumAggregation, A.MinAggregation,
                             A.MaxAggregation)):
            if field_type(spec.field_name) is ColumnType.STRING:
                raise UnsupportedAggregation(
                    f"numeric agg over string column {spec.field_name!r}")
            kind = {"SumAggregation": "sum", "MinAggregation": "min",
                    "MaxAggregation": "max"}[type(spec).__name__]
            return AggPlan(spec.name, kind, (spec.field_name,),
                           acc_dtype_for(spec), filter_fn)
        if isinstance(spec, A.CardinalityAggregation):
            fields = tuple(spec.fields)
            return AggPlan(spec.name, "hll", fields, np.int32, filter_fn,
                           is_string_input=tuple(
                               field_type(f) is ColumnType.STRING
                               for f in fields),
                           by_row=spec.by_row,
                           hash_tables=_hash_tables(fields, table, pool,
                                                    field_type))
        if isinstance(spec, A.HyperUniqueAggregation):
            f = (spec.field_name,)
            return AggPlan(spec.name, "hll", f, np.int32, filter_fn,
                           is_string_input=(field_type(spec.field_name)
                                            is ColumnType.STRING,),
                           hash_tables=_hash_tables(f, table, pool,
                                                    field_type))
        if isinstance(spec, A.ThetaSketchAggregation):
            k = min(int(spec.size), theta_k_cap)
            f = (spec.field_name,)
            return AggPlan(spec.name, "theta", f,
                           np.float64, filter_fn, theta_k=k,
                           is_string_input=(field_type(spec.field_name)
                                            is ColumnType.STRING,),
                           hash_tables=_hash_tables(f, table, pool,
                                                    field_type))
        raise UnsupportedAggregation(
            f"cannot lower aggregation {type(spec).__name__}")

    for a in aggs:
        plans.append(lower(a))
    return plans


def _hash_tables(fields, table, pool, field_type):
    """Per-field value-hash const tables for string fields (None slots for
    numeric fields). table[0] (null) is 0 — nulls are masked out anyway.
    The table depends only on the dictionary, so it's memoized there (it's
    an O(cardinality) host loop that must not run per query)."""
    out = []
    for f in fields:
        if field_type(f) is ColumnType.STRING:
            d = table.dictionaries[f]
            t = getattr(d, "_value_hash_table", None)
            if t is None:
                import zlib
                t = np.zeros(d.size + 1, np.int32)
                for i, v in enumerate(d.values):
                    t[i + 1] = np.int32(zlib.crc32(v.encode()) & 0x7FFFFFFF)
                d._value_hash_table = t
            out.append(pool.add(t))
        else:
            out.append(None)
    return tuple(out)


def build_group_key(ids, sizes, xp):
    """Mixed-radix combine of dense id arrays into one int32 key.

    ids: list of arrays in [0, size_i); sizes: list of ints. The product
    must fit in int32 — callers enforce the dense-K budget.
    """
    total = 1
    for s in sizes:
        total *= int(s)
    if total > (1 << 31) - 1:
        raise UnsupportedAggregation(
            f"dense group space {total} overflows int32")
    key = None
    for i, s in zip(ids, sizes):
        i = i.astype(xp.int32)
        key = i if key is None else key * xp.int32(s) + i
    if key is None:
        key = xp.zeros((), xp.int32)
    return key, total


def group_reduce(key, mask, env, plans, num_groups, consts):
    """One segment batch -> per-group partial aggregates.

    key: [N] int32 dense group ids; mask: [N] bool (validity ∧ filter);
    env: {"cols", "nulls"} with numeric/virtual columns materialized.
    Returns dict: "_rows" -> [K] row counts, then one entry per plan —
    [K] arrays for sum/min/max/count, [K, m] registers for hll,
    ([K, k] hashes, [K] counts) for theta. All outputs are mergeable
    across segments/chips (add for sums/counts, min/max elementwise,
    hll max, theta re-merge).
    """
    xp = jnp if not isinstance(mask, np.ndarray) else np
    out = {}
    key = xp.where(mask, key, 0)  # masked rows: contribute zeros to group 0
    out["_rows"] = _seg_sum(mask.astype(np.int32), key, num_groups, xp)

    for p in plans:
        m = mask if p.filter_fn is None else (mask & p.filter_fn(env, consts))
        if p.filter_fn is not None:
            m_key = xp.where(m, key, 0)
        else:
            m_key = key
        if p.kind == "count":
            if p.filter_fn is None:
                # unfiltered COUNT(*) is the _rows scatter, already
                # computed — a [K] cast instead of a second [N]->[K]
                # segment reduction (scatters dominate grouped cost)
                out[p.name] = out["_rows"].astype(p.acc_dtype)
            else:
                out[p.name] = _seg_sum(m.astype(p.acc_dtype), m_key,
                                       num_groups, xp)
            continue
        if p.kind in ("sum", "min", "max"):
            x = _field_value(env, p.fields[0], xp)
            nulls = env["nulls"].get(p.fields[0])
            mm = m & ~nulls if nulls is not None else m
            if p.kind == "sum":
                v = xp.where(mm, x, 0).astype(p.acc_dtype)
                out[p.name] = _seg_sum(v, xp.where(mm, key, 0), num_groups, xp)
            else:
                ident = _ident(p.acc_dtype, p.kind)
                v = xp.where(mm, x.astype(p.acc_dtype), ident)
                out[p.name] = _seg_minmax(v, xp.where(mm, key, 0), num_groups,
                                          p.kind, xp)
            # per-plan non-null counts for null-correct finalize. With no
            # per-agg filter and no null bitmap, mm IS the row mask, so
            # the non-null count IS _rows — reuse it instead of paying a
            # third segment scatter per aggregate.
            if p.filter_fn is None and nulls is None:
                out[f"_nn_{p.name}"] = out["_rows"]
            else:
                out[f"_nn_{p.name}"] = _seg_sum(mm.astype(np.int32),
                                                xp.where(mm, key, 0),
                                                num_groups, xp)
            continue
        if p.kind == "hll":
            if p.by_row or len(p.fields) <= 1:
                h, valid = _hash_fields(env, p, m, xp, consts)
                out[p.name] = hll_mod.hll_update(h, valid,
                                                 xp.where(valid, key, 0),
                                                 num_groups, xp)
            else:
                # Druid byRow=False: distinct over the UNION of each
                # field's values — update once per field, max-merge
                regs = None
                for i, f in enumerate(p.fields):
                    sub = AggPlan(p.name, "hll", (f,), p.acc_dtype,
                                  is_string_input=(p.is_string_input[i],),
                                  hash_tables=(p.hash_tables[i],))
                    h, valid = _hash_fields(env, sub, m, xp, consts)
                    r = hll_mod.hll_update(h, valid,
                                           xp.where(valid, key, 0),
                                           num_groups, xp)
                    regs = r if regs is None else xp.maximum(regs, r)
                out[p.name] = regs
            continue
        if p.kind == "theta":
            h, valid = _hash_fields(env, p, m, xp, consts)
            out[p.name] = theta_mod.theta_update(h, valid, key, num_groups,
                                                 p.theta_k, xp)
            continue
        raise UnsupportedAggregation(p.kind)
    return out


def group_reduce_batch(legs, consts_by_leg) -> list:
    """Multi-plan shared-scan reduce: N query legs over ONE column env.

    legs: list of (key, mask, env, plans, num_groups) — each leg's dense
    group ids and row mask were computed from the same materialized
    segment stream (executor.batch builds them via PhysicalPlan.key_fn),
    so tracing this function into a single jitted program yields ONE
    device pass in which every shared column is read once and fed to all
    N (filter-mask, agg-plan) legs. Returns N independent partials dicts
    (the same shape group_reduce emits), one per leg — mergeable and
    finalizable exactly like single-query partials.
    """
    return [group_reduce(key, mask, env, plans, num_groups, consts)
            for (key, mask, env, plans, num_groups), consts
            in zip(legs, consts_by_leg)]


def partials_radix(plans) -> int:
    """Per-group state width (in scalar slots) of a partial-aggregate
    dict: 1 for _rows, then each agg's unfinalized representation —
    the HLL register file, the theta table, or value + _nn. Shared by
    every state-budget guard over partials (segment-cache bypass, cube
    serve, delta fold) so the widths cannot drift apart."""
    from tpu_olap.kernels.hll import NUM_REGISTERS
    radix = 1  # _rows
    for p in plans:
        if p.kind == "hll":
            radix += NUM_REGISTERS
        elif p.kind == "theta":
            radix += p.theta_k
        else:
            radix += 2  # value + _nn
    return radix


def merge_partials(a: dict, b: dict, plans) -> dict:
    """Merge two partial-aggregate dicts (tree-reduce across segments; the
    same op runs as an ICI collective across chips)."""
    xp = jnp if not isinstance(a["_rows"], np.ndarray) else np
    out = {"_rows": a["_rows"] + b["_rows"]}
    for p in plans:
        if p.kind in ("count", "sum"):
            out[p.name] = a[p.name] + b[p.name]
        elif p.kind == "min":
            out[p.name] = xp.minimum(a[p.name], b[p.name])
        elif p.kind == "max":
            out[p.name] = xp.maximum(a[p.name], b[p.name])
        elif p.kind == "hll":
            out[p.name] = xp.maximum(a[p.name], b[p.name])
        elif p.kind == "theta":
            out[p.name] = theta_mod.theta_merge(a[p.name], b[p.name], xp)
        if f"_nn_{p.name}" in a:
            out[f"_nn_{p.name}"] = a[f"_nn_{p.name}"] + b[f"_nn_{p.name}"]
    return out


# ---------------------------------------------------------------------------


def _field_value(env, field, xp):
    if field in env["cols"]:
        return env["cols"][field]
    raise UnsupportedAggregation(f"field {field!r} not materialized")


def _seg_sum(v, key, k, xp):
    if k == 1:
        # single group (granularity=all, no dims — the BI total shape):
        # a plain sum vectorizes where a 1-slot scatter-add would not
        return v.sum(axis=0).reshape((1,) + v.shape[1:])
    if xp is np:
        out = np.zeros((k,) + v.shape[1:], v.dtype)
        np.add.at(out, key, v)
        return out
    return jax.ops.segment_sum(v, key, num_segments=k)


def _seg_minmax(v, key, k, kind, xp):
    if k == 1:
        # single group: plain reduction, not a 1-slot scatter
        red = v.min if kind == "min" else v.max
        return red(axis=0).reshape((1,) + v.shape[1:])
    if xp is np:
        ident = _ident(v.dtype, kind)
        out = np.full((k,), ident, v.dtype)
        (np.minimum if kind == "min" else np.maximum).at(out, key, v)
        return out
    f = jax.ops.segment_min if kind == "min" else jax.ops.segment_max
    return f(v, key, num_segments=k)


def _ident(dtype, kind):
    dt = np.dtype(dtype)
    if dt.kind == "f":
        return dt.type(np.inf if kind == "min" else -np.inf)
    info = np.iinfo(dt)
    return dt.type(info.max if kind == "min" else info.min)


def _hash_fields(env, p: AggPlan, mask, xp, consts):
    """Rows -> 32-bit hashes of the (possibly multi-)field value; valid
    excludes SQL-null inputs (nulls don't count toward COUNT DISTINCT).
    String fields hash their dictionary VALUES via host-built tables."""
    from tpu_olap.kernels.hashing import hash32_int, hash_combine

    h = None
    valid = mask
    for f, is_code, tbl in zip(p.fields, p.is_string_input, p.hash_tables):
        x = env["cols"][f]
        if is_code:
            valid = valid & (x > 0)  # code 0 = null
            hx = hash32_int(consts[tbl][x], xp)
        else:
            nulls = env["nulls"].get(f)
            if nulls is not None:
                valid = valid & ~nulls
            if x.dtype.kind == "f":
                xi = _float_bits(x, xp)
            elif x.dtype.itemsize == 8:
                # fold all 64 bits before narrowing so values differing
                # only in high bits don't collide structurally
                xi = (x ^ (x >> 32)).astype(xp.int32)
            else:
                xi = x.astype(xp.int32)
            hx = hash32_int(xi, xp)
        h = hx if h is None else hash_combine(h, hx, xp)
    return h, valid


def _float_bits(x, xp):
    x32 = x.astype(xp.float32)
    if xp is np:
        return x32.view(np.int32)
    return jax.lax.bitcast_convert_type(x32, jnp.int32)
