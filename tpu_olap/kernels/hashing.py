"""32-bit integer mixing for sketch hashing (HLL / theta).

All ops stay in int32 so the TPU path never needs 64-bit lanes. The mix is
the standard Murmur3 finalizer, good avalanche for dense dictionary codes.
Both numpy and jax.numpy accept the same code (with explicit uint casts).
"""

from __future__ import annotations

import numpy as np


def _u32(x, xp):
    return x.astype(xp.uint32)


def hash32_int(x, xp):
    """Murmur3 fmix32 over an int32 array -> int32 (well-mixed)."""
    h = _u32(x, xp)
    h = h ^ (h >> 16)
    h = h * xp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * xp.uint32(0xC2B2AE35)
    h = h ^ (h >> 16)
    return h.astype(xp.int32)


def hash_combine(a, b, xp):
    """Order-dependent combine (boost::hash_combine flavored)."""
    ua = _u32(a, xp)
    ub = _u32(b, xp)
    ua = ua ^ (ub + xp.uint32(0x9E3779B9) + (ua << 6) + (ua >> 2))
    return hash32_int(ua.astype(xp.int32), xp)


def to_unit_float(h, xp):
    """int32 hash -> float in [0, 1) (treating bits as uint32)."""
    u = _u32(h, xp).astype(xp.float64 if has_x64(xp) else xp.float32)
    return u / np.float64(2**32)


def has_x64(xp) -> bool:
    """Widest float available for this array module (shared helper)."""
    if xp is np:
        return True
    from jax import config
    return bool(config.jax_enable_x64)
