"""Device kernels — the in-tree replacement for Druid's segment scan/agg
engine (SURVEY.md §3.7: "the actual scan+aggregate hot loop").

Design (TPU-first, SURVEY.md §8.2 step 3):
- Filters lower to vectorized mask math; string predicates become boolean
  lookup tables over the global dictionary evaluated host-side, so
  selector/in/regex/like are all one gather on device (filtereval).
- GROUP BY lowers to a mixed-radix dense group key + XLA segmented reduce
  (groupby) — static group-table size, no hashing on device.
- Time bucketing is integer math for uniform periods and a searchsorted
  over host-computed calendar boundaries otherwise (timebucket).
- Approximate COUNT DISTINCT: HyperLogLog registers via scatter-max (hll)
  and theta/KMV sketches via sort-based per-group k-minimums (theta); both
  merge with elementwise max / re-merge across chips.
- Query literals are passed as device constants (ConstPool) so the compile
  cache hits across literal changes (SURVEY.md §8.4 #3).
"""

from tpu_olap.kernels.filtereval import ConstPool, compile_filter  # noqa: F401
from tpu_olap.kernels.exprs import eval_expr  # noqa: F401
from tpu_olap.kernels.timebucket import BucketPlan, compile_granularity  # noqa: F401
from tpu_olap.kernels.groupby import AggPlan, compile_aggregations, group_reduce  # noqa: F401
from tpu_olap.kernels.hll import (LOG2M, NUM_REGISTERS, hll_estimate,  # noqa: F401
                                  hll_update)
from tpu_olap.kernels.topk import top_k_groups  # noqa: F401
