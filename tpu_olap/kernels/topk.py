"""Top-K group selection (TopN queries).

Unlike Druid's *approximate* per-segment topN + broker re-rank (SURVEY.md
§8.4 #2), the dense group table makes exact top-K cheap: one lax.top_k
over the [K] metric array. Druid-approximate behavior is therefore a
strict-accuracy win, not a compatibility break; the context flag
`useApproximateTopN` exists for parity testing but maps to the same exact
kernel.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from tpu_olap.kernels.hashing import has_x64


def top_k_groups(metric, present, threshold: int, inverted: bool, xp):
    """metric: [K] values; present: [K] bool (group has rows).

    Returns (indices [threshold], valid [threshold]) — group ids of the
    top-`threshold` by metric (bottom if inverted), absent groups last.
    """
    k = min(int(threshold), metric.shape[-1])
    v = metric.astype(xp.float64 if has_x64(xp) else xp.float32)
    v = xp.where(present, -v if inverted else v, -xp.inf)
    if xp is np:
        order = np.argsort(-v, kind="stable")[:k]
        vals = v[order]
    else:
        import jax
        vals, order = jax.lax.top_k(v, k)
    valid = vals > -xp.inf
    return order, valid
