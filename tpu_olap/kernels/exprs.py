"""Row-expression evaluation over column arrays (numpy or jax.numpy).

Backs virtual columns and expression filters (tpu_olap.ir.expr). The same
evaluator serves the device path (jnp) and the CPU fallback (np) so both
paths share semantics by construction.
"""

from __future__ import annotations

from tpu_olap.ir.expr import BinOp, Col, Expr, FuncCall, Lit

_ARITH = {
    "+": lambda a, b: a + b,
    "-": lambda a, b: a - b,
    "*": lambda a, b: a * b,
    "/": lambda a, b: a / b,
    "%": lambda a, b: a % b,
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "&&": lambda a, b: a & b,
    "||": lambda a, b: a | b,
}


def eval_expr(expr: Expr, env: dict, xp, narrow_ints: bool = False):
    """Evaluate an expression AST.

    env maps column name -> array (numeric values; dict codes are NOT
    valid inputs — the planner resolves string columns before lowering).
    xp is the array module (numpy or jax.numpy). narrow_ints=True is the
    Pallas-kernel mode: every node was proven to fit int32 at eligibility
    time, so int literals may be coerced to int32 (required — Mosaic
    cannot lower the weak-i64 scalars x64 would otherwise produce).
    """
    if isinstance(expr, Lit):
        return expr.value
    if isinstance(expr, Col):
        if expr.name not in env:
            raise KeyError(f"unknown column {expr.name!r} in expression")
        return env[expr.name]
    if isinstance(expr, BinOp):
        left = eval_expr(expr.left, env, xp, narrow_ints)
        right = eval_expr(expr.right, env, xp, narrow_ints)
        if expr.op == "/":
            # SQL-style: integer operands still divide as floats
            left = _as_float(left, xp)
        return _ARITH[expr.op](left, right)
    if isinstance(expr, FuncCall):
        args = [eval_expr(a, env, xp, narrow_ints) for a in expr.args]
        return _call(expr.name, args, xp, narrow_ints)
    raise TypeError(f"not an expression: {expr!r}")


def virtual_null_mask(expr: Expr, nulls: dict, xp):
    """SQL null propagation for virtual columns: the result is null where
    ANY referenced input is null. Returns a bool mask or None when no
    referenced column carries nulls."""
    mask = None
    for col in expr.columns():
        m = nulls.get(col)
        if m is not None:
            mask = m if mask is None else (mask | m)
    return mask


def widen_int_env(expr: Expr, cols: dict, xp) -> dict:
    """Copy of `cols` with the expression's narrow-int inputs upcast to
    int64: device columns may be stored int32 (executor.dataset narrow
    storage), and products/sums must not wrap. XLA fuses the widening
    into the consumer, so the HBM read stays narrow. No-op without x64
    (int64 lanes unavailable — matches pre-narrowing behavior)."""
    from tpu_olap.kernels.hashing import has_x64
    if not has_x64(xp):
        return cols
    out = None
    for c in expr.columns():
        v = cols.get(c)
        if v is not None and getattr(v, "dtype", None) is not None and \
                v.dtype.kind in "iu" and v.dtype.itemsize < 8:
            if out is None:
                out = dict(cols)
            out[c] = v.astype(xp.int64)
    return out if out is not None else cols


def materialize_virtuals(vexprs: dict, cols: dict, nulls: dict, xp,
                         wide_ints: bool = True) -> None:
    """Evaluate every virtual column into `cols` AND attach its null mask
    to `nulls` (SQL null propagation). The single shared site for all
    kernels — forgetting the mask half reintroduces a null-leak bug.
    wide_ints=False keeps narrow arithmetic (the Pallas kernel bounds
    every intermediate to int32 at eligibility time)."""
    for name, ex in vexprs.items():
        env = widen_int_env(ex, cols, xp) if wide_ints else cols
        cols[name] = eval_expr(ex, env, xp, narrow_ints=not wide_ints)
        nm = virtual_null_mask(ex, nulls, xp)
        if nm is not None:
            nulls[name] = nm


def _as_float(v, xp):
    from tpu_olap.kernels.hashing import has_x64
    if hasattr(v, "dtype") and v.dtype.kind in "iu":
        return v.astype(xp.float64 if has_x64(xp) else xp.float32)
    return v


def _call(name, args, xp, narrow_ints: bool = False):
    if name == "abs":
        return xp.abs(args[0])
    if name == "floor":
        return xp.floor(args[0])
    if name == "ceil":
        return xp.ceil(args[0])
    if name == "sqrt":
        return xp.sqrt(args[0])
    if name == "log":
        return xp.log(args[0])
    if name == "exp":
        return xp.exp(args[0])
    if name == "pow":
        return xp.power(args[0], args[1])
    if name == "if":
        a1, a2 = args[1], args[2]
        if narrow_ints:
            # Pallas-kernel mode only: Python-int branches would enter
            # xp.where as weak i64 scalars under x64, and Mosaic cannot
            # lower scalar i64->i32 (infinite recursion). Eligibility
            # bounded every node to int32, so the coercion is exact. The
            # wide (XLA/numpy) path keeps i64 literals — downstream
            # arithmetic may legitimately exceed int32 there.
            import numpy as _np
            if type(a1) is int and -2**31 <= a1 < 2**31:
                a1 = _np.int32(a1)
            if type(a2) is int and -2**31 <= a2 < 2**31:
                a2 = _np.int32(a2)
        return xp.where(args[0], a1, a2)
    if name in ("min", "least"):
        return xp.minimum(args[0], args[1])
    if name in ("max", "greatest"):
        return xp.maximum(args[0], args[1])
    if name == "cast_double":
        return _as_float(args[0], xp)
    if name == "cast_long":
        x = args[0]
        if hasattr(x, "dtype") and x.dtype.kind in "iu":
            return x  # already integral
        from tpu_olap.kernels.hashing import has_x64
        it = xp.int64 if has_x64(xp) else xp.int32
        return xp.trunc(x).astype(it)  # SQL casts truncate toward zero
    raise ValueError(f"unknown function {name!r} in expression")
