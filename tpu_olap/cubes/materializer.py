"""Cube materializer + registry — Druid ingest-time rollup, generalized.

`CubeRegistry.create` materializes a `CubeSpec` by running its rollup
GroupBy over the base table ON THE DEVICE (`QueryRunner.compute_partials`
rides the ordinary lowering/dispatch/admission/breaker machinery) and
keeping the result as *unfinalized partials*:

* scalar state (row counts, sums, min/max folds, per-aggregate non-null
  counts) lands in an ordinary time-partitioned segment table registered
  in the catalog as `__cube_<name>` — queryable with plain SQL, visible
  in sys.tables/sys.segments, sized by the normal bytes accounting;
* sketch state (HLL register files, theta hash tables) is kept as
  row-aligned sidecar arrays on the cube entry (`__cube_row` in the
  table is the correlation key), exactly the register/hash layout
  `kernels.groupby.group_reduce` emits — so rewrite-time merges use the
  same algebra the per-segment cache already trusts (sums add, min/max
  fold, HLL max-merges, theta re-merges losslessly).

Every build stamps the base table's ingest generation. A cube whose
base generation moved is STALE: the rewrite pass refuses it at
generation-check time (mirroring the PR 9 result-cache contract — a
stale entry is unservable before any purge runs) and the background
maintainer thread rebuilds it under the same admission/breaker
machinery. `register_table`/`drop_table` cascade through
`on_table_registered`/`on_table_dropped`.
"""

from __future__ import annotations

import json
import threading
import time

import numpy as np

from tpu_olap.cubes.spec import (CUBE_TIME_COL, CubeSpec, CubeSpecError,
                                 agg_signature)
from tpu_olap.resilience.errors import UserError

__all__ = ["CubeData", "CubeEntry", "CubeRegistry", "CubeBuildError"]


class CubeBuildError(RuntimeError):
    """The rollup could not be materialized (shape over budget, base
    table gone mid-build, device refusal). Recorded on the entry."""


class StoredAgg:
    """One materialized aggregation's serve-time identity: signature,
    merge kind, theta width. (The partial VALUES ride next to it in
    CubeData.aggs; the storage table's m<i>/__nn_m<i> columns are the
    durable/queryable copy of the same arrays, not a serve input.)"""

    __slots__ = ("sig", "kind", "theta_k")

    def __init__(self, sig, kind, theta_k=0):
        self.sig = sig
        self.kind = kind          # count | sum | min | max | hll | theta
        self.theta_k = theta_k


class CubeData:
    """Immutable serve-time view of one build: cube rows as flat arrays
    in `__cube_row` order. Swapped atomically on refresh, so a serve
    that grabbed a reference keeps a consistent snapshot."""

    __slots__ = ("times", "ends", "rows", "dims", "aggs", "base_tmax",
                 "n_rows", "sketch_bytes")

    def __init__(self, times, ends, rows, dims, aggs, base_tmax):
        self.times = times          # [N] int64 bucket starts (ms)
        self.ends = ends            # [N] int64 bucket ends (exclusive)
        self.rows = rows            # [N] int64 base rows rolled up
        # {col: ("codes", int32 base-dict codes) |
        #       ("values", ndarray, null mask | None)}
        self.dims = dims
        self.aggs = aggs            # {sig: (StoredAgg, values, nn, sketch)}
        self.base_tmax = base_tmax  # base table max __time at build
        self.n_rows = len(times)
        self.sketch_bytes = sum(
            int(sk.nbytes) for _, _, _, sk in aggs.values()
            if sk is not None)


class CubeEntry:
    """Registry entry: spec + mutable build state."""

    def __init__(self, spec: CubeSpec):
        self.spec = spec
        self.status = "building"    # building | ready | error
        # serializes (re)builds of THIS cube: create(), refresh_now(),
        # and the maintainer tick must never run two device rollups of
        # one cube concurrently (interleaved register_table calls could
        # pair one build's storage table with the other's serve arrays)
        self.build_lock = threading.Lock()
        # generation of the base table the LAST build attempt (success
        # or failure) saw: a deterministically-failing spec is retried
        # only when the base data actually changes, not every tick
        self.attempted_generation: int | None = None
        self.error: str | None = None
        self.base_generation: int | None = None
        self.config_sig: tuple | None = None
        self.data: CubeData | None = None
        self.build_ms = 0.0
        self.build_rows_scanned = 0
        self.last_refresh_ms = 0    # wall-clock ms of last (re)build
        self.refreshes = 0
        self.serves = 0
        self.storage_bytes = 0      # registered segment table bytes

    @property
    def ready(self) -> bool:
        return self.status == "ready" and self.data is not None

    def snapshot_row(self, engine) -> dict:
        base = engine.catalog.maybe(self.spec.datasource)
        # SEALED-scope generation (docs/INGEST.md): delta-only appends
        # do not stale a cube — serves fold the delta remainder through
        # the base path (planner.cuberewrite)
        base_gen = base.segments.sealed_generation \
            if base is not None and base.is_accelerated else None
        data = self.data  # one read: a concurrent failed replace nulls it
        return {
            "name": self.spec.name,
            "base_table": self.spec.datasource,
            "table": self.spec.table_name,
            "dims": ",".join(self.spec.dimensions),
            "granularity": self.spec.granularity,
            "status": self.status,
            "rows": data.n_rows if data is not None else None,
            "base_generation": base_gen,
            "cube_generation": self.base_generation,
            "stale": (base_gen is not None
                      and base_gen != self.base_generation),
            "last_refresh_ms": self.last_refresh_ms,
            "build_ms": round(self.build_ms, 3),
            "refreshes": self.refreshes,
            "serve_count": self.serves,
            "storage_bytes": self.storage_bytes,
            "sketch_bytes": (data.sketch_bytes
                             if data is not None else 0),
            "error": self.error,
        }


class CubeRegistry:
    """All cubes of one engine + the background refresh maintainer."""

    def __init__(self, engine):
        self.engine = engine
        self._cubes: dict[str, CubeEntry] = {}
        self._lock = threading.RLock()
        # the refresh maintainer is a scheduler-managed background
        # stage graph (executor.stages.register_periodic), not a
        # bespoke daemon thread — this is its PeriodicHandle
        self._handle = None
        self._stopped = False
        m = engine.metrics
        self._m_req = m.counter(
            "cube_rewrite_total",
            "Aggregate-rewrite attempts against materialized cubes by "
            "outcome (served / refused / stale / no_cube / error).",
            ("result",))
        self._m_builds = m.counter(
            "cube_builds_total",
            "Cube materializations by outcome.", ("result",))
        self._m_cubes = m.gauge(
            "cubes_registered", "Materialized rollup cubes registered.")

    # ------------------------------------------------------------- admin

    @property
    def active(self) -> bool:
        """Cheap pre-check on the per-query hot path: is there anything
        the rewrite pass could possibly serve from?"""
        return bool(self._cubes) \
            and bool(self.engine.config.cube_rewrite_enabled)

    def names(self):
        with self._lock:
            return sorted(self._cubes)

    def get(self, name: str) -> CubeEntry | None:
        with self._lock:
            return self._cubes.get(name)

    def count_request(self, result: str):
        self._m_req.inc(result=result)

    def note_serve(self, entry: CubeEntry):
        with self._lock:
            entry.serves += 1

    def serveable(self, datasource: str, generation: int) -> list:
        """(entry, CubeData, config_sig) triples for ready, generation-
        current cubes over `datasource`, smallest first — the rewrite
        pass probes them in order and takes the first cover (fewest
        cube rows scanned). The data reference is SNAPSHOT under the
        lock together with the generation check: a concurrent refresh
        swapping `entry.data` mid-serve cannot hand the fold a mix of
        two builds."""
        with self._lock:
            out = [(e, e.data, e.config_sig)
                   for e in self._cubes.values()
                   if e.spec.datasource == datasource and e.ready
                   and e.base_generation == generation]
        out.sort(key=lambda t: (t[1].n_rows, t[0].spec.name))
        return out

    def snapshot(self) -> list[dict]:
        with self._lock:
            entries = list(self._cubes.values())
        return [e.snapshot_row(self.engine)
                for e in sorted(entries, key=lambda e: e.spec.name)]

    # ------------------------------------------------------ create / drop

    def create(self, spec, replace: bool = True) -> CubeEntry:
        """Validate + materialize a cube synchronously. `spec` is a
        CubeSpec or its JSON dict. Build failures mark the entry and
        re-raise so DDL/API callers see the reason; the entry stays
        registered (the maintainer retries it when the base generation
        moves)."""
        if not isinstance(spec, CubeSpec):
            spec = CubeSpec.from_json(spec)
        with self._lock:
            if spec.name in self._cubes and not replace:
                raise UserError(f"cube {spec.name!r} already exists")
            entry = CubeEntry(spec)
            self._cubes[spec.name] = entry
            self._m_cubes.set(len(self._cubes))
        try:
            self._build(entry)
        except Exception:
            with self._lock:
                # a replace that failed must not keep serving the OLD
                # spec's data under the new spec's name
                entry.data = None
            raise
        self._ensure_maintainer()
        return entry

    def drop(self, name: str) -> bool:
        with self._lock:
            entry = self._cubes.pop(name, None)
            self._m_cubes.set(len(self._cubes))
        if entry is None:
            return False
        self._drop_storage(entry.spec.table_name)
        self.engine.runner.events.emit("cube_drop", cube=name)
        return True

    def _drop_storage(self, table_name: str):
        eng = self.engine
        if eng.catalog.maybe(table_name) is not None:
            with eng.device_lock:
                eng.runner.clear_cache(table_name)
            eng.catalog.drop(table_name)

    # ------------------------------------------------- catalog cascades

    def on_table_dropped(self, name: str):
        """DROP cascades: a cube over a dropped base is dropped too."""
        with self._lock:
            victims = [n for n, e in self._cubes.items()
                       if e.spec.datasource == name]
        for n in victims:
            self.drop(n)

    def on_table_registered(self, name: str):
        """Re-ingest cascade: cubes over `name` are now stale (their
        recorded generation no longer matches — the rewrite pass stops
        serving them instantly); wake the maintainer to rebuild.
        _ensure_maintainer honors a cube_auto_refresh flag flipped ON
        after the cubes were created (the thread starts lazily)."""
        with self._lock:
            stale = any(e.spec.datasource == name
                        for e in self._cubes.values())
        if stale:
            self._ensure_maintainer()
            h = self._handle
            if h is not None:
                h.wake()

    # -------------------------------------------------------- maintenance

    def stale_cubes(self) -> list[CubeEntry]:
        eng = self.engine
        out = []
        with self._lock:
            entries = list(self._cubes.values())
        for e in entries:
            if e.status == "building":
                # an in-progress create() is not stale — a maintainer
                # tick racing it would launch a SECOND device rollup of
                # the same cube (the per-entry build_lock still guards
                # the narrower refresh_now-vs-maintainer overlap)
                continue
            base = eng.catalog.maybe(e.spec.datasource)
            if base is None or not base.is_accelerated:
                continue  # base gone: on_table_dropped handles real drops
            # sealed scope: a delta-only append must NOT queue a cube
            # rebuild — only registration/compaction moves this
            gen = base.segments.sealed_generation
            if e.status == "error" and e.attempted_generation == gen:
                # the last attempt at THIS generation already failed;
                # retrying every tick would re-run a device pass to the
                # same refusal forever — wait for the data to change
                continue
            if gen != e.base_generation:
                out.append(e)
        return out

    def refresh_now(self) -> dict:
        """Synchronously rebuild every stale cube. Returns
        {cube: "ok" | error string} — the `REFRESH DRUID CUBES` verb's
        payload and the deterministic hook tests drive instead of
        waiting on the maintainer thread."""
        results: dict = {}
        for e in self.stale_cubes():
            try:
                self._build(e, refresh=True)
                results[e.spec.name] = "ok"
            except Exception as ex:  # noqa: BLE001 — per-cube isolation
                results[e.spec.name] = f"{type(ex).__name__}: {ex}"
        return results

    def _ensure_maintainer(self):
        """Register the `cube-maintain` background graph on the stage
        scheduler (lazily — honors a cube_auto_refresh flag flipped on
        at runtime; re-registers after Engine.close cancelled it)."""
        if not self.engine.config.cube_auto_refresh or self._stopped:
            return
        with self._lock:
            h = self._handle
            if h is not None and not h.cancelled:
                return
            self._handle = self.engine.runner.stages.register_periodic(
                "cube-maintain",
                lambda: self.engine.config.cube_refresh_interval_s,
                self._maintain_pass)

    def stop(self, join: bool = False):
        """Cancel the maintainer graph; `join=True` (Engine.close)
        blocks until an in-progress pass exits so shutdown is
        deterministic instead of leaving work behind."""
        self._stopped = True
        h = self._handle
        if h is not None:
            h.cancel(join_timeout=10.0 if join else None)

    def _maintain_pass(self):
        """One background-graph tick: rebuild stale cubes one at a
        time. Runs on the scheduler's background stage pool every
        cube_refresh_interval_s (or on an ingest wake). Builds go
        through compute_partials, i.e. the same admission slot +
        breaker check as foreground queries — an open breaker or a
        shed just means 'retry next tick', never a dead graph."""
        for e in self.stale_cubes():
            if self._stopped:
                return
            try:
                self._build(e, refresh=True)
            except Exception:  # noqa: BLE001 — retried next tick
                pass

    # --------------------------------------------------------------- build

    def _build(self, entry: CubeEntry, refresh: bool = False):
        with entry.build_lock:
            if refresh:
                # the racer we queued behind may already have rebuilt
                # to the current generation — re-check under the lock
                base = self.engine.catalog.maybe(entry.spec.datasource)
                if base is not None and base.is_accelerated \
                        and entry.status == "ready" \
                        and entry.base_generation \
                        == base.segments.sealed_generation:
                    return
            self._build_locked(entry, refresh)

    def _is_current(self, entry: CubeEntry) -> bool:
        """True while `entry` still owns its name in the registry — a
        DROP or a replacing CREATE displaces it, and a displaced
        entry's in-flight build must not (re)register the storage
        table the displacer just dropped or now owns."""
        with self._lock:
            return self._cubes.get(entry.spec.name) is entry

    def _build_locked(self, entry: CubeEntry, refresh: bool):
        eng = self.engine
        spec = entry.spec
        t0 = time.perf_counter()
        try:
            if not self._is_current(entry):
                return
            base = eng.catalog.maybe(spec.datasource)
            if base is None or not base.is_accelerated:
                raise CubeSpecError(
                    f"cube base table {spec.datasource!r} is not a "
                    "registered accelerated datasource")
            # build over the SEALED scope only (docs/INGEST.md): the
            # cube's partials must never swallow delta rows the
            # compactor will later fold into a new sealed set — serves
            # cover the delta remainder through the base path instead.
            # With no delta this IS the live snapshot (zero cost).
            table = base.segments.sealed_view()  # generation-consistent
            entry.attempted_generation = table.generation
            query = spec.build_query(eng)
            plan, present, compact, metrics = \
                eng.runner.compute_partials(query, table)
            data, frame = _decode_build(plan, query, present,
                                        compact, table)
            if not self._is_current(entry):
                return  # dropped/replaced while the rollup computed
            # the scalar half becomes an ordinary time-partitioned
            # segment table in the catalog (queryable, sys.* visible)
            eng.register_table(spec.table_name, frame,
                               time_column=CUBE_TIME_COL,
                               time_partition="auto")
            cube_tbl = eng.catalog.get(spec.table_name)
            storage = sum(
                int(a.nbytes)
                for s in cube_tbl.segments.segments
                for a in s.columns.values()) + sum(
                int(a.nbytes)
                for s in cube_tbl.segments.segments
                for a in s.null_masks.values())
            from tpu_olap.executor.resultcache import _config_sig
            with self._lock:
                entry.data = data
                entry.base_generation = table.generation
                entry.config_sig = _config_sig(eng.config)
                entry.status = "ready"
                entry.error = None
                entry.build_ms = (time.perf_counter() - t0) * 1000
                entry.build_rows_scanned = int(
                    metrics.get("rows_scanned") or table.num_rows)
                entry.last_refresh_ms = int(time.time() * 1000)
                entry.refreshes += 1 if refresh else 0
                entry.storage_bytes = storage
            if not self._is_current(entry):
                # displaced between register_table and the swap: the
                # storage table we just recreated is orphaned — clean
                # it up (idempotent vs the displacer's own drop)
                self._drop_storage(spec.table_name)
                return
            self._m_builds.inc(result="refresh" if refresh else "ok")
            eng.runner.events.emit(
                "cube_build", cube=spec.name, base=spec.datasource,
                refresh=refresh, rows=data.n_rows,
                base_generation=table.generation,
                rows_scanned=entry.build_rows_scanned,
                build_ms=round(entry.build_ms, 3),
                storage_bytes=storage,
                sketch_bytes=data.sketch_bytes)
        except Exception as e:
            with self._lock:
                entry.status = "error"
                entry.error = f"{type(e).__name__}: {e}"
                entry.build_ms = (time.perf_counter() - t0) * 1000
                entry.last_refresh_ms = int(time.time() * 1000)
            self._m_builds.inc(result="error")
            eng.runner.events.emit(
                "cube_error", cube=spec.name, base=spec.datasource,
                refresh=refresh, error=str(e)[:300])
            raise


# ----------------------------------------------------------- build decode

def _bucket_ends(plan, bucket_ids: np.ndarray, table) -> np.ndarray:
    """Exclusive end timestamp of each present bucket, from the build
    plan's bucket layout (the serve-time interval-containment bound)."""
    bp = plan.bucket_plan
    starts = np.asarray(bp.starts, np.int64)
    if bp.kind == "all":
        return np.full(len(bucket_ids), table.time_boundary[1] + 1,
                       np.int64)
    if bp.kind == "uniform":
        step = int(plan.pool.consts[bp.step_name])
        return starts[bucket_ids] + step
    bs = np.asarray(plan.pool.consts[bp.boundaries_name], np.int64)
    return bs[bucket_ids + 1]


def _decode_build(plan, query, present, compact, table):
    """(plan, present flat ids, compact partials) -> (CubeData, pandas
    frame for the segment table). Present ids decode via the plan's
    mixed-radix layout (bucket first, dims in order) — the same
    arithmetic as QueryRunner._decode_groups."""
    import pandas as pd

    from tpu_olap.executor.dimplan import DimPlan  # noqa: F401 (doc)

    order = np.argsort(present, kind="stable")
    present = np.asarray(present, np.int64)[order]
    compact = {k: np.asarray(v)[order] for k, v in compact.items()}

    sizes = plan.sizes
    rem = present
    radix_vals = []
    for s in sizes[::-1]:
        radix_vals.append(rem % s)
        rem = rem // s
    radix_vals = radix_vals[::-1]
    bucket_ids = radix_vals[0].astype(np.int64)
    starts = np.asarray(plan.bucket_plan.starts, np.int64)
    times = starts[bucket_ids]
    ends = _bucket_ends(plan, bucket_ids, table)

    n = len(present)
    frame_cols: dict = {CUBE_TIME_COL: pd.to_datetime(times, unit="ms")}
    dims: dict = {}
    for dp, ids in zip(plan.dim_plans, radix_vals[1:]):
        ids = ids.astype(np.int64)
        if dp.kind == "codes":
            # plan ids for a string Default dim ARE the base dictionary
            # codes — keep them for exact serve-time remapping
            dims[dp.source_col] = ("codes", ids.astype(np.int32))
            frame_cols[dp.source_col] = dp.labels[ids]
        elif dp.kind == "numeric":
            vals = np.zeros(n, np.int64)
            nz = ids > 0
            if nz.any():
                vals[nz] = np.asarray(
                    [int(v) for v in dp.labels[ids[nz]]], np.int64)
            nulls = ~nz if (~nz).any() else None
            dims[dp.source_col] = ("values", vals, nulls)
            col = dp.labels[ids]  # object: None for the null slot
            frame_cols[dp.source_col] = col
        else:  # pragma: no cover — build dims are Default specs only
            raise CubeBuildError(
                f"cannot materialize dimension plan kind {dp.kind!r}")

    frame_cols["__rows"] = compact["_rows"].astype(np.int64)
    vexprs = {v.name: v.expression for v in query.virtual_columns}
    aggs: dict = {}
    for i, (spec, p) in enumerate(zip(query.aggregations,
                                      plan.agg_plans)):
        sig = agg_signature(spec, vexprs)
        if sig in aggs:
            continue
        col = f"m{i}"
        nn_key = f"_nn_{p.name}"
        nn = compact[nn_key].astype(np.int64) \
            if nn_key in compact else None
        if p.kind in ("count", "sum", "min", "max"):
            vals = compact[p.name]
            frame_cols[col] = vals
            if nn is not None:
                frame_cols[f"__nn_{col}"] = nn
            aggs[sig] = (StoredAgg(sig, p.kind), vals, nn, None)
        elif p.kind == "hll":
            # register files as a row-aligned sidecar (int8: rho <= 32)
            sk = np.ascontiguousarray(compact[p.name]).astype(np.int8)
            aggs[sig] = (StoredAgg(sig, "hll"), None, None, sk)
        elif p.kind == "theta":
            sk = np.ascontiguousarray(compact[p.name], np.float64)
            aggs[sig] = (StoredAgg(sig, "theta", theta_k=p.theta_k),
                         None, None, sk)
        else:  # pragma: no cover
            raise CubeBuildError(f"unmergeable agg kind {p.kind!r}")

    frame_cols["__cend"] = ends
    frame_cols["__cube_row"] = np.arange(n, dtype=np.int64)
    frame = pd.DataFrame(frame_cols)
    data = CubeData(times, ends, compact["_rows"].astype(np.int64),
                    dims, aggs, table.time_boundary[1])
    return data, frame
