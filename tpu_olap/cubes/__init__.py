"""Materialized rollup cubes (ROADMAP item 1; docs/CUBES.md).

The Druid ingest-time-rollup analog, generalized: background-materialize
coarse-grained (dim subset x time granularity) rollups as unfinalized
partial-aggregate tables, and let the planner rewrite covered aggregate
queries onto them (planner.cuberewrite) so repeated dashboard grains
cost a few thousand cube rows instead of a full base-table scan.
"""

from tpu_olap.cubes.advisor import cube_specs_from_workload
from tpu_olap.cubes.materializer import (CubeBuildError, CubeEntry,
                                         CubeRegistry)
from tpu_olap.cubes.spec import (CUBE_TABLE_PREFIX, CUBE_TIME_COL,
                                 CubeSpec, CubeSpecError, agg_signature,
                                 period_contains)

__all__ = [
    "CUBE_TABLE_PREFIX", "CUBE_TIME_COL", "CubeBuildError", "CubeEntry",
    "CubeRegistry", "CubeSpec", "CubeSpecError", "agg_signature",
    "cube_specs_from_workload", "period_contains",
]
