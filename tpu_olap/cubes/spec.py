"""Cube specs — the rollup contract between advisor, DDL, and builder.

A `CubeSpec` names a (datasource, dimension subset, time granularity,
aggregation set) rollup — exactly the cuboid coordinates of the data-cube
materialization literature (PAPERS.md 1709.10072: each cuboid is a
group-by over a dimension subset) restricted to the single cuboid the
workload actually demands (obs.workload.recommend_rollups ranks them).
Specs arrive from three places and normalize identically:

* `CREATE DRUID CUBE` DDL (api.engine) — dims/grain/agg clauses;
* advisor emission (cubes.advisor / tools/workload_report.py
  --emit-cubes) — JSON with IR-shaped aggregations;
* direct API (`Engine.create_cube(dict)`).

Aggregations may be SQL aggregate expressions ("sum(x * y)",
"approx_count_distinct(c)") or Druid-shaped aggregation JSON (with
optional `virtualColumns`). SQL strings ride through the planner's
ordinary aggregate translation (AVG splits into sum+count, COUNT
DISTINCT lowers to HLL), so a cube spec never needs its own aggregate
dialect.

`agg_signature` is the identity under which partial-aggregate columns
are stored and matched at rewrite time: the aggregation JSON minus its
output name, with virtual-column field references replaced by their
rendered expressions (two queries spelling `sum(a*b)` through
differently-named virtual columns must match one stored partial).
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass, field

from tpu_olap.ir.granularity import (AllGranularity, PeriodGranularity,
                                     _SIMPLE)
from tpu_olap.resilience.errors import UserError

__all__ = ["CubeSpec", "CubeSpecError", "agg_signature",
           "period_contains", "spec_period"]

CUBE_TIME_COL = "__ctime"
CUBE_TABLE_PREFIX = "__cube_"


class CubeSpecError(UserError):
    """Malformed or un-materializable cube spec (HTTP 400 shaped)."""


_NAME_RE = re.compile(r"^[A-Za-z_]\w*$")

# ISO period strings the containment ladder understands. Calendar
# periods nest (every month starts on a day boundary, every year on a
# month/quarter boundary); weeks are whole days but do NOT align to
# month/quarter/year starts, so they only contain the sub-day chain.
_CHAIN_RANK = {"PT1S": 0, "PT1M": 1, "PT1H": 2, "P1D": 3,
               "P1M": 4, "P3M": 5, "P1Y": 6}
_WEEK_FINE = {"P1D", "PT1H", "PT1M", "PT1S"}


def spec_period(granularity: str) -> str | None:
    """Spec granularity label -> ISO period (None = 'all'). Accepts the
    simple names ('month', ...) and raw ISO periods ('P1M')."""
    g = (granularity or "all").strip()
    if g.lower() == "all":
        return None
    period = _SIMPLE.get(g.lower(), g)
    if period not in _CHAIN_RANK and period != "P1W":
        raise CubeSpecError(
            f"unsupported cube granularity {granularity!r} (use all, "
            f"{', '.join(sorted(_SIMPLE))}, or an ISO period)")
    return period


def period_contains(coarse: str, fine: str) -> bool:
    """True when every `coarse` bucket is a union of whole `fine`
    buckets under natural calendar alignment (same timezone). This is
    the re-rollup eligibility rule: a query at `coarse` grain can be
    served exactly from partials materialized at `fine` grain."""
    if coarse == fine:
        return True
    if fine == "P1W":
        return False  # weeks don't align to month/quarter/year starts
    if coarse == "P1W":
        return fine in _WEEK_FINE
    rc, rf = _CHAIN_RANK.get(coarse), _CHAIN_RANK.get(fine)
    return rc is not None and rf is not None and rc > rf


@dataclass
class CubeSpec:
    """Normalized rollup-cube specification."""

    name: str
    datasource: str
    dimensions: tuple = ()
    granularity: str = "all"          # "all" | simple name | ISO period
    aggregations: tuple = ()          # SQL strings and/or agg-spec JSON
    virtual_columns: tuple = ()       # vcol JSON for JSON aggregations
    source: str = "api"               # api | ddl | advisor (provenance)
    templates: tuple = ()             # advisor: template ids this serves

    def __post_init__(self):
        if not _NAME_RE.match(self.name or ""):
            raise CubeSpecError(f"invalid cube name {self.name!r}")
        if not self.datasource:
            raise CubeSpecError("cube spec needs a datasource")
        self.dimensions = tuple(dict.fromkeys(self.dimensions))
        self.aggregations = tuple(self.aggregations)
        self.virtual_columns = tuple(self.virtual_columns)
        spec_period(self.granularity)  # validate eagerly

    @property
    def period(self) -> str | None:
        return spec_period(self.granularity)

    @property
    def table_name(self) -> str:
        """Catalog name of the cube's backing segment table."""
        return CUBE_TABLE_PREFIX + self.name

    def to_json(self) -> dict:
        return {"name": self.name, "datasource": self.datasource,
                "dimensions": list(self.dimensions),
                "granularity": self.granularity,
                "aggregations": list(self.aggregations),
                **({"virtualColumns": list(self.virtual_columns)}
                   if self.virtual_columns else {}),
                "source": self.source,
                **({"templates": list(self.templates)}
                   if self.templates else {})}

    @staticmethod
    def from_json(d: dict) -> "CubeSpec":
        if not isinstance(d, dict):
            raise CubeSpecError(f"cube spec must be an object, got "
                                f"{type(d).__name__}")
        unknown = set(d) - {"name", "datasource", "dimensions",
                            "granularity", "aggregations",
                            "virtualColumns", "source", "templates"}
        if unknown:
            raise CubeSpecError(
                f"unknown cube spec keys {sorted(unknown)}")
        try:
            return CubeSpec(
                name=str(d.get("name") or ""),
                datasource=str(d.get("datasource") or ""),
                dimensions=tuple(d.get("dimensions") or ()),
                granularity=str(d.get("granularity") or "all"),
                aggregations=tuple(d.get("aggregations") or ()),
                virtual_columns=tuple(d.get("virtualColumns") or ()),
                source=str(d.get("source") or "api"),
                templates=tuple(d.get("templates") or ()))
        except TypeError as e:
            raise CubeSpecError(f"malformed cube spec: {e}") from e

    # ------------------------------------------------------- build query

    def build_query(self, engine):
        """The rollup's materialization query: a GroupByQuerySpec over
        the WHOLE base table (no filter, eternity intervals) grouping by
        the cube dims (+ the grain's time buckets) with the spec's
        aggregations. SQL aggregate strings translate through the
        planner so AVG/COUNT DISTINCT/filtered forms lower exactly like
        user queries; JSON aggregations deserialize directly."""
        from tpu_olap.ir.aggregations import aggregation_from_json
        from tpu_olap.ir.dimensions import (DefaultDimensionSpec,
                                            VirtualColumn)
        from tpu_olap.ir.query import GroupByQuerySpec
        from tpu_olap.segments.segment import TIME_COLUMN

        entry = engine.catalog.maybe(self.datasource)
        if entry is None or not entry.is_accelerated:
            raise CubeSpecError(
                f"cube base table {self.datasource!r} is not a "
                "registered accelerated datasource")
        table = entry.segments
        for dcol in self.dimensions:
            if dcol == TIME_COLUMN or dcol == entry.time_column:
                raise CubeSpecError(
                    f"dimension {dcol!r} is the time column — model it "
                    "with the GRANULARITY clause instead")
            if dcol not in table.schema:
                raise CubeSpecError(
                    f"unknown cube dimension {dcol!r} on "
                    f"{self.datasource!r}")
        if not self.aggregations:
            raise CubeSpecError("cube spec needs at least one "
                                "aggregation")

        aggs: list = []
        vcols = [VirtualColumn.from_json(v)
                 for v in self.virtual_columns]
        sql_aggs = [a for a in self.aggregations if isinstance(a, str)]
        for a in self.aggregations:
            if not isinstance(a, str):
                aggs.append(aggregation_from_json(a))
        if sql_aggs:
            sql = (f"SELECT {', '.join(sql_aggs)} "
                   f"FROM {self.datasource}")
            plan = engine.planner.plan(sql)
            if not plan.rewritten:
                raise CubeSpecError(
                    f"cube aggregation list is not device-rewritable: "
                    f"{plan.fallback_reason}")
            # the rewriter's post-aggs (AVG quotients, sketch
            # estimates) belong to SERVING queries; the cube stores
            # only the mergeable aggregation state
            aggs.extend(plan.query.aggregations)
            vcols.extend(plan.query.virtual_columns)

        # dedupe by signature (two spellings of one partial store once)
        vexprs = {v.name: v.expression for v in vcols}
        seen, uniq = set(), []
        for a in aggs:
            sig = agg_signature(a, vexprs)
            if sig not in seen:
                seen.add(sig)
                uniq.append(a)

        period = self.period
        gran = AllGranularity() if period is None else \
            PeriodGranularity(period, engine.config.time_zone)
        return GroupByQuerySpec(
            data_source=self.datasource,
            intervals=(),
            dimensions=tuple(DefaultDimensionSpec(d, d)
                             for d in self.dimensions),
            granularity=gran,
            aggregations=tuple(uniq),
            virtual_columns=tuple(vcols))


# ----------------------------------------------------------- signatures

def _sig_json(j: dict, vexprs: dict) -> dict:
    """Aggregation JSON -> canonical identity: output name dropped,
    virtual-column field references replaced by rendered expressions.
    Filtered aggregations keep their filter verbatim (filter literals
    change the partials, so they MUST fragment the identity) plus the
    rendered expressions of any virtual columns the filter reads."""
    from tpu_olap.planner.exprutil import render
    out = {k: v for k, v in j.items() if k != "name"}
    if out.get("type") == "filtered":
        out["aggregator"] = _sig_json(dict(out["aggregator"]), vexprs)
        cols = _filter_json_columns(out.get("filter"))
        vrefs = sorted(c for c in cols if c in vexprs)
        if vrefs:
            out["filterVirtuals"] = {c: render(vexprs[c]) for c in vrefs}
        return out
    f = out.get("fieldName")
    if f in vexprs:
        out["fieldName"] = "expr:" + render(vexprs[f])
    fs = out.get("fieldNames")
    if fs:
        out["fieldNames"] = ["expr:" + render(vexprs[c])
                             if c in vexprs else c for c in fs]
    return out


def _filter_json_columns(node) -> set:
    cols: set = set()
    if isinstance(node, dict):
        d = node.get("dimension")
        if isinstance(d, str):
            cols.add(d)
        for v in node.values():
            cols |= _filter_json_columns(v)
    elif isinstance(node, (list, tuple)):
        for v in node:
            cols |= _filter_json_columns(v)
    return cols


def agg_signature(spec, vexprs: dict | None = None) -> str:
    """Stable identity of an aggregation's PARTIAL STATE: equal
    signatures merge from one stored cube column; differing ones never
    alias. `vexprs` maps virtual-column names to expressions for the
    query/spec the aggregation came from."""
    return json.dumps(_sig_json(spec.to_json(), vexprs or {}),
                      sort_keys=True, default=str)
