"""Advisor -> materializer bridge: workload profile to cube specs.

`recommend_rollups` (obs.workload, PR 11) ranks (datasource, dim-set,
finest-grain) groups by wall spent — the DEMAND signal. This module
turns each ranked group into a `CubeSpec` the materializer accepts
verbatim, by mining the group's member templates (the profiler keeps
the literal-masked query-IR template) for everything a covering cube
needs that the demand key alone doesn't say:

* **filter dimensions** — a cube can only serve filters over its own
  dims, so the dims of a spec are the union of the group's GROUPING
  dims and every column its templates FILTER on (the masked literals
  don't matter: the dim column must be present whatever the literal);
* **aggregations + virtual columns** — kept verbatim from the template
  IR (only WHERE/HAVING literals are masked there), renamed per
  template so same-named virtual columns with different expressions
  never collide; deduped by `agg_signature`;
* **grain** — the group's finest requested grain; groups at grain
  'all' floor to 'year' so calendar-interval dashboards (year(t)=Y
  windows over an all-grain template) stay servable.

Specs whose dense group-space estimate exceeds the engine budgets split
into per-template specs; anything still over budget is skipped with a
recorded reason (the emit never silently drops demand).
"""

from __future__ import annotations

import hashlib
import json
import re

from tpu_olap.cubes.spec import CubeSpec, CubeSpecError, spec_period
from tpu_olap.obs.workload import recommend_rollups
from tpu_olap.segments.segment import ColumnType, TIME_COLUMN
from tpu_olap.utils import timeutil

__all__ = ["cube_specs_from_workload"]

_IDENT_RE = re.compile(r"[A-Za-z_]\w*")

# coarse per-bucket millis for group-count estimation (calendar periods
# estimated, not exact — this sizes a budget check, not a result)
_PERIOD_EST_MS = {"P1M": 2_629_800_000, "P3M": 7_889_400_000,
                  "P1Y": 31_557_600_000}


def _grain_label(g: str) -> str:
    """Group grain -> spec granularity; 'all' floors to 'year' (an
    all-grain cube can only serve whole-table intervals; a year-grain
    one also serves the year(t)=Y dashboard windows)."""
    g = (g or "all").lower()
    return "year" if g == "all" else g


def _filter_columns(node, schema) -> set:
    """Dimension columns a (masked) filter JSON tree touches. Expression
    filters carry a masked rendered string — identifiers intersected
    with the schema are the best-effort column set."""
    cols: set = set()
    if isinstance(node, dict):
        d = node.get("dimension")
        if isinstance(d, str):
            cols.add(d)
        ds = node.get("dimensions")
        if isinstance(ds, (list, tuple)):
            cols.update(x for x in ds if isinstance(x, str))
        ex = node.get("expression")
        if isinstance(ex, str):
            cols.update(t for t in _IDENT_RE.findall(ex) if t in schema)
        for v in node.values():
            cols |= _filter_columns(v, schema)
    elif isinstance(node, (list, tuple)):
        for v in node:
            cols |= _filter_columns(v, schema)
    return cols


def _rename_template_refs(aggs, vcols, tag):
    """Per-template rename of virtual columns (+ references from aggs
    and filtered-agg filters) so unioned templates can't alias each
    other's v0/v1 names."""
    names = {v.get("name") for v in vcols}

    def fix_filter(node):
        if isinstance(node, dict):
            d = node.get("dimension")
            if isinstance(d, str) and d in names:
                node["dimension"] = f"{tag}_{d}"
            for v in node.values():
                fix_filter(v)
        elif isinstance(node, list):
            for v in node:
                fix_filter(v)

    def fix_agg(a):
        f = a.get("fieldName")
        if f in names:
            a["fieldName"] = f"{tag}_{f}"
        fs = a.get("fieldNames")
        if fs:
            a["fieldNames"] = [f"{tag}_{x}" if x in names else x
                               for x in fs]
        if a.get("type") == "filtered":
            fix_filter(a.get("filter"))
            fix_agg(a["aggregator"])

    out_v = []
    for v in vcols:
        v = json.loads(json.dumps(v))
        v["name"] = f"{tag}_{v['name']}"
        out_v.append(v)
    out_a = []
    for a in aggs:
        a = json.loads(json.dumps(a))
        fix_agg(a)
        out_a.append(a)
    return out_a, out_v


def _template_parts(template: str, table):
    """One template -> (dims, filter dims, agg JSON, vcol JSON) or
    (None, reason) when its queries cannot be cube-served anyway."""
    if not template or not template.startswith("ir:"):
        return None, "fallback-path template (no query IR)"
    q = json.loads(template[3:])
    schema = table.schema
    dims: list = []
    specs = list(q.get("dimensions") or ())
    if q.get("dimension") is not None:
        specs.append(q["dimension"])
    for d in specs:
        if not isinstance(d, dict):
            d = {"dimension": str(d)}
        col = d.get("dimension")
        fn = d.get("extractionFn")
        if col == TIME_COLUMN and isinstance(fn, dict):
            continue  # time-derived dim: the grain covers it
        if col not in schema:
            return None, f"dimension {col!r} is not a base column"
        if schema[col] is ColumnType.DOUBLE:
            return None, f"dimension {col!r} is DOUBLE (not rollable)"
        dims.append(col)
    fcols = _filter_columns(q.get("filter"), schema)
    fcols.discard(TIME_COLUMN)
    for c in fcols:
        if c not in schema or schema[c] is ColumnType.DOUBLE:
            return None, f"filter column {c!r} is not a rollable dim"
    aggs = list(q.get("aggregations") or ())
    if not aggs:
        return None, "no aggregations"
    vcols = list(q.get("virtualColumns") or ())
    return (dims, sorted(fcols), aggs, vcols), None


def _dim_cardinality(table, col) -> int | None:
    typ = table.schema.get(col)
    if typ is ColumnType.STRING:
        d = table.dictionaries.get(col)
        return (d.size + 1) if d is not None else None
    if typ is ColumnType.LONG:
        md = table.column_metadata([col]).get(col, {})
        lo, hi = md.get("min"), md.get("max")
        if lo is None:
            return 1
        return int(hi) - int(lo) + 2
    return None


def _estimate_groups(table, dims, granularity) -> int:
    """FD-aware group-space estimate: a dim functionally determined by
    the OTHER dims (declared star FDs — c_city -> c_nation, p_brand1 ->
    p_category, ...) contributes no combinatorial factor, so a cube
    that carries both the filter column and its determinant isn't
    over-counted into a budget refusal."""
    star = getattr(table, "star", None)
    free = list(dims)
    if star is not None and len(dims) > 1:
        # greedy: keep a dim only when the dims kept so far don't
        # already determine it (cycle-safe — the first member of a
        # mutual pair is always kept)
        free = []
        for c in dims:
            if c not in star.fd_closure(set(free)):
                free.append(c)
    total = 1
    for c in free:
        card = _dim_cardinality(table, c)
        if card is None:
            return 1 << 62
        total *= max(1, card)
        if total > (1 << 62):
            return 1 << 62
    period = spec_period(granularity)
    if period is not None:
        t0, t1 = table.time_boundary
        try:
            ms = timeutil.period_millis(period)
        except ValueError:
            ms = _PERIOD_EST_MS.get(period, _PERIOD_EST_MS["P1M"])
        total *= max(1, int((t1 - t0) // ms) + 1)
    return total


# sparse builds discover the TRUE present-group count at runtime and
# refuse legibly past sparse_group_budget; the advisor's estimate only
# bounds what is worth ATTEMPTING. Estimates up to this factor past the
# budget still try (FD-correlated dim sets routinely present far fewer
# groups than any product bound), at the cost of one refused device
# pass when the estimate was right after all.
_SPARSE_TRY_FACTOR = 4


def _spec_fits(table, dims, granularity, config) -> str | None:
    """None when the rollup's group space is worth materializing under
    the engine's build budgets (dense, or sparse within the attempt
    band), else why."""
    est = _estimate_groups(table, dims, granularity)
    if est <= config.dense_group_budget:
        return None
    present = min(est, table.num_rows)
    if present <= config.sparse_group_budget * _SPARSE_TRY_FACTOR:
        return None
    return (f"~{est} dense groups (~{present} present) exceed the "
            f"dense/sparse build budgets")


def cube_specs_from_workload(rows, engine, top: int = 8):
    """Workload-profile rows (WorkloadProfiler.snapshot) -> ranked cube
    specs + per-group notes. Returns (specs: [CubeSpec], notes: [str]).
    The specs are exactly what `Engine.create_cube` /
    `CREATE DRUID CUBES FROM '<file>'` accept."""
    by_tid = {r["template_id"]: r for r in rows}
    specs: list[CubeSpec] = []
    notes: list[str] = []
    seen_names: set = set()
    for rec in recommend_rollups(rows, top=top):
        ds = rec["datasource"]
        entry = engine.catalog.maybe(ds)
        if entry is None or not entry.is_accelerated:
            notes.append(f"{ds}: not an accelerated datasource")
            continue
        table = entry.segments
        grain = _grain_label(rec.get("granularity"))
        try:
            spec_period(grain)
        except CubeSpecError:
            notes.append(f"{ds}@{rec.get('granularity')}: "
                         "unsupported grain")
            continue
        parts, t_notes = [], []
        for tid in rec.get("templates") or ():
            row = by_tid.get(tid)
            got, why = _template_parts(
                (row or {}).get("template"), table)
            if got is None:
                t_notes.append(f"{tid}: {why}")
                continue
            parts.append((tid, got))
        notes.extend(f"{ds}: skipped template {n}" for n in t_notes)
        if not parts:
            continue

        def build(name_seed, members):
            dims: list = []
            aggs: list = []
            vcols: list = []
            tids: list = []
            for ti, (tid, (tdims, tfcols, taggs, tvcols)) \
                    in enumerate(members):
                for c in list(tdims) + list(tfcols):
                    if c not in dims:
                        dims.append(c)
                ra, rv = _rename_template_refs(taggs, tvcols, f"t{ti}")
                aggs.extend(ra)
                vcols.extend(rv)
                tids.append(tid)
            name = "cube_" + re.sub(r"\W+", "_", ds) + "_" + \
                hashlib.sha1(name_seed.encode()).hexdigest()[:8]
            return CubeSpec(
                name=name, datasource=ds, dimensions=tuple(dims),
                granularity=grain, aggregations=tuple(aggs),
                virtual_columns=tuple(vcols), source="advisor",
                templates=tuple(tids))

        union = build("|".join(t for t, _ in parts), parts)
        fit = _spec_fits(table, union.dimensions, grain, engine.config)
        candidates = [union] if fit is None else []
        if fit is not None:
            notes.append(f"{union.name}: split per-template ({fit})")
            for tid, got in parts:
                one = build(tid, [(tid, got)])
                f1 = _spec_fits(table, one.dimensions, grain,
                                engine.config)
                if f1 is None:
                    candidates.append(one)
                else:
                    notes.append(f"{ds}/{tid}: skipped ({f1})")
        for c in candidates:
            if c.name not in seen_names:
                seen_names.add(c.name)
                specs.append(c)
    return specs, notes
