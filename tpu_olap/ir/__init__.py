"""Query IR — the Druid-query-DSL-shaped contract between planner and engine.

This is the analog of the reference's `org.sparklinedata.druid` spec-class
family (SURVEY.md §3.3): pure frozen dataclasses with a Druid-compatible JSON
round-trip, used (a) by the planner as its compilation target, (b) by the
executor as its input language, and (c) by parity tests to compare against
real-Druid semantics.
"""

from tpu_olap.ir.serde import to_json, query_from_json, from_json  # noqa: F401
from tpu_olap.ir.expr import Expr, Col, Lit, BinOp, FuncCall, parse_expr  # noqa: F401
from tpu_olap.ir.granularity import (  # noqa: F401
    Granularity, AllGranularity, NoneGranularity, PeriodGranularity,
    DurationGranularity, granularity_from_json,
)
from tpu_olap.ir.interval import Interval  # noqa: F401
from tpu_olap.ir.filters import (  # noqa: F401
    FilterSpec, SelectorFilter, InFilter, BoundFilter, RegexFilter,
    LikeFilter, AndFilter, OrFilter, NotFilter, ExpressionFilter,
    filter_from_json,
)
from tpu_olap.ir.dimensions import (  # noqa: F401
    DimensionSpec, DefaultDimensionSpec, ExtractionDimensionSpec,
    ExtractionFunctionSpec, TimeFormatExtractionFn, RegexExtractionFn,
    SubstringExtractionFn, LookupExtractionFn, CaseExtractionFn,
    VirtualColumn,
)
from tpu_olap.ir.aggregations import (  # noqa: F401
    AggregationSpec, CountAggregation, SumAggregation, MinAggregation,
    MaxAggregation, CardinalityAggregation, HyperUniqueAggregation,
    ThetaSketchAggregation, FilteredAggregation, aggregation_from_json,
)
from tpu_olap.ir.postaggs import (  # noqa: F401
    PostAggregationSpec, ArithmeticPostAgg, FieldAccessPostAgg,
    ConstantPostAgg, HyperUniqueCardinalityPostAgg, ThetaSketchEstimatePostAgg,
)
from tpu_olap.ir.having import (  # noqa: F401
    HavingSpec, GreaterThanHaving, LessThanHaving, EqualToHaving,
    AndHaving, OrHaving, NotHaving, DimSelectorHaving,
)
from tpu_olap.ir.limit import LimitSpec, OrderByColumnSpec  # noqa: F401
from tpu_olap.ir.query import (  # noqa: F401
    QuerySpec, TimeseriesQuerySpec, GroupByQuerySpec, TopNQuerySpec,
    ScanQuerySpec, SelectQuerySpec, SearchQuerySpec, SearchQueryContains,
    SegmentMetadataQuerySpec, TimeBoundaryQuerySpec,
)
