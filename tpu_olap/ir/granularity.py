"""Granularity specs: all | none | period (tz-aware) | duration.

Mirrors the reference's granularity model (SURVEY.md §3.3 "Granularity"),
which drives time bucketing for Timeseries/GroupBy. Simple string forms
("all", "hour", "day", ...) are accepted in JSON like Druid does.
"""

from __future__ import annotations

from dataclasses import dataclass

from tpu_olap.ir.serde import register
from tpu_olap.utils import timeutil

_SIMPLE = {
    "second": "PT1S", "minute": "PT1M", "fifteen_minute": "PT15M",
    "thirty_minute": "PT30M", "hour": "PT1H", "six_hour": "PT6H",
    "day": "P1D", "week": "P1W", "month": "P1M", "quarter": "P3M",
    "year": "P1Y",
}


class Granularity:
    pass


@register("granularity", "all")
@dataclass(frozen=True)
class AllGranularity(Granularity):
    def to_json(self):
        return {"type": "all"}

    @staticmethod
    def from_json(d):
        return AllGranularity()


@register("granularity", "none")
@dataclass(frozen=True)
class NoneGranularity(Granularity):
    """Bucket per distinct timestamp (Druid 'none' ~ millisecond buckets)."""

    def to_json(self):
        return {"type": "none"}

    @staticmethod
    def from_json(d):
        return NoneGranularity()


@register("granularity", "period")
@dataclass(frozen=True)
class PeriodGranularity(Granularity):
    period: str  # ISO-8601: PT1H, P1D, P1M, ...
    time_zone: str = "UTC"
    origin: int | None = None  # epoch millis; None = natural calendar origin

    def is_uniform(self) -> bool:
        """Fixed-duration bucketing valid: no calendar months/years, and
        day/week only in UTC (sub-day is DST-safe in any tz)."""
        return timeutil.period_is_uniform(self.period) and (
            self.time_zone == "UTC"
            or timeutil.period_is_subday(self.period))

    def to_json(self):
        d = {"type": "period", "period": self.period, "timeZone": self.time_zone}
        if self.origin is not None:
            d["origin"] = timeutil.millis_to_iso(self.origin)
        return d

    @staticmethod
    def from_json(d):
        origin = d.get("origin")
        if isinstance(origin, str):
            origin = timeutil.parse_iso_datetime(origin)
        return PeriodGranularity(d["period"], d.get("timeZone", "UTC"), origin)


@register("granularity", "duration")
@dataclass(frozen=True)
class DurationGranularity(Granularity):
    duration: int  # millis
    origin: int = 0

    def to_json(self):
        return {"type": "duration", "duration": self.duration, "origin": self.origin}

    @staticmethod
    def from_json(d):
        return DurationGranularity(int(d["duration"]), int(d.get("origin", 0)))


def granularity_from_json(d) -> Granularity:
    from tpu_olap.ir.serde import from_json
    if d is None:
        return AllGranularity()
    if isinstance(d, str):
        s = d.lower()
        if s == "all":
            return AllGranularity()
        if s == "none":
            return NoneGranularity()
        if s in _SIMPLE:
            return PeriodGranularity(_SIMPLE[s])
        raise ValueError(f"unknown simple granularity {d!r}")
    return from_json("granularity", d)
