"""Dimension specs, extraction functions, and virtual columns.

Mirrors the reference's DefaultDimensionSpec / ExtractionDimensionSpec with
TimeFormat/regex/lookup extraction fns (SURVEY.md §3.3 "Dimensions"); the
javascript extraction fn is dropped in favor of expression virtual columns.
"""

from __future__ import annotations

from dataclasses import dataclass

from tpu_olap.ir.expr import Expr
from tpu_olap.ir.serde import register, from_json


class ExtractionFunctionSpec:
    pass


@register("extractionFn", "timeFormat")
@dataclass(frozen=True)
class TimeFormatExtractionFn(ExtractionFunctionSpec):
    """strftime-style formatting of the time dimension, tz-aware.

    The reference emits joda format strings from Spark date functions
    (SparkNativeTimeElementExtractor, SURVEY.md §3.2); we use strftime
    patterns, plus shorthands: "YYYY" (year), "MM" (month), "dd" (day of
    month) which the planner emits for year()/month()/dayofmonth().
    """

    format: str
    time_zone: str = "UTC"
    granularity: object | None = None  # optional pre-bucketing

    def to_json(self):
        d = {"type": "timeFormat", "format": self.format, "timeZone": self.time_zone}
        if self.granularity is not None:
            d["granularity"] = self.granularity.to_json()
        return d

    @staticmethod
    def from_json(d):
        from tpu_olap.ir.granularity import granularity_from_json
        g = granularity_from_json(d["granularity"]) if "granularity" in d else None
        return TimeFormatExtractionFn(d["format"], d.get("timeZone", "UTC"), g)


@register("extractionFn", "regex")
@dataclass(frozen=True)
class RegexExtractionFn(ExtractionFunctionSpec):
    expr: str
    replace_missing_value: str | None = None

    def to_json(self):
        return {"type": "regex", "expr": self.expr,
                "replaceMissingValue": self.replace_missing_value}

    @staticmethod
    def from_json(d):
        return RegexExtractionFn(d["expr"], d.get("replaceMissingValue"))


@register("extractionFn", "substring")
@dataclass(frozen=True)
class SubstringExtractionFn(ExtractionFunctionSpec):
    index: int
    length: int | None = None

    def to_json(self):
        return {"type": "substring", "index": self.index, "length": self.length}

    @staticmethod
    def from_json(d):
        return SubstringExtractionFn(int(d["index"]), d.get("length"))


@register("extractionFn", "lower")
@register("extractionFn", "upper")
@dataclass(frozen=True)
class CaseExtractionFn(ExtractionFunctionSpec):
    """Druid's upper/lower extraction functions (case folding)."""
    mode: str  # "upper" | "lower"

    def to_json(self):
        return {"type": self.mode}

    @staticmethod
    def from_json(d):
        return CaseExtractionFn(d["type"])


@register("extractionFn", "lookup")
@dataclass(frozen=True)
class LookupExtractionFn(ExtractionFunctionSpec):
    lookup: tuple  # tuple of (key, value) pairs, canonicalized sorted
    retain_missing_value: bool = False
    replace_missing_value: str | None = None

    def __post_init__(self):
        object.__setattr__(self, "lookup", tuple(sorted(self.lookup)))

    def to_json(self):
        return {"type": "lookup",
                "lookup": {"type": "map", "map": dict(self.lookup)},
                "retainMissingValue": self.retain_missing_value,
                "replaceMissingValueWith": self.replace_missing_value}

    @staticmethod
    def from_json(d):
        m = d["lookup"]["map"]
        return LookupExtractionFn(tuple(sorted(m.items())),
                                  bool(d.get("retainMissingValue", False)),
                                  d.get("replaceMissingValueWith"))


class DimensionSpec:
    pass


@register("dimension", "default")
@dataclass(frozen=True)
class DefaultDimensionSpec(DimensionSpec):
    dimension: str
    output_name: str | None = None

    @property
    def name(self):
        return self.output_name or self.dimension

    def to_json(self):
        d = {"type": "default", "dimension": self.dimension}
        if self.output_name is not None:
            d["outputName"] = self.output_name
        return d

    @staticmethod
    def from_json(d):
        return DefaultDimensionSpec(d["dimension"], d.get("outputName"))


@register("dimension", "extraction")
@dataclass(frozen=True)
class ExtractionDimensionSpec(DimensionSpec):
    dimension: str
    extraction_fn: ExtractionFunctionSpec
    output_name: str | None = None

    @property
    def name(self):
        return self.output_name or self.dimension

    def to_json(self):
        d = {"type": "extraction", "dimension": self.dimension,
             "extractionFn": self.extraction_fn.to_json()}
        if self.output_name is not None:
            d["outputName"] = self.output_name
        return d

    @staticmethod
    def from_json(d):
        return ExtractionDimensionSpec(
            d["dimension"], from_json("extractionFn", d["extractionFn"]),
            d.get("outputName"))


def dimension_from_json(d) -> DimensionSpec:
    if isinstance(d, str):  # Druid shorthand: bare column name
        return DefaultDimensionSpec(d)
    return from_json("dimension", d)


@dataclass(frozen=True)
class VirtualColumn:
    """Expression virtual column — input to aggregators/filters/dimensions."""

    name: str
    expression: Expr
    output_type: str = "double"  # double | long | string

    def to_json(self):
        return {"type": "expression", "name": self.name,
                "expression": self.expression.to_json(),
                "outputType": self.output_type}

    @staticmethod
    def from_json(d):
        return VirtualColumn(d["name"], from_json("expr", d["expression"]),
                             d.get("outputType", "double"))
