"""ISO-8601 query intervals — the time-partition pruning mechanism.

Reference: per-query interval lists restrict which Druid segments are
touched (SURVEY.md §3.3 "Intervals", §3.5 P4). Here they prune the segment
manifest before dispatch and clamp the time filter in-kernel.
"""

from __future__ import annotations

from dataclasses import dataclass

from tpu_olap.utils import timeutil


@dataclass(frozen=True)
class Interval:
    """Half-open [start, end) in epoch millis UTC."""

    start: int
    end: int

    @staticmethod
    def parse(s: str) -> "Interval":
        a, b = s.split("/")
        return Interval(timeutil.parse_iso_datetime(a), timeutil.parse_iso_datetime(b))

    @staticmethod
    def of(start, end) -> "Interval":
        if isinstance(start, str):
            start = timeutil.parse_iso_datetime(start)
        if isinstance(end, str):
            end = timeutil.parse_iso_datetime(end)
        return Interval(int(start), int(end))

    def to_json(self) -> str:
        return f"{timeutil.millis_to_iso(self.start)}/{timeutil.millis_to_iso(self.end)}"

    def overlaps(self, start: int, end: int) -> bool:
        return self.start < end and start < self.end

    def intersect(self, other: "Interval") -> "Interval | None":
        s, e = max(self.start, other.start), min(self.end, other.end)
        return Interval(s, e) if s < e else None


ETERNITY = Interval(-(2**62), 2**62)


def intervals_from_json(lst) -> tuple[Interval, ...]:
    if not lst:
        return ()
    return tuple(Interval.parse(s) if isinstance(s, str) else s for s in lst)


def intervals_to_json(ivals) -> list[str]:
    return [iv.to_json() for iv in ivals]
