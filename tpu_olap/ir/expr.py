"""Scalar row-expression AST for virtual columns and expression filters.

Druid's escape hatches were javascript aggregators/filters and (in modern
Druid) expression virtual columns (SURVEY.md §3.3). We keep a small typed
arithmetic/comparison expression language instead: enough to express
projected aggregate inputs (e.g. SSB Q1.1's sum(lo_extendedprice *
lo_discount)) and residual predicates, and directly evaluable with
numpy/jax without an interpreter loop.
"""

from __future__ import annotations

import re
from dataclasses import dataclass

from tpu_olap.ir.serde import register


class Expr:
    def columns(self) -> set[str]:
        raise NotImplementedError

    def __mul__(self, o): return BinOp("*", self, _wrap(o))
    def __add__(self, o): return BinOp("+", self, _wrap(o))
    def __sub__(self, o): return BinOp("-", self, _wrap(o))
    def __truediv__(self, o): return BinOp("/", self, _wrap(o))


def _wrap(v) -> "Expr":
    if isinstance(v, Expr):
        return v
    return Lit(v)


@register("expr", "col")
@dataclass(frozen=True)
class Col(Expr):
    name: str

    def columns(self):
        return {self.name}

    def to_json(self):
        return {"type": "col", "name": self.name}

    @staticmethod
    def from_json(d):
        return Col(d["name"])


@register("expr", "lit")
@dataclass(frozen=True)
class Lit(Expr):
    value: float | int | str | bool | None

    def columns(self):
        return set()

    def to_json(self):
        return {"type": "lit", "value": self.value}

    @staticmethod
    def from_json(d):
        return Lit(d["value"])


@register("expr", "binop")
@dataclass(frozen=True)
class BinOp(Expr):
    op: str  # + - * / % == != < <= > >= && ||
    left: Expr
    right: Expr

    def columns(self):
        return self.left.columns() | self.right.columns()

    def to_json(self):
        return {"type": "binop", "op": self.op,
                "left": self.left.to_json(), "right": self.right.to_json()}

    @staticmethod
    def from_json(d):
        from tpu_olap.ir.serde import from_json
        return BinOp(d["op"], from_json("expr", d["left"]),
                     from_json("expr", d["right"]))


@register("expr", "func")
@dataclass(frozen=True)
class FuncCall(Expr):
    name: str  # abs, floor, ceil, sqrt, log, exp, if
    args: tuple

    def columns(self):
        out = set()
        for a in self.args:
            out |= a.columns()
        return out

    def to_json(self):
        return {"type": "func", "name": self.name,
                "args": [a.to_json() for a in self.args]}

    @staticmethod
    def from_json(d):
        from tpu_olap.ir.serde import from_json
        return FuncCall(d["name"], tuple(from_json("expr", a) for a in d["args"]))


@dataclass(frozen=True)
class WindowCall(Expr):
    """fn(args) OVER (PARTITION BY ... ORDER BY ...). Fallback-only,
    like Subquery: the planner declines statements containing one and
    the pandas interpreter evaluates it (whole-partition aggregates
    without ORDER BY; running aggregates / rank functions with it)."""
    name: str
    args: tuple
    partition_by: tuple = ()
    order_by: tuple = ()       # ((expr, descending), ...)
    # ROWS BETWEEN frame as (lo, hi) row offsets relative to the current
    # row (negative = preceding); None in a slot = UNBOUNDED on that
    # side. None overall = the standard default (running aggregate with
    # ORDER BY, whole partition without)
    frame: tuple | None = None

    def columns(self):
        out = set()
        for a in self.args:
            out |= a.columns()
        for p in self.partition_by:
            out |= p.columns()
        for e, _ in self.order_by:
            out |= e.columns()
        return out

    def to_json(self):
        # structural identity only (expr_key); never sent to a device
        return {"type": "window", "name": self.name, "repr": repr(self)}


@dataclass(frozen=True)
class Subquery(Expr):
    """A nested SELECT used as a scalar or IN-list source. Never lowers
    to the device IR (no to_json on purpose): the planner treats any
    statement containing one as non-rewritable and the fallback
    interpreter resolves it before evaluation — the analog of the
    reference delegating to full Spark SQL for shapes outside the
    rewrite rules (SURVEY.md §3.1)."""
    stmt: object  # planner.sqlparse.SelectStmt | UnionStmt

    def columns(self):
        return set()  # correlated subqueries are not supported

    def to_json(self):
        # structural identity only (expr_key); never sent to a device
        return {"type": "subquery", "stmt": repr(self.stmt)}


def map_expr(e, fn):
    """Shared expression-rebuild walker: apply `fn` to each node
    top-down; a non-None return REPLACES the node (children are not
    visited — whole-subtree substitutions match first), None means
    rebuild the node from its mapped children. Subquery internals are an
    inner scope and are never descended into. Every rebuilding traversal
    (alias substitution, windows-over-groups rewrite, lookup inlining)
    rides this one walker so a future Expr field is threaded in exactly
    one place."""
    r = fn(e)
    if r is not None:
        return r
    if isinstance(e, BinOp):
        return BinOp(e.op, map_expr(e.left, fn), map_expr(e.right, fn))
    if isinstance(e, WindowCall):
        return WindowCall(
            e.name, tuple(map_expr(a, fn) for a in e.args),
            tuple(map_expr(p, fn) for p in e.partition_by),
            tuple((map_expr(x, fn), d) for x, d in e.order_by),
            e.frame)
    if isinstance(e, FuncCall):
        return FuncCall(e.name, tuple(map_expr(a, fn) for a in e.args))
    return e  # Col, Lit, Subquery


# ---------------------------------------------------------------------------
# Tiny recursive-descent parser for expression strings: "a * b + 2.5"

_TOKEN_RE = re.compile(
    r"\s*(?:(?P<num>\d+(?:\.\d+)?(?:[eE][+-]?\d+)?)"
    r"|(?P<name>[A-Za-z_][A-Za-z0-9_.]*)"
    r"|(?P<str>'[^']*')"
    r"|(?P<op>==|!=|<=|>=|&&|\|\||[-+*/%()<>,]))"
)

_CMP_OPS = ("==", "!=", "<=", ">=", "<", ">")


def _tokenize(s: str):
    pos, out = 0, []
    while pos < len(s):
        m = _TOKEN_RE.match(s, pos)
        if not m:
            if s[pos:].strip() == "":
                break
            raise ValueError(f"bad token at {s[pos:]!r}")
        pos = m.end()
        if m.lastgroup == "num":
            t = m.group("num")
            out.append(("num", float(t) if ("." in t or "e" in t or "E" in t) else int(t)))
        elif m.lastgroup == "name":
            out.append(("name", m.group("name")))
        elif m.lastgroup == "str":
            out.append(("str", m.group("str")[1:-1]))
        else:
            out.append(("op", m.group("op")))
    out.append(("eof", None))
    return out


class _P:
    def __init__(self, toks):
        self.toks = toks
        self.i = 0

    def peek(self):
        return self.toks[self.i]

    def take(self, kind=None, val=None):
        k, v = self.toks[self.i]
        if kind and k != kind or (val is not None and v != val):
            raise ValueError(f"expected {kind}:{val}, got {k}:{v}")
        self.i += 1
        return v

    def expr(self):
        return self.or_()

    def or_(self):
        e = self.and_()
        while self.peek() == ("op", "||"):
            self.take()
            e = BinOp("||", e, self.and_())
        return e

    def and_(self):
        e = self.cmp()
        while self.peek() == ("op", "&&"):
            self.take()
            e = BinOp("&&", e, self.cmp())
        return e

    def cmp(self):
        e = self.add()
        k, v = self.peek()
        if k == "op" and v in _CMP_OPS:
            self.take()
            e = BinOp(v, e, self.add())
        return e

    def add(self):
        e = self.mul()
        while self.peek()[0] == "op" and self.peek()[1] in ("+", "-"):
            op = self.take()
            e = BinOp(op, e, self.mul())
        return e

    def mul(self):
        e = self.unary()
        while self.peek()[0] == "op" and self.peek()[1] in ("*", "/", "%"):
            op = self.take()
            e = BinOp(op, e, self.unary())
        return e

    def unary(self):
        if self.peek() == ("op", "-"):
            self.take()
            return BinOp("-", Lit(0), self.unary())
        return self.atom()

    def atom(self):
        k, v = self.peek()
        if k == "num":
            self.take()
            return Lit(v)
        if k == "str":
            self.take()
            return Lit(v)
        if k == "name":
            self.take()
            if self.peek() == ("op", "("):
                self.take()
                args = []
                if self.peek() != ("op", ")"):
                    args.append(self.expr())
                    while self.peek() == ("op", ","):
                        self.take()
                        args.append(self.expr())
                self.take("op", ")")
                return FuncCall(v, tuple(args))
            return Col(v)
        if (k, v) == ("op", "("):
            self.take()
            e = self.expr()
            self.take("op", ")")
            return e
        raise ValueError(f"unexpected token {k}:{v}")


def parse_expr(s: str) -> Expr:
    p = _P(_tokenize(s))
    e = p.expr()
    p.take("eof")
    return e
