"""LimitSpec: multi-column ordered limit on GroupBy results.

Mirrors the reference's LimitSpec + OrderByColumnSpec (SURVEY.md §3.3
"Limit"); TopN queries carry their own (dimension, metric, threshold).
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass(frozen=True)
class OrderByColumnSpec:
    dimension: str  # a dimension output name or aggregator/post-agg name
    direction: str = "ascending"  # ascending | descending
    dimension_order: str = "lexicographic"  # lexicographic | numeric

    def to_json(self):
        return {"dimension": self.dimension, "direction": self.direction,
                "dimensionOrder": {"type": self.dimension_order}}

    @staticmethod
    def from_json(d):
        if isinstance(d, str):
            return OrderByColumnSpec(d)
        order = d.get("dimensionOrder", "lexicographic")
        if isinstance(order, dict):
            order = order.get("type", "lexicographic")
        return OrderByColumnSpec(d["dimension"], d.get("direction", "ascending"),
                                 order)


@dataclass(frozen=True)
class LimitSpec:
    limit: int | None = None
    columns: tuple = field(default_factory=tuple)  # OrderByColumnSpec
    offset: int = 0

    def to_json(self):
        d = {"type": "default",
             "columns": [c.to_json() for c in self.columns]}
        if self.limit is not None:
            d["limit"] = self.limit
        if self.offset:
            d["offset"] = self.offset
        return d

    @staticmethod
    def from_json(d):
        if d is None:
            return None
        return LimitSpec(d.get("limit"),
                         tuple(OrderByColumnSpec.from_json(c)
                               for c in d.get("columns", [])),
                         int(d.get("offset", 0)))
