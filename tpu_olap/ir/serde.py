"""JSON (de)serialization registry for the polymorphic IR spec classes.

The reference used json4s formats per spec class (SURVEY.md §3.6
"Serialization"). Here every IR dataclass implements ``to_json`` and
registers a ``from_json`` under a (kind, type-tag) key, mirroring Druid's
``{"type": ...}`` polymorphic JSON.
"""

from __future__ import annotations

from typing import Any, Callable

_REGISTRY: dict[tuple[str, str], Callable[[dict], Any]] = {}


def register(kind: str, type_tag: str):
    """Class decorator: register cls.from_json for (kind, type_tag)."""

    def deco(cls):
        _REGISTRY[(kind, type_tag)] = cls.from_json
        cls._serde_kind = kind
        cls._serde_type = type_tag
        return cls

    return deco


def from_json(kind: str, d: dict | None):
    if d is None:
        return None
    t = d.get("type")
    key = (kind, t)
    if key not in _REGISTRY:
        raise ValueError(f"unknown {kind} type {t!r} (known: "
                         f"{sorted(t2 for k2, t2 in _REGISTRY if k2 == kind)})")
    return _REGISTRY[key](d)


def to_json(obj) -> Any:
    if obj is None:
        return None
    return obj.to_json()


def query_from_json(d: dict):
    """Entry point for raw-IR passthrough (reference: `ON DRUID DATASOURCE ds
    EXECUTE QUERY '<json>'`, SURVEY.md §4.5). Accepts Druid's "queryType"
    tag as well as our canonical "type"."""
    if "type" not in d and "queryType" in d:
        d = dict(d)
        d["type"] = d["queryType"]
    return from_json("query", d)
