"""Post-aggregation specs: arithmetic, field access, constant, HLL finalize.

Mirrors the reference's PostAggregationSpec family (SURVEY.md §3.3
"Post-aggregations") — e.g. AVG is compiled to doubleSum/count arithmetic.
"""

from __future__ import annotations

from dataclasses import dataclass

from tpu_olap.ir.serde import register, from_json


class PostAggregationSpec:
    name: str

    def inputs(self) -> set[str]:
        """Names of aggregator / post-agg outputs this reads."""
        raise NotImplementedError


@register("postAggregation", "arithmetic")
@dataclass(frozen=True)
class ArithmeticPostAgg(PostAggregationSpec):
    name: str
    fn: str  # + - * / quotient
    fields: tuple

    def inputs(self):
        out = set()
        for f in self.fields:
            out |= f.inputs()
        return out

    def to_json(self):
        return {"type": "arithmetic", "name": self.name, "fn": self.fn,
                "fields": [f.to_json() for f in self.fields]}

    @staticmethod
    def from_json(d):
        return ArithmeticPostAgg(d["name"], d["fn"],
                                 tuple(from_json("postAggregation", f)
                                       for f in d["fields"]))


@register("postAggregation", "fieldAccess")
@dataclass(frozen=True)
class FieldAccessPostAgg(PostAggregationSpec):
    field_name: str
    name: str = ""

    def inputs(self):
        return {self.field_name}

    def to_json(self):
        return {"type": "fieldAccess", "name": self.name,
                "fieldName": self.field_name}

    @staticmethod
    def from_json(d):
        return FieldAccessPostAgg(d["fieldName"], d.get("name", ""))


@register("postAggregation", "constant")
@dataclass(frozen=True)
class ConstantPostAgg(PostAggregationSpec):
    value: float
    name: str = ""

    def inputs(self):
        return set()

    def to_json(self):
        return {"type": "constant", "name": self.name, "value": self.value}

    @staticmethod
    def from_json(d):
        return ConstantPostAgg(d["value"], d.get("name", ""))


@register("postAggregation", "hyperUniqueCardinality")
@dataclass(frozen=True)
class HyperUniqueCardinalityPostAgg(PostAggregationSpec):
    """Finalize an HLL aggregator output to a (float) cardinality estimate."""

    field_name: str
    name: str = ""

    def inputs(self):
        return {self.field_name}

    def to_json(self):
        return {"type": "hyperUniqueCardinality", "name": self.name,
                "fieldName": self.field_name}

    @staticmethod
    def from_json(d):
        return HyperUniqueCardinalityPostAgg(d["fieldName"], d.get("name", ""))


@register("postAggregation", "thetaSketchEstimate")
@dataclass(frozen=True)
class ThetaSketchEstimatePostAgg(PostAggregationSpec):
    field_name: str
    name: str = ""

    def inputs(self):
        return {self.field_name}

    def to_json(self):
        return {"type": "thetaSketchEstimate", "name": self.name,
                "field": {"type": "fieldAccess", "fieldName": self.field_name}}

    @staticmethod
    def from_json(d):
        fld = d.get("field", {})
        fn = d.get("fieldName") or fld.get("fieldName")
        return ThetaSketchEstimatePostAgg(fn, d.get("name", ""))


def postagg_from_json(d):
    return from_json("postAggregation", d)
