"""Post-aggregation specs: arithmetic, field access, constant, HLL finalize.

Mirrors the reference's PostAggregationSpec family (SURVEY.md §3.3
"Post-aggregations") — e.g. AVG is compiled to doubleSum/count arithmetic.
"""

from __future__ import annotations

from dataclasses import dataclass

from tpu_olap.ir.serde import register, from_json


class PostAggregationSpec:
    name: str

    def inputs(self) -> set[str]:
        """Names of aggregator / post-agg outputs this reads."""
        raise NotImplementedError


@register("postAggregation", "arithmetic")
@dataclass(frozen=True)
class ArithmeticPostAgg(PostAggregationSpec):
    name: str
    fn: str  # + - * / quotient
    fields: tuple

    def inputs(self):
        out = set()
        for f in self.fields:
            out |= f.inputs()
        return out

    def to_json(self):
        return {"type": "arithmetic", "name": self.name, "fn": self.fn,
                "fields": [f.to_json() for f in self.fields]}

    @staticmethod
    def from_json(d):
        return ArithmeticPostAgg(d["name"], d["fn"],
                                 tuple(from_json("postAggregation", f)
                                       for f in d["fields"]))


@register("postAggregation", "fieldAccess")
@dataclass(frozen=True)
class FieldAccessPostAgg(PostAggregationSpec):
    field_name: str
    name: str = ""

    def inputs(self):
        return {self.field_name}

    def to_json(self):
        return {"type": "fieldAccess", "name": self.name,
                "fieldName": self.field_name}

    @staticmethod
    def from_json(d):
        return FieldAccessPostAgg(d["fieldName"], d.get("name", ""))


@register("postAggregation", "constant")
@dataclass(frozen=True)
class ConstantPostAgg(PostAggregationSpec):
    value: float
    name: str = ""

    def inputs(self):
        return set()

    def to_json(self):
        return {"type": "constant", "name": self.name, "value": self.value}

    @staticmethod
    def from_json(d):
        return ConstantPostAgg(d["value"], d.get("name", ""))


@register("postAggregation", "hyperUniqueCardinality")
@dataclass(frozen=True)
class HyperUniqueCardinalityPostAgg(PostAggregationSpec):
    """Finalize an HLL aggregator output to a (float) cardinality estimate."""

    field_name: str
    name: str = ""

    def inputs(self):
        return {self.field_name}

    def to_json(self):
        return {"type": "hyperUniqueCardinality", "name": self.name,
                "fieldName": self.field_name}

    @staticmethod
    def from_json(d):
        return HyperUniqueCardinalityPostAgg(d["fieldName"], d.get("name", ""))


@register("postAggregation", "thetaSketchEstimate")
@dataclass(frozen=True)
class ThetaSketchEstimatePostAgg(PostAggregationSpec):
    """Finalize a theta sketch to a number. `field_name` references a
    theta aggregator directly; `field` (mutually exclusive) nests a
    thetaSketchSetOp tree, matching the datasketches extension."""
    field_name: str = ""
    name: str = ""
    field: PostAggregationSpec | None = None

    def inputs(self):
        return self.field.inputs() if self.field is not None \
            else {self.field_name}

    def to_json(self):
        fld = (self.field.to_json() if self.field is not None else
               {"type": "fieldAccess", "fieldName": self.field_name})
        return {"type": "thetaSketchEstimate", "name": self.name,
                "field": fld}

    @staticmethod
    def from_json(d):
        fld = d.get("field", {})
        if fld.get("type") == "thetaSketchSetOp":
            return ThetaSketchEstimatePostAgg(
                "", d.get("name", ""), ThetaSketchSetOpPostAgg.from_json(fld))
        fn = d.get("fieldName") or fld.get("fieldName")
        return ThetaSketchEstimatePostAgg(fn, d.get("name", ""))


@register("postAggregation", "thetaSketchSetOp")
@dataclass(frozen=True)
class ThetaSketchSetOpPostAgg(PostAggregationSpec):
    """INTERSECT / UNION / NOT over theta sketches (the datasketches
    extension's set operations — the reason to choose theta over HLL,
    SURVEY.md §3.3). `fields` entries are FieldAccessPostAgg references
    to theta aggregators or nested set ops. NOT is left-fold A \\ B \\ C.
    Executed host-side on the raw per-group hash tables (the broker-side
    finalize analog); referenced aggregators keep their raw tables
    through finalization."""
    func: str                       # "INTERSECT" | "UNION" | "NOT"
    fields: tuple
    name: str = ""

    def inputs(self):
        out = set()
        for f in self.fields:
            out |= f.inputs()
        return out

    def to_json(self):
        return {"type": "thetaSketchSetOp", "name": self.name,
                "func": self.func,
                "fields": [f.to_json() for f in self.fields]}

    @staticmethod
    def from_json(d):
        fields = []
        for f in d.get("fields", ()):
            if f.get("type") == "thetaSketchSetOp":
                fields.append(ThetaSketchSetOpPostAgg.from_json(f))
            else:
                fields.append(FieldAccessPostAgg(f["fieldName"],
                                                 f.get("name", "")))
        func = d["func"].upper()
        if func not in ("INTERSECT", "UNION", "NOT"):
            raise ValueError(f"unknown theta set op {d['func']!r}")
        if len(fields) < 2:
            raise ValueError("thetaSketchSetOp needs at least 2 fields")
        return ThetaSketchSetOpPostAgg(func, tuple(fields),
                                       d.get("name", ""))


def postagg_from_json(d):
    return from_json("postAggregation", d)
