"""Aggregation specs: count, sum/min/max, HLL & theta cardinality, filtered.

Mirrors the reference's AggregationSpec family (SURVEY.md §3.3
"Aggregations"; BASELINE.json:5 "sum/min/max/count, HyperLogLog/Theta
cardinality"). Long/double variants carry a value_type instead of separate
classes, but serialize to the Druid type tags (longSum, doubleSum, ...).
"""

from __future__ import annotations

from dataclasses import dataclass

from tpu_olap.ir.serde import register, from_json


class AggregationSpec:
    name: str

    def field_names(self) -> set[str]:
        raise NotImplementedError


@register("aggregation", "count")
@dataclass(frozen=True)
class CountAggregation(AggregationSpec):
    name: str

    def field_names(self):
        return set()

    def to_json(self):
        return {"type": "count", "name": self.name}

    @staticmethod
    def from_json(d):
        return CountAggregation(d["name"])


@dataclass(frozen=True)
class SumAggregation(AggregationSpec):
    name: str
    field_name: str
    value_type: str = "double"  # "long" | "double"

    def field_names(self):
        return {self.field_name}

    def to_json(self):
        return {"type": f"{self.value_type}Sum", "name": self.name,
                "fieldName": self.field_name}


@dataclass(frozen=True)
class MinAggregation(AggregationSpec):
    name: str
    field_name: str
    value_type: str = "double"

    def field_names(self):
        return {self.field_name}

    def to_json(self):
        return {"type": f"{self.value_type}Min", "name": self.name,
                "fieldName": self.field_name}


@dataclass(frozen=True)
class MaxAggregation(AggregationSpec):
    name: str
    field_name: str
    value_type: str = "double"

    def field_names(self):
        return {self.field_name}

    def to_json(self):
        return {"type": f"{self.value_type}Max", "name": self.name,
                "fieldName": self.field_name}


def _reg_typed(cls, kind_cls, vt):
    @register("aggregation", f"{vt}{kind_cls}")
    class _Shim:  # noqa: N801 - registration shim only
        @staticmethod
        def from_json(d):
            return cls(d["name"], d["fieldName"], vt)
    return _Shim


for _vt in ("long", "double", "float"):
    _reg_typed(SumAggregation, "Sum", _vt)
    _reg_typed(MinAggregation, "Min", _vt)
    _reg_typed(MaxAggregation, "Max", _vt)


@register("aggregation", "cardinality")
@dataclass(frozen=True)
class CardinalityAggregation(AggregationSpec):
    """Approximate COUNT(DISTINCT dims...) via HyperLogLog over dimension
    values at query time (reference: COUNT(DISTINCT dim) -> cardinality
    aggregator, SURVEY.md §3.2 AggregateTransform)."""

    name: str
    fields: tuple
    by_row: bool = False
    round: bool = True

    def field_names(self):
        return set(self.fields)

    def to_json(self):
        return {"type": "cardinality", "name": self.name,
                "fields": list(self.fields), "byRow": self.by_row,
                "round": self.round}

    @staticmethod
    def from_json(d):
        return CardinalityAggregation(d["name"], tuple(d["fields"]),
                                      bool(d.get("byRow", False)),
                                      bool(d.get("round", True)))


@register("aggregation", "hyperUnique")
@dataclass(frozen=True)
class HyperUniqueAggregation(AggregationSpec):
    """HLL over a single column (reference: hyperUnique over a pre-built HLL
    metric column; here computed from the raw column at query time)."""

    name: str
    field_name: str
    round: bool = True

    def field_names(self):
        return {self.field_name}

    def to_json(self):
        return {"type": "hyperUnique", "name": self.name,
                "fieldName": self.field_name, "round": self.round}

    @staticmethod
    def from_json(d):
        return HyperUniqueAggregation(d["name"], d["fieldName"],
                                      bool(d.get("round", True)))


@register("aggregation", "thetaSketch")
@dataclass(frozen=True)
class ThetaSketchAggregation(AggregationSpec):
    """Theta (KMV) sketch count-distinct — the datasketches-extension analog
    (SURVEY.md §3.3: Theta-sketch aggregator)."""

    name: str
    field_name: str
    size: int = 16384  # nominal entries (k)

    def field_names(self):
        return {self.field_name}

    def to_json(self):
        return {"type": "thetaSketch", "name": self.name,
                "fieldName": self.field_name, "size": self.size}

    @staticmethod
    def from_json(d):
        return ThetaSketchAggregation(d["name"], d["fieldName"],
                                      int(d.get("size", 16384)))


@register("aggregation", "filtered")
@dataclass(frozen=True)
class FilteredAggregation(AggregationSpec):
    filter: object  # FilterSpec
    aggregator: AggregationSpec

    @property
    def name(self):
        return self.aggregator.name

    def field_names(self):
        return self.aggregator.field_names() | self.filter.columns()

    def to_json(self):
        return {"type": "filtered", "filter": self.filter.to_json(),
                "aggregator": self.aggregator.to_json()}

    @staticmethod
    def from_json(d):
        return FilteredAggregation(from_json("filter", d["filter"]),
                                   from_json("aggregation", d["aggregator"]))


def aggregation_from_json(d):
    return from_json("aggregation", d)
