"""QuerySpec hierarchy: GroupBy, Timeseries, TopN, Scan, Select, Search,
SegmentMetadata, TimeBoundary.

Mirrors the reference's query-type family (SURVEY.md §3.3 "Query types";
BASELINE.json:5 names GroupBy/TimeSeries/TopN). Each is a frozen dataclass
with Druid-shaped JSON round-trip; the executor lowers these to jitted XLA
programs (tpu_olap.executor.lowering).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from tpu_olap.ir.aggregations import AggregationSpec, aggregation_from_json
from tpu_olap.ir.dimensions import (DimensionSpec, VirtualColumn,
                                    dimension_from_json)
from tpu_olap.ir.filters import FilterSpec, filter_from_json
from tpu_olap.ir.granularity import (AllGranularity, Granularity,
                                     granularity_from_json)
from tpu_olap.ir.having import HavingSpec, having_from_json
from tpu_olap.ir.interval import (Interval, intervals_from_json,
                                  intervals_to_json)
from tpu_olap.ir.limit import LimitSpec
from tpu_olap.ir.postaggs import PostAggregationSpec, postagg_from_json
from tpu_olap.ir.serde import register


@dataclass(frozen=True)
class QuerySpec:
    data_source: str
    intervals: tuple = field(default_factory=tuple)  # tuple[Interval]
    filter: FilterSpec | None = None
    virtual_columns: tuple = field(default_factory=tuple)
    context: tuple = field(default_factory=tuple)  # sorted (k, v) pairs

    @property
    def query_type(self) -> str:
        return type(self).query_type_name  # type: ignore[attr-defined]

    def context_dict(self) -> dict:
        return dict(self.context)

    def _common_json(self, d: dict) -> dict:
        d["dataSource"] = self.data_source
        d["intervals"] = intervals_to_json(self.intervals) if self.intervals else []
        if self.filter is not None:
            d["filter"] = self.filter.to_json()
        if self.virtual_columns:
            d["virtualColumns"] = [v.to_json() for v in self.virtual_columns]
        if self.context:
            d["context"] = dict(self.context)
        return d

    @staticmethod
    def _common_kwargs(d: dict) -> dict:
        return dict(
            data_source=d["dataSource"] if isinstance(d["dataSource"], str)
            else d["dataSource"]["name"],
            intervals=intervals_from_json(d.get("intervals")),
            filter=filter_from_json(d["filter"]) if d.get("filter") else None,
            virtual_columns=tuple(VirtualColumn.from_json(v)
                                  for v in d.get("virtualColumns", [])),
            context=tuple(sorted(d.get("context", {}).items())),
        )


@register("query", "timeseries")
@dataclass(frozen=True)
class TimeseriesQuerySpec(QuerySpec):
    query_type_name = "timeseries"

    granularity: Granularity = field(default_factory=AllGranularity)
    aggregations: tuple = field(default_factory=tuple)
    post_aggregations: tuple = field(default_factory=tuple)
    descending: bool = False

    def to_json(self):
        d = {"queryType": "timeseries", "type": "timeseries"}
        self._common_json(d)
        d["granularity"] = self.granularity.to_json()
        d["aggregations"] = [a.to_json() for a in self.aggregations]
        if self.post_aggregations:
            d["postAggregations"] = [p.to_json() for p in self.post_aggregations]
        if self.descending:
            d["descending"] = True
        return d

    @staticmethod
    def from_json(d):
        return TimeseriesQuerySpec(
            granularity=granularity_from_json(d.get("granularity")),
            aggregations=tuple(aggregation_from_json(a)
                               for a in d.get("aggregations", [])),
            post_aggregations=tuple(postagg_from_json(p)
                                    for p in d.get("postAggregations", [])),
            descending=bool(d.get("descending", False)),
            **QuerySpec._common_kwargs(d),
        )


@register("query", "groupBy")
@dataclass(frozen=True)
class GroupByQuerySpec(QuerySpec):
    query_type_name = "groupBy"

    dimensions: tuple = field(default_factory=tuple)
    granularity: Granularity = field(default_factory=AllGranularity)
    aggregations: tuple = field(default_factory=tuple)
    post_aggregations: tuple = field(default_factory=tuple)
    having: HavingSpec | None = None
    limit_spec: LimitSpec | None = None

    def to_json(self):
        d = {"queryType": "groupBy", "type": "groupBy"}
        self._common_json(d)
        d["dimensions"] = [x.to_json() for x in self.dimensions]
        d["granularity"] = self.granularity.to_json()
        d["aggregations"] = [a.to_json() for a in self.aggregations]
        if self.post_aggregations:
            d["postAggregations"] = [p.to_json() for p in self.post_aggregations]
        if self.having is not None:
            d["having"] = self.having.to_json()
        if self.limit_spec is not None:
            d["limitSpec"] = self.limit_spec.to_json()
        return d

    @staticmethod
    def from_json(d):
        return GroupByQuerySpec(
            dimensions=tuple(dimension_from_json(x)
                             for x in d.get("dimensions", [])),
            granularity=granularity_from_json(d.get("granularity")),
            aggregations=tuple(aggregation_from_json(a)
                               for a in d.get("aggregations", [])),
            post_aggregations=tuple(postagg_from_json(p)
                                    for p in d.get("postAggregations", [])),
            having=having_from_json(d["having"]) if d.get("having") else None,
            limit_spec=LimitSpec.from_json(d.get("limitSpec")),
            **QuerySpec._common_kwargs(d),
        )


@register("query", "topN")
@dataclass(frozen=True)
class TopNQuerySpec(QuerySpec):
    query_type_name = "topN"

    dimension: DimensionSpec = None  # type: ignore[assignment]
    metric: str = ""
    threshold: int = 0
    granularity: Granularity = field(default_factory=AllGranularity)
    aggregations: tuple = field(default_factory=tuple)
    post_aggregations: tuple = field(default_factory=tuple)
    inverted: bool = False  # bottom-N (Druid {"type": "inverted"} metric)

    def to_json(self):
        d = {"queryType": "topN", "type": "topN"}
        self._common_json(d)
        d["dimension"] = self.dimension.to_json()
        d["metric"] = ({"type": "inverted", "metric": self.metric}
                       if self.inverted else self.metric)
        d["threshold"] = self.threshold
        d["granularity"] = self.granularity.to_json()
        d["aggregations"] = [a.to_json() for a in self.aggregations]
        if self.post_aggregations:
            d["postAggregations"] = [p.to_json() for p in self.post_aggregations]
        return d

    @staticmethod
    def from_json(d):
        metric = d["metric"]
        inverted = False
        if isinstance(metric, dict):
            mtype = metric.get("type", "numeric")
            if mtype == "inverted":
                inverted = True
                inner = metric.get("metric")
                if isinstance(inner, dict):
                    metric = inner.get("metric", inner.get("fieldName", ""))
                else:
                    metric = inner
            elif mtype == "numeric":
                metric = metric.get("metric", metric.get("fieldName", ""))
            else:
                raise ValueError(f"unsupported topN metric spec type {mtype!r}")
        return TopNQuerySpec(
            dimension=dimension_from_json(d["dimension"]),
            metric=metric,
            inverted=inverted,
            threshold=int(d["threshold"]),
            granularity=granularity_from_json(d.get("granularity")),
            aggregations=tuple(aggregation_from_json(a)
                               for a in d.get("aggregations", [])),
            post_aggregations=tuple(postagg_from_json(p)
                                    for p in d.get("postAggregations", [])),
            **QuerySpec._common_kwargs(d),
        )


@register("query", "scan")
@dataclass(frozen=True)
class ScanQuerySpec(QuerySpec):
    query_type_name = "scan"

    columns: tuple = field(default_factory=tuple)  # () = all columns
    limit: int | None = None
    offset: int = 0
    order: str = "none"  # none | ascending | descending (by __time)

    def to_json(self):
        d = {"queryType": "scan", "type": "scan"}
        self._common_json(d)
        d["columns"] = list(self.columns)
        if self.limit is not None:
            d["limit"] = self.limit
        if self.offset:
            d["offset"] = self.offset
        d["order"] = self.order
        return d

    @staticmethod
    def from_json(d):
        return ScanQuerySpec(
            columns=tuple(d.get("columns", [])),
            limit=d.get("limit"),
            offset=int(d.get("offset", 0)),
            order=d.get("order", "none"),
            **QuerySpec._common_kwargs(d),
        )


@register("query", "select")
@dataclass(frozen=True)
class SelectQuerySpec(QuerySpec):
    """Legacy paged row fetch (reference SelectSpec, SURVEY.md §3.3/§4.4).

    Paging: paging_offset is the row offset into the time-ordered result;
    results carry the next offset as a paging identifier.
    """

    query_type_name = "select"

    dimensions: tuple = field(default_factory=tuple)  # bare names
    metrics: tuple = field(default_factory=tuple)
    page_size: int = 1000
    paging_offset: int = 0
    descending: bool = False

    def to_json(self):
        d = {"queryType": "select", "type": "select"}
        self._common_json(d)
        d["dimensions"] = list(self.dimensions)
        d["metrics"] = list(self.metrics)
        d["pagingSpec"] = {"threshold": self.page_size,
                           "pagingIdentifiers": {"offset": self.paging_offset}}
        if self.descending:
            d["descending"] = True
        return d

    @staticmethod
    def from_json(d):
        paging = d.get("pagingSpec", {})
        ids = paging.get("pagingIdentifiers", {})
        return SelectQuerySpec(
            dimensions=tuple(d.get("dimensions", [])),
            metrics=tuple(d.get("metrics", [])),
            page_size=int(paging.get("threshold", 1000)),
            paging_offset=int(ids.get("offset", 0)),
            descending=bool(d.get("descending", False)),
            **QuerySpec._common_kwargs(d),
        )


@dataclass(frozen=True)
class SearchQueryContains:
    value: str
    case_sensitive: bool = False
    fragments: tuple = field(default_factory=tuple)  # non-empty => fragment search

    def to_json(self):
        if self.fragments:
            return {"type": "fragment", "values": list(self.fragments),
                    "caseSensitive": self.case_sensitive}
        t = "contains" if self.case_sensitive else "insensitive_contains"
        return {"type": t, "value": self.value}

    @staticmethod
    def from_json(d):
        if d["type"] == "fragment":
            return SearchQueryContains("", bool(d.get("caseSensitive", False)),
                                       tuple(d["values"]))
        return SearchQueryContains(d["value"], d["type"] == "contains")


@register("query", "search")
@dataclass(frozen=True)
class SearchQuerySpec(QuerySpec):
    """Dimension-value search (reference SearchQuerySpec, SURVEY.md §3.3)."""

    query_type_name = "search"

    search_dimensions: tuple = field(default_factory=tuple)  # () = all dims
    query: SearchQueryContains = None  # type: ignore[assignment]
    limit: int = 1000
    sort: str = "lexicographic"  # lexicographic | alphanumeric | strlen

    def to_json(self):
        d = {"queryType": "search", "type": "search"}
        self._common_json(d)
        if self.search_dimensions:
            d["searchDimensions"] = list(self.search_dimensions)
        d["query"] = self.query.to_json()
        d["limit"] = self.limit
        d["sort"] = {"type": self.sort}
        return d

    @staticmethod
    def from_json(d):
        sort = d.get("sort", "lexicographic")
        if isinstance(sort, dict):
            sort = sort.get("type", "lexicographic")
        return SearchQuerySpec(
            search_dimensions=tuple(d.get("searchDimensions", [])),
            query=SearchQueryContains.from_json(d["query"]),
            limit=int(d.get("limit", 1000)),
            sort=sort,
            **QuerySpec._common_kwargs(d),
        )


@register("query", "segmentMetadata")
@dataclass(frozen=True)
class SegmentMetadataQuerySpec(QuerySpec):
    """Per-column type/cardinality/size metadata (reference: populates the
    metadata cache and cost model, SURVEY.md §4.1)."""

    query_type_name = "segmentMetadata"

    to_include: tuple = field(default_factory=tuple)  # () = all columns
    merge: bool = True

    def to_json(self):
        d = {"queryType": "segmentMetadata", "type": "segmentMetadata"}
        self._common_json(d)
        if self.to_include:
            d["toInclude"] = {"type": "list", "columns": list(self.to_include)}
        d["merge"] = self.merge
        return d

    @staticmethod
    def from_json(d):
        inc = d.get("toInclude", {})
        return SegmentMetadataQuerySpec(
            to_include=tuple(inc.get("columns", [])) if isinstance(inc, dict) else (),
            merge=bool(d.get("merge", True)),
            **QuerySpec._common_kwargs(d),
        )


@register("query", "timeBoundary")
@dataclass(frozen=True)
class TimeBoundaryQuerySpec(QuerySpec):
    query_type_name = "timeBoundary"

    bound: str | None = None  # None | minTime | maxTime

    def to_json(self):
        d = {"queryType": "timeBoundary", "type": "timeBoundary"}
        self._common_json(d)
        if self.bound:
            d["bound"] = self.bound
        return d

    @staticmethod
    def from_json(d):
        return TimeBoundaryQuerySpec(
            bound=d.get("bound"),
            **QuerySpec._common_kwargs(d),
        )
