"""Having specs for GroupBy: >, <, =, and/or/not, dim selector.

Mirrors the reference's HavingSpec family (SURVEY.md §3.3 "Having").
"""

from __future__ import annotations

from dataclasses import dataclass

from tpu_olap.ir.serde import register, from_json


class HavingSpec:
    pass


@register("having", "greaterThan")
@dataclass(frozen=True)
class GreaterThanHaving(HavingSpec):
    aggregation: str
    value: float

    def to_json(self):
        return {"type": "greaterThan", "aggregation": self.aggregation,
                "value": self.value}

    @staticmethod
    def from_json(d):
        return GreaterThanHaving(d["aggregation"], d["value"])


@register("having", "lessThan")
@dataclass(frozen=True)
class LessThanHaving(HavingSpec):
    aggregation: str
    value: float

    def to_json(self):
        return {"type": "lessThan", "aggregation": self.aggregation,
                "value": self.value}

    @staticmethod
    def from_json(d):
        return LessThanHaving(d["aggregation"], d["value"])


@register("having", "equalTo")
@dataclass(frozen=True)
class EqualToHaving(HavingSpec):
    aggregation: str
    value: float

    def to_json(self):
        return {"type": "equalTo", "aggregation": self.aggregation,
                "value": self.value}

    @staticmethod
    def from_json(d):
        return EqualToHaving(d["aggregation"], d["value"])


@register("having", "dimSelector")
@dataclass(frozen=True)
class DimSelectorHaving(HavingSpec):
    dimension: str
    value: str

    def to_json(self):
        return {"type": "dimSelector", "dimension": self.dimension,
                "value": self.value}

    @staticmethod
    def from_json(d):
        return DimSelectorHaving(d["dimension"], d["value"])


@register("having", "and")
@dataclass(frozen=True)
class AndHaving(HavingSpec):
    having_specs: tuple

    def to_json(self):
        return {"type": "and",
                "havingSpecs": [h.to_json() for h in self.having_specs]}

    @staticmethod
    def from_json(d):
        return AndHaving(tuple(from_json("having", h) for h in d["havingSpecs"]))


@register("having", "or")
@dataclass(frozen=True)
class OrHaving(HavingSpec):
    having_specs: tuple

    def to_json(self):
        return {"type": "or",
                "havingSpecs": [h.to_json() for h in self.having_specs]}

    @staticmethod
    def from_json(d):
        return OrHaving(tuple(from_json("having", h) for h in d["havingSpecs"]))


@register("having", "not")
@dataclass(frozen=True)
class NotHaving(HavingSpec):
    having_spec: HavingSpec

    def to_json(self):
        return {"type": "not", "havingSpec": self.having_spec.to_json()}

    @staticmethod
    def from_json(d):
        return NotHaving(from_json("having", d["havingSpec"]))


def having_from_json(d):
    return from_json("having", d)
