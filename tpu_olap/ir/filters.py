"""Filter specs: selector, bound, in, regex, like, and/or/not, expression.

Mirrors the reference's FilterSpec family (SURVEY.md §3.3 "Filters"); the
javascript escape hatch is replaced by ExpressionFilter over the typed
expression AST. Evaluation strategy lives in tpu_olap.kernels.filtereval:
string-dimension predicates compile to boolean lookup tables over the
dictionary, so selector/in/regex/like/bound-lexicographic all lower to one
gather kernel.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from tpu_olap.ir.expr import Expr
from tpu_olap.ir.serde import register, from_json


class FilterSpec:
    def columns(self) -> set[str]:
        raise NotImplementedError


def _reject_extraction_fn(d: dict, kind: str) -> None:
    """Refuse rather than silently drop an extractionFn we don't evaluate."""
    if d.get("extractionFn") is not None:
        raise ValueError(
            f"extractionFn on {kind!r} filter is not supported "
            "(supported on 'selector', 'in', and 'bound'); rewrite via a "
            "virtual column or an extraction filter")


@register("filter", "selector")
@dataclass(frozen=True)
class SelectorFilter(FilterSpec):
    dimension: str
    value: str | int | float | None
    extraction_fn: object | None = None

    def columns(self):
        return {self.dimension}

    def to_json(self):
        d = {"type": "selector", "dimension": self.dimension, "value": self.value}
        if self.extraction_fn is not None:
            d["extractionFn"] = self.extraction_fn.to_json()
        return d

    @staticmethod
    def from_json(d):
        ef = from_json("extractionFn", d.get("extractionFn"))
        return SelectorFilter(d["dimension"], d.get("value"), ef)


@register("filter", "in")
@dataclass(frozen=True)
class InFilter(FilterSpec):
    dimension: str
    values: tuple
    extraction_fn: object = None  # ExtractionFunctionSpec | None

    def columns(self):
        return {self.dimension}

    def to_json(self):
        out = {"type": "in", "dimension": self.dimension,
               "values": list(self.values)}
        if self.extraction_fn is not None:
            out["extractionFn"] = self.extraction_fn.to_json()
        return out

    @staticmethod
    def from_json(d):
        ef = from_json("extractionFn", d.get("extractionFn"))
        return InFilter(d["dimension"], tuple(d["values"]), ef)


@register("filter", "bound")
@dataclass(frozen=True)
class BoundFilter(FilterSpec):
    dimension: str
    lower: str | int | float | None = None
    upper: str | int | float | None = None
    lower_strict: bool = False
    upper_strict: bool = False
    ordering: str = "lexicographic"  # or "numeric"
    extraction_fn: object = None     # ExtractionFunctionSpec | None

    def columns(self):
        return {self.dimension}

    def to_json(self):
        d = {"type": "bound", "dimension": self.dimension,
             "ordering": self.ordering}
        if self.lower is not None:
            d["lower"] = self.lower
            d["lowerStrict"] = self.lower_strict
        if self.upper is not None:
            d["upper"] = self.upper
            d["upperStrict"] = self.upper_strict
        if self.extraction_fn is not None:
            d["extractionFn"] = self.extraction_fn.to_json()
        return d

    @staticmethod
    def from_json(d):
        ef = from_json("extractionFn", d.get("extractionFn"))
        return BoundFilter(d["dimension"], d.get("lower"), d.get("upper"),
                           bool(d.get("lowerStrict", False)),
                           bool(d.get("upperStrict", False)),
                           d.get("ordering", "lexicographic"), ef)


@register("filter", "regex")
@dataclass(frozen=True)
class RegexFilter(FilterSpec):
    dimension: str
    pattern: str

    def columns(self):
        return {self.dimension}

    def to_json(self):
        return {"type": "regex", "dimension": self.dimension, "pattern": self.pattern}

    @staticmethod
    def from_json(d):
        _reject_extraction_fn(d, "regex")
        return RegexFilter(d["dimension"], d["pattern"])


@register("filter", "like")
@dataclass(frozen=True)
class LikeFilter(FilterSpec):
    dimension: str
    pattern: str  # SQL LIKE: % and _

    def columns(self):
        return {self.dimension}

    def to_json(self):
        return {"type": "like", "dimension": self.dimension, "pattern": self.pattern}

    @staticmethod
    def from_json(d):
        _reject_extraction_fn(d, "like")
        return LikeFilter(d["dimension"], d["pattern"])


@register("filter", "and")
@dataclass(frozen=True)
class AndFilter(FilterSpec):
    fields: tuple = field(default_factory=tuple)

    def columns(self):
        out = set()
        for f in self.fields:
            out |= f.columns()
        return out

    def to_json(self):
        return {"type": "and", "fields": [f.to_json() for f in self.fields]}

    @staticmethod
    def from_json(d):
        return AndFilter(tuple(from_json("filter", f) for f in d["fields"]))


@register("filter", "or")
@dataclass(frozen=True)
class OrFilter(FilterSpec):
    fields: tuple = field(default_factory=tuple)

    def columns(self):
        out = set()
        for f in self.fields:
            out |= f.columns()
        return out

    def to_json(self):
        return {"type": "or", "fields": [f.to_json() for f in self.fields]}

    @staticmethod
    def from_json(d):
        return OrFilter(tuple(from_json("filter", f) for f in d["fields"]))


@register("filter", "not")
@dataclass(frozen=True)
class NotFilter(FilterSpec):
    field: FilterSpec

    def columns(self):
        return self.field.columns()

    def to_json(self):
        return {"type": "not", "field": self.field.to_json()}

    @staticmethod
    def from_json(d):
        return NotFilter(from_json("filter", d["field"]))


@register("filter", "columnComparison")
@dataclass(frozen=True)
class ColumnComparisonFilter(FilterSpec):
    """Row-vs-row equality across two (or more, chained pairwise) columns
    — the reference family's columnComparison filter (SURVEY.md §3.3),
    the shape TPC-H Q5/Q7 need (`c_nation = s_nation` on the denormalized
    fact). Divergence from Druid, by design: a NULL operand never matches
    (engine-wide boolean rule, see kernels.filtereval module docstring;
    Druid treats two missing values as equal). SQL `a <> b` composes as
    NotFilter(ColumnComparisonFilter), under which NULL rows match — the
    same inversion semantics every other NOT shape has here.

    String/string pairs compare via a cross-dictionary code translation
    map built host-side and hoisted to a device-resident derived stream
    (executor/dataset.py::derived), so the device cost is one elementwise
    int32 compare, not a per-dispatch gather."""
    dimensions: tuple  # >= 2 column names

    def columns(self):
        return set(self.dimensions)

    def to_json(self):
        return {"type": "columnComparison",
                "dimensions": list(self.dimensions)}

    @staticmethod
    def from_json(d):
        dims = tuple(d["dimensions"])
        if len(dims) < 2:
            raise ValueError("columnComparison needs >= 2 dimensions")
        return ColumnComparisonFilter(dims)


@register("filter", "expression")
@dataclass(frozen=True)
class ExpressionFilter(FilterSpec):
    expression: Expr

    def columns(self):
        return self.expression.columns()

    def to_json(self):
        return {"type": "expression", "expression": self.expression.to_json()}

    @staticmethod
    def from_json(d):
        return ExpressionFilter(from_json("expr", d["expression"]))


def filter_from_json(d):
    return from_json("filter", d)


def and_of(*specs) -> FilterSpec | None:
    specs = [s for s in specs if s is not None]
    if not specs:
        return None
    if len(specs) == 1:
        return specs[0]
    flat = []
    for s in specs:
        if isinstance(s, AndFilter):
            flat.extend(s.fields)
        else:
            flat.append(s)
    return AndFilter(tuple(flat))
