"""Star-Schema Benchmark (O'Neil et al.): generator + the 13 queries.

The reference is validated on a TPC-H-flavored denormalized star
(SURVEY.md §5: `orderLineItemPartSupplier` registered once as the plain
source DF and once as the druid-backed relation, plus the individual star
tables); SSB is the standardized form of that same workload and the
driver's north-star metric (BASELINE.json:2: SSB SF100 Q1.1–Q4.3 < 500 ms
p50). This module plays the role of the reference's test fixture AND its
benchmark harness data: `generate_tables` builds the four dimension tables
+ the lineorder fact at a row count of choice (SF1 ≈ 6M lineorder rows),
`denormalize` produces the wide fact (the "Druid datasource"), and
`register_ssb` wires both into an Engine with the declared star schema so
join queries collapse (SURVEY.md §4.3).

All monetary columns are int64 so SUM parity between the device path and
the pandas fallback is exact (SURVEY.md §8.4 #2: float summation order is
the parity hazard — integers dodge it wherever the benchmark allows).
"""

from __future__ import annotations

import numpy as np
import pandas as pd

from tpu_olap.catalog.star import (FunctionalDependency, StarDimension,
                                   StarSchema)

# TPC-H / SSB region -> nations mapping (5 × 5)
_REGION_NATIONS = {
    "AFRICA": ["ALGERIA", "ETHIOPIA", "KENYA", "MOROCCO", "MOZAMBIQUE"],
    "AMERICA": ["ARGENTINA", "BRAZIL", "CANADA", "PERU", "UNITED STATES"],
    "ASIA": ["CHINA", "INDIA", "INDONESIA", "JAPAN", "VIETNAM"],
    "EUROPE": ["FRANCE", "GERMANY", "ROMANIA", "RUSSIA", "UNITED KINGDOM"],
    "MIDDLE EAST": ["EGYPT", "IRAN", "IRAQ", "JORDAN", "SAUDI ARABIA"],
}
_NATIONS = [n for ns in _REGION_NATIONS.values() for n in ns]
_REGION_OF = {n: r for r, ns in _REGION_NATIONS.items() for n in ns}
# SSB: city = first 9 chars of nation (space-padded) + digit 0-9
_CITIES = [f"{n[:9]:<9}{i}" for n in _NATIONS for i in range(10)]
_CITY_NATION = {c: n for n in _NATIONS for c in
                [f"{n[:9]:<9}{i}" for i in range(10)]}

_MONTHS = ["January", "February", "March", "April", "May", "June", "July",
           "August", "September", "October", "November", "December"]
_SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "HOUSEHOLD", "MACHINERY"]


def _city_probs() -> np.ndarray:
    """City sampling weights. Q3.3/Q3.4 filter on the specific cities
    'UNITED KI1'/'UNITED KI5'; at sub-SF1 row counts a uniform 1/250 city
    distribution leaves them empty, so those two cities carry extra mass
    (the fixture's job is query coverage, not dbgen distribution
    fidelity)."""
    p = np.ones(len(_CITIES))
    for i, c in enumerate(_CITIES):
        if c in ("UNITED KI1", "UNITED KI5"):
            p[i] = len(_CITIES) * 0.06  # ~6% each
    return p / p.sum()


def _date_table() -> pd.DataFrame:
    """SSB `date` dimension: one row per day, 1992-01-01 .. 1998-12-31."""
    days = pd.date_range("1992-01-01", "1998-12-31", freq="D")
    month_abbr = days.strftime("%b")
    return pd.DataFrame({
        "d_datekey": (days.year * 10000 + days.month * 100
                      + days.day).astype(np.int64),
        "d_date": days.strftime("%B %d, %Y"),
        "d_dayofweek": days.day_name(),
        "d_month": [_MONTHS[m - 1] for m in days.month],
        "d_year": days.year.astype(np.int64),
        "d_yearmonthnum": (days.year * 100 + days.month).astype(np.int64),
        "d_yearmonth": month_abbr + days.year.astype(str),
        "d_daynuminweek": days.dayofweek.astype(np.int64) + 1,
        "d_daynuminmonth": days.day.astype(np.int64),
        "d_daynuminyear": days.dayofyear.astype(np.int64),
        "d_monthnuminyear": days.month.astype(np.int64),
        "d_weeknuminyear": ((days.dayofyear - 1) // 7 + 1).astype(np.int64),
    })


def generate_tables(lineorder_rows: int = 60_000, seed: int = 0,
                    customers: int | None = None,
                    suppliers: int | None = None,
                    parts: int | None = None) -> dict:
    """Build the 5 SSB tables. Default table sizes scale with the fact the
    way SF does (SF1: 6M lineorder, 30k customers, 2k suppliers, 200k
    parts)."""
    rng = np.random.default_rng(seed)
    n = lineorder_rows
    n_cust = customers or max(200, n // 200)
    n_supp = suppliers or max(150, n // 3000)
    n_part = parts or max(500, n // 30)
    dims = _gen_dimensions(rng, n_cust, n_supp, n_part)
    dims["lineorder"] = _gen_lineorder(
        rng, n, n_cust, n_supp, n_part,
        dims["date"]["d_datekey"].to_numpy(), start_key=1)
    return dims


def _gen_dimensions(rng, n_cust: int, n_supp: int, n_part: int) -> dict:
    date = _date_table()

    city_p = _city_probs()
    ci = rng.choice(len(_CITIES), n_cust, p=city_p)
    c_city = np.asarray(_CITIES, object)[ci]
    customer = pd.DataFrame({
        "c_custkey": np.arange(1, n_cust + 1, dtype=np.int64),
        "c_name": [f"Customer#{i:09d}" for i in range(1, n_cust + 1)],
        "c_city": c_city,
        "c_nation": [_CITY_NATION[c] for c in c_city],
        "c_region": [_REGION_OF[_CITY_NATION[c]] for c in c_city],
        "c_mktsegment": rng.choice(_SEGMENTS, n_cust),
    })

    si = rng.choice(len(_CITIES), n_supp, p=city_p)
    s_city = np.asarray(_CITIES, object)[si]
    supplier = pd.DataFrame({
        "s_suppkey": np.arange(1, n_supp + 1, dtype=np.int64),
        "s_name": [f"Supplier#{i:09d}" for i in range(1, n_supp + 1)],
        "s_city": s_city,
        "s_nation": [_CITY_NATION[c] for c in s_city],
        "s_region": [_REGION_OF[_CITY_NATION[c]] for c in s_city],
    })

    a = rng.integers(1, 6, n_part)        # mfgr digit
    b = rng.integers(1, 6, n_part)        # category digit
    c = rng.integers(1, 41, n_part)       # brand number (1..40, unpadded)
    part = pd.DataFrame({
        "p_partkey": np.arange(1, n_part + 1, dtype=np.int64),
        "p_mfgr": [f"MFGR#{x}" for x in a],
        "p_category": [f"MFGR#{x}{y}" for x, y in zip(a, b)],
        "p_brand1": [f"MFGR#{x}{y}{z}" for x, y, z in zip(a, b, c)],
        "p_size": rng.integers(1, 51, n_part).astype(np.int64),
    })

    return {"date": date, "customer": customer,
            "supplier": supplier, "part": part}


def _gen_lineorder(rng, n: int, n_cust: int, n_supp: int, n_part: int,
                   datekeys: np.ndarray, start_key: int) -> pd.DataFrame:
    quantity = rng.integers(1, 51, n).astype(np.int64)
    discount = rng.integers(0, 11, n).astype(np.int64)
    extendedprice = rng.integers(90_000, 10_000_000, n).astype(np.int64)
    return pd.DataFrame({
        "lo_orderkey": np.arange(start_key, start_key + n, dtype=np.int64),
        "lo_custkey": rng.integers(1, n_cust + 1, n).astype(np.int64),
        "lo_partkey": rng.integers(1, n_part + 1, n).astype(np.int64),
        "lo_suppkey": rng.integers(1, n_supp + 1, n).astype(np.int64),
        "lo_orderdate": datekeys[rng.integers(0, len(datekeys), n)],
        "lo_quantity": quantity,
        "lo_discount": discount,
        "lo_extendedprice": extendedprice,
        "lo_revenue": extendedprice * (100 - discount) // 100,
        "lo_supplycost": rng.integers(50_000, 6_000_000, n).astype(np.int64),
        "lo_tax": rng.integers(0, 9, n).astype(np.int64),
        "lo_shipmode": rng.choice(
            ["AIR", "FOB", "MAIL", "RAIL", "REG AIR", "SHIP", "TRUCK"], n),
    })


def write_ssb_parquet(out_dir: str, lineorder_rows: int, seed: int = 0,
                      chunk_rows: int = 2_000_000,
                      row_group_rows: int = 1 << 18) -> tuple[list, dict]:
    """Generate the denormalized SSB fact as a multi-file parquet dataset
    in bounded-memory chunks (the SF10/SF100 generation path — a whole
    SF10 denormalized frame would not be polite to host RAM, and the
    row-group structure is what ingest_parquet_stream streams over).

    Returns (fact parquet paths, dimension tables dict)."""
    import os

    import pyarrow as pa
    import pyarrow.parquet as pq

    n = lineorder_rows
    n_cust = max(200, n // 200)
    n_supp = max(150, n // 3000)
    n_part = max(500, n // 30)
    rng = np.random.default_rng(seed)
    dims = _gen_dimensions(rng, n_cust, n_supp, n_part)
    datekeys = dims["date"]["d_datekey"].to_numpy()

    os.makedirs(out_dir, exist_ok=True)
    paths = []
    start = 1
    chunk_idx = 0
    while start <= n:
        m = min(chunk_rows, n - start + 1)
        crng = np.random.default_rng((seed, 7919, chunk_idx))
        fact = _gen_lineorder(crng, m, n_cust, n_supp, n_part, datekeys,
                              start_key=start)
        chunk = denormalize({"lineorder": fact, **dims})
        path = os.path.join(out_dir, f"lineorder-{chunk_idx:05d}.parquet")
        pq.write_table(pa.Table.from_pandas(chunk, preserve_index=False),
                       path, row_group_size=row_group_rows)
        paths.append(path)
        start += m
        chunk_idx += 1
    return paths, dims


def register_ssb_parquet(engine, paths, dims: dict,
                         block_rows: int | None = None):
    """Register a write_ssb_parquet dataset: the fact streams row-group
    batches into segments; dimension tables stay fallback-only."""
    kw = {"block_rows": block_rows} if block_rows else {}
    engine.register_table("lineorder", list(paths), time_column=TIME_COL,
                          star_schema=star_schema(), **kw)
    for t in ("date", "customer", "supplier", "part"):
        engine.register_table(t, dims[t], accelerate=False)


# dimension attributes carried onto the denormalized fact ("the Druid
# datasource" — the reference denormalizes the star the same way, §1)
_DENORM_COLS = {
    "date": ("lo_orderdate", "d_datekey",
             ["d_year", "d_yearmonthnum", "d_yearmonth", "d_weeknuminyear",
              "d_month", "d_monthnuminyear"]),
    "customer": ("lo_custkey", "c_custkey",
                 ["c_city", "c_nation", "c_region", "c_mktsegment"]),
    "supplier": ("lo_suppkey", "s_suppkey",
                 ["s_city", "s_nation", "s_region"]),
    "part": ("lo_partkey", "p_partkey",
             ["p_mfgr", "p_category", "p_brand1"]),
}

TIME_COL = "lo_orderdate_ts"


def denormalize(tables: dict) -> pd.DataFrame:
    df = tables["lineorder"]
    for t, (fk, pk, cols) in _DENORM_COLS.items():
        df = df.merge(tables[t][[pk] + cols], left_on=fk, right_on=pk,
                      how="left").drop(columns=[pk])
    df[TIME_COL] = pd.to_datetime(df["lo_orderdate"].astype(str),
                                  format="%Y%m%d")
    return df


def star_schema() -> StarSchema:
    return StarSchema(
        fact="lineorder",
        dimensions=tuple(
            StarDimension(t, fk, pk)
            for t, (fk, pk, _) in _DENORM_COLS.items()),
        functional_dependencies=(
            FunctionalDependency("c_city", "c_nation"),
            FunctionalDependency("c_nation", "c_region"),
            FunctionalDependency("s_city", "s_nation"),
            FunctionalDependency("s_nation", "s_region"),
            FunctionalDependency("p_brand1", "p_category"),
            FunctionalDependency("p_category", "p_mfgr"),
            FunctionalDependency("d_datekey", "d_year"),
        ))


def register_ssb(engine, tables: dict | None = None,
                 lineorder_rows: int = 60_000, seed: int = 0,
                 block_rows: int | None = None):
    """Register the denormalized fact (accelerated, star-declared) plus the
    four dimension tables (fallback-only) — the reference's double
    registration of its test fixture (SURVEY.md §5)."""
    tables = tables or generate_tables(lineorder_rows, seed)
    denorm = denormalize(tables)
    kw = {"block_rows": block_rows} if block_rows else {}
    engine.register_table("lineorder", denorm, time_column=TIME_COL,
                          star_schema=star_schema(), **kw)
    for t in ("date", "customer", "supplier", "part"):
        engine.register_table(t, tables[t], accelerate=False)
    return tables, denorm


# --------------------------------------------------------------------------
# The 13 SSB queries (O'Neil et al. 2009), in the engine's SQL dialect.
# Join order/conditions follow the published text; filters reference the
# dimension attributes, which the planner renames onto the denormalized
# fact after star-join collapse (SURVEY.md §4.3).

QUERIES = {
    "q1.1": """
        SELECT sum(lo_extendedprice * lo_discount) AS revenue
        FROM lineorder JOIN date ON lo_orderdate = d_datekey
        WHERE d_year = 1993 AND lo_discount BETWEEN 1 AND 3
          AND lo_quantity < 25
    """,
    "q1.2": """
        SELECT sum(lo_extendedprice * lo_discount) AS revenue
        FROM lineorder JOIN date ON lo_orderdate = d_datekey
        WHERE d_yearmonthnum = 199401 AND lo_discount BETWEEN 4 AND 6
          AND lo_quantity BETWEEN 26 AND 35
    """,
    "q1.3": """
        SELECT sum(lo_extendedprice * lo_discount) AS revenue
        FROM lineorder JOIN date ON lo_orderdate = d_datekey
        WHERE d_weeknuminyear = 6 AND d_year = 1994
          AND lo_discount BETWEEN 5 AND 7
          AND lo_quantity BETWEEN 26 AND 35
    """,
    "q2.1": """
        SELECT sum(lo_revenue) AS revenue, d_year, p_brand1
        FROM lineorder
          JOIN date ON lo_orderdate = d_datekey
          JOIN part ON lo_partkey = p_partkey
          JOIN supplier ON lo_suppkey = s_suppkey
        WHERE p_category = 'MFGR#12' AND s_region = 'AMERICA'
        GROUP BY d_year, p_brand1
        ORDER BY d_year, p_brand1
    """,
    "q2.2": """
        SELECT sum(lo_revenue) AS revenue, d_year, p_brand1
        FROM lineorder
          JOIN date ON lo_orderdate = d_datekey
          JOIN part ON lo_partkey = p_partkey
          JOIN supplier ON lo_suppkey = s_suppkey
        WHERE p_brand1 BETWEEN 'MFGR#2221' AND 'MFGR#2228'
          AND s_region = 'ASIA'
        GROUP BY d_year, p_brand1
        ORDER BY d_year, p_brand1
    """,
    "q2.3": """
        SELECT sum(lo_revenue) AS revenue, d_year, p_brand1
        FROM lineorder
          JOIN date ON lo_orderdate = d_datekey
          JOIN part ON lo_partkey = p_partkey
          JOIN supplier ON lo_suppkey = s_suppkey
        WHERE p_brand1 = 'MFGR#2239' AND s_region = 'EUROPE'
        GROUP BY d_year, p_brand1
        ORDER BY d_year, p_brand1
    """,
    "q3.1": """
        SELECT c_nation, s_nation, d_year, sum(lo_revenue) AS revenue
        FROM lineorder
          JOIN customer ON lo_custkey = c_custkey
          JOIN supplier ON lo_suppkey = s_suppkey
          JOIN date ON lo_orderdate = d_datekey
        WHERE c_region = 'ASIA' AND s_region = 'ASIA'
          AND d_year >= 1992 AND d_year <= 1997
        GROUP BY c_nation, s_nation, d_year
        ORDER BY d_year ASC, revenue DESC
    """,
    "q3.2": """
        SELECT c_city, s_city, d_year, sum(lo_revenue) AS revenue
        FROM lineorder
          JOIN customer ON lo_custkey = c_custkey
          JOIN supplier ON lo_suppkey = s_suppkey
          JOIN date ON lo_orderdate = d_datekey
        WHERE c_nation = 'UNITED STATES' AND s_nation = 'UNITED STATES'
          AND d_year >= 1992 AND d_year <= 1997
        GROUP BY c_city, s_city, d_year
        ORDER BY d_year ASC, revenue DESC
    """,
    "q3.3": """
        SELECT c_city, s_city, d_year, sum(lo_revenue) AS revenue
        FROM lineorder
          JOIN customer ON lo_custkey = c_custkey
          JOIN supplier ON lo_suppkey = s_suppkey
          JOIN date ON lo_orderdate = d_datekey
        WHERE (c_city = 'UNITED KI1' OR c_city = 'UNITED KI5')
          AND (s_city = 'UNITED KI1' OR s_city = 'UNITED KI5')
          AND d_year >= 1992 AND d_year <= 1997
        GROUP BY c_city, s_city, d_year
        ORDER BY d_year ASC, revenue DESC
    """,
    "q3.4": """
        SELECT c_city, s_city, d_year, sum(lo_revenue) AS revenue
        FROM lineorder
          JOIN customer ON lo_custkey = c_custkey
          JOIN supplier ON lo_suppkey = s_suppkey
          JOIN date ON lo_orderdate = d_datekey
        WHERE (c_city = 'UNITED KI1' OR c_city = 'UNITED KI5')
          AND (s_city = 'UNITED KI1' OR s_city = 'UNITED KI5')
          AND d_yearmonth = 'Dec1997'
        GROUP BY c_city, s_city, d_year
        ORDER BY d_year ASC, revenue DESC
    """,
    "q4.1": """
        SELECT d_year, c_nation, sum(lo_revenue - lo_supplycost) AS profit
        FROM lineorder
          JOIN date ON lo_orderdate = d_datekey
          JOIN customer ON lo_custkey = c_custkey
          JOIN supplier ON lo_suppkey = s_suppkey
          JOIN part ON lo_partkey = p_partkey
        WHERE c_region = 'AMERICA' AND s_region = 'AMERICA'
          AND (p_mfgr = 'MFGR#1' OR p_mfgr = 'MFGR#2')
        GROUP BY d_year, c_nation
        ORDER BY d_year, c_nation
    """,
    "q4.2": """
        SELECT d_year, s_nation, p_category,
               sum(lo_revenue - lo_supplycost) AS profit
        FROM lineorder
          JOIN date ON lo_orderdate = d_datekey
          JOIN customer ON lo_custkey = c_custkey
          JOIN supplier ON lo_suppkey = s_suppkey
          JOIN part ON lo_partkey = p_partkey
        WHERE c_region = 'AMERICA' AND s_region = 'AMERICA'
          AND (d_year = 1997 OR d_year = 1998)
          AND (p_mfgr = 'MFGR#1' OR p_mfgr = 'MFGR#2')
        GROUP BY d_year, s_nation, p_category
        ORDER BY d_year, s_nation, p_category
    """,
    "q4.3": """
        SELECT d_year, s_city, p_brand1,
               sum(lo_revenue - lo_supplycost) AS profit
        FROM lineorder
          JOIN date ON lo_orderdate = d_datekey
          JOIN customer ON lo_custkey = c_custkey
          JOIN supplier ON lo_suppkey = s_suppkey
          JOIN part ON lo_partkey = p_partkey
        WHERE c_region = 'AMERICA' AND s_nation = 'UNITED STATES'
          AND (d_year = 1997 OR d_year = 1998)
          AND p_category = 'MFGR#14'
        GROUP BY d_year, s_city, p_brand1
        ORDER BY d_year, s_city, p_brand1
    """,
}
