"""Benchmark workloads. `ssb` is the Star-Schema Benchmark — the
reference's target workload (BASELINE.json:2: SSB SF100 Q1.1–Q4.3) and the
direct analog of its TPC-H-flavored star test fixture (SURVEY.md §5)."""

from tpu_olap.bench.ssb import (QUERIES, denormalize, generate_tables,
                                register_ssb, register_ssb_parquet,
                                star_schema, write_ssb_parquet)
from tpu_olap.bench.parity import (assert_frame_parity, check_query,
                                   run_both)

__all__ = ["QUERIES", "denormalize", "generate_tables", "register_ssb",
           "register_ssb_parquet", "star_schema", "write_ssb_parquet",
           "assert_frame_parity", "check_query", "run_both"]
