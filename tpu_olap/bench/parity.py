"""Result-parity harness: device path vs pandas fallback on the same SQL.

The direct analog of the reference's live-Druid parity tests (SURVEY.md §5:
druid-path results vs fallback-path results on identical data) and of the
driver's "result parity" metric (BASELINE.json:2). Tolerance rules per
query class (SURVEY.md §8.4 #2): exact for integers/strings/row sets,
relative float tolerance for float accumulations (summation order differs
between XLA tree reduction and pandas), and a wide relative band for
HLL/theta approximate count-distinct columns.
"""

from __future__ import annotations

import numpy as np
import pandas as pd

from tpu_olap.planner.fallback import execute_fallback


class ParityError(AssertionError):
    pass


def _f64(s: pd.Series) -> np.ndarray:
    """float64 view with any NA flavor (np.nan, pd.NA, None) -> NaN."""
    if pd.api.types.is_extension_array_dtype(s.dtype):
        return s.to_numpy(dtype=np.float64, na_value=np.nan)
    if s.dtype == object:
        return np.asarray([np.nan if pd.isna(v) else float(v) for v in s],
                          dtype=np.float64)
    return s.to_numpy(dtype=np.float64)


def pure_config(config):
    """The oracle-side config: derived-table bodies stay on the pandas
    interpreter so the fallback is an INDEPENDENT execution — never a
    re-run of the device path it is checking."""
    import dataclasses
    if not getattr(config, "fallback_derived_on_device", False):
        return config
    return dataclasses.replace(config, fallback_derived_on_device=False)


def run_both(engine, sql: str):
    """Execute `sql` on the accelerated path AND the fallback interpreter.
    Returns (device_df, fallback_df, plan). Raises if the planner did not
    rewrite (use engine.sql alone for fallback-only shapes)."""
    device = engine.sql(sql)
    plan = engine.last_plan
    if not plan.rewritten:
        raise ParityError(
            f"query did not stay on the device path: {plan.fallback_reason}")
    fb = execute_fallback(plan.stmt, engine.catalog,
                          pure_config(engine.config))
    return device, fb, plan


def assert_frame_parity(a: pd.DataFrame, b: pd.DataFrame,
                        float_rtol: float = 1e-9, float_atol: float = 1e-6,
                        approx_cols: tuple = (), approx_rtol: float = 0.12,
                        ordered: bool = False, label: str = ""):
    """Compare two result frames column-wise. When `ordered` is False the
    frames are canonically re-sorted by every exact column first (ORDER BY
    ties may legally differ between paths)."""
    tag = f"[{label}] " if label else ""
    if list(a.columns) != list(b.columns):
        raise ParityError(f"{tag}column sets differ: "
                          f"{list(a.columns)} vs {list(b.columns)}")
    if len(a) != len(b):
        raise ParityError(f"{tag}row counts differ: {len(a)} vs {len(b)}")
    if len(a) == 0:
        return
    a = a.reset_index(drop=True)
    b = b.reset_index(drop=True)

    def is_float(s):
        return pd.api.types.is_float_dtype(s)

    if not ordered:
        keys = [c for c in a.columns
                if not is_float(a[c]) and c not in approx_cols]
        quantized = not keys
        if quantized:
            # all-float frame: sort by scale-relative quantized keys so
            # path-dependent summation jitter (well inside float_rtol)
            # cannot flip the canonical order and misalign rows
            keys = list(a.columns)

        def canon(df):
            sk = df[keys]
            if quantized:
                scale = sk.abs().max().replace(0, 1.0)
                sk = (sk / scale).round(7)
            idx = sk.sort_values(keys, kind="stable").index
            return df.loc[idx].reset_index(drop=True)

        a, b = canon(a), canon(b)

    for c in a.columns:
        av, bv = a[c], b[c]
        if c in approx_cols:
            x = av.to_numpy(dtype=np.float64)
            y = bv.to_numpy(dtype=np.float64)
            bad = ~np.isclose(x, y, rtol=approx_rtol, atol=2.0)
            if bad.any():
                i = int(np.argmax(bad))
                raise ParityError(
                    f"{tag}approx column {c!r} out of band at row {i}: "
                    f"{x[i]} vs {y[i]} (rtol={approx_rtol})")
            continue
        if is_float(av) or is_float(bv):
            x = _f64(av)
            y = _f64(bv)
            both_nan = np.isnan(x) & np.isnan(y)
            bad = ~(np.isclose(x, y, rtol=float_rtol, atol=float_atol)
                    | both_nan)
            if bad.any():
                i = int(np.argmax(bad))
                raise ParityError(
                    f"{tag}float column {c!r} mismatch at row {i}: "
                    f"{x[i]} vs {y[i]}")
            continue
        if pd.api.types.is_datetime64_any_dtype(av) or \
                pd.api.types.is_datetime64_any_dtype(bv):
            if not (pd.to_datetime(av).reset_index(drop=True)
                    .equals(pd.to_datetime(bv).reset_index(drop=True))):
                raise ParityError(f"{tag}datetime column {c!r} mismatch")
            continue
        # NOT Series.where(cond, None): pandas treats other=None as "use
        # the default fill" (NaN), so nulls would survive and nan != nan
        xa = [None if pd.isna(v) else v for v in av]
        xb = [None if pd.isna(v) else v for v in bv]
        for i, (va, vb) in enumerate(zip(xa, xb)):
            if va != vb:
                raise ParityError(
                    f"{tag}column {c!r} mismatch at row {i}: "
                    f"{va!r} vs {vb!r}")


def check_query(engine, sql: str, approx_cols: tuple = (),
                ordered: bool = False, label: str = "", **tol):
    """run_both + assert_frame_parity in one call; returns the device frame."""
    device, fb, _ = run_both(engine, sql)
    assert_frame_parity(device, fb, approx_cols=approx_cols,
                        ordered=ordered, label=label, **tol)
    return device
