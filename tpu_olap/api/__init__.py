from tpu_olap.api.engine import Engine  # noqa: F401
