"""HTTP query server — the BI-connectivity analog of the reference's
ThriftServer wrapper (SURVEY.md §3.1: "Lets Tableau/BI tools hit
accelerated tables over JDBC/ODBC").

JDBC/ODBC is JVM plumbing with no TPU-native counterpart; the idiomatic
equivalent is a JSON-over-HTTP surface (stdlib only, no new deps):

  POST /sql          {"query": "SELECT ..."}      -> {columns, rows}
                     (statement verbs work too: CLEAR DRUID CACHE,
                     EXPLAIN ANALYZE, ...; the response carries an
                     X-Query-Id header correlating it with
                     /debug/queries, sys.queries, and Perfetto traces —
                     /sql/batch returns a comma-separated id list)
  POST /druid/v2     native Druid query JSON      -> Druid-wire results
                     (the raw-IR passthrough, SURVEY.md §4.5 — lets
                     existing Druid clients talk to the TPU engine)
  POST /ingest       {"table": t, "rows": [{...}, ...]} -> real-time
                     append (Engine.append; docs/INGEST.md): rows are
                     queryable immediately, WAL-durable before the 200,
                     and a full delta sheds with 429 + Retry-After
  GET  /debug/ingest real-time ingest state: per-table delta sizes,
                     watermarks, WAL bytes/lag, compactor state, the
                     measured drain rate behind 429 Retry-After, and
                     durable-checkpoint store stats (manifest id, WAL
                     watermark, spilled bytes — docs/DURABILITY.md;
                     the SQL spelling is SELECT * FROM sys.checkpoints)
  GET  /status       engine + per-table summary + counters
  GET  /status/metadata/<table>  column metadata (segmentMetadata shape)
  GET  /metrics      Prometheus text exposition (tpu_olap.obs.metrics:
                     latency histograms by query_type/path, scan/cache/
                     retry counters, HBM ledger gauges, resilience
                     gauges/counters, pipelined-execution series —
                     dispatch_lock_wait_ms, pipeline_inflight,
                     inflight_transfers)
  GET  /debug/queries  recent span trees + the slow-query log ring
                     (EngineConfig.slow_query_ms; docs/OBSERVABILITY.md)
  GET  /debug/events   the structured event log ring, newest first
                     (query/breaker/shed/cache_clear/ingest events;
                     ?n= bounds the count)
  GET  /debug/profile  recent traces exported as Chrome-trace JSON —
                     loads directly in Perfetto (?n= bounds traces)
  GET  /debug/cache  semantic result-cache state: per-tier entries/
                     bytes/hits/misses/evictions + per-table ingest
                     generations (docs/CACHING.md)
  GET  /debug/cubes  materialized rollup cubes (tpu_olap.cubes):
                     per cube dims/grain/rows, base-vs-cube generation,
                     last refresh, build cost, and rewrite serve counts
                     — the SQL spelling is SELECT * FROM sys.cubes
  GET  /debug/devices  per-chip serving state (executor/sharding.py):
                     interleaved segment placement, resident bytes,
                     dispatch participation, tier-1 cache-shard entries
                     — the SQL spelling is SELECT * FROM sys.devices
  GET  /debug/workload  the query-template profiler (obs.workload):
                     top templates with latency percentiles and cache
                     hit-rates, plus ranked rollup-cube recommendations
                     — the SQL spelling is SELECT ... FROM
                     sys.query_templates (docs/OBSERVABILITY.md)
  POST /debug/profile?ms=N
                     on-demand jax.profiler capture for N ms (capped);
                     dispatches inside the window are annotated with
                     their query_id. Degrades to {"ok": false, ...}
                     where the profiler is unavailable.
  GET  /healthz      liveness: 200 while the process serves requests
  GET  /readyz       readiness: 503 while the device circuit breaker is
                     open or the device is wedged — tells a load
                     balancer to stop ROUTING to a sick replica instead
                     of queueing onto it (docs/RESILIENCE.md)

Error contract (docs/RESILIENCE.md): failures carry the structured
taxonomy (tpu_olap.resilience.errors) — the body is {"error", "code",
"retriable"} and the status distinguishes retry-later from
your-request-is-wrong:

  400  user error (bad SQL / unknown path / unsupported statement)
  429  admission shed (dispatch queue full or deadline budget < wait)
  503  circuit breaker open (Retry-After: cooldown remaining)
  504  query deadline exceeded with no fallback available
  500  internal / unclassified

Concurrency: requests run on ThreadingHTTPServer threads; device
dispatch admission is bounded (EngineConfig.max_inflight_dispatches /
admission_queue_limit) so a traffic spike sheds with 429 instead of
piling unboundedly onto the device lock. stop() drains gracefully:
stops accepting, waits for in-flight handlers up to a bounded timeout,
then force-closes.
"""

from __future__ import annotations

import json
import math
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pandas as pd

from tpu_olap.resilience.errors import QueryError, UserError


def _parse_query(path: str) -> dict:
    """Query-string dict of a request path ({} when none)."""
    if "?" not in path:
        return {}
    from urllib.parse import parse_qs
    return parse_qs(path.split("?", 1)[1])


def _int_param(qs: dict, names, cap: int | None = None,
               default: int | None = None) -> int | None:
    """Validated integer query param shared by the /debug endpoints
    (ISSUE 8 satellite): first present name wins, non-integers and
    negatives are rejected with a 400 UserError (not a 500 traceback),
    and values are capped (at the serving ring's size) so a client
    cannot request an unbounded response."""
    for nm in names:
        vals = qs.get(nm)
        if not vals:
            continue
        raw = vals[0]
        try:
            v = int(raw)
        except (TypeError, ValueError):
            raise UserError(
                f"query param {nm}={raw!r}: must be an integer")
        if v < 0:
            raise UserError(
                f"query param {nm}={raw!r}: must be >= 0")
        return v if cap is None else min(v, cap)
    return default


def _jsonable(x):
    """Strict-JSON sanitizer: NaN/inf and SQL nulls that surface as pandas
    scalars (NaT, pd.NA) -> JSON null; BI clients reject bare NaN/Infinity
    literals and would otherwise receive the strings "NaT"/"<NA>" via
    default=str."""
    if isinstance(x, float) and not math.isfinite(x):
        return None
    if isinstance(x, dict):
        return {k: _jsonable(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return [_jsonable(v) for v in x]
    if x is None or isinstance(x, (str, int, bool)):
        return x
    try:
        if pd.isna(x):
            return None
    except (TypeError, ValueError):
        pass
    return x


class QueryServer:
    def __init__(self, engine, host: str = "127.0.0.1", port: int = 0):
        self.engine = engine
        server = self
        # graceful-drain bookkeeping: handlers register in/out so stop()
        # can wait for mid-flight responses instead of severing them
        self._inflight = 0
        self._inflight_cond = threading.Condition()

        class Handler(BaseHTTPRequestHandler):
            # HTTP/1.1 keep-alive: a BI client (or the concurrency
            # bench) reuses one connection per thread instead of a TCP
            # handshake + accept-loop round trip per request — under
            # high client churn the single accept thread was the p99
            # tail, not the engine. Safe because every response path
            # (_send/_send_text) sets an exact Content-Length.
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet; engine.history observes
                pass

            def _send(self, code: int, payload, headers=()):
                body = json.dumps(_jsonable(payload), default=str,
                                  allow_nan=False).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in headers:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _send_query_error(self, e: QueryError):
                """Structured taxonomy mapping: status from the error,
                machine-readable body, Retry-After while the breaker
                cools down."""
                headers = []
                retry_after = getattr(e, "retry_after_s", None)
                if retry_after is not None:
                    headers.append(
                        ("Retry-After",
                         str(max(1, int(math.ceil(retry_after))))))
                self._send(e.http_status, e.to_json(), headers)

            def _body(self):
                n = int(self.headers.get("Content-Length", 0))
                return self.rfile.read(n).decode()

            def _send_text(self, code: int, text: str, content_type: str):
                body = text.encode()
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                server._enter()
                try:
                    if self.path == "/metrics":
                        # Prometheus exposition is a text format, not
                        # JSON — version 0.0.4 per the scrape protocol
                        self._send_text(
                            200, server._get_metrics(),
                            "text/plain; version=0.0.4; charset=utf-8")
                        return
                    if self.path == "/healthz":
                        self._send(200, {"status": "ok"})
                        return
                    if self.path == "/readyz":
                        ready, detail = server._readiness()
                        self._send(200 if ready else 503, detail)
                        return
                    self._send(200, server._get(self.path))
                except QueryError as e:
                    self._send_query_error(e)
                except KeyError as e:
                    self._send(404, {"error": str(e)})
                except Exception as e:
                    self._send(500, {"error": str(e)})
                finally:
                    server._leave()

            def do_POST(self):
                server._enter()
                try:
                    payload, headers = server._post(
                        self.path, self._body(),
                        traceparent=self.headers.get("traceparent"))
                    self._send(200, payload, headers)
                except QueryError as e:
                    # taxonomy first: UserError IS a ValueError and
                    # FallbackError maps to 400 through http_status, so
                    # the legacy clause below only sees untyped errors
                    self._send_query_error(e)
                except (ValueError, KeyError) as e:
                    self._send(400, {"error": str(e)})
                except Exception as e:
                    self._send(500, {"error": str(e)})
                finally:
                    server._leave()

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self.httpd.server_address
        self._thread = None

    # ------------------------------------------------------------ control

    def start(self):
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def _enter(self):
        with self._inflight_cond:
            self._inflight += 1

    def _leave(self):
        with self._inflight_cond:
            self._inflight -= 1
            if self._inflight == 0:
                self._inflight_cond.notify_all()

    def stop(self, drain_timeout_s: float = 10.0):
        """Graceful drain: stop accepting new requests, wait for
        in-flight handler threads up to `drain_timeout_s`, then
        force-close. ThreadingHTTPServer handler threads are daemonic,
        so a bare shutdown()+server_close() could sever a mid-flight
        device query's response; the drain window lets it finish."""
        self.httpd.shutdown()  # stop the accept loop (blocks until out)
        deadline = time.monotonic() + max(0.0, drain_timeout_s)
        with self._inflight_cond:
            while self._inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break  # force-close severs the stragglers, by contract
                self._inflight_cond.wait(min(remaining, 0.1))
        self.httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)
        # deterministic engine shutdown (ISSUE 13 satellite): stop and
        # JOIN the background threads the engine owns — compactor, WAL
        # flushers, cube maintainer — and flush the async event sink so
        # the tail emitted by draining handlers reaches disk before the
        # process exits. The engine stays queryable afterwards.
        self.engine.close()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    # ----------------------------------------------------------- handlers

    def _readiness(self) -> tuple[bool, dict]:
        """Readiness probe payload: not ready while the breaker is open
        (device sick, degraded serving only) or the device is wedged
        awaiting a reprobe. Liveness (/healthz) stays green either way —
        the replica is alive, it just should not receive new traffic."""
        runner = self.engine.runner
        state = runner.breaker.state
        wedged = bool(runner._wedged)
        ready = state != "open" and not wedged
        return ready, {"ready": ready, "breaker": state,
                       "wedged": wedged,
                       "admission": runner.admission.snapshot()}

    def _get(self, path: str):
        if path == "/status":
            eng = self.engine
            return {
                "engine": "tpu_olap",
                "tables": {name: {
                    "accelerated": e.is_accelerated,
                    # null until the lazy fallback frame materializes —
                    # a monitoring ping must not force a parquet load
                    "numRows": (e.segments.num_rows if e.is_accelerated
                                else e.materialized_rows),
                } for name, e in ((n, eng.catalog.get(n))
                                  for n in eng.catalog.names())},
                "counters": eng.counters(),
                "resilience": {
                    "breaker": eng.runner.breaker.state,
                    "wedged": bool(eng.runner._wedged),
                    "admission": eng.runner.admission.snapshot(),
                },
                "slo": eng.runner.slo.snapshot(),
                "stages": eng.runner.stages.snapshot(),
                "device_bytes": eng.runner.device_bytes_by_table(),
            }
        if path.startswith("/status/metadata/"):
            name = path.rsplit("/", 1)[1]
            entry = self.engine.catalog.get(name)
            if not entry.is_accelerated:
                return {"table": name, "accelerated": False}
            return {"table": name,
                    "columns": entry.segments.column_metadata()}
        if path == "/debug/queries" or path.startswith("/debug/queries?"):
            limit = _int_param(_parse_query(path), ("n", "limit"),
                               cap=self.engine.tracer.ring_limit)
            return self.engine.tracer.snapshot(limit)
        if path == "/debug/events" or path.startswith("/debug/events?"):
            ev = self.engine.runner.events
            n = _int_param(_parse_query(path), ("n", "limit"),
                           cap=ev.limit)
            out = {"limit": ev.limit, "events": ev.snapshot(n)}
            if ev.path is not None:
                out["sink"] = {"path": ev.path,
                               "errors": ev.sink_errors}
            return out
        if path == "/debug/profile" or path.startswith("/debug/profile?"):
            # span-tree timelines in Chrome-trace JSON (obs.profile):
            # save the body to a file and open it in Perfetto
            from tpu_olap.obs.profile import chrome_trace
            n = _int_param(_parse_query(path), ("n", "limit"),
                           cap=self.engine.tracer.ring_limit)
            return chrome_trace(self.engine.tracer.recent_traces(n))
        if path == "/debug/workload" or path.startswith("/debug/workload?"):
            # the workload profiler (obs.workload; ISSUE 11): top query
            # templates by count plus the cube advisor's ranked rollup
            # recommendations — the same signal as SELECT ... FROM
            # sys.query_templates, without going through SQL. ?n= bounds
            # the template rows (default 20); recommendations always
            # rank over the full template set.
            from tpu_olap.obs.workload import recommend_rollups
            prof = self.engine.runner.workload
            n = _int_param(_parse_query(path), ("n", "limit"),
                           default=20)
            rows = prof.snapshot()
            return {"totals": prof.totals(),
                    "templates": rows[:n] if n else rows,
                    "recommendations": recommend_rollups(rows)}
        if path == "/debug/cubes" or path.startswith("/debug/cubes?"):
            # materialized rollup cubes (tpu_olap.cubes; docs/CUBES.md):
            # per cube name/base/dims/grain/rows, base-vs-cube
            # generation (stale detection), last refresh, build cost,
            # and rewrite serve counts — the SQL spelling is
            # SELECT * FROM sys.cubes
            eng = self.engine
            return {"enabled": bool(eng.config.cube_rewrite_enabled),
                    "auto_refresh": bool(eng.config.cube_auto_refresh),
                    "cubes": eng.cubes.snapshot()}
        if path == "/debug/devices" or path.startswith("/debug/devices?"):
            # per-chip serving state (executor/sharding.py): interleaved
            # segment placement, resident bytes, dispatch participation,
            # tier-1 cache-shard entries, incremental re-place stats —
            # the SQL spelling is SELECT * FROM sys.devices
            eng = self.engine
            return {"num_shards": int(eng.config.num_shards or 1),
                    "devices": eng.runner.device_snapshot()}
        if path == "/debug/ingest" or path.startswith("/debug/ingest?"):
            # real-time ingest state (segments/delta.py;
            # docs/INGEST.md): per-table delta rows/segments, sealed
            # watermark, WAL bytes + fsync lag, compactor state — the
            # SQL spelling of the per-segment half is
            # SELECT * FROM sys.segments (kind/watermark columns)
            return self.engine.ingest.snapshot()
        if path == "/debug/timeseries" \
                or path.startswith("/debug/timeseries?"):
            # the telemetry plane's metrics history (obs.timeseries;
            # ISSUE 17): bounded per-series rings sampled from the
            # metrics registry on the background telemetry graph. ?n=
            # caps points per series — the SQL spelling is
            # SELECT * FROM sys.metrics_history
            n = _int_param(_parse_query(path), ("n", "limit"))
            return self.engine.runner.telemetry.snapshot(
                limit_per_series=n)
        if path == "/debug/health" or path.startswith("/debug/health?"):
            # regression-sentinel verdict (obs.sentinel; ISSUE 17):
            # ok=false while any structured alert (latency drift with
            # stage attribution, HBM pressure, eviction thrash, WAL
            # lag, open breaker, admission sheds) is active — the SQL
            # spelling is SELECT * FROM sys.alerts. Always HTTP 200:
            # /readyz answers "can I serve", this answers "am I well"
            return self.engine.runner.sentinel.health()
        if path == "/debug/cache" or path.startswith("/debug/cache?"):
            # semantic result-cache state (executor.resultcache;
            # docs/CACHING.md): per-tier entries/bytes/hit counters plus
            # each accelerated table's live ingest generation — the key
            # component that invalidates both tiers
            eng = self.engine
            snap = eng.runner.result_cache.snapshot()
            snap["generations"] = {
                n: eng.catalog.get(n).segments.generation
                for n in eng.catalog.names()
                if eng.catalog.get(n).is_accelerated}
            return snap
        raise KeyError(f"unknown path {path!r}")

    def _get_metrics(self) -> str:
        """GET /metrics: refresh the point-in-time gauges from engine
        state (counters/histograms are maintained incrementally at query
        completion — QueryRunner.record), then render the registry."""
        eng = self.engine
        m = eng.metrics
        ledger = eng.runner._hbm_ledger
        m.gauge("hbm_bytes_in_use").set(ledger.bytes_in_use)
        eng.runner._m_hbm_evict.set_total(ledger.evictions)
        m.gauge("history_records",
                "Records retained in the bounded history ring.") \
            .set(len(eng.runner.history))
        m.gauge("tables_registered").set(len(eng.catalog.names()))
        # memory/cache gauges + the SLO burn rate are point-in-time:
        # walk resident buffers and re-prune the SLO window at scrape,
        # not per query
        eng.runner.refresh_resource_gauges()
        m.gauge("slo_burn_rate").set(eng.runner.slo.burn_rate())
        return m.render()

    def _post(self, path: str, body: str, traceparent: str | None = None):
        """(payload, headers) for a POST. /sql and /sql/batch answer
        with an X-Query-Id header (ISSUE 11 satellite) so a client can
        correlate a response with /debug/queries, SELECT ... FROM
        sys.queries, and Perfetto traces. A valid W3C `traceparent`
        request header (ISSUE 17) joins the query records and span
        trees to the caller's distributed trace and is echoed back on
        the response; an invalid one is ignored, never an error."""
        from tpu_olap.obs.trace import parse_traceparent
        tp = parse_traceparent(traceparent)
        tp_headers = [("traceparent", tp["traceparent"])] if tp else []
        if path == "/sql":
            req = json.loads(body)
            frame, trace = self.engine._sql_traced(
                req["query"], traceparent=traceparent)
            headers = [("X-Query-Id", trace.query_id)] \
                if trace is not None else []
            return {"columns": list(frame.columns),
                    "rows": frame.to_dict("records")}, \
                headers + tp_headers
        if path == "/sql/batch":
            # explicit batch submission: one POST, N statements, shared
            # scans where compatible (Engine.sql_batch / executor.batch)
            req = json.loads(body)
            frames, qids = self.engine.sql_batch_ids(
                req["queries"], traceparent=traceparent)
            return {"results": [{"columns": list(f.columns),
                                 "rows": f.to_dict("records")}
                                for f in frames]}, \
                [("X-Query-Id", ",".join(qids))] + tp_headers
        if path in ("/druid/v2", "/druid/v2/"):
            spec = json.loads(body)
            res = self.engine.execute_ir(spec)
            return res.druid, []
        if path == "/ingest":
            # real-time append (docs/INGEST.md): acknowledged only
            # after the WAL frame is durable; backpressure surfaces as
            # IngestBackpressure -> 429 + Retry-After via the taxonomy
            req = json.loads(body)
            if "table" not in req or "rows" not in req:
                raise UserError(
                    "/ingest expects {\"table\": ..., \"rows\": [...]}")
            return self.engine.append(
                req["table"], req["rows"],
                traceparent=traceparent), tp_headers
        if path == "/debug/profile" or path.startswith("/debug/profile?"):
            # on-demand device capture: blocks THIS handler thread for
            # the window while other threads keep serving (their
            # dispatches get query_id annotations); ms is validated and
            # capped like every /debug param
            from tpu_olap.obs import profile as profile_mod
            ms = _int_param(_parse_query(path), ("ms",),
                            cap=profile_mod.CAPTURE_MS_MAX,
                            default=profile_mod.CAPTURE_MS_DEFAULT)
            return profile_mod.capture_device_profile(ms), []
        raise KeyError(f"unknown path {path!r}")
