"""Engine — the L7 surface (SURVEY.md §3.1): table registration (the
DefaultSource OPTIONS analog), SQL entry point with transparent fallback,
EXPLAIN DRUID REWRITE, raw-IR passthrough (ON DRUID DATASOURCE ... EXECUTE
QUERY), and CLEAR DRUID CACHE.
"""

from __future__ import annotations

import time

import numpy as np
import pandas as pd

from tpu_olap.catalog import (Catalog, StarSchema, SysTableProvider,
                              TableEntry, stmt_uses_sys)
from tpu_olap.obs.workload import (fingerprint_sql,
                                   introspection_execution)
from tpu_olap.executor import EngineConfig, QueryRunner
from tpu_olap.obs.trace import (Trace, current_query_id,
                                in_nested_execution, nested_execution,
                                parse_traceparent, span as _span,
                                use_query_id, use_traceparent)
from tpu_olap.executor.dimplan import UnsupportedDimension
from tpu_olap.executor.runner import QueryResult
from tpu_olap.ir.serde import query_from_json
from tpu_olap.kernels.filtereval import UnsupportedFilter
from tpu_olap.kernels.groupby import UnsupportedAggregation
from tpu_olap.kernels.timebucket import UnsupportedGranularity
from tpu_olap.planner import DruidPlanner
from tpu_olap.planner.fallback import FallbackError, execute_fallback
from tpu_olap.resilience.errors import (BreakerOpen, QueryShed,
                                        UserError)
from tpu_olap.resilience.faults import maybe_inject
from tpu_olap.segments.ingest import (DEFAULT_BLOCK_ROWS, ingest_arrow,
                                      ingest_pandas, ingest_parquet,
                                      ingest_parquet_stream)

_UNSUPPORTED = (UnsupportedAggregation, UnsupportedFilter,
                UnsupportedGranularity, UnsupportedDimension)


def _mark_slo_observed(e: BaseException):
    """Stamp an exception whose failure was already counted against the
    SLO (a recorded fallback failure, a raw-IR boundary observation) so
    the statement-boundary catch-all (Engine._observe_failure) never
    counts one served failure twice. Only set on exceptions that are
    NEVER shared across statements — the coalescer fans one exception
    object out to N callers, and each caller is its own served
    response, so those must stay unmarked."""
    try:
        e._slo_observed = True
    except Exception:  # noqa: BLE001 — slotted/exotic exceptions
        pass


def _failure_status(e: BaseException) -> int:
    """HTTP shape of a propagating failure: the taxonomy's http_status,
    or the server's legacy mapping for untyped errors (api.server:
    ValueError/KeyError -> 400, rest -> 500)."""
    status = getattr(e, "http_status", None)
    if status is None:
        return 400 if isinstance(e, (ValueError, KeyError)) else 500
    return int(status)


class Engine:
    def __init__(self, config: EngineConfig | None = None):
        self.config = config or EngineConfig()
        self.catalog = Catalog()
        self.runner = QueryRunner(self.config)
        self.planner = DruidPlanner(self.catalog, self.config)
        # observability surfaces (tpu_olap.obs): the runner owns both —
        # it is where records complete — these aliases are the API
        self.tracer = self.runner.tracer
        self.metrics = self.runner.metrics
        self.last_plan = None
        # Serializes device dispatch only (the runner's compile/arg caches
        # are not concurrent and the chip has one program queue anyway,
        # SURVEY.md §3.5 P1). Planning and the pandas fallback run outside
        # it, so concurrent HTTP clients aren't wedged behind one slow
        # device query (VERDICT round 1 "missing" #6). The lock LIVES
        # on the runner (QueryRunner.dispatch_lock) so the shared-scan
        # coalescer can let concurrent callers wait outside it and ride
        # one fused dispatch (executor.batch); this alias keeps the
        # engine-level admin surface (clear_cache) on the same lock.
        # With pipelined execution (EngineConfig.pipeline_depth > 0, the
        # default) the runner holds it only for stage-1 enqueue — host
        # transfer, finalize, and assembly overlap other queries'
        # device work (docs/PERF_MODEL.md "execution pipeline").
        self.device_lock = self.runner.dispatch_lock
        # planner-initiated subquery execution (uncorrelated shapes
        # inline as literals so the outer query can push down; the inner
        # aggregate itself rides the device path when rewritable)
        self.planner.run_subquery = self._run_stmt
        # fallback-initiated derived-table execution (round 5): a FROM/
        # JOIN (SELECT ...) body is usually the scan-heavy, device-
        # eligible part of a statement the outer interpreter serves —
        # route it back through the statement executor so the inner
        # aggregate rides the device path (fallback._run_inner_stmt)
        self.catalog.device_runner = self._run_stmt
        # sys.* virtual datasources (catalog.systables; ISSUE 11): the
        # engine is observable through its own SQL — sys.tables /
        # sys.segments / sys.queries / sys.query_templates / sys.metrics
        # / sys.caches / sys.cubes / sys.checkpoints / sys.devices
        # resolve through the catalog to live-state frames served on
        # the interpreter path with accounting suppressed
        self.catalog.sys_provider = SysTableProvider(self)
        # materialized rollup cubes (tpu_olap.cubes; docs/CUBES.md):
        # registry of (dim subset x grain) partial-aggregate rollups;
        # the planner's cube-rewrite pass serves covered aggregates
        # from them, the background maintainer rebuilds stale ones
        from tpu_olap.cubes import CubeRegistry
        self.cubes = CubeRegistry(self)
        # real-time ingest (segments/delta.py; docs/INGEST.md):
        # Engine.append / POST /ingest / INSERT INTO land rows in a
        # WAL-backed mutable delta scope, queryable immediately; a
        # background compactor seals deltas into time-partitioned
        # segments under the admission/breaker machinery
        from tpu_olap.segments.delta import IngestManager
        self.ingest = IngestManager(self)
        # WAL sync-lag probe for the regression sentinel (obs.sentinel;
        # ISSUE 17): per-table unsynced frame counts from the ingest
        # snapshot, consulted on the telemetry tick — wired here
        # because the runner (which owns the sentinel) predates the
        # ingest manager
        self.runner.sentinel.add_probe("wal", self._wal_lag_probe)

    def _wal_lag_probe(self) -> dict:
        """{table: unsynced WAL frames} for tables with live WALs."""
        out = {}
        snap = self.ingest.snapshot() or {}
        for name, st in (snap.get("tables") or {}).items():
            wal = st.get("wal") if isinstance(st, dict) else None
            if wal and wal.get("lag_records") is not None:
                out[name] = int(wal["lag_records"])
        return out

    # ------------------------------------------------------- registration

    def register_table(self, name: str, data, time_column: str | None = None,
                       star_schema=None, accelerate: bool = True,
                       block_rows: int = DEFAULT_BLOCK_ROWS,
                       column_map: dict | None = None,
                       columns=None, time_partition="auto", **options):
        """Register a datasource. `data`: pandas DataFrame, pyarrow Table,
        parquet path, or a list of parquet paths (a multi-file dataset).
        accelerate=False registers a plain (dimension) table served only
        by the fallback path — the reference's non-druid-backed relation.

        Parquet inputs stream row-group batches into segments under
        bounded host memory (SURVEY.md §8.4 #4); Arrow inputs ingest
        straight from the Arrow columns (no pandas detour); the fallback
        DataFrame materializes lazily on first fallback use. `columns`
        optionally prunes the ingested column set — always POST-rename
        names (after column_map), for every input type; parquet reads
        skip pruned columns entirely.

        `time_partition` is the Druid segmentGranularity analog:
        "day"/"month"/"year" buckets rows into disjoint calendar
        partitions (interval pruning then drops whole segments, and the
        residual row-level time mask — with its 8-bytes/row __time scan
        traffic — elides when every scanned segment sits inside the
        query interval); "auto" (default) picks the finest granularity
        the table can amortize; None disables partitioning.
        """
        # "ingest" fault site (resilience.faults): a raised fault aborts
        # registration before any segment state is built, so a failed
        # ingest never leaves a half-registered table behind
        maybe_inject(self.config, "ingest", 0)
        column_map = dict(column_map) if column_map else None
        if column_map and time_column in column_map:
            time_column = column_map[time_column]

        def _renamed_arrow(tbl):
            if column_map:
                tbl = tbl.rename_columns(
                    [column_map.get(c, c) for c in tbl.schema.names])
            return tbl

        segments = None
        pq_fields = {}
        if isinstance(data, str) or (
                isinstance(data, (list, tuple))
                and all(isinstance(p, str) for p in data)):
            import pyarrow.parquet as pq
            paths = [data] if isinstance(data, str) else list(data)
            inverse = {v: k for k, v in (column_map or {}).items()}
            read_cols = [inverse.get(c, c) for c in columns] \
                if columns else None

            def load_frame(_paths=tuple(paths), _cols=read_cols):
                f = pd.concat(
                    [pq.read_table(p, columns=_cols).to_pandas()
                     for p in _paths], ignore_index=True) \
                    if len(_paths) > 1 else \
                    pq.read_table(_paths[0], columns=_cols).to_pandas()
                return f.rename(columns=column_map) if column_map else f

            if accelerate:
                segments = ingest_parquet_stream(
                    name, paths, time_column, block_rows,
                    columns=columns, column_map=column_map,
                    time_partition=time_partition)
            frame_source = load_frame
            pq_fields = dict(
                parquet_paths=tuple(paths),
                parquet_read_cols=tuple(read_cols) if read_cols else None,
                parquet_column_map=column_map,
                parquet_rows=sum(pq.ParquetFile(p).metadata.num_rows
                                 for p in paths))
        elif isinstance(data, pd.DataFrame):
            frame = data.copy()
            if column_map:
                frame = frame.rename(columns=column_map)
            if columns:
                frame = frame[list(columns)]
            import pyarrow as pa
            table = pa.Table.from_pandas(frame, preserve_index=False) \
                if accelerate else None
            frame_source = frame
        else:  # pyarrow table
            table = _renamed_arrow(data)
            if columns:
                table = table.select(list(columns))

            def frame_source(_t=table):
                return _t.to_pandas()

        if accelerate and segments is None:
            segments = ingest_arrow(name, table, time_column, block_rows,
                                    time_partition=time_partition)
        star = star_schema
        if isinstance(star, dict):
            star = StarSchema.from_json(star)
        if segments is not None:
            segments.star = star  # FD-aware dim-domain restriction
        entry = TableEntry(name=name, segments=segments,
                           frame_source=frame_source,
                           time_column=time_column, star=star,
                           options=dict(options), **pq_fields)
        self.catalog.register(entry)
        # ingest invalidation (docs/CACHING.md): the fresh TableSegments
        # took the next generation, orphaning every semantic-cache entry
        # for this name at key level; purge them eagerly so the byte
        # budget doesn't stay occupied by unreachable entries
        self.runner.result_cache.invalidate_table(name)
        self.runner.events.emit(
            "ingest", table=name, accelerated=bool(accelerate),
            generation=segments.generation if segments is not None
            else None,
            rows=segments.num_rows if segments is not None else None,
            segments=len(segments.segments) if segments is not None
            else 0)
        # real-time ingest hook (docs/INGEST.md): a first registration
        # with an existing WAL is crash recovery — replay appends to
        # the exact acknowledged state; re-registering a live table
        # resets its log instead (the appends belonged to the old data)
        self.ingest.on_register(entry)
        # cube cascade (docs/CUBES.md): rollups over this table are now
        # stale — the rewrite pass stops serving them at generation-
        # check time; the maintainer wakes to rebuild
        self.cubes.on_table_registered(name)
        return entry

    def append(self, table: str, rows,
               traceparent: str | None = None) -> dict:
        """Real-time append (docs/INGEST.md): `rows` (list of dicts or
        a DataFrame, columns ⊆ the table's schema, time under the
        registered time column or ``__time``) land in the table's
        mutable in-memory delta and are queryable immediately alongside
        sealed segments — same kernels, same caches, exact results.
        With `ingest_wal_dir` set the batch is framed into the table's
        write-ahead log BEFORE acknowledgment, so a crash replays to
        the exact acknowledged state at the next registration. A delta
        at `ingest_max_delta_rows` sheds with IngestBackpressure (HTTP
        429 + Retry-After) — never a silent drop. SQL spelling:
        ``INSERT INTO t (cols) VALUES (...)``; HTTP: ``POST /ingest``.

        Returns {table, rows, generation, sealed_generation,
        delta_rows, watermark, wal_seq}. A valid W3C `traceparent`
        (ISSUE 17) is stamped into the ack and the emitted events."""
        tp = parse_traceparent(traceparent)
        with use_traceparent(tp["traceparent"] if tp else None):
            ack = self.ingest.append(table, rows)
        if tp is not None and isinstance(ack, dict):
            ack.setdefault("traceparent", tp["traceparent"])
        return ack

    def compact_now(self, table: str | None = None):
        """Synchronously seal delta rows into time-partitioned sealed
        segments (the background compactor's deterministic spelling).
        `table=None` compacts every table with a non-empty delta."""
        if table is None:
            return self.ingest.compact_all()
        return self.ingest.compact_now(table)

    def checkpoint_now(self, table: str | None = None):
        """Durably checkpoint a table's sealed scope
        (docs/DURABILITY.md): compact the delta, spill the sealed
        segments as checksummed chunk files under
        `EngineConfig.ingest_store_dir`, atomically advance the
        checkpoint manifest, and truncate the WAL through the lag-one
        watermark — after which a process restart replays only the
        post-checkpoint tail. SQL spelling: ``CHECKPOINT DRUID TABLE
        t``. `table=None` checkpoints every table with ingest state."""
        if table is None:
            return self.ingest.checkpoint_all()
        return self.ingest.checkpoint_now(table)

    def close(self):
        """Deterministically cancel every background stage graph the
        engine owns — the compactor and WAL flushers (ingest.stop),
        the cube maintainer, and the stage scheduler's ticker — and
        flush the event sink. The engine stays queryable afterwards;
        appends reopen WALs lazily and re-register the compactor/flush
        graphs on demand. Server.stop() calls this."""
        self.ingest.stop()
        self.cubes.stop(join=True)
        self.runner.stages.stop()
        self.runner.events.flush(2.0)

    def register_lookup(self, name: str, mapping: dict):
        """Register a named lookup map (Druid lookup extraction fn). SQL
        reaches it as LOOKUP(col, 'name') in projections, GROUP BY, and
        filters (SURVEY.md §3.3 lookup extraction dims)."""
        self.catalog.lookups[name] = {str(k): v for k, v in mapping.items()}

    # --------------------------------------------------------------- SQL

    def sql(self, query: str) -> pd.DataFrame:
        """Plan, execute (device or fallback), and return a DataFrame.

        Statement-level verbs beyond SELECT (the reference's extended
        parser, SURVEY.md §3.1): `CLEAR DRUID CACHE [table]`,
        `EXPLAIN DRUID REWRITE <sql>`, `EXPLAIN ANALYZE <sql>`, and
        `ON DRUID DATASOURCE <ds> EXECUTE QUERY '<json>'`.
        """
        return self._sql_traced(query)[0]

    def _sql_traced(self, query: str, traceparent: str | None = None):
        """sql() plus the completed trace (None for statement verbs or
        when tracing is off) — the EXPLAIN ANALYZE entry point.

        `traceparent` (ISSUE 17): a W3C trace-context header value from
        the HTTP edge. A valid header is stamped on the root span and
        the query record (distributed-trace join key); an invalid one
        is ignored — trace propagation must never fail a query."""
        tp = parse_traceparent(traceparent)
        with use_traceparent(tp["traceparent"] if tp else None):
            return self._sql_traced_inner(query, tp)

    def _sql_traced_inner(self, query: str, tp: dict | None = None):
        verb = _match_verb(query)
        if verb is not None:
            return verb(self), None
        from tpu_olap.planner.sqlparse import parse_sql
        pre_stmt = None
        if _SYS_HINT_RE.search(query):
            # probable sys.* introspection statement: confirm against
            # the parsed tree (a string literal mentioning "sys." must
            # not hijack a user query) and serve it outside the trace —
            # introspection appears nowhere in its own stats. A parse
            # failure defers to the traced path so the error records
            # like any other bad statement; a confirmed non-sys parse
            # is reused below (no double parse).
            try:
                pre_stmt = parse_sql(query)
            except Exception:
                pre_stmt = None
            if pre_stmt is not None \
                    and stmt_uses_sys(pre_stmt, self.catalog):
                return self._execute_sys_stmt(pre_stmt), None
        with self.tracer.trace("sql") as root:
            root.set(sql=query)
            if tp is not None:
                root.set(traceparent=tp["traceparent"],
                         trace_id=tp["trace_id"],
                         parent_span_id=tp["parent_id"])
            try:
                with root.span("parse"):
                    stmt = pre_stmt if pre_stmt is not None \
                        else parse_sql(query)
                with root.span("plan") as sp:
                    plan = self.planner.plan_stmt(stmt, query)
                    sp.set(rewritten=plan.rewritten)
                    if plan.fallback_reason:
                        sp.set(fallback_reason=plan.fallback_reason)
                self.last_plan = plan
                out = self._execute_plan(plan)
            except Exception as e:
                # statement-boundary SLO accounting: failures that
                # escaped every inner observation site (e.g. a shed
                # grouping-sets leg, a planner-subquery refusal) still
                # count against the budget exactly once
                self._observe_failure(e)
                raise
        return out, root if isinstance(root, Trace) else None

    def _observe_failure(self, e: BaseException):
        """Count a failure propagating to the client against the SLO —
        exactly once (sites whose record already counted it marked the
        exception), never for nested statements (the outer statement
        accounts), and never for client-shaped errors (a 400 for bad
        SQL must not burn the error budget; 429+ does). Does NOT mark
        the exception itself: a coalescer-shared exception is one
        served failure PER caller, and each caller's own boundary runs
        this exactly once."""
        if getattr(e, "_slo_observed", False) or in_nested_execution():
            return
        if _failure_status(e) < 429:
            return
        self.runner.slo.observe(0.0, failed=True)

    def _execute_plan(self, plan) -> pd.DataFrame:
        stmt = getattr(plan, "stmt", None)
        if stmt is not None and getattr(stmt, "grouping_sets", None) \
                is not None and not plan.rewritten:
            out = self._try_grouping_sets_union(plan)
            if out is not None:
                return out
        device_ms = 0.0  # user-visible time burned on a failed device try
        if plan.rewritten and self.cubes.active:
            # aggregate rewrite onto a materialized rollup cube
            # (planner.cuberewrite; docs/CUBES.md): a covered query is
            # served by folding thousands of stored cube rows instead
            # of scanning the base table — None falls through to the
            # ordinary device path, never an error
            from tpu_olap.planner.cuberewrite import try_serve_cube
            res = try_serve_cube(self, plan)
            if res is not None:
                with _span("render"):
                    return self._frame_from(plan, res)
        if plan.rewritten:
            res = None
            t_dev = time.perf_counter()
            try:
                # the runner serializes dispatch internally
                # (dispatch_lock) — and with batch_window_ms set,
                # concurrent callers coalesce into one fused dispatch
                with _span("execute"):
                    res = self.runner.execute(plan.query,
                                              plan.entry.segments)
            except _UNSUPPORTED as e:
                plan.query = None
                plan.fallback_reason = f"lowering failed: {e}"
                device_ms = (time.perf_counter() - t_dev) * 1000
            except QueryShed:
                # admission shed = the system is OVERLOADED: routing the
                # query to the (slower) interpreter would amplify the
                # overload. Propagate -> HTTP 429, client retries later
                # (the statement boundary counts it against the SLO).
                raise
            except BreakerOpen as e:
                # breaker open = the DEVICE is sick, the host is fine:
                # degraded-but-correct serving from the interpreter,
                # stamped path="fallback_breaker" in the record schema.
                if not self.config.fallback_on_device_failure:
                    raise  # refusal: SLO-counted at the boundary
                plan.query = None
                plan.breaker_fallback = True
                plan.fallback_reason = f"breaker open: {e}"
            except Exception as e:
                # Structural "never an error" guarantee (SURVEY.md §2
                # property 2): dispatch retries exhausted on a
                # non-structural failure (device loss, deadline, compiler
                # bug) -> correct-but-slow fallback, not a user error.
                if not self.config.fallback_on_device_failure:
                    # the interim record never SLO-counts; the
                    # statement boundary counts this propagation
                    raise
                plan.query = None
                plan.fallback_reason = \
                    f"device failure: {type(e).__name__}: {e}"
                device_ms = (time.perf_counter() - t_dev) * 1000
            if res is not None:
                # conversion bugs in _frame_from must surface, not be
                # silently reclassified as device failures
                with _span("render"):
                    return self._frame_from(plan, res)
        return self._execute_fallback_recorded(plan, device_ms)

    def _execute_fallback_recorded(self, plan,
                                   device_ms: float = 0.0) -> pd.DataFrame:
        """Run the pandas fallback under a span AND a history record, so
        the fallback path shares the dashboard metric schema (query_id /
        total_ms / rows_scanned / ... — the observability contract) the
        device paths emit. Failures record too, then propagate.
        `device_ms` is the wall already burned on a failed device
        attempt (deadline wait, exhausted retries): stamped on the
        record so the SLO classifies the query by the latency the USER
        saw, not just the fallback's own wall."""
        stmt = plan.stmt
        entry = plan.entry if plan.entry is not None \
            else self.catalog.maybe(getattr(stmt, "table", None) or "")
        rows = 0
        if entry is not None:
            rows = (entry.segments.num_rows if entry.is_accelerated
                    else entry.materialized_rows) or 0
        m = {"query_type": "fallback",
             "datasource": getattr(stmt, "table", None) or "(derived)",
             "rows_scanned": rows, "cache_hit": False}
        if device_ms > 0:
            m["device_attempt_ms"] = round(device_ms, 3)
        if plan.fallback_reason:
            m["fallback_reason"] = plan.fallback_reason
        if getattr(plan, "breaker_fallback", False):
            m["fallback_breaker"] = True
        # workload attribution (obs.workload): fallback statements
        # fingerprint from their literal-masked SQL text, so the
        # interpreter path lands in sys.query_templates too
        if self.runner.workload.enabled:
            try:
                m["_wl"] = fingerprint_sql(plan.sql or "", stmt,
                                           m["datasource"])
            except Exception:  # noqa: BLE001 — profiling never raises
                pass
        t0 = time.perf_counter()
        with _span("fallback") as sp:
            sp.set(reason=plan.fallback_reason)
            try:
                out = execute_fallback(stmt, self.catalog, self.config)
            except Exception as e:
                m["failed"] = True
                m["total_ms"] = (time.perf_counter() - t0) * 1000
                if _failure_status(e) < 429:
                    # client-shaped failure (unsupported SQL -> 400):
                    # recorded and event-logged, but it must not burn
                    # the SLO error budget (record() honors this key)
                    m["client_error"] = True
                self.runner.record(m)
                if not in_nested_execution():
                    _mark_slo_observed(e)  # record() accounted for it
                raise
            m["total_ms"] = (time.perf_counter() - t0) * 1000
            m["rows_returned"] = len(out)
            self.runner.record(m)
        return out

    def _try_grouping_sets_union(self, plan):
        """GROUPING SETS/ROLLUP/CUBE on the device path (VERDICT r4
        missing #4): a union of per-set GROUP BY dispatches sharing the
        compile cache — each leg differs only in dimension list, so the
        legs land on the same jit template family as their plain GROUP
        BY twins. Absent group keys / GROUPING() markers are reattached
        as constant columns after each leg runs. Returns None when the
        shape cannot be unioned (SELECT *; ORDER BY not on an output
        column) — the caller then takes the whole-statement fallback."""
        from tpu_olap.planner.fallback import (FallbackError,
                                               _sort_order_items,
                                               grouping_set_legs,
                                               union_order_keys)
        stmt = plan.stmt
        # only worth decomposing when the legs can ride the device path:
        # an unaccelerated or derived source would re-run the scan/join
        # once per set where the whole-statement fallback filters once
        # (and gating here keeps that fallback an independent oracle for
        # the union path in tests)
        if stmt.derived is not None or stmt.grouping_sets == []:
            return None
        entry = self.catalog.maybe(stmt.table)
        if entry is None or not entry.is_accelerated:
            return None
        try:
            out_names, legs = grouping_set_legs(stmt)
        except FallbackError:
            return None
        order_keys = union_order_keys(stmt, out_names) \
            if stmt.order_by else []
        if order_keys is None:
            return None  # union ORDER BY must name output columns
        t0 = time.perf_counter()
        frames, leg_plans = [], []
        for leg_stmt, consts in legs:
            lp = self.planner.plan_stmt(leg_stmt)
            leg_plans.append(lp)
            with nested_execution():
                # legs are internal: one SLO observation + one `query`
                # event for the whole union, stamped below
                f = self._execute_plan(lp)
            for name, val in consts.items():
                # absent group keys reattach as np.nan (float64 NULL),
                # matching the whole-statement fallback's dtype — a bare
                # None would make an object column that breaks numeric
                # comparisons/sorts over the union
                f[name] = np.nan if val is None else val
            frames.append(f.loc[:, out_names])
        plan.grouping_legs = leg_plans
        n_dev = sum(1 for lp in leg_plans if lp.rewritten)
        plan.fallback_reason = (
            None if n_dev == len(leg_plans) else
            f"grouping-sets union: {n_dev}/{len(leg_plans)} legs "
            "device-rewritten")
        out = pd.concat(frames, ignore_index=True) if frames else \
            pd.DataFrame(columns=out_names)
        if order_keys:
            out = _sort_order_items(out, order_keys, stmt.order_by)
        lo = stmt.offset
        hi = None if stmt.limit is None else lo + stmt.limit
        out = out.iloc[lo:hi].reset_index(drop=True)
        # the union is the served response: ONE SLO observation + ONE
        # `query` event spanning every leg (the legs' own records were
        # marked nested above)
        if not in_nested_execution():
            total_ms = (time.perf_counter() - t0) * 1000
            self.runner.slo.observe(total_ms)
            self.runner.events.emit(
                "query",
                query_id=current_query_id() or self.tracer.new_query_id(),
                query_type="groupBy", path="grouping_sets",
                datasource=stmt.table, total_ms=round(total_ms, 3),
                cache_hit=False)
        return out

    def sql_batch(self, queries) -> list[pd.DataFrame]:
        """Execute several SQL statements as one submission, fusing
        rewritten device queries against the same table into shared-scan
        batch dispatches (executor.batch): identical statements scan
        once, compatible aggregations ride one fused device pass.
        Statement verbs and fallback statements run individually; any
        leg that fails on the batch path re-runs through the ordinary
        single-query path (device retry, then pandas fallback), so the
        'never an error' property holds per statement. Results come
        back in input order."""
        return self.sql_batch_ids(queries)[0]

    def sql_batch_ids(self, queries, traceparent: str | None = None):
        """sql_batch plus each statement's query_id (parallel to the
        results) — the ids the /sql/batch X-Query-Id header carries so
        clients can correlate responses with /debug/queries,
        sys.queries, and Perfetto traces. A valid W3C `traceparent`
        covers every statement in the submission (ISSUE 17)."""
        tp = parse_traceparent(traceparent)
        with use_traceparent(tp["traceparent"] if tp else None):
            return self._sql_batch_ids_inner(queries, tp)

    def _sql_batch_ids_inner(self, queries, tp: dict | None = None):
        queries = list(queries)
        outs: list = [None] * len(queries)
        plans: dict[int, object] = {}
        groups: dict[str, list[int]] = {}
        # one query_id per logical statement, minted up front so the
        # fused batch legs' records stay attributable (obs.trace)
        qids = [self.tracer.new_query_id() for _ in queries]
        with self.tracer.trace("sql_batch") as root:
            root.set(statements=len(queries))
            if tp is not None:
                root.set(traceparent=tp["traceparent"],
                         trace_id=tp["trace_id"],
                         parent_span_id=tp["parent_id"])
            for i, q in enumerate(queries):
                verb = _match_verb(q)
                if verb is not None:
                    # statement verbs and sys.* introspection produce
                    # no history record: "-" in the X-Query-Id slot
                    # keeps the header positional without handing the
                    # client an id that matches nothing
                    outs[i], qids[i] = verb(self), "-"
                    continue
                if _SYS_HINT_RE.search(q):
                    from tpu_olap.planner.sqlparse import parse_sql
                    try:
                        stmt = parse_sql(q)
                    except Exception:
                        stmt = None  # the plan span raises it properly
                    if stmt is not None \
                            and stmt_uses_sys(stmt, self.catalog):
                        outs[i] = self._execute_sys_stmt(stmt)
                        qids[i] = "-"
                        continue
                with root.span("plan", query_id=qids[i]):
                    plan = self.planner.plan(q)
                if plan.rewritten and self.cubes.active:
                    # cube-covered statements serve immediately (their
                    # record carries the statement's own query_id) and
                    # never join a fused base-table scan they don't need
                    from tpu_olap.planner.cuberewrite import \
                        try_serve_cube
                    with use_query_id(qids[i]):
                        res = try_serve_cube(self, plan)
                    if res is not None:
                        outs[i] = self._frame_from(plan, res)
                        continue
                plans[i] = plan
                stmt = getattr(plan, "stmt", None)
                if plan.rewritten and not (
                        stmt is not None
                        and getattr(stmt, "grouping_sets", None)
                        is not None):
                    groups.setdefault(plan.entry.name, []).append(i)
            done = set()
            for name, idxs in groups.items():
                if len(idxs) < 2:
                    continue
                entry = self.catalog.get(name)
                try:
                    boxed = self.runner._execute_batch_boxed(
                        [plans[i].query for i in idxs], entry.segments,
                        [qids[i] for i in idxs])
                except QueryShed:
                    # a shed aborts the WHOLE submission with 429: every
                    # statement that has not yet produced a result is a
                    # user-visible failure, counted per statement like
                    # the /sql path would (statements that completed
                    # before the shed keep their good/bad observations)
                    for o in outs:
                        if o is None:
                            self.runner.slo.observe(0.0, failed=True)
                    raise
                for i, b in zip(idxs, boxed):
                    if isinstance(b, BaseException):
                        if not isinstance(b, Exception):
                            # KeyboardInterrupt/SystemExit: abort the
                            # whole submission — retrying would turn a
                            # cancel into double work
                            raise b
                        continue  # single-query path (retry+fallback)
                    outs[i] = self._frame_from(plans[i], b)
                    done.add(i)
            for i, plan in plans.items():
                if i in done:
                    continue
                # non-fused legs run inside the sql_batch trace but must
                # record under their OWN statement id, not the root's
                with use_query_id(qids[i]):
                    try:
                        outs[i] = self._execute_plan(plan)
                    except Exception as e:
                        # ANY server-shaped abort (shed, breaker
                        # refusal, device failure with fallback off)
                        # kills the whole submission: count every
                        # statement still without a result — including
                        # this one, unless its own record already
                        # counted it (marked fallback failures)
                        if _failure_status(e) >= 429:
                            for j, o in enumerate(outs):
                                if o is not None:
                                    continue
                                if j == i and getattr(
                                        e, "_slo_observed", False):
                                    continue
                                self.runner.slo.observe(0.0,
                                                        failed=True)
                        raise
            if plans:
                self.last_plan = plans[max(plans)]
        return outs, qids

    def _run_stmt(self, stmt) -> pd.DataFrame:
        """Execute one parsed statement end-to-end (device path when
        rewritable, else fallback) — the planner's subquery executor.
        Does not touch last_plan: the user-visible plan is the outer
        query's. Marked nested: the inner statement's record must not
        add a second SLO observation / `query` event to the outer
        statement's served response."""
        with nested_execution():
            return self._execute_plan(self.planner.plan_stmt(stmt))

    def _execute_sys_stmt(self, stmt) -> pd.DataFrame:
        """Serve a sys.* introspection statement (catalog.systables) on
        the host/interpreter path: a sys datasource is never device
        dispatch, never cached, and its execution is accounting-
        suppressed — no trace, no history record, no metric/SLO
        observation, no profiler template — so introspection can never
        recurse into its own stats (ISSUE 11). The statement still gets
        the planner's normalization passes, so aliases, windows over
        groups, and expression simplification behave exactly like any
        other fallback statement."""
        from tpu_olap.obs.trace import detached_trace
        from tpu_olap.planner.exprutil import simplify_stmt
        from tpu_olap.planner.plan import _apply_windows_over_groups
        from tpu_olap.planner.sqlparse import UnionStmt
        # detached_trace: a sys statement inside a live trace (an
        # sql_batch submission) must not leak its fallback spans into
        # that trace's ring/Perfetto export
        with introspection_execution(), nested_execution(), \
                detached_trace():
            stmt = self.planner._resolve_aliases(stmt)
            stmt = _apply_windows_over_groups(stmt)
            if not isinstance(stmt, UnionStmt):
                stmt = simplify_stmt(stmt)
            return execute_fallback(stmt, self.catalog, self.config)

    def _frame_from(self, plan, res: QueryResult) -> pd.DataFrame:
        # full-result cache hits carry their entry's live meta dict
        # (runner._serve_full_cache): memoize the rendered DataFrame on
        # it — construction dominates the warm-serve wall for small
        # results. Always hand out copies so a caller mutating the
        # frame cannot poison the cache. Keyed on the output spec: two
        # SQL spellings can share one IR entry but project differently.
        meta = getattr(res, "_cache_meta", None)
        fkey = tuple((o.name, o.source, o.cast) for o in plan.outputs)
        if meta is not None:
            cached = meta.get("frame")
            if cached is not None and meta.get("frame_key") == fkey:
                return cached.copy()
        cols = {}
        for o in plan.outputs:
            vals = [r.get(o.source) for r in res.rows]
            if o.cast == "int":
                vals = [int(v) if v is not None else None for v in vals]
            elif o.cast == "datetime":
                # naive UTC timestamps, matching pandas semantics
                vals = pd.to_datetime(vals, utc=True).tz_localize(None)
            cols[o.name] = vals
        frame = pd.DataFrame(cols,
                             columns=[o.name for o in plan.outputs])
        if meta is not None:
            meta["frame_key"] = fkey
            meta["frame"] = frame.copy()
        return frame

    def explain(self, query: str) -> dict:
        """EXPLAIN DRUID REWRITE analog: the chosen QuerySpec (or the
        fallback reason) without executing (SURVEY.md §4.5), plus the
        cost-model dispatch decision (the reference logs its
        DruidQueryCostModel choice the same way, SURVEY.md §6)."""
        plan = self.planner.plan(query)
        out = plan.explain()
        if plan.rewritten and plan.entry.is_accelerated:
            from tpu_olap.executor.lowering import lower
            from tpu_olap.planner import cost as cost_mod
            try:
                phys = lower(plan.query, plan.entry.segments, self.config)
                if phys.kind == "agg":  # scan/select has no dispatch choice
                    out["cost"] = cost_mod.decide(
                        phys, self.config,
                        self.config.num_shards or 1).to_json()
            except _UNSUPPORTED as e:
                out["cost"] = {"error": str(e)}
        return out

    # -------------------------------------------------------- passthrough

    def execute_ir(self, query) -> QueryResult:
        """Raw query-IR passthrough (`ON DRUID DATASOURCE ds EXECUTE QUERY
        '<json>'`): accepts a QuerySpec or Druid-shaped JSON dict."""
        if isinstance(query, dict):
            query = query_from_json(query)
        entry = self.catalog.get(query.data_source)
        if not entry.is_accelerated:
            raise UserError(
                f"table {query.data_source!r} is not accelerated")
        # the runner locks (or coalesces) internally; holding the lock
        # here would deadlock a coalesced submission against its leader.
        # The root trace makes raw-IR queries first-class in
        # /debug/queries AND gives the runner's records and the
        # boundary handlers below one shared query_id, so an operator
        # can correlate a served failure with its query_error narrative
        # in /debug/events.
        with self.tracer.trace("ir", datasource=query.data_source):
            try:
                return self.runner.execute(query, entry.segments)
            except (QueryShed, BreakerOpen):
                # no record ever fires for a shed/refusal: the
                # user-visible failure counts against the SLO at this
                # boundary (the shed/breaker events tell the story).
                # Never marked: a coalescer-shared exception is one
                # failure per caller, and nothing downstream of
                # execute_ir observes this statement again.
                self.runner.slo.observe(0.0, failed=True)
                raise
            except Exception:
                # the runner's failed record is interim (query_error
                # event, no SLO count) whatever the config — the raw-IR
                # path has no fallback, so the propagated failure is
                # the served response: count it and emit its terminal
                # `query` event here (unmarked, as above)
                self.runner.slo.observe(0.0, failed=True)
                self.runner.events.emit(
                    "query",
                    query_id=current_query_id()
                    or self.tracer.new_query_id(),
                    query_type=getattr(query, "query_type", "?"),
                    path="raw_ir", datasource=query.data_source,
                    total_ms=0.0, cache_hit=False, failed=True)
                raise

    def select_page(self, table: str, columns=None, page_size: int = 100,
                    offset: int = 0, descending: bool = False,
                    filter_spec=None, intervals=()):
        """Paged Select (SURVEY.md §3.3 SelectSpec): fetch one page of
        raw rows plus the paging offset to pass back for the next page.
        Returns (rows, next_offset). The SQL spellings LIMIT/OFFSET map
        to Scan; this is the resumable-cursor flavor."""
        from tpu_olap.ir.query import SelectQuerySpec
        q = SelectQuerySpec(
            data_source=table, intervals=tuple(intervals),
            filter=filter_spec,
            dimensions=tuple(columns or ()), metrics=(),
            page_size=page_size, paging_offset=offset,
            descending=descending)
        res = self.execute_ir(q)
        return res.rows, offset + len(res.rows)

    # -------------------------------------------------------------- admin

    def clear_cache(self, table: str | None = None):
        """CLEAR DRUID CACHE analog: drop device-resident columns,
        compiled programs, and both semantic result-cache tiers
        (catalog entries stay registered)."""
        with self.device_lock:
            self.runner.clear_cache(table)

    def drop_table(self, name: str):
        """DROP the datasource: unregister it and purge every cache that
        could still serve its data (device buffers, compiled programs,
        both semantic result-cache tiers). A later re-registration under
        the same name takes a fresh generation, so even an entry that
        somehow survived could never be served."""
        with self.device_lock:
            self.runner.clear_cache(name)
        self.catalog.drop(name)
        # ingest cascade: delta state dies with the table and its WAL
        # is deleted (a later re-registration starts a fresh log)
        self.ingest.on_drop(name)
        # cube cascade: rollups over a dropped base are dropped too
        # (their storage tables unregister with them)
        self.cubes.on_table_dropped(name)
        self.runner.events.emit("drop", table=name)

    # -------------------------------------------------------------- cubes

    def create_cube(self, spec):
        """Materialize a rollup cube (docs/CUBES.md). `spec` is a
        CubeSpec or its JSON dict: {name, datasource, dimensions,
        granularity, aggregations[, virtualColumns]} — the same payload
        `CREATE DRUID CUBES FROM '<file>'` reads and
        `tools/workload_report.py --emit-cubes` writes. Builds
        synchronously on the device; returns the registry entry."""
        return self.cubes.create(spec)

    def drop_cube(self, name: str) -> bool:
        """DROP DRUID CUBE analog: unregister the cube and its backing
        segment table. Returns False when no such cube exists."""
        return self.cubes.drop(name)

    @property
    def history(self):
        """Per-query observability records (SURVEY.md §6 tracing)."""
        return self.runner.history

    def counters(self) -> dict:
        """Aggregate observability counters (SURVEY.md §6 metrics:
        'counters exported as a dict') — maintained incrementally at
        query completion (QueryRunner.record), so a /status ping is O(1)
        and the totals stay exact after history-ring eviction."""
        return self.runner.counters()


# --------------------------------------------------------------------------
# Statement-level verbs (the reference's SparklineDataParser additions)

import json as _json
import re as _re

_CLEAR_RE = _re.compile(
    r"^\s*clear\s+druid\s+cache(?:\s+(\w+))?\s*;?\s*$", _re.I)
_EXPLAIN_RE = _re.compile(
    r"^\s*explain\s+druid\s+rewrite\s+(.+?)\s*;?\s*$", _re.I | _re.S)
_EXPLAIN_ANALYZE_RE = _re.compile(
    r"^\s*explain\s+analyze\s+(.+?)\s*;?\s*$", _re.I | _re.S)
_EXEC_RE = _re.compile(
    r"^\s*on\s+druid\s+datasource\s+(\w+)\s+execute\s+query\s+"
    r"'(.+)'\s*;?\s*$", _re.I | _re.S)
_SEARCH_RE = _re.compile(
    r"^\s*search\s+druid\s+datasource\s+(\w+)\s+for\s+'((?:[^']|'')*)'"
    r"(?:\s+in\s+([\w\s,]+?))?(?:\s+limit\s+(\d+))?\s*;?\s*$", _re.I)
# rollup-cube DDL (docs/CUBES.md): CREATE DRUID CUBE <name> ON <table>
# [DIMENSIONS (a, b)] [GRANULARITY g] AGGREGATES (sum(x), ...);
# CREATE DRUID CUBES FROM '<specs.json>'; DROP DRUID CUBE <name>;
# REFRESH DRUID CUBES
_CREATE_CUBE_RE = _re.compile(
    r"^\s*create\s+druid\s+cube\s+(\w+)\s+on\s+(\w+)\s+(.*?)\s*;?\s*$",
    _re.I | _re.S)
_CREATE_CUBES_FROM_RE = _re.compile(
    r"^\s*create\s+druid\s+cubes\s+from\s+'((?:[^']|'')+)'\s*;?\s*$",
    _re.I)
_DROP_CUBE_RE = _re.compile(
    r"^\s*drop\s+druid\s+cube\s+(\w+)\s*;?\s*$", _re.I)
_REFRESH_CUBES_RE = _re.compile(
    r"^\s*refresh\s+druid\s+cubes\s*;?\s*$", _re.I)
# real-time ingest verbs (docs/INGEST.md): INSERT INTO t (a, b) VALUES
# (...), (...); COMPACT DRUID TABLE t — the SQL spellings of
# Engine.append / Engine.compact_now; CHECKPOINT DRUID TABLE t spills
# the sealed scope to the durable segment store and truncates the WAL
# (Engine.checkpoint_now; docs/DURABILITY.md)
_INSERT_RE = _re.compile(
    r"^\s*insert\s+into\s+(\w+)\s*\(([^)]*)\)\s*values\s*(.+?)\s*;?\s*$",
    _re.I | _re.S)
_COMPACT_RE = _re.compile(
    r"^\s*compact\s+druid\s+table\s+(\w+)\s*;?\s*$", _re.I)
_CHECKPOINT_RE = _re.compile(
    r"^\s*checkpoint\s+druid\s+table\s+(\w+)\s*;?\s*$", _re.I)
# cheap pre-parse hint that a statement MIGHT reference a sys.* virtual
# datasource (catalog.systables): a match still confirms against the
# parsed tree before taking the introspection path
_SYS_HINT_RE = _re.compile(r"\bsys\.[A-Za-z_]\w*", _re.I)


def _match_verb(query: str):
    m = _CLEAR_RE.match(query)
    if m:
        table = m.group(1)
        return lambda eng: _run_clear(eng, table)
    m = _EXPLAIN_RE.match(query)
    if m:
        inner = m.group(1)
        return lambda eng: _run_explain(eng, inner)
    m = _EXPLAIN_ANALYZE_RE.match(query)
    if m:
        inner = m.group(1)
        return lambda eng: _run_explain_analyze(eng, inner)
    m = _EXEC_RE.match(query)
    if m:
        ds, body = m.group(1), m.group(2).replace("''", "'")
        return lambda eng: _run_passthrough(eng, ds, body)
    m = _SEARCH_RE.match(query)
    if m:
        ds, pat = m.group(1), m.group(2).replace("''", "'")
        dims = tuple(d.strip() for d in m.group(3).split(",")) \
            if m.group(3) else ()
        limit = int(m.group(4)) if m.group(4) else 1000
        return lambda eng: _run_search_verb(eng, ds, pat, dims, limit)
    m = _CREATE_CUBE_RE.match(query)
    if m:
        name, base, clauses = m.group(1), m.group(2), m.group(3)
        return lambda eng: _run_create_cube(eng, name, base, clauses)
    m = _CREATE_CUBES_FROM_RE.match(query)
    if m:
        path = m.group(1).replace("''", "'")
        return lambda eng: _run_create_cubes_from(eng, path)
    m = _DROP_CUBE_RE.match(query)
    if m:
        name = m.group(1)
        return lambda eng: _run_drop_cube(eng, name)
    if _REFRESH_CUBES_RE.match(query):
        return _run_refresh_cubes
    m = _INSERT_RE.match(query)
    if m:
        table, cols, values = m.group(1), m.group(2), m.group(3)
        return lambda eng: _run_insert(eng, table, cols, values)
    m = _COMPACT_RE.match(query)
    if m:
        table = m.group(1)
        return lambda eng: _run_compact(eng, table)
    m = _CHECKPOINT_RE.match(query)
    if m:
        table = m.group(1)
        return lambda eng: _run_checkpoint(eng, table)
    return None


# ------------------------------------------------------------- cube DDL

_CUBE_CLAUSE_RE = _re.compile(
    r"(dimensions|aggregates|granularity)\b\s*", _re.I)


def _scan_quote(s: str, i: int) -> int:
    """Index just past the SQL string literal starting at s[i] == "'"
    ('' is the escape). Unterminated -> len(s)."""
    i += 1
    n = len(s)
    while i < n:
        if s[i] == "'":
            if i + 1 < n and s[i + 1] == "'":
                i += 2
                continue
            return i + 1
        i += 1
    return n


def _split_top_commas(s: str) -> list[str]:
    """Comma split at paren depth 0, quote-aware (aggregate lists nest
    parens, and filter literals may contain commas/parens)."""
    out, depth, cur, i, n = [], 0, [], 0, len(s)
    while i < n:
        ch = s[i]
        if ch == "'":
            j = _scan_quote(s, i)
            cur.append(s[i:j])
            i = j
            continue
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        if ch == "," and depth == 0:
            out.append("".join(cur).strip())
            cur = []
        else:
            cur.append(ch)
        i += 1
    tail = "".join(cur).strip()
    if tail:
        out.append(tail)
    return out


def _parse_cube_clauses(clauses: str) -> dict:
    """DIMENSIONS (...) / GRANULARITY g / AGGREGATES (...) in any
    order -> spec fields. Parenthesized lists are matched by depth so
    aggregate expressions may contain commas and parens."""
    out = {"dimensions": (), "granularity": "all", "aggregations": ()}
    i, n = 0, len(clauses)
    while i < n:
        m = _CUBE_CLAUSE_RE.match(clauses, i)
        if m is None:
            if clauses[i].isspace():
                i += 1
                continue
            raise UserError(
                f"cannot parse CREATE DRUID CUBE clause at "
                f"{clauses[i:i + 40]!r}")
        kw = m.group(1).lower()
        i = m.end()
        if kw == "granularity":
            g = _re.match(r"\s*(\w+)", clauses[i:])
            if g is None:
                raise UserError("GRANULARITY needs a grain name")
            out["granularity"] = g.group(1)
            i += g.end()
            continue
        j = clauses.find("(", i)
        if j < 0 or clauses[i:j].strip():
            # junk between the keyword and its list must not silently
            # drop items (DIMENSIONS cat (region) would lose `cat`)
            raise UserError(f"{kw.upper()} needs a parenthesized list")
        depth, k = 0, j
        while k < n:
            c = clauses[k]
            if c == "'":
                # parens/commas inside a filter literal (e.g.
                # FILTER (WHERE cat = 'a)')) are text, not structure
                k = _scan_quote(clauses, k)
                continue
            if c == "(":
                depth += 1
            elif c == ")":
                depth -= 1
                if depth == 0:
                    break
            k += 1
        if depth != 0:
            raise UserError(f"unbalanced parens in {kw.upper()} list")
        items = _split_top_commas(clauses[j + 1:k])
        if kw == "dimensions":
            out["dimensions"] = tuple(items)
        else:
            out["aggregations"] = tuple(items)
        i = k + 1
    return out


def _cube_status_frame(rows) -> pd.DataFrame:
    return pd.DataFrame(rows, columns=["cube", "status", "detail"])


def _run_create_cube(eng: Engine, name, base, clauses) -> pd.DataFrame:
    from tpu_olap.cubes import CubeSpec
    fields = _parse_cube_clauses(clauses)
    spec = CubeSpec(name=name, datasource=base, source="ddl", **fields)
    entry = eng.create_cube(spec)
    return _cube_status_frame([{
        "cube": name, "status": entry.status,
        "detail": f"{entry.data.n_rows} rows @ {spec.granularity} "
                  f"in {entry.build_ms:.0f} ms"}])


def _run_create_cubes_from(eng: Engine, path: str) -> pd.DataFrame:
    """CREATE DRUID CUBES FROM '<file.json>': materialize every spec in
    the file (a list, or {"cubes": [...]} — the exact artifact
    tools/workload_report.py --emit-cubes writes). Per-spec isolation:
    one bad spec reports its error without aborting the rest."""
    with open(path) as f:
        payload = _json.load(f)
    specs = payload.get("cubes", payload) if isinstance(payload, dict) \
        else payload
    if not isinstance(specs, list):
        raise UserError(f"{path!r}: expected a list of cube specs")
    rows = []
    for s in specs:
        cname = (s or {}).get("name", "?") if isinstance(s, dict) else "?"
        try:
            entry = eng.create_cube(s)
            rows.append({"cube": entry.spec.name,
                         "status": entry.status,
                         "detail": f"{entry.data.n_rows} rows in "
                                   f"{entry.build_ms:.0f} ms"})
        except Exception as e:  # noqa: BLE001 — per-spec isolation
            rows.append({"cube": cname, "status": "error",
                         "detail": str(e)[:300]})
    return _cube_status_frame(rows)


def _run_drop_cube(eng: Engine, name: str) -> pd.DataFrame:
    found = eng.drop_cube(name)
    return _cube_status_frame([{
        "cube": name, "status": "dropped" if found else "absent",
        "detail": ""}])


def _run_refresh_cubes(eng: Engine) -> pd.DataFrame:
    results = eng.cubes.refresh_now()
    if not results:
        return _cube_status_frame([])
    return _cube_status_frame([
        {"cube": n, "status": "ok" if r == "ok" else "error",
         "detail": "" if r == "ok" else r}
        for n, r in sorted(results.items())])


# ------------------------------------------------- real-time ingest DDL

_TS_LITERAL_RE = _re.compile(r"^timestamp\s+'((?:[^']|'')*)'$", _re.I)


def _parse_sql_literal(tok: str):
    """One VALUES literal -> python scalar: NULL, TRUE/FALSE, numbers,
    'string' ('' escapes), TIMESTAMP 'iso'."""
    t = tok.strip()
    up = t.upper()
    if up == "NULL":
        return None
    if up == "TRUE":
        return 1
    if up == "FALSE":
        return 0
    m = _TS_LITERAL_RE.match(t)
    if m:
        return m.group(1).replace("''", "'")
    if t.startswith("'"):
        if not t.endswith("'") or len(t) < 2:
            raise UserError(f"unterminated string literal {tok!r}")
        return t[1:-1].replace("''", "'")
    try:
        return int(t)
    except ValueError:
        pass
    try:
        return float(t)
    except ValueError:
        raise UserError(
            f"cannot parse INSERT literal {tok!r}") from None


def _run_insert(eng: Engine, table: str, cols: str,
                values: str) -> pd.DataFrame:
    """INSERT INTO t (a, b, ...) VALUES (...), (...) — the SQL spelling
    of Engine.append (docs/INGEST.md). Literal lists are quote-aware
    (strings may contain commas/parens); every tuple must match the
    column list's arity."""
    names = [c.strip() for c in cols.split(",") if c.strip()]
    if not names:
        raise UserError("INSERT INTO needs a column list")
    rows = []
    for tup in _split_top_commas(values):
        t = tup.strip()
        if not (t.startswith("(") and t.endswith(")")):
            raise UserError(
                f"INSERT VALUES expects parenthesized tuples, got "
                f"{t[:40]!r}")
        items = _split_top_commas(t[1:-1])
        if len(items) != len(names):
            raise UserError(
                f"INSERT tuple has {len(items)} values for "
                f"{len(names)} columns")
        rows.append({n: _parse_sql_literal(v)
                     for n, v in zip(names, items)})
    out = eng.append(table, rows)
    return pd.DataFrame([{
        "table": table, "rows": out["rows"],
        "delta_rows": out["delta_rows"],
        "generation": out["generation"],
        "wal_seq": out["wal_seq"]}])


def _run_compact(eng: Engine, table: str) -> pd.DataFrame:
    res = eng.compact_now(table)
    if res is None:
        return pd.DataFrame([{"table": table, "status": "empty-delta",
                              "rows_sealed": 0, "ms": 0.0}])
    if res.get("status") != "compacted":
        # skipped, not empty: a compaction already in flight or the
        # breaker is open — the operator should retry
        return pd.DataFrame([{"table": table, "status": res["status"],
                              "rows_sealed": 0, "ms": 0.0}])
    return pd.DataFrame([{
        "table": table, "status": "compacted",
        "rows_sealed": res["rows_sealed"],
        "ms": round(res["ms"], 3)}])


def _run_checkpoint(eng: Engine, table: str) -> pd.DataFrame:
    """CHECKPOINT DRUID TABLE t (docs/DURABILITY.md): compact + spill
    + manifest advance + WAL truncation, reported honestly — `status`
    is `checkpointed`, `noop` (sealed scope unchanged since the last
    manifest), `busy`, `no-store` (ingest_store_dir unset), or `error`
    (from the compaction's auto-hook)."""
    res = eng.checkpoint_now(table)
    return pd.DataFrame([{
        "table": table, "status": res.get("status"),
        "checkpoint_id": res.get("checkpoint_id"),
        "segments": res.get("segments"),
        "files_written": res.get("files_written"),
        "chunks_reused": res.get("chunks_reused"),
        "bytes": res.get("bytes"),
        "wal_frames_truncated": res.get("wal_frames_truncated"),
        "ms": round(res.get("ms") or 0.0, 3)}])


def _run_clear(eng: Engine, table: str | None) -> pd.DataFrame:
    eng.clear_cache(table)
    return pd.DataFrame({"status": [
        f"cleared cache for {table}" if table else "cleared cache"]})


def _run_explain(eng: Engine, inner_sql: str) -> pd.DataFrame:
    info = eng.explain(inner_sql)
    lines = _json.dumps(info, indent=2, default=str).splitlines()
    return pd.DataFrame({"plan": lines})


def _run_explain_analyze(eng: Engine, inner_sql: str) -> pd.DataFrame:
    """EXPLAIN ANALYZE <sql> — the observability analog of EXPLAIN DRUID
    REWRITE: EXECUTES the statement and returns its span tree as rows
    (one per span, depth-indented; attrs as a JSON detail column). Stage
    durations are wall-clock children of the root, so they sum to within
    the root's total (obs.trace; docs/OBSERVABILITY.md)."""
    frame, trace = eng._sql_traced(inner_sql)
    if trace is None:
        return pd.DataFrame({
            "span": ["(no trace: tracing disabled or statement verb)"],
            "ms": [0.0], "detail": ["{}"]})
    rows = []
    for depth, s in trace.walk():
        detail = dict(s.attrs)
        if depth == 0:
            detail["query_id"] = trace.query_id
            detail["rows_returned"] = len(frame)
        rows.append({"span": ("  " * depth) + s.name,
                     "ms": round(s.duration_ms or 0.0, 3),
                     "detail": _json.dumps(detail, default=str)})
    return pd.DataFrame(rows, columns=["span", "ms", "detail"])


def _run_passthrough(eng: Engine, datasource: str, body: str) -> pd.DataFrame:
    spec = _json.loads(body)
    spec.setdefault("dataSource", datasource)
    res = eng.execute_ir(spec)
    return res.to_pandas()


def _run_search_verb(eng: Engine, datasource: str, pattern: str,
                     dims: tuple, limit: int) -> pd.DataFrame:
    """SEARCH DRUID DATASOURCE t FOR 'pat' [IN d1, d2] [LIMIT n] — the
    SQL spelling of SearchQuerySpec (SURVEY.md §3.3; VERDICT round-2
    missing #6)."""
    from tpu_olap.ir.query import SearchQueryContains, SearchQuerySpec
    q = SearchQuerySpec(
        data_source=datasource, intervals=(),
        search_dimensions=dims,
        query=SearchQueryContains(pattern, case_sensitive=False),
        limit=limit)
    return eng.execute_ir(q).to_pandas()
