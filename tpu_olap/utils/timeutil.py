"""Time utilities: ISO-8601 parsing, epoch-millis math, calendar bucketing.

The reference delegates granularity math to Druid + joda-time (SURVEY.md
§3.3 "Granularity"). Here all calendar-aware work happens host-side: we
compute explicit bucket *boundary arrays* over the queried time range, and
device kernels bucket timestamps with a vectorized searchsorted. Uniform
(sub-day) granularities use pure integer arithmetic instead.
"""

from __future__ import annotations

import datetime as _dt
import re
from zoneinfo import ZoneInfo

UTC = _dt.timezone.utc

MILLIS_SECOND = 1000
MILLIS_MINUTE = 60 * MILLIS_SECOND
MILLIS_HOUR = 60 * MILLIS_MINUTE
MILLIS_DAY = 24 * MILLIS_HOUR

_PERIOD_RE = re.compile(
    r"^P(?:(?P<years>\d+)Y)?(?:(?P<months>\d+)M)?(?:(?P<weeks>\d+)W)?"
    r"(?:(?P<days>\d+)D)?"
    r"(?:T(?:(?P<hours>\d+)H)?(?:(?P<minutes>\d+)M)?(?:(?P<seconds>\d+)S)?)?$"
)


def parse_period(period: str) -> dict:
    """Parse an ISO-8601 period string (P1D, PT1H, P3M, ...) to components."""
    m = _PERIOD_RE.match(period)
    if not m or period in ("P", "PT"):
        raise ValueError(f"invalid ISO-8601 period: {period!r}")
    parts = {k: int(v) for k, v in m.groupdict().items() if v}
    if not parts or not any(parts.values()):
        raise ValueError(f"empty/zero ISO-8601 period: {period!r}")
    return parts


def period_is_uniform(period: str) -> bool:
    """True if the period is a fixed number of millis (no months/years).

    Weeks/days count as uniform in UTC only; in a DST-observing timezone
    day/week buckets must track local midnight (see period_is_subday and
    calendar_boundaries' path selection).
    """
    parts = parse_period(period)
    return not (parts.get("years") or parts.get("months"))


def period_is_subday(period: str) -> bool:
    """True for pure hour/minute/second periods — DST-safe under fixed
    epoch stepping in any timezone (DST only shifts whole-period-multiple
    offsets for these)."""
    parts = parse_period(period)
    return not (parts.get("years") or parts.get("months")
                or parts.get("weeks") or parts.get("days"))


def period_millis(period: str) -> int:
    """Fixed millis for a uniform period. Raises for calendar periods."""
    parts = parse_period(period)
    if parts.get("years") or parts.get("months"):
        raise ValueError(f"period {period!r} is not a fixed duration")
    return (
        parts.get("weeks", 0) * 7 * MILLIS_DAY
        + parts.get("days", 0) * MILLIS_DAY
        + parts.get("hours", 0) * MILLIS_HOUR
        + parts.get("minutes", 0) * MILLIS_MINUTE
        + parts.get("seconds", 0) * MILLIS_SECOND
    )


def parse_iso_datetime(s: str) -> int:
    """ISO-8601 datetime (or date) string -> epoch millis (UTC). Also
    accepts the millis_to_iso eternity spellings for exact round-trips
    of open interval endpoints."""
    s = s.strip()
    if s.startswith(("-eternity(", "+eternity(")) and s.endswith(")"):
        return int(s[s.index("(") + 1:-1])
    if s.endswith("Z"):
        s = s[:-1] + "+00:00"
    d = _dt.datetime.fromisoformat(s)
    if d.tzinfo is None:
        d = d.replace(tzinfo=UTC)
    return int(d.timestamp() * 1000)


# datetime can only render years 1..9999; open interval endpoints carry
# eternity-scale sentinels (ir.interval.ETERNITY = ±2^62 ms) that must
# still serialize stably (plan fingerprints, Druid-wire output)
_MIN_RENDER_MS = -62135596800000   # 0001-01-01T00:00:00Z
_MAX_RENDER_MS = 253402300799999   # 9999-12-31T23:59:59.999Z


def millis_to_iso(ms: int) -> str:
    if ms < _MIN_RENDER_MS:
        return f"-eternity({ms})"
    if ms > _MAX_RENDER_MS:
        return f"+eternity({ms})"
    d = _dt.datetime.fromtimestamp(ms / 1000.0, tz=UTC)
    return d.strftime("%Y-%m-%dT%H:%M:%S.") + f"{ms % 1000:03d}Z"


def date_to_millis(year: int, month: int = 1, day: int = 1) -> int:
    return int(_dt.datetime(year, month, day, tzinfo=UTC).timestamp() * 1000)


def _advance(d: _dt.datetime, parts: dict) -> _dt.datetime:
    """Advance a tz-aware datetime by one ISO period, calendar-correct."""
    y = d.year
    mo = d.month
    y += parts.get("years", 0)
    mo += parts.get("months", 0)
    y += (mo - 1) // 12
    mo = (mo - 1) % 12 + 1
    day = min(d.day, _days_in_month(y, mo))
    d2 = d.replace(year=y, month=mo, day=day)
    delta = _dt.timedelta(
        weeks=parts.get("weeks", 0),
        days=parts.get("days", 0),
        hours=parts.get("hours", 0),
        minutes=parts.get("minutes", 0),
        seconds=parts.get("seconds", 0),
    )
    if delta:
        # wall-clock advance: convert through naive local time so that
        # day-steps land on the same local wall time across DST shifts
        naive = d2.replace(tzinfo=None) + delta
        d2 = naive.replace(tzinfo=d2.tzinfo)
    return d2


def _days_in_month(year: int, month: int) -> int:
    if month == 12:
        nxt = _dt.date(year + 1, 1, 1)
    else:
        nxt = _dt.date(year, month + 1, 1)
    return (nxt - _dt.date(year, month, 1)).days


def _floor_to_period_start(d: _dt.datetime, parts: dict) -> _dt.datetime:
    """Floor a local datetime to the natural start of its period bucket."""
    if parts.get("years"):
        n = parts["years"]
        y = d.year - (d.year % n)
        return d.replace(year=y, month=1, day=1, hour=0, minute=0, second=0, microsecond=0)
    if parts.get("months"):
        n = parts["months"]
        mo0 = (d.month - 1) - ((d.month - 1) % n)
        return d.replace(month=mo0 + 1, day=1, hour=0, minute=0, second=0, microsecond=0)
    if parts.get("weeks"):
        # ISO week: floor to Monday, aligned modulo n weeks from the epoch
        # Monday (1970-01-05) so PnW bucket starts don't depend on t_min
        n = parts["weeks"]
        start = d.replace(hour=0, minute=0, second=0, microsecond=0)
        start = start - _dt.timedelta(days=start.weekday())
        week_idx = (start.date() - _dt.date(1970, 1, 5)).days // 7
        return start - _dt.timedelta(weeks=week_idx % n)
    if parts.get("days"):
        return d.replace(hour=0, minute=0, second=0, microsecond=0)
    if parts.get("hours"):
        n = parts["hours"]
        return d.replace(hour=d.hour - d.hour % n, minute=0, second=0, microsecond=0)
    if parts.get("minutes"):
        n = parts["minutes"]
        return d.replace(minute=d.minute - d.minute % n, second=0, microsecond=0)
    if parts.get("seconds"):
        n = parts["seconds"]
        return d.replace(second=d.second - d.second % n, microsecond=0)
    return d


def calendar_boundaries(period: str, tz: str, t_min_ms: int, t_max_ms: int) -> list[int]:
    """Bucket boundaries (epoch millis, ascending) covering [t_min, t_max].

    boundaries[i] is the inclusive start of bucket i; the list has one extra
    trailing boundary past t_max so searchsorted(...)-1 is always valid for
    timestamps in range. Calendar-correct in the given IANA timezone.
    """
    if t_max_ms < t_min_ms:
        return [t_min_ms, t_min_ms + 1]
    parts = parse_period(period)
    zone = ZoneInfo(tz)
    d = _dt.datetime.fromtimestamp(t_min_ms / 1000.0, tz=zone)
    d = _floor_to_period_start(d, parts)
    out = []
    if period_is_uniform(period) and (tz == "UTC" or period_is_subday(period)):
        # Fixed-duration stepping in epoch space. Valid in UTC always, and
        # for sub-day periods in any tz (hour buckets stay hour-aligned
        # across DST, including the repeated fall-back hour). Day/week in a
        # DST tz must follow local midnight, so they take the wall-clock
        # _advance path below (which dedupes the spring-forward instant).
        step = period_millis(period)
        ms = int(d.timestamp() * 1000)
        while True:
            out.append(ms)
            if ms > t_max_ms:
                break
            ms += step
    else:
        guard = 0
        while True:
            ms = int(d.timestamp() * 1000)
            if not out or ms > out[-1]:
                out.append(ms)
            if ms > t_max_ms:
                break
            d = _advance(d, parts)
            guard += 1
            if guard > 2_000_000:
                raise ValueError(f"too many buckets for period {period!r}")
    if len(out) < 2:
        out.append(out[-1] + 1)
    return out
