"""Sandbox/platform helpers shared by tests, bench, and driver entries.

The sandbox's sitecustomize registers the accelerator PJRT plugin at
interpreter startup with the platform env already snapshotted, so exporting
``JAX_PLATFORMS=cpu`` from a caller is not always enough to avoid
initializing it; ``jax.config.update('jax_platforms', 'cpu')`` works as
long as no backend has been initialized yet. This module is the single
home for that workaround (used by tests/conftest.py, bench.py, and
__graft_entry__.py) so the three drivers cannot drift.
"""

from __future__ import annotations

import os
import re


def ensure_host_device_count(n: int) -> None:
    """Set (or raise) the virtual host-platform device count to >= n.

    Only effective before jax initializes its backends; a no-op when the
    flag is already >= n.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
    if m is None:
        if "xla_force_host_platform_device_count" in flags:
            return  # caller set it in a spelling we don't parse; trust it
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}").strip()
    elif int(m.group(1)) < n:
        os.environ["XLA_FLAGS"] = flags.replace(
            m.group(0), f"--xla_force_host_platform_device_count={n}")


def force_cpu_platform() -> bool:
    """Force jax onto the CPU platform; True if the config took effect.

    Safe to call when a backend is already up (returns False then — the
    caller decides whether the current platform is acceptable).
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
        return True
    except Exception:
        return False


def force_cpu_devices(n: int) -> None:
    """Ensure >= n JAX devices exist on the virtual-CPU platform."""
    ensure_host_device_count(n)
    force_cpu_platform()
    import jax

    if jax.device_count() < n:
        raise RuntimeError(
            f"need {n} devices, have {jax.devices()}; set "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={n} "
            "JAX_PLATFORMS=cpu before jax initializes")


def env_flag(name: str, default: bool = False) -> bool:
    """Parse a 0/1/true/false-style env flag."""
    v = os.environ.get(name)
    if v is None:
        return default
    return v.strip().lower() not in ("", "0", "false", "no", "off")
