"""`sys.*` virtual datasources — the engine observable through its own
SQL (ISSUE 11 tentpole, the Druid `sys` schema analog).

A `sys.<name>` reference resolves through the catalog to a fresh
TableEntry whose frame builds from LIVE engine state at access time —
never ingested, never accelerated, never cached. The engine routes any
statement touching a sys datasource onto the host/interpreter path
inside `introspection_execution()` (obs.workload), so introspection
queries are served by the ordinary SQL machinery (filters, aggregates,
ORDER BY/LIMIT, joins — even against user tables) while appearing
nowhere in their own stats: no history record, no metrics, no SLO
observation, no profiler template, no cache entry.

Datasources (column tables in docs/OBSERVABILITY.md):

  sys.tables           registered datasources + size/generation
  sys.segments         per-segment rows/interval/generation/bytes +
                       whether the tier-1 cache pins partials for it
  sys.queries          the per-query history ring (QueryRunner.history)
  sys.query_templates  the workload profiler (obs.workload) — count,
                       latency percentiles, cache hit-rate, dims, grains
  sys.metrics          the metrics registry, one row per series
  sys.caches           result-cache tiers + runner cache populations
  sys.cubes            materialized rollup cubes: dims/grain/rows,
                       base-vs-cube generation (stale detection),
                       build cost, rewrite serve counts (docs/CUBES.md)
  sys.checkpoints      durable sealed-segment checkpoints per table:
                       manifest id, WAL watermark vs acked seq, spilled
                       bytes, chunk reuse (docs/DURABILITY.md)
  sys.devices          per-chip serving state under the interleaved
                       segment placement (executor/sharding.py):
                       segments owned, resident bytes, dispatch
                       participation, tier-1 cache-shard entries,
                       per-(chip, owner-class) HBM bytes with
                       high-watermark + headroom (ISSUE 17)
  sys.metrics_history  the telemetry sampler's bounded per-series
                       rings (obs.timeseries) — the engine answers
                       SQL over its own recent metric history
  sys.alerts           the regression sentinel's alert history
                       (obs.sentinel): latency drift attributed to a
                       stage, HBM pressure, eviction thrash, WAL lag,
                       breaker/admission events
"""

from __future__ import annotations

import pandas as pd

SYS_PREFIX = "sys."

__all__ = ["SYS_PREFIX", "SysTableProvider", "stmt_uses_sys"]


def _expr_uses_sys(e, catalog) -> bool:
    """Expression-level subqueries (WHERE x IN (SELECT ... FROM
    sys.queries), EXISTS, scalar) reference sys datasources too — they
    must route the WHOLE statement onto the suppressed introspection
    path, or the inner sys scan would execute unsuppressed."""
    from tpu_olap.ir.expr import BinOp, FuncCall, Subquery, WindowCall
    if isinstance(e, Subquery):
        return stmt_uses_sys(e.stmt, catalog)
    if isinstance(e, BinOp):
        return _expr_uses_sys(e.left, catalog) \
            or _expr_uses_sys(e.right, catalog)
    if isinstance(e, FuncCall):
        return any(_expr_uses_sys(a, catalog) for a in e.args)
    if isinstance(e, WindowCall):
        return any(_expr_uses_sys(a, catalog) for a in e.args) \
            or any(_expr_uses_sys(p, catalog) for p in e.partition_by) \
            or any(_expr_uses_sys(oe, catalog)
                   for oe, _ in e.order_by)
    return False


def stmt_uses_sys(stmt, catalog) -> bool:
    """True when any datasource reference in the statement tree —
    FROM/JOIN position, derived tables, or expression subqueries —
    resolves to a sys datasource (a REGISTERED table shadowing a sys
    name stays a user table)."""
    from tpu_olap.planner.sqlparse import UnionStmt
    if stmt is None:
        return False
    if isinstance(stmt, UnionStmt):
        return any(stmt_uses_sys(p, catalog) for p in stmt.parts)
    if catalog.is_sys(getattr(stmt, "table", None)):
        return True
    if stmt_uses_sys(getattr(stmt, "derived", None), catalog):
        return True
    for j in getattr(stmt, "joins", ()):
        if catalog.is_sys(j.table) or \
                stmt_uses_sys(getattr(j, "derived", None), catalog):
            return True
        if j.on is not None and _expr_uses_sys(j.on, catalog):
            return True
    exprs = [e for e, _ in getattr(stmt, "projections", ())]
    exprs += list(getattr(stmt, "group_by", ()) or ())
    exprs.append(getattr(stmt, "where", None))
    exprs.append(getattr(stmt, "having", None))
    exprs += [o.expr for o in getattr(stmt, "order_by", ()) or ()]
    return any(e is not None and _expr_uses_sys(e, catalog)
               for e in exprs)


# ------------------------------------------------------- frame builders

def _tables_frame(engine) -> pd.DataFrame:
    dev = engine.runner.device_bytes_by_table()
    rows = []
    for name in engine.catalog.names():
        e = engine.catalog.get(name)
        acc = e.is_accelerated
        rows.append({
            "table": name,
            "accelerated": acc,
            # null until the lazy fallback frame materializes — listing
            # tables must not force a parquet load (same rule as /status)
            "rows": (e.segments.num_rows if acc else e.materialized_rows),
            "segments": len(e.segments.segments) if acc else 0,
            "generation": e.segments.generation if acc else None,
            "time_column": e.time_column,
            "device_bytes": dev.get(name, 0),
        })
    return pd.DataFrame(rows, columns=[
        "table", "accelerated", "rows", "segments", "generation",
        "time_column", "device_bytes"])


def _segments_frame(engine) -> pd.DataFrame:
    pinned = engine.runner.result_cache.cached_segments()
    rows = []
    for name in engine.catalog.names():
        e = engine.catalog.get(name)
        if not e.is_accelerated:
            continue
        ts = e.segments
        wm = ts.watermark
        for sid, s in enumerate(ts.segments):
            nbytes = sum(int(a.nbytes) for a in s.columns.values()) \
                + sum(int(a.nbytes) for a in s.null_masks.values())
            sealed = ts.segment_sealed(sid)
            rows.append({
                "table": name,
                "segment_id": s.meta.segment_id,
                "rows": s.meta.n_valid,
                "time_min": s.meta.time_min,
                "time_max": s.meta.time_max,
                # kind/watermark (docs/INGEST.md): sealed segments key
                # caches by the sealed generation and are complete up
                # to the table's watermark; delta blocks hold real-time
                # appends awaiting compaction
                "kind": "sealed" if sealed else "delta",
                "generation": ts.segment_generation(sid),
                "watermark": wm,
                "bytes": nbytes,
                "cache_pinned": (name, s.meta.segment_id) in pinned,
            })
    return pd.DataFrame(rows, columns=[
        "table", "segment_id", "rows", "time_min", "time_max", "kind",
        "generation", "watermark", "bytes", "cache_pinned"])


_QUERY_COLS = (
    "query_id", "ts_ms", "query_type", "datasource", "path",
    "template_id", "total_ms", "rows_scanned", "segments_scanned",
    "rows_returned", "cache_hit", "cache_tier", "failed", "pipelined",
    "batch_id", "fallback_reason")


def _queries_frame(engine) -> pd.DataFrame:
    recs = list(engine.runner.history)
    rows = []
    for r in recs:
        if r.get("query_type", "?") == "?":
            continue  # runner notes (healer/reprobe), not queries
        row = {c: r.get(c) for c in _QUERY_COLS}
        row["cache_hit"] = bool(r.get("cache_hit"))
        row["failed"] = bool(r.get("failed"))
        rows.append(row)
    return pd.DataFrame(rows, columns=list(_QUERY_COLS))


_TEMPLATE_COLS = (
    "template_id", "datasource", "query_type", "count", "failures",
    "p50_ms", "p95_ms", "p99_ms", "mean_ms", "total_ms", "rows_scanned",
    "segments_scanned", "cache_hit_rate", "cache_full_hits",
    "cache_segment_hits", "segments_cached", "dims", "granularities",
    "paths", "first_seen_ms", "last_seen_ms", "template")


def _templates_frame(engine) -> pd.DataFrame:
    return pd.DataFrame(engine.runner.workload.snapshot(),
                        columns=list(_TEMPLATE_COLS))


def _metrics_frame(engine) -> pd.DataFrame:
    engine.runner.refresh_resource_gauges()
    return pd.DataFrame(engine.metrics.snapshot_rows(), columns=[
        "name", "kind", "labels", "value", "count", "total"])


_CUBE_COLS = (
    "name", "base_table", "table", "dims", "granularity", "status",
    "rows", "base_generation", "cube_generation", "stale",
    "last_refresh_ms", "build_ms", "refreshes", "serve_count",
    "storage_bytes", "sketch_bytes", "error")


def _cubes_frame(engine) -> pd.DataFrame:
    """sys.cubes: the materialized-rollup registry (tpu_olap.cubes) —
    per cube: dims/grain, row count, the base table's LIVE ingest
    generation vs the generation the cube was built from (stale =
    mismatch: unservable until the maintainer rebuilds), build cost,
    and how many queries the rewrite pass served from it."""
    return pd.DataFrame(engine.cubes.snapshot(),
                        columns=list(_CUBE_COLS))


_CHECKPOINT_COLS = (
    "table", "checkpoint_id", "wal_watermark", "sealed_through_seq",
    "acked_seq", "checkpoints", "segments", "bytes", "chunks_reused",
    "manifests_retained", "last_status")


def _checkpoints_frame(engine) -> pd.DataFrame:
    """sys.checkpoints: the durable segment store (segments/store.py;
    docs/DURABILITY.md) — per table: the newest manifest's id and WAL
    watermark (frames past it replay at recovery; frames at or below
    the LAG-ONE watermark are truncated), spilled bytes, and how many
    chunk files the last checkpoint reused instead of rewriting."""
    return pd.DataFrame(engine.ingest.store_rows(),
                        columns=list(_CHECKPOINT_COLS))


_DEVICE_COLS = (
    "index", "device", "platform", "process", "chips", "segments",
    "resident_bytes", "dispatches", "cache_shard_entries",
    "rebased_cols", "rebase_rows_uploaded", "hbm_bytes",
    "table_column_bytes", "cube_table_bytes", "inflight_bytes",
    "cache_pin_bytes", "hbm_high_watermark_bytes",
    "hbm_headroom_bytes")


def _devices_frame(engine) -> pd.DataFrame:
    """sys.devices: one row per mesh chip (or the single device) — the
    interleaved-placement census (logical segments owned = those with
    id ≡ chip mod D), per-chip resident bytes, multi-chip dispatch
    participation, and tier-1 cache-SHARD entry counts (an entry's chip
    is its segment's placement owner). `rebased_*` columns surface the
    incremental re-place path (only delta-touched segments' rows
    re-upload on an ingest snapshot swap). The hbm_* columns (ISSUE
    17) are the ledger's exact per-(chip, owner-class) attribution:
    table_column + cube_table + inflight bytes sum to hbm_bytes (and
    across chips to HbmLedger.bytes_in_use); cache_pin_bytes is the
    tier-1 ResultCache's per-chip byte census; high-watermark and
    headroom are against the per-chip share of hbm_budget_bytes."""
    return pd.DataFrame(engine.runner.device_snapshot(),
                        columns=list(_DEVICE_COLS))


_METRICS_HISTORY_COLS = ("ts_ms", "name", "kind", "labels", "value",
                         "count")


def _metrics_history_frame(engine) -> pd.DataFrame:
    """sys.metrics_history: the telemetry sampler's bounded per-series
    rings (obs.timeseries; ISSUE 17) — one row per retained sample.
    Scalar series carry `value` (the counter/gauge level at ts_ms);
    histogram series carry (`value`=observation sum, `count`=n), the
    _sum/_count pair rates and means derive from. The engine answers
    SQL over its own recent telemetry with no external TSDB."""
    return pd.DataFrame(engine.runner.telemetry.rows(),
                        columns=list(_METRICS_HISTORY_COLS))


_ALERT_COLS = ("alert_id", "kind", "subject", "stage", "status",
               "fired_at_ms", "last_seen_ms", "cleared_at_ms", "count",
               "total_ms", "baseline_ms", "threshold_ms")


def _alerts_frame(engine) -> pd.DataFrame:
    """sys.alerts: the regression sentinel's alert history (active +
    cleared, obs.sentinel; ISSUE 17). `stage` names the attributed
    stage for latency_drift alerts; resource alerts (hbm_pressure,
    eviction_thrash, wal_lag, breaker_open, admission_shed) carry
    their condition under subject/count."""
    rows = [{c: a.get(c) for c in _ALERT_COLS}
            for a in engine.runner.sentinel.alert_rows()]
    return pd.DataFrame(rows, columns=list(_ALERT_COLS))


def _caches_frame(engine) -> pd.DataFrame:
    runner = engine.runner
    snap = runner.result_cache.snapshot()
    rows = []
    for tier in ("full", "segment"):
        t = snap[tier]
        rows.append({
            "cache": tier, "entries": t["entries"], "bytes": t["bytes"],
            "budget_bytes": t["budget_bytes"], "hit": t["hit"],
            "miss": t["miss"], "bypass": t["bypass"],
            "evict": t["evict"], "enabled": snap["enabled"][tier]})
    for cname, store in (("jit", runner._jit_cache),
                         ("plan", runner._plan_cache),
                         ("arg", runner._arg_cache)):
        rows.append({"cache": cname, "entries": len(store), "bytes": None,
                     "budget_bytes": None, "hit": None, "miss": None,
                     "bypass": None, "evict": None, "enabled": True})
    return pd.DataFrame(rows, columns=[
        "cache", "entries", "bytes", "budget_bytes", "hit", "miss",
        "bypass", "evict", "enabled"])


class SysTableProvider:
    """Resolves `sys.<name>` catalog lookups to lazily-built TableEntry
    objects over live engine state. Inside an introspection statement
    (obs.workload.introspection_scope) resolutions memoize per name, so
    however many times planning + execution consult the catalog — alias
    resolution, the scan, both sides of a self-join — the statement
    sees ONE point-in-time snapshot per sys table. Outside that scope
    each resolution is a fresh entry (never staler than its caller)."""

    _BUILDERS = {
        "sys.tables": _tables_frame,
        "sys.segments": _segments_frame,
        "sys.queries": _queries_frame,
        "sys.query_templates": _templates_frame,
        "sys.metrics": _metrics_frame,
        "sys.caches": _caches_frame,
        "sys.cubes": _cubes_frame,
        "sys.checkpoints": _checkpoints_frame,
        "sys.devices": _devices_frame,
        "sys.metrics_history": _metrics_history_frame,
        "sys.alerts": _alerts_frame,
    }

    def __init__(self, engine):
        self.engine = engine

    def has(self, name) -> bool:
        return name in self._BUILDERS

    def names(self):
        return sorted(self._BUILDERS)

    def entry(self, name):
        from tpu_olap.catalog.catalog import TableEntry
        from tpu_olap.obs.workload import introspection_scope
        scope = introspection_scope()
        if scope is not None and name in scope:
            return scope[name]
        build = self._BUILDERS[name]
        eng = self.engine
        entry = TableEntry(name=name, segments=None,
                           frame_source=lambda: build(eng))
        if scope is not None:
            scope[name] = entry
        return entry
