"""Catalog — the analog of the reference's L4 relation/metadata layer
(SURVEY.md §3.4): table registration with per-table options and column
mapping (DefaultSource's OPTIONS map), star-schema declarations with
functional dependencies (StarSchemaInfo), and a process-wide metadata cache
with explicit invalidation (DruidMetadataCache + CLEAR DRUID CACHE).
"""

from tpu_olap.catalog.star import StarSchema, StarDimension, FunctionalDependency  # noqa: F401
from tpu_olap.catalog.catalog import Catalog, TableEntry  # noqa: F401
from tpu_olap.catalog.systables import (SysTableProvider,  # noqa: F401
                                        stmt_uses_sys)
