"""Table registry + metadata cache.

The analog of the reference's DefaultSource.createRelation +
DruidMetadataCache (SURVEY.md §4.1): a registered table pairs the segment
store (the "Druid index") with its source DataFrame (the fallback path) and
per-table options, exactly the dual the reference keeps (DruidRelationInfo
carries the sourceDataframe ref). clear() is `CLEAR DRUID CACHE`.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

from tpu_olap.catalog.star import StarSchema
from tpu_olap.segments.segment import TableSegments


@dataclass
class TableEntry:
    name: str
    segments: TableSegments | None      # None: plain (dimension) table
    # pandas DataFrame source of truth for the fallback path — either the
    # frame itself or a zero-arg loader materialized on first access, so
    # parquet-registered fact tables don't pay a duplicate pandas copy of
    # data already resident as segments (SURVEY.md §8.4 #4 memory budget)
    frame_source: object = None
    time_column: str | None = None
    star: StarSchema | None = None
    options: dict = field(default_factory=dict)
    # parquet provenance (multi-file datasets): lets the fallback stream
    # row-group chunks instead of materializing one giant frame
    # (SURVEY.md §2 property 2 at SF scale — "never an error", not an OOM)
    parquet_paths: tuple = ()
    parquet_read_cols: tuple | None = None   # pre-rename names, None = all
    parquet_column_map: dict | None = None
    parquet_rows: int | None = None          # footer-metadata row estimate
    # real-time ingest (segments/delta.py; docs/INGEST.md): a zero-arg
    # (version, frames) provider of the table's appended delta rows,
    # set by the IngestManager — the fallback path's view of rows that
    # arrived after registration. None until the first append.
    delta_source: object = None
    _frame: object = None
    _frame_aug: object = field(default=None, repr=False, compare=False)
    _frame_sorted: object = field(default=None, repr=False, compare=False)
    _frame_lock: object = field(default_factory=threading.Lock,
                                repr=False, compare=False)

    def iter_chunks(self, batch_rows: int = 1 << 20, units=None):
        """Stream the parquet source as renamed pandas frames of at most
        batch_rows rows (parquet-registered tables only). `units`
        restricts the stream to [(path, [row_group, ...]), ...] — the
        parallel fallback's per-worker assignment — so the read-column
        subset and column-map rename conventions live here once for the
        sequential loop, the fork workers, and the schema probe alike."""
        import pyarrow.parquet as pq
        cmap = self.parquet_column_map
        cols = list(self.parquet_read_cols) if self.parquet_read_cols \
            else None

        def _rename(df):
            return df.rename(columns=cmap) if cmap else df

        if units is not None:
            for path, rgs in units:
                pf = pq.ParquetFile(path)
                try:
                    for rg in rgs:
                        df0 = pf.read_row_group(rg, columns=cols) \
                            .to_pandas()
                        for s in range(0, len(df0), batch_rows):
                            yield _rename(df0.iloc[s:s + batch_rows])
                finally:
                    pf.close()
            return
        for path in self.parquet_paths:
            pf = pq.ParquetFile(path)
            try:
                for batch in pf.iter_batches(batch_size=batch_rows,
                                             columns=cols):
                    yield _rename(batch.to_pandas())
            finally:
                pf.close()
        # appended delta rows ride at the end of the sequential stream
        # (the parallel per-worker path refuses when a delta exists —
        # planner.fallback gates it — so rows are never double-counted)
        ds = self.delta_source
        if ds is not None:
            for f in ds()[1]:
                yield f

    def parquet_empty_frame(self):
        """0-row frame with the post-rename parquet schema (the chunked
        fallback's empty-result prototype), read conventions shared with
        iter_chunks."""
        import pyarrow.parquet as pq
        pf = pq.ParquetFile(self.parquet_paths[0])
        try:
            df = pf.schema_arrow.empty_table().to_pandas()
        finally:
            pf.close()
        if self.parquet_read_cols:
            df = df[[c for c in self.parquet_read_cols
                     if c in df.columns]]
        if self.parquet_column_map:
            df = df.rename(columns=self.parquet_column_map)
        return df

    @property
    def frame(self):
        if self._frame is None:
            # double-checked under a per-entry lock: concurrent fallback
            # queries must not each materialize a multi-GB parquet frame,
            # and independent tables must not serialize each other
            with self._frame_lock:
                if self._frame is None:
                    src = self.frame_source
                    self._frame = src() if callable(src) else src
        ds = self.delta_source
        if ds is None:
            return self._frame
        # appended delta rows (docs/INGEST.md): the fallback path sees
        # base + every appended frame, memoized per delta version so a
        # burst of fallback statements pays one concat per append
        ver, frames = ds()
        if not frames:
            return self._frame
        with self._frame_lock:
            aug = self._frame_aug
            if aug is not None and aug[0] == ver:
                return aug[1]
            import pandas as pd
            cat = pd.concat([self._frame] + frames, ignore_index=True)
            self._frame_aug = (ver, cat)
            return cat

    def time_sorted_frame(self):
        """The fallback frame stably sorted by the time column, memoized
        on the source frame's identity: the interpreter pays the
        O(n log n) time sort once per frame version instead of once per
        query (it dominated warm fallback profiles). Sound because every
        downstream fallback operator produces a new frame — served
        frames are never mutated in place — and because an append
        invalidates by identity: the delta-augmented `frame` is a new
        concat object per version, so the stale sorted cache misses."""
        base = self.frame
        tc = self.time_column
        cached = self._frame_sorted
        if cached is not None and cached[0] is base and cached[1] == tc:
            return cached[2]
        with self._frame_lock:
            cached = self._frame_sorted
            if cached is not None and cached[0] is base \
                    and cached[1] == tc:
                return cached[2]
            out = base.sort_values(tc, kind="stable") \
                if tc is not None and tc in base.columns else base
            self._frame_sorted = (base, tc, out)
            return out

    @property
    def materialized_rows(self) -> int | None:
        """Row count of the fallback frame if already materialized, else
        None — monitoring must never force a lazy parquet load."""
        return len(self._frame) if self._frame is not None else None

    def column_names(self) -> set:
        """Visible SQL column names, computed WITHOUT materializing a
        lazy parquet frame (segments schema, an already-loaded frame, or
        the parquet footer). Used by output-alias resolution to decide
        whether a bare name in GROUP BY / ORDER BY shadows a column.
        Cached: entries are immutable after registration, and the
        parquet-footer read must not sit on the per-query plan path."""
        cached = getattr(self, "_column_names", None)
        if cached is not None:
            return cached
        cols: set = set()
        if self.segments is not None:
            cols.update(self.segments.schema)
        elif self._frame is not None:
            cols.update(self._frame.columns)
        elif self.parquet_paths:
            import pyarrow.parquet as pq
            pf = pq.ParquetFile(self.parquet_paths[0])
            try:
                names = pf.schema_arrow.names
            finally:
                pf.close()
            cmap = self.parquet_column_map or {}
            cols.update(cmap.get(n, n) for n in names)
        elif self.frame_source is not None \
                and not callable(self.frame_source):
            cols.update(self.frame_source.columns)
        else:
            cols.update(self.frame.columns)  # small dimension tables
        if self.time_column:
            cols.add(self.time_column)
        self._column_names = cols
        return cols

    @property
    def is_accelerated(self) -> bool:
        return self.segments is not None


class Catalog:
    def __init__(self):
        self._tables: dict[str, TableEntry] = {}
        # registered lookup maps (Druid's lookup extraction fns): the
        # SQL spelling LOOKUP(col, 'name') resolves through this
        self.lookups: dict[str, dict] = {}
        # `sys.*` virtual datasources (catalog.systables): the engine
        # wires a SysTableProvider; get()/maybe() resolve unregistered
        # sys names through it to fresh live-state entries. A REGISTERED
        # table always shadows a sys name.
        self.sys_provider = None

    def register(self, entry: TableEntry):
        self._tables[entry.name] = entry

    def is_sys(self, name) -> bool:
        """True when `name` resolves to a sys.* virtual datasource (not
        shadowed by a registered table)."""
        return (name is not None and name not in self._tables
                and self.sys_provider is not None
                and self.sys_provider.has(name))

    def get(self, name: str) -> TableEntry:
        if name not in self._tables:
            if self.is_sys(name):
                return self.sys_provider.entry(name)
            raise KeyError(f"unknown table {name!r}")
        return self._tables[name]

    def maybe(self, name: str) -> TableEntry | None:
        e = self._tables.get(name)
        if e is None and self.is_sys(name):
            return self.sys_provider.entry(name)
        return e

    def names(self):
        return sorted(self._tables)

    def drop(self, name: str):
        self._tables.pop(name, None)

    def clear(self):
        self._tables.clear()
