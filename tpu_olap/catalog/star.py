"""Star-schema declaration: fact table + dimension tables + FK edges.

Mirrors the reference's StarSchemaInfo/StarRelationInfo/FunctionalDependency
(SURVEY.md §3.4): the declaration that lets JoinTransform collapse
fact ⋈ dim joins onto the single denormalized datasource (SURVEY.md §4.3).
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass(frozen=True)
class FunctionalDependency:
    """determinant -> dependent within the denormalized fact table."""

    determinant: str
    dependent: str


@dataclass
class StarDimension:
    table: str               # dimension table name (as used in SQL)
    fact_key: str            # FK column on the fact table
    dim_key: str             # PK column on the dimension table
    column_map: dict = field(default_factory=dict)
    # dim column -> denormalized fact column; identity by default for dim
    # columns that exist on the fact table under the same name

    def fact_column(self, dim_col: str) -> str | None:
        return self.column_map.get(dim_col, dim_col)


@dataclass
class StarSchema:
    fact: str
    dimensions: tuple = ()
    functional_dependencies: tuple = ()

    def dim(self, table: str) -> StarDimension | None:
        for d in self.dimensions:
            if d.table == table:
                return d
        return None

    def matches_join(self, dim_table: str, left: str, right: str) -> bool:
        """Does `left == right` (column names) match the declared FK edge
        for dim_table, in either order?"""
        d = self.dim(dim_table)
        if d is None:
            return False
        return {left, right} == {d.fact_key, d.dim_key}

    def fd_closure(self, cols: set) -> set:
        """Closure of a column set under the declared functional
        dependencies: every column transitively determined by `cols`.
        The planner uses this to validate snowflake chain joins whose
        linking column is implied rather than materialized (SURVEY.md
        §3.2 JoinTransform: 'join keys = declared FK paths, functional
        dependencies')."""
        out = set(cols)
        changed = True
        while changed:
            changed = False
            for fd in self.functional_dependencies:
                if fd.determinant in out and fd.dependent not in out:
                    out.add(fd.dependent)
                    changed = True
        return out

    @staticmethod
    def from_json(j: dict) -> "StarSchema":
        dims = tuple(
            StarDimension(d["table"], d["factKey"], d["dimKey"],
                          dict(d.get("columnMap", {})))
            for d in j.get("dimensions", []))
        fds = tuple(
            FunctionalDependency(f["determinant"], f["dependent"])
            for f in j.get("functionalDependencies", []))
        return StarSchema(j["fact"], dims, fds)

    def to_json(self) -> dict:
        return {
            "fact": self.fact,
            "dimensions": [
                {"table": d.table, "factKey": d.fact_key,
                 "dimKey": d.dim_key, "columnMap": dict(d.column_map)}
                for d in self.dimensions],
            "functionalDependencies": [
                {"determinant": f.determinant, "dependent": f.dependent}
                for f in self.functional_dependencies],
        }
