"""Structured error taxonomy for the query path (docs/RESILIENCE.md).

Replaces the ad-hoc RuntimeError/string errors the HTTP surface used to
collapse into bare 400/500 strings: every QueryError carries a stable
machine-readable `code`, a `retriable` hint (may the client retry the
same request later?), and the `http_status` the server maps it to — so
clients can tell "retry later" (429/503/504) from "your SQL is wrong"
(400) without parsing message text.

The hierarchy deliberately double-inherits where the legacy exception
type was part of the contract (UserError is a ValueError so existing
`except (ValueError, KeyError)` surfaces keep mapping it to 400;
InternalError is a RuntimeError for the same reason). The deadline and
fallback exceptions defined elsewhere (executor.runner.
QueryDeadlineExceeded, planner.fallback.FallbackError) subclass
QueryError too — the taxonomy is one tree across runner, fallback,
batch, and engine.
"""

from __future__ import annotations


class QueryError(Exception):
    """Base of the taxonomy. `code` is stable and machine-readable;
    `retriable` means the same request may succeed later (transient
    overload / sick device), not that the client should hammer;
    `http_status` is what api.server maps the error to."""

    code = "internal"
    retriable = False
    http_status = 500

    def to_json(self) -> dict:
        return {"error": str(self), "code": self.code,
                "retriable": self.retriable}


class UserError(QueryError, ValueError):
    """The request itself is wrong (bad SQL, unknown table, malformed
    query JSON) — retrying the same request can never succeed."""

    code = "user_error"
    retriable = False
    http_status = 400


class QueryShed(QueryError):
    """Admission control rejected the query: the dispatch queue is full,
    or the query's remaining deadline budget cannot cover the expected
    queue wait (shedding now beats timing out later). Transient by
    definition — retry with backoff."""

    code = "shed"
    retriable = True
    http_status = 429

    def __init__(self, msg: str, reason: str = "queue_full"):
        super().__init__(msg)
        self.reason = reason


class BreakerOpen(QueryError):
    """The device circuit breaker is open: consecutive failures tripped
    it and the healer has not yet closed it. `retry_after_s` is the
    cooldown remaining — the server sends it as Retry-After."""

    code = "breaker_open"
    retriable = True
    http_status = 503

    def __init__(self, msg: str, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.retry_after_s = max(0.0, float(retry_after_s))


class IngestBackpressure(QueryError):
    """Real-time append rejected: the table's in-memory delta is at
    `ingest_max_delta_rows` and accepting more would grow host memory
    unboundedly ahead of the compactor. Explicit 429 + Retry-After —
    never a silent drop; retry after the compactor drains the delta
    (docs/INGEST.md)."""

    code = "ingest_backpressure"
    retriable = True
    http_status = 429

    def __init__(self, msg: str, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.retry_after_s = max(0.0, float(retry_after_s))


class DeviceFailure(QueryError):
    """Device dispatch failed after retries exhausted and no fallback
    was available (fallback_on_device_failure=False, or a raw-IR
    passthrough with no interpreter equivalent)."""

    code = "device_failure"
    retriable = True
    http_status = 500


class InternalError(QueryError, RuntimeError):
    """Engine-internal invariant violation (e.g. a batch leader exiting
    without producing a result). A bug, not a client problem."""

    code = "internal"
    retriable = False
    http_status = 500
