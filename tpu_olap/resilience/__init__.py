"""Resilience layer: structured errors, admission control, circuit
breaker, and generalized fault injection (docs/RESILIENCE.md).

The reference survives broker flakiness because Spark task retry re-runs
a DruidRDD partition and the planner can always fall back to the raw
scan (SURVEY.md §2 property 2, §6). This package adds what happens
*around* a sick device under heavy concurrent traffic:

- errors:    the QueryError taxonomy (code / retriable / http_status)
             that the HTTP surface maps to 400 / 429 / 503 / 504
- admission: bounded device-dispatch queue (max inflight + max queued,
             deadline-aware shedding)
- breaker:   circuit breaker on consecutive device failures, with a
             background healer thread that half-opens via the device
             probe and routes fallback-capable queries to the
             interpreter while open (path="fallback_breaker")
- faults:    the generalized EngineConfig.fault_injector call sites
             (dispatch / host-transfer / reprobe / ingest / batch-leg /
             append / wal-write / wal-replay / compact)
"""

from tpu_olap.resilience.admission import AdmissionController
from tpu_olap.resilience.breaker import CircuitBreaker
from tpu_olap.resilience.errors import (BreakerOpen, DeviceFailure,
                                        IngestBackpressure,
                                        InternalError, QueryError,
                                        QueryShed, UserError)
from tpu_olap.resilience.faults import FaultInjector, maybe_inject

__all__ = [
    "AdmissionController", "BreakerOpen", "CircuitBreaker",
    "DeviceFailure", "FaultInjector", "IngestBackpressure",
    "InternalError", "QueryError", "QueryShed", "UserError",
    "maybe_inject",
]
