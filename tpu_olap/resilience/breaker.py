"""Device circuit breaker with a background healer thread.

Before this layer, every query against a wedged chip burned a full
`query_deadline_s` plus a reprobe before discovering what the previous
query already knew. The breaker makes that knowledge shared state:

- **closed**: normal serving. Terminal dispatch failures (retries
  exhausted) and deadline hits count consecutively; any success resets
  the count.
- **open**: `failure_threshold` consecutive failures trip it. check()
  fails fast with BreakerOpen (carrying the cooldown remaining as
  Retry-After) — the engine routes fallback-capable queries to the
  interpreter (degraded-but-correct, path="fallback_breaker") and
  legibly refuses the rest. No query touches the device.
- **half_open**: after `cooldown_s` the healer thread (spawned on trip,
  daemon) probes the device via the runner's existing reprobe round
  trip. Probe success closes the breaker; failure re-opens it for
  another cooldown. Queries never race the probe — healing is the
  healer's job, so an open breaker costs callers microseconds, not
  trial-query deadlines.

State is exported as `tpu_olap_breaker_state` (0=closed, 1=half_open,
2=open) plus a `tpu_olap_breaker_transitions_total{state=...}` counter.
`failure_threshold <= 0` disables the breaker entirely.
"""

from __future__ import annotations

import threading
import time

from tpu_olap.obs.metrics import BREAKER_STATE_VALUES as STATE_VALUES
from tpu_olap.resilience.errors import BreakerOpen

CLOSED, HALF_OPEN, OPEN = "closed", "half_open", "open"


class CircuitBreaker:
    def __init__(self, failure_threshold: int, cooldown_s: float,
                 probe=None, metrics=None, events=None):
        self.failure_threshold = int(failure_threshold)
        self.cooldown_s = max(0.05, float(cooldown_s))
        self.probe = probe          # () -> bool; set by the runner
        self._events = events       # obs.events.EventLog (optional)
        self._pending_events: list = []  # emitted outside self._lock
        self._emit_lock = threading.Lock()  # flushers, in pop order
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive = 0
        self._opened_at = 0.0
        self._healer = None
        self._wake = threading.Event()  # close() cancels a healer wait
        self.failures_total = 0
        self.trips_total = 0
        self._m_state = self._m_trans = None
        if metrics is not None:
            self._m_state = metrics.gauge(
                "breaker_state",
                "Device circuit breaker (0=closed,1=half_open,2=open).")
            self._m_trans = metrics.counter(
                "breaker_transitions_total",
                "Breaker state transitions.", ("state",))
            self._m_state.set(0)

    @property
    def enabled(self) -> bool:
        return self.failure_threshold > 0

    @property
    def state(self) -> str:
        return self._state

    def _set_state(self, state: str):
        # caller holds self._lock
        if state == self._state:
            return
        prev, self._state = self._state, state
        if self._m_state is not None:
            self._m_state.set(STATE_VALUES[state])
        if self._m_trans is not None:
            self._m_trans.inc(state=state)
        if self._events is not None:
            # queued, not emitted: emit may write to the JSONL file
            # sink, and a hung sink must not stall every dispatch's
            # record_success/record_failure behind our lock — callers
            # flush after releasing it (_flush_events)
            self._pending_events.append(
                {"state": state, "previous": prev,
                 "consecutive_failures": self._consecutive})

    def _flush_events(self):
        """Emit transitions queued by _set_state, OUTSIDE self._lock."""
        # unlocked empty probe: the common path (no transition) must not
        # pay a second lock round-trip per dispatch. A racing append is
        # never lost — the appending mutator flushes after its own
        # mutation.
        if self._events is None or not self._pending_events:
            return
        # _emit_lock serializes pop+emit across concurrent flushers, so
        # the log's transition order always matches the state machine's
        # (emit only enqueues to the async sink — never file I/O here)
        with self._emit_lock:
            with self._lock:
                pending, self._pending_events = self._pending_events, []
            for p in pending:
                self._events.emit("breaker", **p)

    # ------------------------------------------------------------ events

    def check(self):
        """Fail fast while open. Call before any device work."""
        if not self.enabled or self._state != OPEN:
            return
        with self._lock:
            if self._state != OPEN:
                return
            remaining = max(
                0.0,
                self.cooldown_s - (time.monotonic() - self._opened_at))
            raise BreakerOpen(
                f"device circuit breaker open "
                f"({self._consecutive} consecutive failures; healer "
                f"probes in {remaining:.2f}s)",
                retry_after_s=remaining or self.cooldown_s)

    def record_success(self):
        if not self.enabled:
            return
        with self._lock:
            self._consecutive = 0
            if self._state == HALF_OPEN:
                self._set_state(CLOSED)
        self._flush_events()

    def record_failure(self, kind: str = "failure"):
        """A terminal device failure (retries exhausted, deadline hit,
        or probe failure) — NOT per-attempt errors the retry layer
        already absorbed."""
        if not self.enabled:
            return
        with self._lock:
            self.failures_total += 1
            self._consecutive += 1
            if self._state != OPEN and \
                    self._consecutive >= self.failure_threshold:
                self._trip_locked()
        self._flush_events()

    def _trip_locked(self):
        self.trips_total += 1
        self._opened_at = time.monotonic()
        self._set_state(OPEN)
        # _healer goes back to None ONLY under this lock with the state
        # CLOSED (healer retirement), so either it is None here — spawn
        # — or a live healer will re-check the state before retiring and
        # keep healing. Without that invariant a re-trip racing a
        # retiring healer could leave the breaker open with nobody
        # scheduled to close it.
        if self._healer is None:
            self._healer = threading.Thread(
                target=self._heal_loop, daemon=True,
                name="tpu-olap-breaker-healer")
            self._healer.start()

    def close(self):
        """Force-close (admin surface / tests). Cancels a waiting
        healer."""
        with self._lock:
            self._consecutive = 0
            self._set_state(CLOSED)
        self._flush_events()
        self._wake.set()

    # ------------------------------------------------------------ healer

    def _heal_loop(self):
        """Background healer: sleep out the cooldown, half-open, probe;
        success closes, failure re-opens for another cooldown. Retires
        (sets _healer back to None, under the lock) only once the
        breaker is CLOSED — a re-trip mid-probe (a query slipped through
        during half-open and failed) keeps this same thread healing
        instead of stranding the breaker open with no healer."""
        while True:
            # cleared HERE (the loop owns the event): a stale set() from
            # an earlier close() that raced a re-trip must not turn the
            # cooldown wait into a busy probe loop. A close() landing
            # between clear and wait re-sets it, so cancellation is
            # never lost — the wait returns and the state check retires.
            self._wake.clear()
            self._wake.wait(self.cooldown_s)
            with self._lock:
                if self._state == CLOSED:
                    self._healer = None
                    return
                if self._state == OPEN:
                    self._set_state(HALF_OPEN)
            self._flush_events()
            ok = False
            try:
                ok = bool(self.probe()) if self.probe is not None \
                    else True
            except Exception:  # noqa: BLE001 — a failed probe is data
                ok = False
            healed = False
            with self._lock:
                if self._state == HALF_OPEN:
                    if ok:
                        self._consecutive = 0
                        self._set_state(CLOSED)
                        self._healer = None
                        healed = True
                    else:
                        self._opened_at = time.monotonic()
                        self._set_state(OPEN)
                # OPEN here = re-tripped mid-probe; CLOSED = someone
                # closed us externally — either way the loop top decides
            self._flush_events()
            if healed:
                return
