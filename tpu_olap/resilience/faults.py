"""Generalized fault injection (EngineConfig.fault_injector).

The injector signature is unchanged — ``callable(stage, attempt)`` that
may raise — but the call sites now cover every failure point the
resilience layer must survive, not just the device dispatch:

    dispatch       executor.runner._dispatch (per retry attempt)
    host-transfer  device buffers -> numpy materialization
    reprobe        the post-wedge / healer device probe
    ingest         Engine.register_table's segment build
    batch-leg      per-leg finalize of a fused shared-scan dispatch
    append         Engine.append before any state change (crash before
                   the WAL write: the batch is fully absent)
    wal-write      just before the WAL frame write (crash before
                   durability: the batch was never acknowledged)
    wal-replay     per replayed record during crash recovery (a crash
                   mid-recovery leaves the table cleanly base-only;
                   re-registration replays again)
    compact        the background compactor before the sealed-set swap
                   (a crashed compaction leaves the delta intact)
    spill-write    segments/store.py before any chunk file is written
                   (a crashed spill leaves at most orphan chunks; the
                   manifest — and therefore recovery — is unchanged)
    manifest-swap  before the checkpoint manifest's atomic rename (the
                   spilled chunks exist but the previous manifest stays
                   authoritative; the WAL is not truncated)
    store-load     Engine.register_table before the store's recovery
                   ladder runs (a crash mid-recovery aborts the
                   registration; a retry loads the store again)
    wal-truncate   after the manifest swap, before the WAL rewrite (the
                   log keeps pre-checkpoint frames; replay filters them
                   by the manifest watermark)
    stage-plan     executor.stages boundary entering the plan stage of
    stage-enqueue  a query's stage graph (and likewise for enqueue /
    stage-transfer transfer / finalize / assemble / background) — fired
    stage-finalize by StageScheduler.stage before the pool slot is
    stage-assemble taken, so a fault here is a failure BETWEEN stages:
    stage-background  after the previous stage committed its work, before
                   the next one starts (docs/EXECUTION.md)

Backwards compatibility: a plain callable (no ``stages`` attribute)
fires ONLY at the classic ``dispatch`` site, exactly as before — every
pre-existing test and tool keeps its behavior. An injector that wants
the generalized sites declares them:

    class Chaos:
        stages = None            # None = every site
        # or stages = {"dispatch", "host-transfer"}
        def __call__(self, stage, attempt): ...

or uses the FaultInjector helper below.
"""

from __future__ import annotations

LEGACY_STAGES = ("dispatch",)

ALL_STAGES = ("dispatch", "host-transfer", "reprobe", "ingest",
              "batch-leg", "append", "wal-write", "wal-replay",
              "compact", "spill-write", "manifest-swap", "store-load",
              "wal-truncate", "stage-plan", "stage-enqueue",
              "stage-transfer", "stage-finalize", "stage-assemble",
              "stage-background")


def maybe_inject(config, stage: str, attempt: int = 0) -> None:
    """Fire the configured fault injector at `stage` if it opted in.
    Injectors without a `stages` attribute are legacy dispatch-only."""
    inj = getattr(config, "fault_injector", None)
    if inj is None:
        return
    stages = getattr(inj, "stages", LEGACY_STAGES)
    if stages is not None and stage not in stages:
        return
    inj(stage, attempt)


class FaultInjector:
    """Deterministic seeded chaos injector for tests and bench runs:
    raises RuntimeError at each opted-in site with probability `rate`
    (or on an explicit schedule via `fail_calls`). `stages=None` opts
    into every site.

    `latency_s` > 0 turns a hit into a SLEEP instead of a raise — the
    slow-device/slow-link chaos mode (ISSUE 17): the query still
    succeeds, just late, which is exactly the drift the regression
    sentinel (obs.sentinel) must catch and attribute to the injected
    stage. A hit with `latency_s` at 0 keeps the classic raise."""

    def __init__(self, seed: int = 0, rate: float = 0.0, stages=None,
                 fail_calls=(), latency_s: float = 0.0):
        import random
        self.rng = random.Random(seed)
        self.rate = float(rate)
        self.stages = stages
        self.fail_calls = set(fail_calls)
        self.latency_s = float(latency_s)
        self.calls = 0
        self.faults = 0
        self.by_stage: dict[str, int] = {}

    def __call__(self, stage: str, attempt: int):
        self.calls += 1
        hit = self.calls in self.fail_calls or (
            self.rate > 0 and self.rng.random() < self.rate)
        if hit:
            self.faults += 1
            self.by_stage[stage] = self.by_stage.get(stage, 0) + 1
            if self.latency_s > 0:
                import time
                time.sleep(self.latency_s)
                return
            raise RuntimeError(
                f"injected fault #{self.faults} at {stage} "
                f"(call {self.calls}, attempt {attempt})")
