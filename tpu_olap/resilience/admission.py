"""Admission control: a bounded device-dispatch queue.

The chip has one program queue (SURVEY.md §3.5 P1), so device dispatch
serializes on QueryRunner.dispatch_lock — but the HTTP surface runs on
an unbounded ThreadingHTTPServer thread pool, and before this layer
every concurrent query piled onto that lock and waited however long the
backlog took. The admission controller bounds that pile-up the way a
production broker does:

- at most `max_inflight` dispatches hold slots concurrently (the lock
  still serializes the device itself; extra slots overlap the Python
  pre/post work around it);
- at most `queue_limit` callers wait for a slot — the next one is shed
  immediately with QueryShed (HTTP 429), which a load balancer turns
  into "try another replica" instead of a growing queue;
- **deadline-aware shedding**: a query whose `query_deadline_s` budget
  cannot cover the expected queue wait (EWMA of recent slot hold times
  x queue depth) is shed at the door instead of burning its deadline in
  line and timing out anyway — the difference between a 429 in
  microseconds and a 504 after `query_deadline_s`.

Queue depth, queue wait, and shed counts are first-class metrics
(`tpu_olap_admission_queue_depth`, `tpu_olap_admission_queue_wait_ms`,
`tpu_olap_queries_shed_total{reason=...}`).

Slot acquisition is not strictly FIFO (condition wake order, and a
fresh arrival can take a just-freed slot before a woken waiter) — the
bound is on *how many* wait, not their order; all waiters make progress
because every release notifies.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager

from tpu_olap.resilience.errors import QueryShed

# seed for the service-time EWMA before any dispatch completes; a few
# tens of ms is the observed warm SSB dispatch scale
_EWMA_SEED_S = 0.05
_EWMA_ALPHA = 0.2


class AdmissionController:
    """Bounded, deadline-aware admission to the dispatch section.

    `max_inflight <= 0` disables admission entirely (every slot()
    context is a no-op) — the pre-resilience behavior.
    """

    def __init__(self, max_inflight: int, queue_limit: int,
                 metrics=None, events=None, pipeline_depth: int = 0):
        self.max_inflight = int(max_inflight)
        self.queue_limit = max(0, int(queue_limit))
        self._events = events  # obs.events.EventLog (optional)
        self._cond = threading.Condition()
        self._inflight = 0
        self._queued = 0
        self._service_ewma_s = _EWMA_SEED_S
        self._local = threading.local()  # re-entrancy guard
        # pipelined execution (EngineConfig.pipeline_depth): bounds how
        # many dispatches may sit between stage-1 enqueue and stage-2
        # completion at once, so queued device work and pinned result
        # buffers stay within the HBM budget. Independent of the
        # max_inflight dispatch-slot bound (admission off still bounds
        # the pipeline); 0 disables the gate.
        self.pipeline_depth = max(0, int(pipeline_depth))
        self._p_cond = threading.Condition()
        self._p_inflight = 0
        self._m_shed = self._m_depth = self._m_wait = None
        self._m_pipeline = None
        if metrics is not None:
            from tpu_olap.obs.metrics import QUEUE_WAIT_BUCKETS_MS
            self._m_shed = metrics.counter(
                "queries_shed_total",
                "Queries shed by admission control.", ("reason",))
            self._m_depth = metrics.gauge(
                "admission_queue_depth",
                "Callers currently queued for a dispatch slot.")
            self._m_wait = metrics.histogram(
                "admission_queue_wait_ms",
                "Wait for a dispatch slot (admitted queries only).",
                buckets=QUEUE_WAIT_BUCKETS_MS)
            self._m_pipeline = metrics.gauge(
                "pipeline_inflight",
                "Dispatches between stage-1 enqueue and stage-2 "
                "completion (pipelined execution occupancy).")
            self._m_depth.set(0)
            self._m_pipeline.set(0)

    # ------------------------------------------------------------ stats

    def snapshot(self) -> dict:
        with self._cond:
            out = {"inflight": self._inflight, "queued": self._queued,
                   "max_inflight": self.max_inflight,
                   "queue_limit": self.queue_limit,
                   "service_ewma_ms": round(
                       self._service_ewma_s * 1000, 3)}
        with self._p_cond:
            out["pipeline_depth"] = self.pipeline_depth
            out["pipeline_inflight"] = self._p_inflight
        return out

    def _expected_wait_s(self) -> float:
        """Coarse queue-wait estimate under the lock: everyone ahead of
        a new arrival (current queue, plus the backlog implied by full
        slots) costs ~one EWMA'd service time per max_inflight slots."""
        if self._inflight < self.max_inflight:
            return 0.0
        ahead = self._queued + 1
        return ahead * self._service_ewma_s / max(1, self.max_inflight)

    def _shed(self, reason: str, msg: str):
        # metric inc only (a few dict ops): _shed fires while the caller
        # holds self._cond, so the event emission — which may write to
        # the JSONL file sink — happens in slot(), outside the lock
        if self._m_shed is not None:
            self._m_shed.inc(reason=reason)
        raise QueryShed(msg, reason=reason)

    def _emit_shed(self, e: QueryShed):
        """Shed event, emitted OUTSIDE self._cond: a slow event-log file
        sink must not stall every other thread's admission. A shed query
        never reaches QueryRunner.record(), so this event IS its entry
        in the structured log."""
        if self._events is not None:
            from tpu_olap.obs.trace import current_query_id
            self._events.emit("shed", reason=e.reason, detail=str(e),
                              query_id=current_query_id())

    # ------------------------------------------------------------- slot

    @contextmanager
    def slot(self, budget_s: float | None = None):
        """Hold one dispatch slot for the body. May raise QueryShed
        before the body runs; never after. `budget_s` is the query's
        remaining deadline budget (None = no deadline): used both for
        the at-the-door expected-wait shed and as the cap on actual
        queue wait. Re-entrant per thread (nested holds are free), so
        a batch path that re-enters the runner never deadlocks on its
        own slot."""
        if self.max_inflight <= 0 or getattr(self._local, "held", 0):
            yield
            return
        try:
            waited_ms = self._admit(budget_s)
        except QueryShed as e:
            self._emit_shed(e)
            raise
        if self._m_wait is not None:
            self._m_wait.observe(waited_ms)
        self._local.held = 1
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self._local.held = 0
            held_s = time.perf_counter() - t0
            with self._cond:
                self._inflight -= 1
                self._service_ewma_s += _EWMA_ALPHA * (
                    held_s - self._service_ewma_s)
                self._cond.notify()

    def _admit(self, budget_s: float | None) -> float:
        """Block until a slot frees (bounded by queue_limit and the
        deadline budget); returns the wait in ms."""
        with self._cond:
            if self._inflight < self.max_inflight:
                self._inflight += 1
                return 0.0
            if self._queued >= self.queue_limit:
                self._shed(
                    "queue_full",
                    f"dispatch queue full ({self._queued} queued, "
                    f"limit {self.queue_limit})")
            exp = self._expected_wait_s()
            if budget_s is not None and exp > budget_s:
                self._shed(
                    "deadline_budget",
                    f"expected queue wait {exp * 1000:.0f} ms exceeds "
                    f"the query's deadline budget "
                    f"{budget_s * 1000:.0f} ms")
            self._queued += 1
            if self._m_depth is not None:
                self._m_depth.set(self._queued)
            t0 = time.perf_counter()
            deadline = None if budget_s is None else t0 + budget_s
            try:
                while self._inflight >= self.max_inflight:
                    timeout = None
                    if deadline is not None:
                        timeout = deadline - time.perf_counter()
                        if timeout <= 0:
                            self._shed(
                                "deadline_budget",
                                "deadline budget exhausted while "
                                "queued for a dispatch slot")
                    self._cond.wait(timeout)
            finally:
                self._queued -= 1
                if self._m_depth is not None:
                    self._m_depth.set(self._queued)
            self._inflight += 1
            return (time.perf_counter() - t0) * 1000

    # --------------------------------------------------------- pipeline

    @contextmanager
    def pipeline_slot(self, budget_s: float | None = None):
        """Hold one in-flight pipeline slot for the body (stage-1
        enqueue through stage-2 completion of one device dispatch).
        Bounds queued device work + pinned result buffers at
        pipeline_depth; a waiter whose deadline budget expires before a
        slot frees is shed (the dispatch was doomed anyway). Re-entrant
        per thread, like slot(): a path that re-enters the runner never
        deadlocks on its own pipeline slot. Disabled (depth 0) -> no-op.
        """
        if self.pipeline_depth <= 0 or getattr(self._local, "p_held", 0):
            yield
            return
        try:
            self._p_admit(budget_s)
        except QueryShed as e:
            self._emit_shed(e)  # outside the cond, like slot()
            raise
        self._local.p_held = 1
        try:
            yield
        finally:
            self._local.p_held = 0
            with self._p_cond:
                # clamp: reset_pipeline may have reclaimed this slot
                # while its (abandoned) holder was still running
                self._p_inflight = max(0, self._p_inflight - 1)
                if self._m_pipeline is not None:
                    self._m_pipeline.set(self._p_inflight)
                self._p_cond.notify()

    def reset_pipeline(self):
        """Reclaim in-flight pipeline slots stranded by deadline-
        abandoned dispatch threads — called from wedge recovery once
        the device has been probed healthy and its state purged
        (QueryRunner._recover_after_probe). Without this, pipeline_depth
        hung dispatches would permanently zero the engine's device
        capacity even after the device heals. A stranded worker that
        later wakes releases a slot that was already reclaimed; the
        release clamps at zero, so the worst case is a transiently
        over-admitted dispatch, not permanent starvation."""
        with self._p_cond:
            if self._p_inflight:
                self._p_inflight = 0
                if self._m_pipeline is not None:
                    self._m_pipeline.set(0)
                self._p_cond.notify_all()

    def _p_admit(self, budget_s: float | None):
        with self._p_cond:
            deadline = None if budget_s is None \
                else time.perf_counter() + budget_s
            while self._p_inflight >= self.pipeline_depth:
                timeout = None
                if deadline is not None:
                    timeout = deadline - time.perf_counter()
                    if timeout <= 0:
                        self._shed(
                            "pipeline_stall",
                            "deadline budget exhausted waiting for an "
                            "in-flight pipeline slot")
                self._p_cond.wait(timeout)
            self._p_inflight += 1
            if self._m_pipeline is not None:
                self._m_pipeline.set(self._p_inflight)
