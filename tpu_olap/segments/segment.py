"""Segment blocks, per-segment metadata, and the table-level container.

The analog of Druid's segment files + the reference's DruidDataSource/
SegmentInfo metadata model (SURVEY.md §3.4): fixed-size row blocks sorted by
time, a manifest of per-segment [time_min, time_max] + column stats for
pruning, and table-level schema/dictionaries.
"""

from __future__ import annotations

import enum
import itertools as _itertools
from dataclasses import dataclass, field

import numpy as np

from tpu_olap.segments.dictionary import Dictionary

TIME_COLUMN = "__time"

# per-table-name ingest generation (the Druid segment-version analog):
# every TableSegments construction takes the next value, so ingest and
# re-registration orphan all semantic-cache entries for that table at
# key level (executor.resultcache) — a stale generation can never be
# served even before the eager purge runs. Module-global on purpose:
# two engines registering the same name in one process must not reuse
# generations against each other.
import threading as _threading

_GEN_LOCK = _threading.Lock()
_GENERATIONS: dict = {}


def next_table_generation(name: str) -> int:
    with _GEN_LOCK:
        g = _GENERATIONS.get(name, 0) + 1
        _GENERATIONS[name] = g
        return g


class ColumnType(enum.Enum):
    STRING = "STRING"  # dict-encoded int32 codes (0 = null)
    LONG = "LONG"      # int64
    DOUBLE = "DOUBLE"  # float64

    @property
    def is_dim(self):
        return self is ColumnType.STRING


@dataclass
class SegmentMeta:
    segment_id: int
    n_valid: int              # rows 0..n_valid-1 are real; rest is padding
    time_min: int
    time_max: int
    column_min: dict = field(default_factory=dict)  # numeric cols only
    column_max: dict = field(default_factory=dict)

    def to_json(self):
        return {"segmentId": self.segment_id, "numRows": self.n_valid,
                "timeMin": self.time_min, "timeMax": self.time_max,
                "columnMin": dict(self.column_min),
                "columnMax": dict(self.column_max)}


_SEG_UID = _itertools.count(1)


@dataclass
class Segment:
    """One fixed-size block of rows. All column arrays have block_rows
    entries; rows >= meta.n_valid are padding (never observable: every
    kernel threads a row-validity mask)."""

    meta: SegmentMeta
    columns: dict  # name -> np.ndarray (int32 codes | int64 | float64)
    null_masks: dict  # name -> bool array, only for numeric cols with nulls
    # process-unique identity stamp: snapshots that SHARE a segment by
    # object (delta-only appends, incremental compaction's untouched
    # partitions) share the uid, so per-segment cache state keyed on it
    # survives exactly as long as the data is literally the same block
    uid: int = field(default_factory=lambda: next(_SEG_UID))

    @property
    def block_rows(self) -> int:
        return len(next(iter(self.columns.values())))


class TableSegments:
    """All segments of one registered datasource + shared metadata.

    Segment scopes (docs/INGEST.md): `segments[:sealed_count]` are the
    SEALED store (built by batch ingest or compaction, immutable,
    time-partitioned); anything past it is the mutable table's DELTA —
    frozen append blocks the real-time ingest path swaps in. Two
    generations track the two scopes: `generation` moves on EVERY
    snapshot construction (appends included) and keys whole-result
    state (the tier-2 full-result cache), while `sealed_generation`
    moves only when the sealed set itself changes (registration,
    compaction) — so per-sealed-segment partial-aggregate cache entries
    and materialized cubes survive delta-only appends."""

    def __init__(self, name: str, schema: dict, dictionaries: dict,
                 segments: list, block_rows: int,
                 sealed_count: int | None = None,
                 sealed_generation: int | None = None):
        self.name = name
        self.schema = schema            # col -> ColumnType (incl. __time)
        self.dictionaries = dictionaries  # col -> Dictionary (STRING cols)
        self.segments = segments        # list[Segment], time-ordered
        self.block_rows = block_rows
        # ingest generation: part of every semantic-cache key, bumped by
        # construction (each ingest/re-registration/append builds a
        # fresh TableSegments), so cached results can never outlive the
        # data they were computed from (docs/CACHING.md)
        self.generation = next_table_generation(name)
        self.sealed_count = len(segments) if sealed_count is None \
            else int(sealed_count)
        # sealed-scope generation: defaults to this snapshot's own
        # generation (a fresh registration/compaction IS a new sealed
        # set); delta-only append snapshots carry the predecessor's
        self.sealed_generation = self.generation \
            if sealed_generation is None else int(sealed_generation)
        # resolved time-partition granularity ("day"/"month"/"year" or
        # None), recorded so compaction re-partitions the same way
        self.time_partition = None
        # declared star schema (set at registration when provided):
        # lowering consults its functional dependencies for data-derived
        # dimension-domain restriction (filter on a dependent column
        # shrinking a grouped determinant's dense id space)
        self.star = None
        self._fd_code_maps: dict = {}

    # ---- segment scopes (real-time ingest; docs/INGEST.md) ---------------

    def segment_sealed(self, sid: int) -> bool:
        return sid < self.sealed_count

    def segment_generation(self, sid: int) -> int:
        """Cache-scope generation of one segment: sealed segments share
        `sealed_generation` (stable across delta-only appends), delta
        blocks take the snapshot generation (every append re-keys them
        — their contents change block-in-place across snapshots)."""
        return self.sealed_generation if sid < self.sealed_count \
            else self.generation

    def segment_cache_token(self, sid: int) -> tuple:
        """Tier-1 cache key component for one segment. Sealed segments
        use their Segment uid — identity-stable across delta-only
        appends AND incremental compaction (untouched calendar
        partitions share the object into the new sealed set), so a
        partition-aligned compaction invalidates ONLY the delta-touched
        partitions' entries (under a mesh: only the affected chip's
        cache shard). Delta blocks take the snapshot generation (each
        append re-keys them; they are never cached anyway)."""
        if sid < self.sealed_count:
            return ("u", self.segments[sid].uid)
        return ("g", self.generation)

    def delta_ids(self) -> list:
        return list(range(self.sealed_count, len(self.segments)))

    @property
    def delta_rows(self) -> int:
        return sum(s.meta.n_valid
                   for s in self.segments[self.sealed_count:])

    @property
    def watermark(self) -> int:
        """Max __time over the SEALED scope (0 when empty) — the
        boundary below which cube builds and sealed cache partials are
        complete; delta rows may carry any timestamp and are folded
        through the base path at serve time."""
        sealed = self.segments[:self.sealed_count]
        return max((s.meta.time_max for s in sealed if s.meta.n_valid),
                   default=0)

    def sealed_view(self) -> "TableSegments":
        """A sealed-scope snapshot of this table: `self` when there is
        no delta; otherwise a derived TableSegments sharing the sealed
        segment objects and dictionaries, with BOTH generations pinned
        to `sealed_generation` (the view is the sealed set — cube
        builds run against it so their partials never swallow delta
        rows the compactor would later re-deliver)."""
        if self.sealed_count >= len(self.segments):
            return self
        view = TableSegments.__new__(TableSegments)
        view.name = self.name
        view.schema = self.schema
        view.dictionaries = self.dictionaries
        view.segments = self.segments[:self.sealed_count]
        view.block_rows = self.block_rows
        view.generation = self.sealed_generation
        view.sealed_count = self.sealed_count
        view.sealed_generation = self.sealed_generation
        view.time_partition = self.time_partition
        view.star = self.star
        view._fd_code_maps = {}
        return view

    def fd_code_map(self, det: str, dep: str):
        """[det_codes+?] -> dep code map derived from the data (0 where
        only-null dep observed), or None if the data violates the
        declared FD (then no restriction is applied — correctness never
        rests on a declaration). Cached; verified with a full pass."""
        key = (det, dep)
        if key in self._fd_code_maps:
            return self._fd_code_maps[key]
        d = self.dictionaries.get(det)
        if d is None or dep not in self.dictionaries:
            self._fd_code_maps[key] = None
            return None
        m = np.zeros(d.size + 1, np.int64)
        ok = True
        for s in self.segments:
            nv = s.meta.n_valid
            a = s.columns[det][:nv].astype(np.int64)
            b = s.columns[dep][:nv].astype(np.int64)
            keep = b > 0
            m[a[keep]] = b[keep]
        for s in self.segments:
            nv = s.meta.n_valid
            a = s.columns[det][:nv].astype(np.int64)
            b = s.columns[dep][:nv].astype(np.int64)
            keep = b > 0
            if (m[a[keep]] != b[keep]).any():
                ok = False
                break
        self._fd_code_maps[key] = m if ok else None
        return self._fd_code_maps[key]

    # ---- metadata (feeds SegmentMetadata queries + cost model) -----------

    @property
    def num_rows(self) -> int:
        return sum(s.meta.n_valid for s in self.segments)

    @property
    def time_boundary(self) -> tuple[int, int]:
        if not self.segments:
            return (0, 0)
        return (min(s.meta.time_min for s in self.segments),
                max(s.meta.time_max for s in self.segments))

    def cardinality(self, col: str) -> int | None:
        d = self.dictionaries.get(col)
        return d.cardinality if d is not None else None

    def column_metadata(self, cols=None) -> dict:
        """Per-column type/cardinality/size — the SegmentMetadata query body
        (reference: populates DruidMetadataCache + cost model, §4.1)."""
        out = {}
        for col, typ in self.schema.items():
            if cols and col not in cols:
                continue
            entry = {"type": typ.value, "numRows": self.num_rows}
            d = self.dictionaries.get(col)
            if d is not None:
                entry["cardinality"] = d.cardinality
                entry["size"] = int(sum(len(v) for v in d.values))
            else:
                arrs = []
                for s in self.segments:
                    if not s.meta.n_valid:
                        continue
                    a = s.columns[col][:s.meta.n_valid]
                    nm = s.null_masks.get(col)
                    if nm is not None:
                        a = a[~nm[:s.meta.n_valid]]
                    arrs.append(a)
                entry["size"] = int(sum(a.nbytes for a in arrs))
                arrs = [a for a in arrs if len(a)]
                if arrs:
                    entry["min"] = _scalar(min(a.min() for a in arrs))
                    entry["max"] = _scalar(max(a.max() for a in arrs))
            out[col] = entry
        return out

    # ---- pruning ---------------------------------------------------------

    def prune(self, intervals, numeric_bounds=None) -> list:
        """Segments overlapping any query interval and (optionally) any
        per-column numeric [lo, hi] requirement (SURVEY.md §3.5 P4)."""
        out = []
        for s in self.segments:
            if intervals and not any(
                    iv.overlaps(s.meta.time_min, s.meta.time_max + 1)
                    for iv in intervals):
                continue
            if numeric_bounds and not _bounds_admit(s.meta, numeric_bounds):
                continue
            out.append(s)
        return out


def _bounds_admit(meta: SegmentMeta, numeric_bounds: dict) -> bool:
    for col, (lo, hi) in numeric_bounds.items():
        cmin = meta.column_min.get(col)
        cmax = meta.column_max.get(col)
        if cmin is None or cmax is None:
            continue
        if lo is not None and cmax < lo:
            return False
        if hi is not None and cmin > hi:
            return False
    return True


def _scalar(x):
    return x.item() if isinstance(x, np.generic) else x
