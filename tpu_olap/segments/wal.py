"""Per-table write-ahead log for real-time ingest (docs/INGEST.md).

Durability contract: `Engine.append` acknowledges a batch only after
its rows are framed into the table's log (and, under the default
`ingest_wal_fsync="always"` policy, fsync'd) — a crash/SIGKILL at any
later point replays the log back to the exact acknowledged state at
the next registration of the table. Frames are atomic units:

    [u32 length][u32 crc32(payload)][payload]

where payload is the canonical JSON `{"seq": N, "rows": [...]}` the
append path already normalized (JSON-native scalars only, timestamps
as epoch-millis under ``__time``), so a replayed batch re-encodes to
bit-identical delta state. A torn tail — a partial frame from a crash
mid-write, or trailing garbage — fails the length/CRC check; `replay`
stops at the last intact frame and truncates the file there, so an
UNacknowledged append is either fully applied (it reached the disk
before the crash) or fully absent — never half-applied.

fsync policy (`EngineConfig.ingest_wal_fsync`):

  "always"    fsync before acknowledging every append (default; the
              durability contract above holds against power loss)
  "interval"  a `wal-flush:<table>` background stage graph
              (executor/stages.py) fsyncs every
              `ingest_wal_flush_interval_s`; appends acknowledge after
              the buffered OS write — process crashes lose nothing,
              power loss may lose the last interval (`synced_seq` in
              `GET /debug/ingest` shows the lag)
  "never"     no fsync (tests/benchmarks; OS-crash durability only)

With the durable sealed-segment store disabled (no
`EngineConfig.ingest_store_dir`) the log is the SOLE durable copy of
appended rows, so recovery cost grows with total appended rows until
the table is re-registered with fresh data — which resets the log
(`WriteAheadLog.reset`). With the store enabled (segments/store.py;
docs/DURABILITY.md), a checkpoint spills the sealed scope and then
`truncate_through(seq)` drops the frames the checkpoint covers, so the
log keeps only the post-checkpoint tail and recovery is O(tail):
replay loads the newest verifiable manifest and applies only frames
past its watermark.
"""

from __future__ import annotations

import json
import os
import struct
import threading
import zlib

_HEADER = struct.Struct("<II")  # payload length, crc32(payload)

# single-frame sanity bound for replay: a corrupt length field must not
# make the reader allocate gigabytes before the CRC check can fail
MAX_FRAME_BYTES = 256 << 20

__all__ = ["WriteAheadLog", "replay_wal", "truncate_file_through",
           "wal_path"]


def wal_path(wal_dir: str, table: str) -> str:
    return os.path.join(wal_dir, f"{table}.wal")


def replay_wal(path: str):
    """Read every intact frame of `path` as a list of (seq, rows)
    records, truncating the file at the first torn/corrupt frame (crash
    mid-write). Missing file -> []."""
    if not os.path.exists(path):
        return []
    out = []
    good_end = 0
    with open(path, "rb") as f:
        while True:
            head = f.read(_HEADER.size)
            if len(head) < _HEADER.size:
                break
            length, crc = _HEADER.unpack(head)
            if length > MAX_FRAME_BYTES:
                break
            payload = f.read(length)
            if len(payload) < length or zlib.crc32(payload) != crc:
                break
            try:
                rec = json.loads(payload.decode("utf-8"))
                rows = rec["rows"]
                seq = int(rec.get("seq", len(out) + 1))
            except Exception:  # noqa: BLE001 — corrupt frame = torn tail
                break
            if out and seq <= out[-1][0]:
                # seq must be strictly increasing: a regression means
                # the tail holds a frame from a failed, rolled-back
                # write that survived anyway — never acknowledged, so
                # cut the log before it like any other torn tail
                break
            out.append((seq, rows))
            good_end = f.tell()
    size = os.path.getsize(path)
    if good_end < size:
        # torn tail: cut it off so the next append doesn't interleave a
        # fresh frame behind garbage the next replay would stop at
        with open(path, "r+b") as f:
            f.truncate(good_end)
    return out


def _split_frames(path: str, through_seq: int) -> tuple[bytes, int]:
    """Raw bytes of every intact frame with seq > `through_seq`, plus
    the count of intact frames dropped. Kept frames are copied verbatim
    (headers + payloads untouched) so their CRCs stay valid; parsing
    stops at the first torn/corrupt frame like `replay_wal` and the
    garbage tail is dropped with the covered prefix."""
    kept = bytearray()
    dropped = 0
    with open(path, "rb") as f:
        while True:
            head = f.read(_HEADER.size)
            if len(head) < _HEADER.size:
                break
            length, crc = _HEADER.unpack(head)
            if length > MAX_FRAME_BYTES:
                break
            payload = f.read(length)
            if len(payload) < length or zlib.crc32(payload) != crc:
                break
            try:
                seq = int(json.loads(payload.decode("utf-8"))["seq"])
            except Exception:  # noqa: BLE001 — corrupt frame = torn tail
                break
            if seq > through_seq:
                kept += head + payload
            else:
                dropped += 1
    return bytes(kept), dropped


def _fsync_dir(path: str) -> None:
    """fsync the directory so a rename inside it is durable (best
    effort: some filesystems refuse directory fds)."""
    try:
        fd = os.open(path or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _atomic_rewrite(path: str, kept: bytes, do_fsync: bool) -> None:
    """The crash-safe truncation rewrite both truncation paths share:
    kept tail -> temp file -> (fsync) -> rename over the log ->
    directory fsync. A crash at any point leaves either the old or
    the new file, both of which replay correctly against the
    checkpoint watermark."""
    tmp = path + ".trunc"
    with open(tmp, "wb") as f:
        f.write(kept)
        f.flush()
        if do_fsync:
            os.fsync(f.fileno())
    os.rename(tmp, path)
    _fsync_dir(os.path.dirname(path))


def truncate_file_through(path: str, through_seq: int) -> int:
    """Atomically drop frames with seq <= `through_seq` from a log file
    with NO live handle (recovery housekeeping, closed engines).
    Returns the number of frames dropped; missing file -> 0."""
    if through_seq <= 0 or not os.path.exists(path):
        return 0
    kept, dropped = _split_frames(path, through_seq)
    if dropped == 0:
        return 0
    _atomic_rewrite(path, kept, do_fsync=True)
    return dropped


class WriteAheadLog:
    """Append-only framed log for ONE table. Thread-safe; the engine's
    per-table ingest lock already serializes appends, the internal lock
    just keeps the interval-flush graph and close() honest."""

    def __init__(self, path: str, fsync: str = "always",
                 flush_interval_s: float = 0.05,
                 start_seq: int = 0, flush_scheduler=None):
        self.path = path
        self.fsync_mode = str(fsync)
        self.flush_interval_s = max(0.005, float(flush_interval_s))
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._lock = threading.Lock()
        self._f = open(path, "ab")
        self._seq = int(start_seq)
        self._synced_seq = int(start_seq)
        self._closed = False
        # a write failure that could not be rolled back: the file may
        # hold an unacknowledged frame, so no further append can be
        # honestly acknowledged until the log is reset
        self.tainted = False
        self.bytes_written = os.path.getsize(path)
        # interval fsync runs as a periodic background stage graph:
        # `flush_scheduler` is StageScheduler.register_periodic (wired
        # by IngestManager._wal_for) instead of one daemon thread per
        # log. With no scheduler, interval mode degrades to fsync on
        # append — strictly MORE durable, never silently lagging.
        self._flush_handle = None
        if self.fsync_mode == "interval" and flush_scheduler is not None:
            self._flush_handle = flush_scheduler(
                f"wal-flush:{os.path.basename(path)}",
                lambda: self.flush_interval_s,
                self._flush_once)

    # ------------------------------------------------------------- write

    @property
    def last_seq(self) -> int:
        return self._seq

    @property
    def synced_seq(self) -> int:
        return self._synced_seq

    def append(self, rows: list) -> tuple[int, int]:
        """Frame + write one batch; returns (seq, total log bytes).
        Under fsync "always" the frame is durable on return."""
        with self._lock:
            if self._closed:
                raise RuntimeError(f"WAL {self.path} is closed")
            if self.tainted:
                raise RuntimeError(
                    f"WAL {self.path} failed a write that could not be "
                    "rolled back; re-register the table to reset it")
            seq = self._seq + 1
            payload = json.dumps({"seq": seq, "rows": rows},
                                 separators=(",", ":")).encode("utf-8")
            frame = _HEADER.pack(len(payload),
                                 zlib.crc32(payload)) + payload
            try:
                self._f.write(frame)
                self._f.flush()
                if self.fsync_mode == "always" or (
                        self.fsync_mode == "interval"
                        and self._flush_handle is None):
                    os.fsync(self._f.fileno())
                    self._synced_seq = seq
            except Exception:
                # the frame may be partially — or fully — on disk but
                # will never be acknowledged: roll the file back to the
                # last acked frame so recovery cannot resurrect it and
                # a later append cannot reuse its seq slot. Close first
                # so buffered residue can't land after the truncate.
                try:
                    try:
                        self._f.close()
                    except (OSError, ValueError):
                        pass
                    os.truncate(self.path, self.bytes_written)
                    self._f = open(self.path, "ab")
                except (OSError, ValueError):
                    self.tainted = True
                raise
            self._seq = seq
            self.bytes_written += len(frame)
        h = self._flush_handle
        if h is not None:
            h.wake()
        return seq, self.bytes_written

    def sync(self):
        """Explicit fsync (close / deterministic tests)."""
        with self._lock:
            if self._closed or self.tainted:
                return
            self._f.flush()
            os.fsync(self._f.fileno())
            self._synced_seq = self._seq

    def _flush_once(self):
        """One interval-fsync tick (the `wal-flush:<table>` background
        graph's body): fsync iff frames landed since the last sync."""
        with self._lock:
            if self._closed:
                return
            if self._synced_seq != self._seq:
                try:
                    self._f.flush()
                    os.fsync(self._f.fileno())
                    self._synced_seq = self._seq
                except (OSError, ValueError):
                    pass  # retried next tick; synced_seq shows lag

    def truncate_through(self, through_seq: int) -> int:
        """Atomically drop frames with seq <= `through_seq` — they are
        covered by a durable sealed-segment checkpoint (the caller
        advances the manifest FIRST; docs/DURABILITY.md). The rewrite
        is temp-write -> fsync -> rename, so a crash mid-truncate
        leaves either the full or the truncated log; both replay
        correctly because recovery filters frames by the checkpoint
        watermark. Returns the number of frames dropped. seq counters
        (`last_seq`/`synced_seq`) are untouched: truncation never
        un-acknowledges anything."""
        if through_seq <= 0:
            return 0
        with self._lock:
            if self._closed:
                raise RuntimeError(f"WAL {self.path} is closed")
            if self.tainted:
                raise RuntimeError(
                    f"WAL {self.path} is tainted; re-register the "
                    "table to reset it")
            self._f.flush()
            kept, dropped = _split_frames(self.path, through_seq)
            if dropped == 0:
                return 0
            # close the append handle BEFORE the rename so no buffered
            # residue can land in the replaced file afterwards
            self._f.close()
            _atomic_rewrite(self.path, kept,
                            do_fsync=self.fsync_mode != "never")
            self._f = open(self.path, "ab")
            self.bytes_written = len(kept)
            return dropped

    # ------------------------------------------------------------- admin

    def reset(self):
        """Truncate to empty (fresh registration over a live table: the
        logged appends belonged to the data being replaced)."""
        with self._lock:
            if self._closed:
                raise RuntimeError(f"WAL {self.path} is closed")
            self._f.truncate(0)
            self._f.seek(0)
            self._f.flush()
            if self.fsync_mode != "never":
                os.fsync(self._f.fileno())
            self._seq = 0
            self._synced_seq = 0
            self.bytes_written = 0
            self.tainted = False

    def close(self, final_sync: bool = True):
        """Flush, fsync, cancel the flush graph, close the file.
        Idempotent; joins an in-progress flush tick so Engine.close()
        is deterministic."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            try:
                self._f.flush()
                if final_sync:
                    os.fsync(self._f.fileno())
                    self._synced_seq = self._seq
            except (OSError, ValueError):
                pass
            self._f.close()
        h = self._flush_handle
        if h is not None:
            h.cancel(join_timeout=5.0)
            self._flush_handle = None

    def delete(self):
        """close + unlink (DROP TABLE cascade)."""
        self.close(final_sync=False)
        try:
            os.unlink(self.path)
        except OSError:
            pass
