"""Real-time ingest: mutable delta segments, WAL durability, and the
backpressured background compactor (docs/INGEST.md).

The Druid half of the reference system served queries over *realtime
nodes* — freshly-arrived rows answered immediately from mutable
in-memory state while batch segments compacted behind them. This module
is that path for the in-process engine:

- `Engine.append(table, rows)` lands rows in the table's DELTA: frozen
  append blocks swapped in as a fresh `TableSegments` snapshot (sealed
  segment objects, dictionaries, and earlier delta blocks are shared;
  only the partially-filled tail block is rebuilt copy-on-write), so a
  query that grabbed the previous snapshot keeps an immutable,
  generation-consistent view while the next query sees the new rows —
  through the SAME lowering/kernels/caches as batch data, no separate
  read path.
- Every accepted append is first framed into the table's write-ahead
  log (`segments.wal`); acknowledgment follows durability, and a
  crash/SIGKILL replays the log to the exact acknowledged state at the
  next registration.
- A background compactor seals the delta: all rows re-emit through the
  batch `StreamIngestor` (time-sorted, time-partitioned, dictionary
  re-sorted, dtypes re-narrowed) into a fresh sealed set, while
  appends that raced the compaction are carried over as rebased delta
  blocks — the write path never blocks the compactor and vice versa
  beyond a short swap section ("Partial Partial Aggregates",
  PAPERS.md 2603.26698; contention model PAPERS.md 1311.0059).
- A bounded delta (`ingest_max_delta_rows`) drives write backpressure:
  `IngestBackpressure` -> HTTP 429 + Retry-After, never a silent drop.

Generation contract (the robustness headline): append snapshots take a
fresh overall `generation` (tier-2 full-result cache entries and cube
full-serve keys go stale at key level) but carry the predecessor's
`sealed_generation`, so per-sealed-segment tier-1 cache partials and
generation-current cubes SURVIVE delta-only appends — cube serves clip
at the sealed scope and fold the delta remainder through the base path
(planner.cuberewrite), zero stale serves by construction.
"""

from __future__ import annotations

import os
import threading
import time

import numpy as np

from tpu_olap.obs.trace import span as _span
from tpu_olap.resilience.errors import (IngestBackpressure, QueryShed,
                                        UserError)
from tpu_olap.resilience.faults import maybe_inject
from tpu_olap.segments.segment import (ColumnType, Segment, SegmentMeta,
                                       TableSegments, TIME_COLUMN,
                                       _scalar)
from tpu_olap.segments.wal import WriteAheadLog, replay_wal, wal_path

__all__ = ["IngestManager", "canonicalize_rows", "encode_rows",
           "extend_snapshot", "compact_table"]


# --------------------------------------------------------------------------
# row canonicalization (the WAL wire format IS the append input format)

def _to_ms(v):
    """Any reasonable time spelling -> epoch millis int (None stays
    None for the caller's null check)."""
    if v is None:
        return None
    if isinstance(v, bool):
        raise UserError(f"cannot use boolean {v!r} as a timestamp")
    if isinstance(v, (int, np.integer)):
        return int(v)
    if isinstance(v, (float, np.floating)):
        if np.isnan(v):
            return None
        return int(v)
    import pandas as pd
    ts = pd.Timestamp(v)
    if ts is pd.NaT:
        return None
    return int(ts.value // 1_000_000)


def _canon_scalar(v):
    """JSON-native canonical value: what the WAL stores and the encoder
    consumes, so a replayed batch is bit-identical to the live one."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        f = float(v)
        return None if np.isnan(f) else f
    if isinstance(v, np.bool_):
        return bool(v)
    try:
        import pandas as pd
        if pd.isna(v):
            return None
    except (TypeError, ValueError):
        pass
    return str(v)


def canonicalize_rows(rows, time_column: str | None) -> list:
    """list[dict] / DataFrame -> canonical rows: JSON-native scalars
    only, the time value (accepted under the table's registered time
    column name or ``__time``) normalized to epoch-millis under
    ``__time``. This is exactly what the WAL frames, so replay feeds
    the same dicts back through the same encoder."""
    import pandas as pd
    if isinstance(rows, pd.DataFrame):
        rows = rows.to_dict("records")
    out = []
    for r in rows:
        if not isinstance(r, dict):
            raise UserError(
                f"append rows must be dicts, got {type(r).__name__}")
        cr = {}
        for k, v in r.items():
            k = str(k)
            if k == TIME_COLUMN or (time_column is not None
                                    and k == time_column):
                cr[TIME_COLUMN] = _to_ms(v)
            else:
                cr[k] = _canon_scalar(v)
        out.append(cr)
    return out


# --------------------------------------------------------------------------
# encoding: canonical rows -> column arrays against a live snapshot

class EncodedBatch:
    __slots__ = ("n", "cols", "nulls", "new_dict_values")

    def __init__(self, n, cols, nulls, new_dict_values):
        self.n = n
        self.cols = cols                    # col -> ndarray[n]
        self.nulls = nulls                  # col -> bool[n] (any() true)
        self.new_dict_values = new_dict_values  # col -> [unseen values]


def _numeric_column(table, c, values, mask, dtype, kind):
    """Object values + null mask -> dtype array (nulls zero-filled).
    `astype` on an object array converts element-wise in C — the
    vectorized replacement for the old per-row int()/float() loop
    (ROADMAP 4d: the Python loop capped ingest at ~13k rows/s while WAL
    replay ran 535k rows/s)."""
    filled = values.copy()
    filled[mask] = 0
    try:
        return filled.astype(dtype)
    except (TypeError, ValueError):
        # error path only: find the offending value for the message
        for v in values[~mask]:
            try:
                dtype.type(v)
            except (TypeError, ValueError):
                raise UserError(
                    f"append to {table.name!r}: column {c!r} is "
                    f"{kind}, got {v!r}") from None
        raise


def encode_rows(table: TableSegments, rows: list,
                require_time: bool) -> EncodedBatch:
    """Validate + encode canonical rows against the snapshot's schema
    and dictionaries. Unseen string values take tail codes past the
    current dictionary (the `Dictionary.extended` contract: existing
    codes never move), in first-appearance order — the same codes the
    original per-append sequence assigned, so a batched WAL replay is
    block-identical. Raises UserError before ANY state changes, so a
    bad batch is rejected whole — never half-applied.

    Columns batch-convert through numpy (one object array + one astype
    per column) instead of a per-row Python loop; string codes resolve
    per UNIQUE value, not per row."""
    schema = table.schema
    n = len(rows)
    unknown = set().union(*(r.keys() for r in rows)) - set(schema) \
        if rows else set()
    if unknown:
        raise UserError(
            f"append to {table.name!r}: unknown column(s) "
            f"{sorted(unknown)} (schema: {sorted(schema)})")
    cols: dict = {}
    nulls: dict = {}
    new_vals: dict = {}
    for c, typ in schema.items():
        # one Python pass per column: extract + null-mask fused. The
        # null test is exactly `is None` — NOT pd.isna: a Python float
        # NaN survives canonicalize_rows, and its per-type fate must
        # match the old per-row loop (DOUBLE -> NULL via the isnan
        # fold below, LONG -> UserError like int(nan) always raised,
        # STRING -> the literal "nan")
        values = np.empty(n, dtype=object)
        mask = np.zeros(n, dtype=bool)
        for i, r in enumerate(rows):
            v = r.get(c)
            if v is None:
                mask[i] = True
            else:
                values[i] = v
        if c == TIME_COLUMN:
            if require_time and mask.any():
                raise UserError(
                    f"append to {table.name!r}: a non-null time "
                    "value is required per row (like Druid's __time)")
            cols[c] = _numeric_column(table, c, values, mask,
                                      np.dtype(np.int64), "LONG")
            continue
        if typ is ColumnType.STRING:
            d = table.dictionaries.get(c)
            base = d.cardinality if d is not None else 0
            codes = np.zeros(n, np.int32)
            if not mask.all():
                real = values[~mask].astype(str)
                uniq, first, inv = np.unique(
                    real, return_index=True, return_inverse=True)
                ucodes = np.array(
                    [d.id_of(v) if d is not None else -1 for v in uniq],
                    dtype=np.int64)
                unseen = np.flatnonzero(ucodes <= 0)
                if len(unseen):
                    # tail codes in FIRST-APPEARANCE row order
                    order = unseen[np.argsort(first[unseen],
                                              kind="stable")]
                    news = [str(uniq[j]) for j in order]
                    ucodes[order] = base + 1 + np.arange(len(order))
                    new_vals[c] = news
                codes[~mask] = ucodes[inv].astype(np.int32)
            cols[c] = codes
            continue
        if typ is ColumnType.LONG:
            arr = _numeric_column(table, c, values, mask,
                                  np.dtype(np.int64), "LONG")
        else:
            arr = _numeric_column(table, c, values, mask,
                                  np.dtype(np.float64), "DOUBLE")
            nan = np.isnan(arr)
            if nan.any():
                mask = mask | nan
                arr = np.where(nan, 0.0, arr)
        cols[c] = arr
        if mask.any():
            nulls[c] = mask
    return EncodedBatch(n, cols, nulls, new_vals)


# --------------------------------------------------------------------------
# delta block emission + snapshot extension

def _emit_blocks(schema: dict, block_rows: int, cols: dict, nulls: dict,
                 start_sid: int) -> list:
    """Row arrays -> padded fixed-size Segment blocks with exact metas
    (the same manifest StreamIngestor._emit_block writes, so interval
    and numeric-bound pruning treat delta blocks like sealed ones).
    Rows keep ARRIVAL order — Druid realtime segments are not
    row-sorted either; per-block time_min/max stay exact."""
    n = len(cols[TIME_COLUMN])
    out = []
    for lo in range(0, n, block_rows):
        hi = min(lo + block_rows, n)
        nv = hi - lo
        bcols, bmasks = {}, {}
        for c, v in cols.items():
            block = np.zeros(block_rows, dtype=v.dtype)
            block[:nv] = v[lo:hi]
            bcols[c] = block
        for c, m in nulls.items():
            mm = m[lo:hi]
            if not mm.any():
                continue
            block = np.zeros(block_rows, dtype=bool)
            block[:nv] = mm
            bmasks[c] = block
        t = bcols[TIME_COLUMN][:nv]
        meta = SegmentMeta(
            segment_id=start_sid + len(out), n_valid=nv,
            time_min=int(t.min()) if nv else 0,
            time_max=int(t.max()) if nv else 0)
        for c, typ in schema.items():
            if typ is not ColumnType.STRING and nv:
                cv = bcols[c][:nv]
                nm = bmasks.get(c)
                if nm is not None:
                    if nm[:nv].all():
                        continue
                    cv = cv[~nm[:nv]]
                meta.column_min[c] = _scalar(cv.min())
                meta.column_max[c] = _scalar(cv.max())
        out.append(Segment(meta, bcols, bmasks))
    return out


def extend_snapshot(table: TableSegments,
                    enc: EncodedBatch) -> TableSegments:
    """New snapshot = sealed segments (shared) + delta blocks (shared,
    except a partially-filled tail rebuilt copy-on-write to absorb the
    batch) + extended dictionaries. Takes a fresh overall generation;
    carries the sealed generation (docs/INGEST.md)."""
    sealed = table.segments[:table.sealed_count]
    delta = list(table.segments[table.sealed_count:])
    dicts = dict(table.dictionaries)
    for c, vals in enc.new_dict_values.items():
        dicts[c] = dicts[c].extended(vals)
    cols, nulls = enc.cols, dict(enc.nulls)
    if delta and delta[-1].meta.n_valid < table.block_rows:
        # absorb into the tail block: copy its valid rows in front of
        # the batch (the OLD tail object stays untouched — snapshots
        # that hold it keep serving it)
        tail = delta.pop()
        tv = tail.meta.n_valid
        cols = {c: np.concatenate([np.asarray(tail.columns[c][:tv]), v])
                for c, v in cols.items()}
        merged: dict = {}
        for c in set(tail.null_masks) | set(nulls):
            a = tail.null_masks[c][:tv] if c in tail.null_masks \
                else np.zeros(tv, bool)
            b = nulls.get(c)
            if b is None:
                b = np.zeros(enc.n, bool)
            m = np.concatenate([a, b])
            if m.any():
                merged[c] = m
        nulls = merged
    sid = table.sealed_count + len(delta)
    blocks = _emit_blocks(table.schema, table.block_rows, cols, nulls,
                          sid)
    out = TableSegments(table.name, table.schema, dicts,
                        sealed + delta + blocks, table.block_rows,
                        sealed_count=table.sealed_count,
                        sealed_generation=table.sealed_generation)
    out.time_partition = table.time_partition
    out.star = table.star
    return out


# --------------------------------------------------------------------------
# compaction

def compact_table(table: TableSegments) -> TableSegments:
    """Seal the snapshot: EVERY row (sealed + delta) re-emitted through
    the batch StreamIngestor — globally re-time-sorted into the table's
    calendar partitions, dictionary re-sorted (restoring the code-range
    fast path for lexicographic bounds), dtypes re-narrowed. Returns a
    pure sealed TableSegments (fresh sealed generation); the caller
    rebases any delta blocks that raced in."""
    from tpu_olap.segments.ingest import (DictBuilder, StreamIngestor,
                                          resolve_time_partition)
    t_lo, t_hi = table.time_boundary
    tp = table.time_partition
    if tp is None:
        tp = resolve_time_partition("auto", t_lo or None, t_hi or None,
                                    table.num_rows, table.block_rows)
    ing = StreamIngestor(table.name, None, table.block_rows, tp)
    ing.schema = dict(table.schema)
    for c, d in table.dictionaries.items():
        # seed the builder with the live dictionary: value -> current
        # code, so stored codes ARE valid temp codes and finalize()'s
        # sort+remap handles the unsorted append tail for free
        b = DictBuilder()
        b._map = {str(v): i + 1 for i, v in enumerate(d.values)}
        ing._dicts[c] = b
    for s in table.segments:
        nv = s.meta.n_valid
        if not nv:
            continue
        ing._pending.append(
            {c: np.asarray(v[:nv]) for c, v in s.columns.items()})
        ing._pending_nulls.append(
            {c: np.asarray(m[:nv]) for c, m in s.null_masks.items()})
        ing._pending_rows += nv
    out = ing.finalize()
    out.star = table.star
    return out


def _compact_incremental(table: TableSegments):
    """Incremental compaction (ROADMAP 4b): rewrite ONLY the calendar
    partitions the delta touched; untouched sealed segments are reused
    as shared objects (their spill memos ride along, so the next
    checkpoint reuses their chunk files too). Eligible when the table
    is calendar-partitioned, every sealed segment sits inside one
    partition, and every dictionary is still sorted (an out-of-order
    tail extension needs the full rebuild's re-sort). Returns
    (sealed TableSegments, info) or None when ineligible — the caller
    falls back to the full `compact_table`."""
    from tpu_olap.segments.ingest import (DictBuilder, StreamIngestor,
                                          _partition_ids)
    tp = table.time_partition
    if tp is None or not table.sealed_count:
        return None
    if any(not d.is_sorted for d in table.dictionaries.values()):
        return None
    delta = [s for s in table.segments[table.sealed_count:]
             if s.meta.n_valid]
    if not delta:
        return None
    delta_pids = set()
    for s in delta:
        t = np.asarray(s.columns[TIME_COLUMN][:s.meta.n_valid],
                       np.int64)
        delta_pids.update(int(p) for p in
                          np.unique(_partition_ids(t, tp)))
    untouched, touched = [], []
    for s in table.segments[:table.sealed_count]:
        if not s.meta.n_valid:
            continue  # degenerate empty block: drop it in the rebuild
        lo = int(_partition_ids(np.array([s.meta.time_min],
                                         np.int64), tp)[0])
        hi = int(_partition_ids(np.array([s.meta.time_max],
                                         np.int64), tp)[0])
        if lo != hi:
            return None  # segment straddles partitions: full rebuild
        (touched if lo in delta_pids else untouched).append(s)
    if not untouched:
        return None  # nothing to reuse — the full path costs the same
    ing = StreamIngestor(table.name, None, table.block_rows, tp)
    ing.schema = dict(table.schema)
    for c, d in table.dictionaries.items():
        # seed value -> live code; the dict is sorted, so finalize()'s
        # sort+remap is the identity and stored codes stay valid in
        # BOTH the reused and the rewritten segments
        b = DictBuilder()
        b._map = {str(v): i + 1 for i, v in enumerate(d.values)}
        ing._dicts[c] = b
    for s in touched + delta:
        nv = s.meta.n_valid
        ing._pending.append(
            {c: np.asarray(v[:nv]) for c, v in s.columns.items()})
        ing._pending_nulls.append(
            {c: np.asarray(m[:nv]) for c, m in s.null_masks.items()})
        ing._pending_rows += nv
    rebuilt = ing.finalize()
    merged = []
    for s in untouched:
        # fresh meta with the merged id; column arrays, the spill memo
        # AND the identity uid are shared — the live snapshot's segment
        # objects must never be mutated (queries hold them), while the
        # carried uid keeps tier-1 cache entries and device-resident
        # rows valid for the untouched partition (segment_cache_token /
        # DeviceDataset rebase both key on it)
        ns = Segment(SegmentMeta(
            segment_id=0, n_valid=s.meta.n_valid,
            time_min=s.meta.time_min, time_max=s.meta.time_max,
            column_min=dict(s.meta.column_min),
            column_max=dict(s.meta.column_max)),
            s.columns, s.null_masks, uid=s.uid)
        memo = getattr(s, "_spill_memo", None)
        if memo is not None:
            ns._spill_memo = memo
        merged.append(ns)
    merged.extend(s for s in rebuilt.segments if s.meta.n_valid)
    merged.sort(key=lambda s: (s.meta.time_min, s.meta.segment_id))
    for i, s in enumerate(merged):
        s.meta.segment_id = i
    out = TableSegments(table.name, dict(table.schema),
                        rebuilt.dictionaries, merged, table.block_rows,
                        sealed_count=len(merged))
    out.time_partition = tp
    out.star = table.star
    return out, {"mode": "incremental",
                 "partitions_rewritten": len(delta_pids),
                 "segments_reused": len(untouched),
                 "segments_rewritten": len(merged) - len(untouched)}


def compact_table_auto(table: TableSegments):
    """(sealed TableSegments, info): incremental when the delta's
    partition footprint allows it, else the full O(table) rebuild."""
    inc = _compact_incremental(table)
    if inc is not None:
        return inc
    out = compact_table(table)
    return out, {"mode": "full",
                 "partitions_rewritten": None,
                 "segments_reused": 0,
                 "segments_rewritten": len(out.segments)}


def _remap_codes(live_dict, merged_dict) -> np.ndarray:
    """[live code] -> merged code (0 stays null)."""
    r = np.zeros(live_dict.cardinality + 1, np.int64)
    for i, v in enumerate(live_dict.values):
        r[i + 1] = merged_dict.id_of(v)
    return r


def _gather_delta_rows(table: TableSegments, skip: int):
    """Valid delta rows in append order, minus the first `skip` (the
    rows a compaction snapshot already covered)."""
    delta = table.segments[table.sealed_count:]
    cols = {}
    for c in table.schema:
        cols[c] = np.concatenate(
            [np.asarray(s.columns[c][:s.meta.n_valid]) for s in delta]
        )[skip:] if delta else np.zeros(0, np.int64)
    nulls = {}
    mask_cols = set().union(*(s.null_masks.keys() for s in delta)) \
        if delta else set()
    for c in mask_cols:
        m = np.concatenate(
            [np.asarray(s.null_masks[c][:s.meta.n_valid])
             if c in s.null_masks else np.zeros(s.meta.n_valid, bool)
             for s in delta])[skip:]
        if m.any():
            nulls[c] = m
    return cols, nulls


# --------------------------------------------------------------------------
# the engine-side coordinator

class TableIngestState:
    """Per-table mutable ingest state. `lock` serializes append
    snapshot swaps, WAL writes, and the compactor's swap section —
    never held across the compaction rebuild itself."""

    def __init__(self, name: str):
        self.name = name
        self.lock = threading.RLock()
        self.wal: WriteAheadLog | None = None
        self.frames: list = []   # delta-resident pandas frames (fallback)
        self.frames_version = 0  # bumped on EVERY frames mutation: the
        #                          TableEntry._frame_aug memo key (frame
        #                          count alone could collide after a
        #                          compaction trims the list)
        self.appended_rows = 0
        self.acked_seq = 0
        self.replayed_rows = 0
        self.compactions = 0
        self.last_compact_ms = 0.0
        self.compacting = False
        # durable-checkpoint bookkeeping (segments/store.py): the
        # highest WAL seq whose rows are folded into the SEALED scope
        # (advanced by the compaction swap; a checkpoint records it as
        # the manifest watermark), and the last checkpoint's info
        self.sealed_through_seq = 0
        self.checkpointing = False
        self.checkpoints = 0
        self.last_checkpoint: dict | None = None
        # EWMA of compactor drain rate (rows sealed per second): the
        # measured basis for backpressure Retry-After instead of the
        # fixed ingest_retry_after_s constant
        self.drain_rps: float | None = None

    def delta_source(self):
        """(version, frames) provider TableEntry.frame concatenates —
        the interpreter/fallback path's view of appended rows. Reads
        under the ingest lock so the pair stays consistent with a
        racing compaction's trim."""
        with self.lock:
            return self.frames_version, list(self.frames)


class IngestManager:
    """All real-time ingest state of one Engine: per-table delta
    states, WAL lifecycles, replay-on-register, the backpressure gate,
    and the background compactor thread (docs/INGEST.md)."""

    def __init__(self, engine):
        self.engine = engine
        self.config = engine.config
        self._lock = threading.Lock()
        self._states: dict[str, TableIngestState] = {}
        # the compactor is a scheduler-managed background stage graph
        # (executor.stages.register_periodic), not a bespoke daemon
        # thread — this is its PeriodicHandle
        self._compact_handle = None
        self._stopped = False
        m = engine.metrics
        self._m_rows = m.counter(
            "ingest_rows_total",
            "Rows appended through the real-time ingest path "
            "(Engine.append / POST /ingest / INSERT INTO).", ("table",))
        self._m_backpressure = m.counter(
            "ingest_backpressure_total",
            "Appends rejected with 429 because the delta hit "
            "ingest_max_delta_rows.", ("table",))
        self._m_delta = m.gauge(
            "delta_rows",
            "Rows currently resident in the mutable delta scope.",
            ("table",))
        self._m_wal = m.gauge(
            "wal_bytes", "Bytes in the table's write-ahead log.",
            ("table",))
        self._m_compact = m.counter(
            "compactions_total",
            "Delta-to-sealed compactions completed.", ("table",))
        self._m_compact_err = m.counter(
            "compact_errors_total",
            "Background compactions that raised (retried next tick).",
            ("table",))
        self._m_checkpoint = m.counter(
            "checkpoints_total",
            "Durable sealed-segment checkpoints committed "
            "(segments/store.py; docs/DURABILITY.md).", ("table",))
        self._m_checkpoint_err = m.counter(
            "checkpoint_errors_total",
            "Checkpoints that failed before the manifest swap (the "
            "previous checkpoint stays authoritative).", ("table",))
        self._m_store_bytes = m.gauge(
            "store_bytes",
            "Bytes of spilled sealed-segment chunks referenced by the "
            "table's newest checkpoint manifest.", ("table",))
        self._m_store_fallback = m.counter(
            "store_load_fallbacks_total",
            "Recovery-ladder rungs stepped over (corrupt/missing "
            "chunk or torn manifest) while loading a checkpoint.",
            ("table",))
        # durable sealed-segment store (docs/DURABILITY.md): None when
        # ingest_store_dir is unset — recovery then replays the whole
        # WAL, the pre-checkpoint behavior
        from tpu_olap.segments.store import SegmentStore
        self.store = SegmentStore(
            self.config.ingest_store_dir,
            self.config.ingest_store_keep_manifests,
            config=self.config) \
            if self.config.ingest_store_dir else None

    # ----------------------------------------------------------- helpers

    def _state(self, name: str) -> TableIngestState:
        with self._lock:
            st = self._states.get(name)
            if st is None:
                st = self._states[name] = TableIngestState(name)
            return st

    def _wal_for(self, st: TableIngestState) -> WriteAheadLog | None:
        cfg = self.config
        if not cfg.ingest_wal_dir:
            return None
        if st.wal is not None and st.wal.tainted:
            # taint is sticky across close(): never silently reopen a
            # log whose tail may hold an unacknowledged frame
            raise RuntimeError(
                f"WAL {st.wal.path} failed a write that could not be "
                "rolled back; re-register the table to reset it")
        if st.wal is None or st.wal._closed:
            st.wal = WriteAheadLog(
                wal_path(cfg.ingest_wal_dir, st.name),
                fsync=cfg.ingest_wal_fsync,
                flush_interval_s=cfg.ingest_wal_flush_interval_s,
                start_seq=st.acked_seq,
                # interval fsync rides the stage scheduler's background
                # pool as a `wal-flush:<table>` periodic graph instead
                # of one daemon thread per log
                flush_scheduler=self.engine.runner.stages
                .register_periodic)
        return st.wal

    # EWMA weight for the measured compactor drain rate; clamp bounds
    # for the derived Retry-After (a cold estimate must neither hammer
    # the server nor park a client for minutes)
    _DRAIN_EWMA_ALPHA = 0.3
    _RETRY_AFTER_BOUNDS = (0.05, 60.0)

    def _retry_after(self, st: TableIngestState, need_rows: int) -> float:
        """Backpressure Retry-After from the MEASURED compactor drain
        rate (EWMA of rows sealed per second) — `need_rows` is how many
        delta rows must drain before the shed batch fits. Falls back to
        the fixed `ingest_retry_after_s` until a compaction has been
        observed."""
        rps = st.drain_rps
        if not rps or rps <= 0:
            return float(self.config.ingest_retry_after_s)
        lo, hi = self._RETRY_AFTER_BOUNDS
        return float(min(hi, max(lo, need_rows / rps)))

    def _observe_drain(self, st: TableIngestState, rows: int,
                       ms: float) -> None:
        if rows <= 0 or ms <= 0:
            return
        rps = rows / (ms / 1000.0)
        a = self._DRAIN_EWMA_ALPHA
        st.drain_rps = rps if st.drain_rps is None \
            else a * rps + (1 - a) * st.drain_rps

    @staticmethod
    def _delta_frame(entry, canon_rows):
        """Canonical rows -> a fallback-path frame matching the base
        frame's visible schema (time re-materialized as datetime under
        the registered time column name)."""
        import pandas as pd
        df = pd.DataFrame(canon_rows)
        if TIME_COLUMN in df.columns:
            ts = pd.to_datetime(df[TIME_COLUMN], unit="ms")
            df = df.drop(columns=[TIME_COLUMN])
            df[entry.time_column or TIME_COLUMN] = ts
        return df

    # ------------------------------------------------------------ append

    def append(self, name: str, rows) -> dict:
        """The Engine.append implementation: validate -> backpressure
        gate -> WAL frame (durability precedes acknowledgment) ->
        snapshot swap -> cache invalidation scoped to what actually
        changed (tier-2 only; sealed tier-1 partials and cubes
        survive)."""
        eng = self.engine
        cfg = self.config
        entry = eng.catalog.get(name)
        if not entry.is_accelerated:
            raise UserError(
                f"table {name!r} is not accelerated; append needs a "
                "segment-backed datasource")
        if name.startswith("__cube_"):
            raise UserError(
                "cube storage tables are rebuilt from their base "
                "table; append to the base instead")
        canon = canonicalize_rows(rows, entry.time_column)
        if not canon:
            table = entry.segments
            return {"table": name, "rows": 0,
                    "generation": table.generation,
                    "sealed_generation": table.sealed_generation,
                    "delta_rows": table.delta_rows,
                    "watermark": table.watermark, "wal_seq": None}
        maybe_inject(cfg, "append", 0)
        st = self._state(name)
        with st.lock:
            table = entry.segments
            cap = int(cfg.ingest_max_delta_rows or 0)
            if cap and table.delta_rows + len(canon) > cap:
                self._m_backpressure.inc(table=name)
                self._ensure_compactor(wake=True)
                need = table.delta_rows + len(canon) - cap
                raise IngestBackpressure(
                    f"delta for {name!r} holds {table.delta_rows} rows;"
                    f" +{len(canon)} would exceed ingest_max_delta_rows"
                    f"={cap} — retry after compaction",
                    retry_after_s=self._retry_after(st, need))
            # validation/encoding BEFORE the WAL write: a rejected
            # batch must never reach the durable log. The fallback
            # frame too — pd.to_datetime bounds are narrower than the
            # raw epoch-ms range the encoder accepts, and a failure
            # after the WAL ack would leave the batch durable+device-
            # visible but absent from the interpreter's view
            enc = encode_rows(table, canon,
                              require_time=entry.time_column is not None)
            delta_frame = self._delta_frame(entry, canon)
            seq = wal_bytes = None
            wal = self._wal_for(st)
            if wal is not None:
                maybe_inject(cfg, "wal-write", 0)
                seq, wal_bytes = wal.append(canon)
                st.acked_seq = seq
            new_table = extend_snapshot(table, enc)
            entry.segments = new_table
            st.frames.append(delta_frame)
            st.frames_version += 1
            st.appended_rows += len(canon)
            entry.delta_source = st.delta_source
            entry._frame_aug = None
        runner = eng.runner
        # scoped invalidation (the PR 9 contract, split per scope):
        # whole-result state is stale (keys carry the moved overall
        # generation; purge eagerly), sealed-segment partials are NOT
        # (their scope generation did not move) — docs/INGEST.md
        runner.result_cache.invalidate_full(name)
        self._m_rows.inc(len(canon), table=name)
        self._m_delta.set(new_table.delta_rows, table=name)
        if wal_bytes is not None:
            self._m_wal.set(wal_bytes, table=name)
        runner.events.emit(
            "ingest", table=name, kind="append", rows=len(canon),
            generation=new_table.generation,
            sealed_generation=new_table.sealed_generation,
            delta_rows=new_table.delta_rows, wal_seq=seq)
        if cfg.ingest_auto_compact and \
                new_table.delta_rows >= int(cfg.ingest_compact_rows):
            self._ensure_compactor(wake=True)
        return {"table": name, "rows": len(canon),
                "generation": new_table.generation,
                "sealed_generation": new_table.sealed_generation,
                "delta_rows": new_table.delta_rows,
                "watermark": new_table.watermark, "wal_seq": seq}

    # ------------------------------------------------- register / replay

    def on_register(self, entry):
        """register_table hook. A table already live in THIS engine is
        being REPLACED: its logged appends belonged to the old data —
        reset the log AND drop its checkpoint store. A first
        registration with an existing log/store is crash RECOVERY: load
        the newest verifiable checkpoint (segments/store.py), then
        replay only the WAL tail past its watermark
        (cfg.ingest_wal_replay gates both)."""
        cfg = self.config
        name = entry.name
        with self._lock:
            st_prev = self._states.pop(name, None)
        if st_prev is not None:
            self._m_delta.set(0, table=name)
            if self.store is not None:
                # the spilled checkpoints covered the replaced data
                self.store.delete_table(name)
                self._m_store_bytes.set(0, table=name)
            wal = st_prev.wal
            if wal is not None and not wal._closed and not wal.tainted:
                wal.reset()
                wal.close()
                self._m_wal.set(0, table=name)
            elif cfg.ingest_wal_dir:
                # no live handle to reset through (never appended, or
                # closed by Engine.close, or tainted by a failed
                # write): drop the file itself — the next append
                # recreates it from seq 0
                if wal is not None:
                    wal.close(final_sync=False)
                try:
                    os.unlink(wal_path(cfg.ingest_wal_dir, name))
                except OSError:
                    pass
                self._m_wal.set(0, table=name)
            return
        if not entry.is_accelerated or name.startswith("__cube_") \
                or not cfg.ingest_wal_dir or not cfg.ingest_wal_replay:
            return
        watermark = self._restore_from_store(entry) \
            if self.store is not None else 0
        records = replay_wal(wal_path(cfg.ingest_wal_dir, name))
        if records and records[0][0] > watermark + 1:
            # coverage gap: the surviving log starts PAST what the
            # loaded checkpoint covers — frames below it were
            # truncated on the strength of a checkpoint that now
            # fails verification (or no longer matches the schema).
            # Proceeding would silently serve a table missing
            # acknowledged rows; refuse instead (never a wrong
            # answer). Operator remedies: restore the store files,
            # or delete the table's WAL + store to accept base-only.
            # The entry is DEREGISTERED too: the catalog add ran
            # before this hook, and a caller catching the error must
            # not be left with a live base-only table (nor may a
            # later append restart seq 1 under a log whose surviving
            # frames sit far past it).
            with self._lock:
                self._states.pop(name, None)
            self.engine.catalog.drop(name)
            raise RuntimeError(
                f"recovery for table {name!r} refused: WAL frames "
                f"{watermark + 1}..{records[0][0] - 1} were truncated "
                "by a checkpoint, but no checkpoint covering them "
                "verifies (see store_fallback events) — acknowledged "
                "rows would be silently lost (docs/DURABILITY.md)")
        if watermark:
            # frames at or below the checkpoint watermark are already
            # folded into the restored sealed scope
            records = [(s, r) for s, r in records if s > watermark]
        if records:
            self._replay(entry, records)

    def _restore_from_store(self, entry) -> int:
        """Recovery rung 1: replace the freshly-ingested base with the
        newest verifiable checkpoint's sealed scope (which includes
        every compacted append) and return its WAL watermark. 0 when no
        checkpoint verifies or the schema no longer matches — the
        caller then replays whatever WAL remains over the base, the
        pre-store behavior. The fallback-path frame becomes a lazy
        reconstruction from the stored segments: the registration data
        no longer covers the compacted appends."""
        eng = self.engine
        name = entry.name
        # "store-load" fault site: a raised fault here is a crash in
        # the middle of recovery — registration fails whole (the engine
        # never half-recovers) and a retry loads the store again
        maybe_inject(self.config, "store-load", 0)
        loaded = self.store.load(name)
        if loaded is None:
            return 0
        for mfile, reason in loaded.fallbacks:
            self._m_store_fallback.inc(table=name)
            eng.runner.events.emit(
                "store_fallback", table=name, manifest=mfile,
                reason=reason[:300])
        if loaded.segments is None:
            return 0
        if loaded.segments.schema != entry.segments.schema:
            eng.runner.events.emit(
                "store_fallback", table=name,
                manifest="(schema)",
                reason="checkpoint schema does not match the "
                       "registered base; ignoring the store")
            return 0
        sealed = loaded.segments
        sealed.star = entry.star
        entry.segments = sealed
        from tpu_olap.segments.store import segments_to_frame
        entry.frame_source = (
            lambda _ts=sealed, _tc=entry.time_column:
            segments_to_frame(_ts, _tc))
        entry._frame = None
        entry._frame_aug = None
        # parquet provenance is stale too: the chunked/parallel
        # fallback would stream base-only rows and miss the compacted
        # appends the sealed scope now carries
        entry.parquet_paths = ()
        entry.parquet_read_cols = None
        entry.parquet_column_map = None
        entry.parquet_rows = None
        st = self._state(name)
        st.acked_seq = loaded.wal_seq
        st.sealed_through_seq = loaded.wal_seq
        stats = self.store.table_stats(name) or {}
        st.last_checkpoint = {"status": "loaded", **stats}
        self._m_store_bytes.set(int(stats.get("bytes", 0)), table=name)
        eng.runner.events.emit(
            "store_load", table=name,
            checkpoint_id=loaded.manifest["checkpoint_id"],
            wal_seq=loaded.wal_seq, segments=len(sealed.segments),
            rows=sealed.num_rows,
            fallbacks=len(loaded.fallbacks))
        return loaded.wal_seq

    def _replay(self, entry, records):
        """Apply replayed WAL records as ONE batched extension (the
        per-append tail-rebuild fill is deterministic, so the batched
        result is block-identical to the original append sequence).
        Failure mid-replay restores the clean base snapshot — the
        table is registered base-only, never half-recovered; a retry
        (re-registration) replays again."""
        eng = self.engine
        cfg = self.config
        name = entry.name
        st = self._state(name)
        base_snapshot = entry.segments
        t0 = time.perf_counter()
        try:
            with st.lock:
                all_rows: list = []
                for seq, rows in records:
                    maybe_inject(cfg, "wal-replay", 0)
                    all_rows.extend(rows)
                enc = encode_rows(
                    entry.segments, all_rows,
                    require_time=entry.time_column is not None)
                entry.segments = extend_snapshot(entry.segments, enc)
                if all_rows:
                    st.frames.append(self._delta_frame(entry, all_rows))
                    st.frames_version += 1
                st.appended_rows += len(all_rows)
                st.replayed_rows = len(all_rows)
                st.acked_seq = records[-1][0]
                entry.delta_source = st.delta_source
        except Exception:
            with st.lock:
                entry.segments = base_snapshot
                entry.delta_source = None
            with self._lock:
                self._states.pop(name, None)
            raise
        ms = (time.perf_counter() - t0) * 1000
        self._m_rows.inc(len(all_rows), table=name)
        self._m_delta.set(entry.segments.delta_rows, table=name)
        eng.runner.events.emit(
            "wal_replay", table=name, records=len(records),
            rows=len(all_rows), ms=round(ms, 3),
            generation=entry.segments.generation)
        if cfg.ingest_auto_compact and entry.segments.delta_rows \
                >= int(cfg.ingest_compact_rows):
            self._ensure_compactor(wake=True)

    def on_drop(self, name: str):
        with self._lock:
            st = self._states.pop(name, None)
        if self.store is not None:
            self.store.delete_table(name)
            self._m_store_bytes.set(0, table=name)
        if st is not None:
            self._m_delta.set(0, table=name)
            if st.wal is not None:
                st.wal.delete()
                self._m_wal.set(0, table=name)

    # ---------------------------------------------------------- compactor

    def _ensure_compactor(self, wake: bool = False):
        """Register the `compact` background graph on the stage
        scheduler (lazily; re-registers after Engine.close cancelled
        it). `wake=True` also requests an immediate pass — ingest
        backpressure needs the compactor NOW, not at the next tick."""
        if self._stopped or not self.config.ingest_auto_compact:
            return
        with self._lock:
            h = self._compact_handle
            if h is None or h.cancelled:
                h = self._compact_handle = \
                    self.engine.runner.stages.register_periodic(
                        "compact",
                        lambda: self.config.ingest_compact_interval_s,
                        self._compact_pass)
        if wake:
            h.wake()

    def _compact_pass(self):
        """One background-graph tick: seal every delta past the row
        threshold. Runs on the scheduler's background stage pool every
        ingest_compact_interval_s (or on an append wake); compact_now
        takes an admission slot and honors the breaker, so background
        sealing queues/sheds WITH foreground traffic."""
        cfg = self.config
        with self._lock:
            names = list(self._states)
        for name in names:
            if self._stopped:
                return
            try:
                entry = self.engine.catalog.maybe(name)
                if entry is None or not entry.is_accelerated:
                    continue
                if entry.segments.delta_rows \
                        >= int(cfg.ingest_compact_rows):
                    self.compact_now(name)
            except QueryShed:
                pass     # admission saturated: retry next tick
            except Exception as e:  # noqa: BLE001 — retried, but
                # never silently: a persistently failing compaction
                # means the delta grows until every append sheds,
                # and the operator needs a visible cause
                self._m_compact_err.inc(table=name)
                try:
                    self.engine.runner.events.emit(
                        "compact_error", table=name,
                        error=f"{type(e).__name__}: {e}")
                except Exception:  # noqa: BLE001
                    pass

    def compact_now(self, name: str) -> dict | None:
        """Seal the table's delta (sync spelling; the compactor loop
        calls this too). The rebuild runs OUTSIDE the ingest lock from
        an immutable snapshot; appends that race in are carried over
        as rebased delta blocks in the short swap section. Runs under
        an admission slot and skips while the breaker is open, so
        background sealing queues/sheds with foreground traffic
        instead of around it."""
        eng = self.engine
        runner = eng.runner
        entry = eng.catalog.maybe(name)
        if entry is None or not entry.is_accelerated:
            return None
        st = self._state(name)
        with st.lock:
            if st.compacting:
                return {"table": name, "status": "busy"}
            snapshot = entry.segments
            if snapshot.delta_rows == 0:
                return None
            # the WAL watermark this seal will cover: appends hold the
            # same lock across WAL write + snapshot swap, so every
            # frame <= acked_seq is in `snapshot` and every later one
            # will be carried over as rebased delta in the swap section
            seq_snap = st.acked_seq
            st.compacting = True
        t0 = time.perf_counter()
        try:
            if runner.breaker.state == "open":
                # device sick: don't churn its caches now
                return {"table": name, "status": "breaker-open"}
            with runner.admission.slot(None):
                maybe_inject(self.config, "compact", 0)
                compacted, cinfo = compact_table_auto(snapshot)
            d_snap = snapshot.delta_rows
            with st.lock:
                live = entry.segments
                d_live = live.delta_rows
                dicts = dict(compacted.dictionaries)
                blocks: list = []
                if d_live > d_snap:
                    # appends raced the rebuild: carry the uncovered
                    # tail rows over, remapping string codes into the
                    # compacted (re-sorted, possibly extended) dicts
                    for c, ld in live.dictionaries.items():
                        missing = [v for v in ld.values
                                   if dicts[c].id_of(v) <= 0]
                        if missing:
                            dicts[c] = dicts[c].extended(missing)
                    cols, nulls = _gather_delta_rows(live, d_snap)
                    for c, typ in live.schema.items():
                        if typ is ColumnType.STRING:
                            r = _remap_codes(live.dictionaries[c],
                                             dicts[c])
                            cols[c] = r[np.asarray(cols[c], np.int64)] \
                                .astype(np.int32)
                    blocks = _emit_blocks(
                        live.schema, live.block_rows, cols, nulls,
                        len(compacted.segments))
                merged = TableSegments(
                    name, live.schema, dicts,
                    compacted.segments + blocks, live.block_rows,
                    sealed_count=len(compacted.segments))
                merged.time_partition = compacted.time_partition
                merged.star = snapshot.star
                entry.segments = merged
                st.compactions += 1
                st.sealed_through_seq = seq_snap
                st.last_compact_ms = (time.perf_counter() - t0) * 1000
                entry._frame_aug = None
                # consolidate the fallback frames this compaction
                # sealed into ONE frame (the carried tail stays
                # per-append): appended rows remain host-resident in
                # frame form — the fallback path needs them, exactly
                # as _frame duplicates base rows — but per-append
                # fragmentation no longer accumulates, so a long
                # append history costs one frame, not thousands
                carried = int(d_live - d_snap)
                keep, acc = [], 0
                for f in reversed(st.frames):
                    if acc >= carried:
                        break
                    keep.append(f)
                    acc += len(f)
                keep.reverse()
                folded = st.frames[:len(st.frames) - len(keep)]
                if len(folded) > 1:
                    import pandas as pd
                    folded = [pd.concat(folded, ignore_index=True)]
                st.frames = folded + keep
                st.frames_version += 1
            # the sealed set changed: tier 2 is stale at key level
            # (purged eagerly), but tier-1 entries of UNTOUCHED
            # partitions stay live — incremental compaction carries
            # their Segment uids, so only delta-touched partitions'
            # entries drop (executor.resultcache.invalidate_compacted);
            # cubes over the table are stale, the maintainer rebuilds
            live = {merged.segment_cache_token(i)
                    for i in range(len(merged.segments))}
            runner.result_cache.invalidate_compacted(name, live)
            self._m_compact.inc(table=name)
            self._m_delta.set(merged.delta_rows, table=name)
            self._observe_drain(st, d_snap, st.last_compact_ms)
            runner.events.emit(
                "compact", table=name,
                rows_sealed=compacted.num_rows,
                delta_rows_folded=d_snap,
                delta_rows_carried=int(d_live - d_snap),
                segments=len(compacted.segments),
                mode=cinfo["mode"],
                segments_reused=cinfo["segments_reused"],
                ms=round(st.last_compact_ms, 3),
                generation=merged.generation,
                sealed_generation=merged.sealed_generation)
            eng.cubes.on_table_registered(name)
            # durability hook (docs/DURABILITY.md): the sealed set just
            # changed — spill it, advance the manifest, truncate the
            # WAL. A checkpoint failure never fails the compaction (the
            # previous checkpoint stays authoritative; recovery replays
            # a longer tail).
            checkpoint = None
            if self.store is not None and \
                    self.config.ingest_store_checkpoint_on_compact:
                try:
                    checkpoint = self._checkpoint_sealed(name, entry, st)
                except Exception as e:  # noqa: BLE001 — surfaced, never
                    # silently: durability lag is operator-visible
                    self._m_checkpoint_err.inc(table=name)
                    runner.events.emit(
                        "checkpoint_error", table=name,
                        error=f"{type(e).__name__}: {e}")
                    checkpoint = {"status": "error",
                                  "error": f"{type(e).__name__}: {e}"}
            return {"table": name, "status": "compacted",
                    "rows_sealed": compacted.num_rows,
                    "delta_rows_folded": d_snap,
                    "delta_rows_carried": int(d_live - d_snap),
                    "mode": cinfo["mode"],
                    "segments_reused": cinfo["segments_reused"],
                    "ms": st.last_compact_ms,
                    "generation": merged.generation,
                    "sealed_generation": merged.sealed_generation,
                    **({"checkpoint": checkpoint} if checkpoint else {})}
        finally:
            with st.lock:
                st.compacting = False

    def compact_all(self) -> dict:
        """Compact every table with a non-empty delta (tests, shutdown
        hygiene). Returns {table: result}."""
        out = {}
        with self._lock:
            names = list(self._states)
        for name in names:
            r = self.compact_now(name)
            if r is not None and r.get("status") == "compacted":
                out[name] = r
        return out

    # ---------------------------------------------------------- checkpoint

    def checkpoint_now(self, name: str) -> dict:
        """Durably checkpoint one table (the `CHECKPOINT DRUID TABLE`
        spelling; docs/DURABILITY.md): seal the delta first (so the
        appends enter the sealed scope), then spill + manifest advance
        + WAL truncation. A compaction skip (busy/breaker-open) still
        checkpoints the CURRENT sealed scope — the delta stays covered
        by the WAL tail either way."""
        entry = self.engine.catalog.maybe(name)
        if entry is None or not entry.is_accelerated:
            raise UserError(
                f"table {name!r} is not an accelerated datasource")
        if self.store is None:
            return {"table": name, "status": "no-store",
                    "detail": "set EngineConfig.ingest_store_dir"}
        st = self._state(name)
        if entry.segments.delta_rows:
            res = self.compact_now(name)
            ck = (res or {}).get("checkpoint")
            if ck is not None and ck.get("status") in (
                    "checkpointed", "noop"):
                return {"table": name, **ck}
        return {"table": name, **self._checkpoint_sealed(name, entry,
                                                         st)}

    def checkpoint_all(self) -> dict:
        out = {}
        with self._lock:
            names = list(self._states)
        for name in names:
            entry = self.engine.catalog.maybe(name)
            if entry is None or not entry.is_accelerated:
                continue
            out[name] = self.checkpoint_now(name)
        return out

    def _checkpoint_sealed(self, name: str, entry, st) -> dict:
        """Checkpoint rides the stage graph too: chained after a
        compaction it re-enters the background stage section for free
        (same thread); invoked sync (the CHECKPOINT verb) it takes one
        slot — either way the spill shows up as a `checkpoint` span
        under background-stage occupancy accounting."""
        with self.engine.runner.stages.stage("background"), \
                _span("checkpoint"):
            return self._checkpoint_commit(name, entry, st)

    def _checkpoint_commit(self, name: str, entry, st) -> dict:
        """Spill the sealed scope + advance the manifest + truncate the
        WAL through the lag-one watermark. Serialized per table; a
        second caller while one runs reports "busy" (the compactor's
        auto-hook and an operator verb must not interleave spills).

        The whole commit runs under the store's per-table lock and
        re-checks that `st` is still the table's live ingest state
        before keeping anything: a re-registration/drop that raced in
        mid-spill has already deleted (or will, blocked on this lock,
        delete) the store — a checkpoint of the REPLACED data must not
        survive it, and above all must not truncate the NEW table's
        WAL with the old watermark (recovery would then silently drop
        every newly acknowledged row)."""
        with st.lock:
            if st.checkpointing:
                return {"status": "busy"}
            st.checkpointing = True
            sealed = entry.segments.sealed_view()
            wal_seq = st.sealed_through_seq
        t0 = time.perf_counter()
        try:
            with self.store.table_lock(name):
                info = self.store.checkpoint(name, sealed, wal_seq)
                with self._lock:
                    stale = self._states.get(name) is not st
                if stale:
                    self.store.delete_table(name)
                    return {"status": "stale"}
                truncated = 0
                if info["status"] in ("checkpointed", "noop"):
                    # truncate on noop too: a crash in the
                    # wal-truncate window would otherwise leave the
                    # covered prefix on disk forever (every later
                    # checkpoint of the unchanged sealed set is a
                    # noop)
                    truncated = self._truncate_wal(
                        st, name,
                        int(info.get("truncate_through") or 0))
                if info["status"] == "checkpointed":
                    st.checkpoints += 1
                    self._m_checkpoint.inc(table=name)
            self._m_store_bytes.set(int(info.get("bytes", 0)),
                                    table=name)
            ms = (time.perf_counter() - t0) * 1000
            info = {**info, "wal_seq": wal_seq,
                    "wal_frames_truncated": truncated,
                    "ms": round(ms, 3)}
            with st.lock:
                st.last_checkpoint = info
            if info["status"] == "checkpointed":
                self.engine.runner.events.emit(
                    "checkpoint", table=name,
                    checkpoint_id=info["checkpoint_id"],
                    segments=info["segments"],
                    files_written=info["files_written"],
                    chunks_reused=info["chunks_reused"],
                    bytes=info["bytes"], wal_seq=wal_seq,
                    truncate_through=info["truncate_through"],
                    wal_frames_truncated=truncated,
                    ms=info["ms"])
            return info
        finally:
            with st.lock:
                st.checkpointing = False

    def _truncate_wal(self, st, name: str, through_seq: int) -> int:
        """Drop WAL frames a (lag-one) durable checkpoint covers. The
        "wal-truncate" fault site sits between the manifest swap and
        the rewrite: a crash here leaves pre-checkpoint frames in the
        log, and recovery filters them by the manifest watermark.
        Runs under st.lock: appends hold it across their lazy WAL open
        + frame write, so the no-handle rewrite below can never rename
        the log out from under a handle a racing append just opened
        (an acked frame written to an unlinked inode would be LOST)."""
        if through_seq <= 0:
            return 0
        maybe_inject(self.config, "wal-truncate", 0)
        from tpu_olap.segments.wal import truncate_file_through
        with st.lock:
            wal = st.wal
            if wal is not None and not wal._closed and not wal.tainted:
                dropped = wal.truncate_through(through_seq)
                self._m_wal.set(wal.bytes_written, table=name)
                return dropped
            if self.config.ingest_wal_dir:
                return truncate_file_through(
                    wal_path(self.config.ingest_wal_dir, name),
                    through_seq)
            return 0

    # ------------------------------------------------------------- admin

    def snapshot(self) -> dict:
        """GET /debug/ingest payload: per-table delta sizes, WAL lag,
        compactor state."""
        cfg = self.config
        eng = self.engine
        tables = {}
        with self._lock:
            states = dict(self._states)
        for name, st in sorted(states.items()):
            entry = eng.catalog.maybe(name)
            if entry is None or not entry.is_accelerated:
                continue
            ts = entry.segments
            wal = None
            if st.wal is not None:
                wal = {"path": st.wal.path,
                       "bytes": st.wal.bytes_written,
                       "last_seq": st.wal.last_seq,
                       "synced_seq": st.wal.synced_seq,
                       "lag_records": st.wal.last_seq
                       - st.wal.synced_seq}
            store = None
            if self.store is not None:
                store = {"checkpoints": st.checkpoints,
                         "sealed_through_seq": st.sealed_through_seq,
                         "last": st.last_checkpoint,
                         **(self.store.table_stats(name) or {})}
            tables[name] = {
                "delta_rows": ts.delta_rows,
                "delta_segments": len(ts.segments) - ts.sealed_count,
                "sealed_segments": ts.sealed_count,
                "watermark": ts.watermark,
                "generation": ts.generation,
                "sealed_generation": ts.sealed_generation,
                "appended_rows": st.appended_rows,
                "replayed_rows": st.replayed_rows,
                "acked_seq": st.acked_seq,
                "compacting": st.compacting,
                "compactions": st.compactions,
                "last_compact_ms": round(st.last_compact_ms, 3),
                # backpressure pacing (docs/INGEST.md): the measured
                # compactor drain rate a 429's Retry-After derives from
                "drain_rows_per_s": round(st.drain_rps, 1)
                if st.drain_rps else None,
                "wal": wal,
                "store": store,
            }
        h = self._compact_handle
        return {
            "tables": tables,
            "compactor": {
                "running": h is not None and not h.cancelled,
                "graph": h.snapshot() if h is not None else None,
                "auto": bool(cfg.ingest_auto_compact),
                "compact_rows": int(cfg.ingest_compact_rows),
                "interval_s": float(cfg.ingest_compact_interval_s),
                "max_delta_rows": int(cfg.ingest_max_delta_rows or 0),
            },
            "wal": {"dir": cfg.ingest_wal_dir,
                    "fsync": cfg.ingest_wal_fsync,
                    "replay_on_register": bool(cfg.ingest_wal_replay)},
            "store": {"dir": cfg.ingest_store_dir,
                      "keep_manifests":
                          int(cfg.ingest_store_keep_manifests),
                      "checkpoint_on_compact":
                          bool(cfg.ingest_store_checkpoint_on_compact)},
        }

    def store_rows(self) -> list:
        """sys.checkpoints rows (catalog.systables): one per table with
        durable-checkpoint state — manifest id, WAL watermark, spilled
        bytes/files, and how much of the log the checkpoint let the
        engine truncate away."""
        rows = []
        with self._lock:
            states = dict(self._states)
        for name, st in sorted(states.items()):
            entry = self.engine.catalog.maybe(name)
            if entry is None or not entry.is_accelerated:
                continue
            stats = (self.store.table_stats(name) or {}) \
                if self.store is not None else {}
            last = st.last_checkpoint or {}
            rows.append({
                "table": name,
                "checkpoint_id": stats.get("checkpoint_id"),
                "wal_watermark": stats.get("wal_seq"),
                "sealed_through_seq": st.sealed_through_seq,
                "acked_seq": st.acked_seq,
                "checkpoints": st.checkpoints,
                "segments": stats.get("segments"),
                "bytes": stats.get("bytes"),
                "chunks_reused": last.get("chunks_reused"),
                "manifests_retained": stats.get("manifests_retained"),
                "last_status": last.get("status"),
            })
        return rows

    def stop(self):
        """Deterministically cancel the compactor graph (joining an
        in-progress pass) and close every WAL (Engine.close). Appends
        afterwards reopen WALs lazily; the compactor graph re-registers
        on the next append that wants it."""
        self._stopped = True
        h = self._compact_handle
        joined = True
        if h is not None:
            h.cancel(join_timeout=10.0)
            joined = not h.running
            if joined:
                self._compact_handle = None
        with self._lock:
            states = list(self._states.values())
        for st in states:
            if st.wal is not None:
                st.wal.close()
        if joined:
            # re-arm: a later append may re-register the graph cleanly.
            # A join timeout (compaction wedged mid-rebuild) keeps the
            # stop flag set so the straggler exits at its next check
            # instead of being revived as a zombie.
            self._stopped = False
