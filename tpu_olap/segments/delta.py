"""Real-time ingest: mutable delta segments, WAL durability, and the
backpressured background compactor (docs/INGEST.md).

The Druid half of the reference system served queries over *realtime
nodes* — freshly-arrived rows answered immediately from mutable
in-memory state while batch segments compacted behind them. This module
is that path for the in-process engine:

- `Engine.append(table, rows)` lands rows in the table's DELTA: frozen
  append blocks swapped in as a fresh `TableSegments` snapshot (sealed
  segment objects, dictionaries, and earlier delta blocks are shared;
  only the partially-filled tail block is rebuilt copy-on-write), so a
  query that grabbed the previous snapshot keeps an immutable,
  generation-consistent view while the next query sees the new rows —
  through the SAME lowering/kernels/caches as batch data, no separate
  read path.
- Every accepted append is first framed into the table's write-ahead
  log (`segments.wal`); acknowledgment follows durability, and a
  crash/SIGKILL replays the log to the exact acknowledged state at the
  next registration.
- A background compactor seals the delta: all rows re-emit through the
  batch `StreamIngestor` (time-sorted, time-partitioned, dictionary
  re-sorted, dtypes re-narrowed) into a fresh sealed set, while
  appends that raced the compaction are carried over as rebased delta
  blocks — the write path never blocks the compactor and vice versa
  beyond a short swap section ("Partial Partial Aggregates",
  PAPERS.md 2603.26698; contention model PAPERS.md 1311.0059).
- A bounded delta (`ingest_max_delta_rows`) drives write backpressure:
  `IngestBackpressure` -> HTTP 429 + Retry-After, never a silent drop.

Generation contract (the robustness headline): append snapshots take a
fresh overall `generation` (tier-2 full-result cache entries and cube
full-serve keys go stale at key level) but carry the predecessor's
`sealed_generation`, so per-sealed-segment tier-1 cache partials and
generation-current cubes SURVIVE delta-only appends — cube serves clip
at the sealed scope and fold the delta remainder through the base path
(planner.cuberewrite), zero stale serves by construction.
"""

from __future__ import annotations

import os
import threading
import time

import numpy as np

from tpu_olap.resilience.errors import (IngestBackpressure, QueryShed,
                                        UserError)
from tpu_olap.resilience.faults import maybe_inject
from tpu_olap.segments.segment import (ColumnType, Segment, SegmentMeta,
                                       TableSegments, TIME_COLUMN,
                                       _scalar)
from tpu_olap.segments.wal import WriteAheadLog, replay_wal, wal_path

__all__ = ["IngestManager", "canonicalize_rows", "encode_rows",
           "extend_snapshot", "compact_table"]


# --------------------------------------------------------------------------
# row canonicalization (the WAL wire format IS the append input format)

def _to_ms(v):
    """Any reasonable time spelling -> epoch millis int (None stays
    None for the caller's null check)."""
    if v is None:
        return None
    if isinstance(v, bool):
        raise UserError(f"cannot use boolean {v!r} as a timestamp")
    if isinstance(v, (int, np.integer)):
        return int(v)
    if isinstance(v, (float, np.floating)):
        if np.isnan(v):
            return None
        return int(v)
    import pandas as pd
    ts = pd.Timestamp(v)
    if ts is pd.NaT:
        return None
    return int(ts.value // 1_000_000)


def _canon_scalar(v):
    """JSON-native canonical value: what the WAL stores and the encoder
    consumes, so a replayed batch is bit-identical to the live one."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        f = float(v)
        return None if np.isnan(f) else f
    if isinstance(v, np.bool_):
        return bool(v)
    try:
        import pandas as pd
        if pd.isna(v):
            return None
    except (TypeError, ValueError):
        pass
    return str(v)


def canonicalize_rows(rows, time_column: str | None) -> list:
    """list[dict] / DataFrame -> canonical rows: JSON-native scalars
    only, the time value (accepted under the table's registered time
    column name or ``__time``) normalized to epoch-millis under
    ``__time``. This is exactly what the WAL frames, so replay feeds
    the same dicts back through the same encoder."""
    import pandas as pd
    if isinstance(rows, pd.DataFrame):
        rows = rows.to_dict("records")
    out = []
    for r in rows:
        if not isinstance(r, dict):
            raise UserError(
                f"append rows must be dicts, got {type(r).__name__}")
        cr = {}
        for k, v in r.items():
            k = str(k)
            if k == TIME_COLUMN or (time_column is not None
                                    and k == time_column):
                cr[TIME_COLUMN] = _to_ms(v)
            else:
                cr[k] = _canon_scalar(v)
        out.append(cr)
    return out


# --------------------------------------------------------------------------
# encoding: canonical rows -> column arrays against a live snapshot

class EncodedBatch:
    __slots__ = ("n", "cols", "nulls", "new_dict_values")

    def __init__(self, n, cols, nulls, new_dict_values):
        self.n = n
        self.cols = cols                    # col -> ndarray[n]
        self.nulls = nulls                  # col -> bool[n] (any() true)
        self.new_dict_values = new_dict_values  # col -> [unseen values]


def encode_rows(table: TableSegments, rows: list,
                require_time: bool) -> EncodedBatch:
    """Validate + encode canonical rows against the snapshot's schema
    and dictionaries. Unseen string values take tail codes past the
    current dictionary (the `Dictionary.extended` contract: existing
    codes never move). Raises UserError before ANY state changes, so a
    bad batch is rejected whole — never half-applied."""
    schema = table.schema
    n = len(rows)
    unknown = set()
    for r in rows:
        unknown.update(k for k in r if k not in schema)
    if unknown:
        raise UserError(
            f"append to {table.name!r}: unknown column(s) "
            f"{sorted(unknown)} (schema: {sorted(schema)})")
    cols: dict = {}
    nulls: dict = {}
    new_vals: dict = {}
    for c, typ in schema.items():
        if c == TIME_COLUMN:
            arr = np.zeros(n, np.int64)
            for i, r in enumerate(rows):
                v = r.get(TIME_COLUMN)
                if v is None:
                    if require_time:
                        raise UserError(
                            f"append to {table.name!r}: a non-null time "
                            "value is required per row (like Druid's "
                            "__time)")
                    v = 0
                arr[i] = int(v)
            cols[c] = arr
            continue
        if typ is ColumnType.STRING:
            d = table.dictionaries.get(c)
            base = d.cardinality if d is not None else 0
            codes = np.zeros(n, np.int32)
            pending: dict = {}
            news: list = []
            for i, r in enumerate(rows):
                v = r.get(c)
                if v is None:
                    continue
                v = str(v)
                code = d.id_of(v) if d is not None else -1
                if code <= 0:
                    code = pending.get(v)
                    if code is None:
                        code = base + len(news) + 1
                        news.append(v)
                        pending[v] = code
                codes[i] = code
            cols[c] = codes
            if news:
                new_vals[c] = news
            continue
        mask = np.zeros(n, bool)
        if typ is ColumnType.LONG:
            arr = np.zeros(n, np.int64)
            for i, r in enumerate(rows):
                v = r.get(c)
                if v is None:
                    mask[i] = True
                    continue
                try:
                    arr[i] = int(v)
                except (TypeError, ValueError):
                    raise UserError(
                        f"append to {table.name!r}: column {c!r} is "
                        f"LONG, got {v!r}") from None
        else:
            arr = np.zeros(n, np.float64)
            for i, r in enumerate(rows):
                v = r.get(c)
                if v is None:
                    mask[i] = True
                    continue
                try:
                    f = float(v)
                except (TypeError, ValueError):
                    raise UserError(
                        f"append to {table.name!r}: column {c!r} is "
                        f"DOUBLE, got {v!r}") from None
                if np.isnan(f):
                    mask[i] = True
                else:
                    arr[i] = f
        cols[c] = arr
        if mask.any():
            nulls[c] = mask
    return EncodedBatch(n, cols, nulls, new_vals)


# --------------------------------------------------------------------------
# delta block emission + snapshot extension

def _emit_blocks(schema: dict, block_rows: int, cols: dict, nulls: dict,
                 start_sid: int) -> list:
    """Row arrays -> padded fixed-size Segment blocks with exact metas
    (the same manifest StreamIngestor._emit_block writes, so interval
    and numeric-bound pruning treat delta blocks like sealed ones).
    Rows keep ARRIVAL order — Druid realtime segments are not
    row-sorted either; per-block time_min/max stay exact."""
    n = len(cols[TIME_COLUMN])
    out = []
    for lo in range(0, n, block_rows):
        hi = min(lo + block_rows, n)
        nv = hi - lo
        bcols, bmasks = {}, {}
        for c, v in cols.items():
            block = np.zeros(block_rows, dtype=v.dtype)
            block[:nv] = v[lo:hi]
            bcols[c] = block
        for c, m in nulls.items():
            mm = m[lo:hi]
            if not mm.any():
                continue
            block = np.zeros(block_rows, dtype=bool)
            block[:nv] = mm
            bmasks[c] = block
        t = bcols[TIME_COLUMN][:nv]
        meta = SegmentMeta(
            segment_id=start_sid + len(out), n_valid=nv,
            time_min=int(t.min()) if nv else 0,
            time_max=int(t.max()) if nv else 0)
        for c, typ in schema.items():
            if typ is not ColumnType.STRING and nv:
                cv = bcols[c][:nv]
                nm = bmasks.get(c)
                if nm is not None:
                    if nm[:nv].all():
                        continue
                    cv = cv[~nm[:nv]]
                meta.column_min[c] = _scalar(cv.min())
                meta.column_max[c] = _scalar(cv.max())
        out.append(Segment(meta, bcols, bmasks))
    return out


def extend_snapshot(table: TableSegments,
                    enc: EncodedBatch) -> TableSegments:
    """New snapshot = sealed segments (shared) + delta blocks (shared,
    except a partially-filled tail rebuilt copy-on-write to absorb the
    batch) + extended dictionaries. Takes a fresh overall generation;
    carries the sealed generation (docs/INGEST.md)."""
    sealed = table.segments[:table.sealed_count]
    delta = list(table.segments[table.sealed_count:])
    dicts = dict(table.dictionaries)
    for c, vals in enc.new_dict_values.items():
        dicts[c] = dicts[c].extended(vals)
    cols, nulls = enc.cols, dict(enc.nulls)
    if delta and delta[-1].meta.n_valid < table.block_rows:
        # absorb into the tail block: copy its valid rows in front of
        # the batch (the OLD tail object stays untouched — snapshots
        # that hold it keep serving it)
        tail = delta.pop()
        tv = tail.meta.n_valid
        cols = {c: np.concatenate([np.asarray(tail.columns[c][:tv]), v])
                for c, v in cols.items()}
        merged: dict = {}
        for c in set(tail.null_masks) | set(nulls):
            a = tail.null_masks[c][:tv] if c in tail.null_masks \
                else np.zeros(tv, bool)
            b = nulls.get(c)
            if b is None:
                b = np.zeros(enc.n, bool)
            m = np.concatenate([a, b])
            if m.any():
                merged[c] = m
        nulls = merged
    sid = table.sealed_count + len(delta)
    blocks = _emit_blocks(table.schema, table.block_rows, cols, nulls,
                          sid)
    out = TableSegments(table.name, table.schema, dicts,
                        sealed + delta + blocks, table.block_rows,
                        sealed_count=table.sealed_count,
                        sealed_generation=table.sealed_generation)
    out.time_partition = table.time_partition
    out.star = table.star
    return out


# --------------------------------------------------------------------------
# compaction

def compact_table(table: TableSegments) -> TableSegments:
    """Seal the snapshot: EVERY row (sealed + delta) re-emitted through
    the batch StreamIngestor — globally re-time-sorted into the table's
    calendar partitions, dictionary re-sorted (restoring the code-range
    fast path for lexicographic bounds), dtypes re-narrowed. Returns a
    pure sealed TableSegments (fresh sealed generation); the caller
    rebases any delta blocks that raced in."""
    from tpu_olap.segments.ingest import (DictBuilder, StreamIngestor,
                                          resolve_time_partition)
    t_lo, t_hi = table.time_boundary
    tp = table.time_partition
    if tp is None:
        tp = resolve_time_partition("auto", t_lo or None, t_hi or None,
                                    table.num_rows, table.block_rows)
    ing = StreamIngestor(table.name, None, table.block_rows, tp)
    ing.schema = dict(table.schema)
    for c, d in table.dictionaries.items():
        # seed the builder with the live dictionary: value -> current
        # code, so stored codes ARE valid temp codes and finalize()'s
        # sort+remap handles the unsorted append tail for free
        b = DictBuilder()
        b._map = {str(v): i + 1 for i, v in enumerate(d.values)}
        ing._dicts[c] = b
    for s in table.segments:
        nv = s.meta.n_valid
        if not nv:
            continue
        ing._pending.append(
            {c: np.asarray(v[:nv]) for c, v in s.columns.items()})
        ing._pending_nulls.append(
            {c: np.asarray(m[:nv]) for c, m in s.null_masks.items()})
        ing._pending_rows += nv
    out = ing.finalize()
    out.star = table.star
    return out


def _remap_codes(live_dict, merged_dict) -> np.ndarray:
    """[live code] -> merged code (0 stays null)."""
    r = np.zeros(live_dict.cardinality + 1, np.int64)
    for i, v in enumerate(live_dict.values):
        r[i + 1] = merged_dict.id_of(v)
    return r


def _gather_delta_rows(table: TableSegments, skip: int):
    """Valid delta rows in append order, minus the first `skip` (the
    rows a compaction snapshot already covered)."""
    delta = table.segments[table.sealed_count:]
    cols = {}
    for c in table.schema:
        cols[c] = np.concatenate(
            [np.asarray(s.columns[c][:s.meta.n_valid]) for s in delta]
        )[skip:] if delta else np.zeros(0, np.int64)
    nulls = {}
    mask_cols = set().union(*(s.null_masks.keys() for s in delta)) \
        if delta else set()
    for c in mask_cols:
        m = np.concatenate(
            [np.asarray(s.null_masks[c][:s.meta.n_valid])
             if c in s.null_masks else np.zeros(s.meta.n_valid, bool)
             for s in delta])[skip:]
        if m.any():
            nulls[c] = m
    return cols, nulls


# --------------------------------------------------------------------------
# the engine-side coordinator

class TableIngestState:
    """Per-table mutable ingest state. `lock` serializes append
    snapshot swaps, WAL writes, and the compactor's swap section —
    never held across the compaction rebuild itself."""

    def __init__(self, name: str):
        self.name = name
        self.lock = threading.RLock()
        self.wal: WriteAheadLog | None = None
        self.frames: list = []   # delta-resident pandas frames (fallback)
        self.frames_version = 0  # bumped on EVERY frames mutation: the
        #                          TableEntry._frame_aug memo key (frame
        #                          count alone could collide after a
        #                          compaction trims the list)
        self.appended_rows = 0
        self.acked_seq = 0
        self.replayed_rows = 0
        self.compactions = 0
        self.last_compact_ms = 0.0
        self.compacting = False

    def delta_source(self):
        """(version, frames) provider TableEntry.frame concatenates —
        the interpreter/fallback path's view of appended rows. Reads
        under the ingest lock so the pair stays consistent with a
        racing compaction's trim."""
        with self.lock:
            return self.frames_version, list(self.frames)


class IngestManager:
    """All real-time ingest state of one Engine: per-table delta
    states, WAL lifecycles, replay-on-register, the backpressure gate,
    and the background compactor thread (docs/INGEST.md)."""

    def __init__(self, engine):
        self.engine = engine
        self.config = engine.config
        self._lock = threading.Lock()
        self._states: dict[str, TableIngestState] = {}
        self._wake = threading.Event()
        self._compactor: threading.Thread | None = None
        self._stopped = False
        m = engine.metrics
        self._m_rows = m.counter(
            "ingest_rows_total",
            "Rows appended through the real-time ingest path "
            "(Engine.append / POST /ingest / INSERT INTO).", ("table",))
        self._m_backpressure = m.counter(
            "ingest_backpressure_total",
            "Appends rejected with 429 because the delta hit "
            "ingest_max_delta_rows.", ("table",))
        self._m_delta = m.gauge(
            "delta_rows",
            "Rows currently resident in the mutable delta scope.",
            ("table",))
        self._m_wal = m.gauge(
            "wal_bytes", "Bytes in the table's write-ahead log.",
            ("table",))
        self._m_compact = m.counter(
            "compactions_total",
            "Delta-to-sealed compactions completed.", ("table",))
        self._m_compact_err = m.counter(
            "compact_errors_total",
            "Background compactions that raised (retried next tick).",
            ("table",))

    # ----------------------------------------------------------- helpers

    def _state(self, name: str) -> TableIngestState:
        with self._lock:
            st = self._states.get(name)
            if st is None:
                st = self._states[name] = TableIngestState(name)
            return st

    def _wal_for(self, st: TableIngestState) -> WriteAheadLog | None:
        cfg = self.config
        if not cfg.ingest_wal_dir:
            return None
        if st.wal is not None and st.wal.tainted:
            # taint is sticky across close(): never silently reopen a
            # log whose tail may hold an unacknowledged frame
            raise RuntimeError(
                f"WAL {st.wal.path} failed a write that could not be "
                "rolled back; re-register the table to reset it")
        if st.wal is None or st.wal._closed:
            st.wal = WriteAheadLog(
                wal_path(cfg.ingest_wal_dir, st.name),
                fsync=cfg.ingest_wal_fsync,
                flush_interval_s=cfg.ingest_wal_flush_interval_s,
                start_seq=st.acked_seq)
        return st.wal

    @staticmethod
    def _delta_frame(entry, canon_rows):
        """Canonical rows -> a fallback-path frame matching the base
        frame's visible schema (time re-materialized as datetime under
        the registered time column name)."""
        import pandas as pd
        df = pd.DataFrame(canon_rows)
        if TIME_COLUMN in df.columns:
            ts = pd.to_datetime(df[TIME_COLUMN], unit="ms")
            df = df.drop(columns=[TIME_COLUMN])
            df[entry.time_column or TIME_COLUMN] = ts
        return df

    # ------------------------------------------------------------ append

    def append(self, name: str, rows) -> dict:
        """The Engine.append implementation: validate -> backpressure
        gate -> WAL frame (durability precedes acknowledgment) ->
        snapshot swap -> cache invalidation scoped to what actually
        changed (tier-2 only; sealed tier-1 partials and cubes
        survive)."""
        eng = self.engine
        cfg = self.config
        entry = eng.catalog.get(name)
        if not entry.is_accelerated:
            raise UserError(
                f"table {name!r} is not accelerated; append needs a "
                "segment-backed datasource")
        if name.startswith("__cube_"):
            raise UserError(
                "cube storage tables are rebuilt from their base "
                "table; append to the base instead")
        canon = canonicalize_rows(rows, entry.time_column)
        if not canon:
            table = entry.segments
            return {"table": name, "rows": 0,
                    "generation": table.generation,
                    "sealed_generation": table.sealed_generation,
                    "delta_rows": table.delta_rows,
                    "watermark": table.watermark, "wal_seq": None}
        maybe_inject(cfg, "append", 0)
        st = self._state(name)
        with st.lock:
            table = entry.segments
            cap = int(cfg.ingest_max_delta_rows or 0)
            if cap and table.delta_rows + len(canon) > cap:
                self._m_backpressure.inc(table=name)
                self._ensure_compactor()
                self._wake.set()
                raise IngestBackpressure(
                    f"delta for {name!r} holds {table.delta_rows} rows;"
                    f" +{len(canon)} would exceed ingest_max_delta_rows"
                    f"={cap} — retry after compaction",
                    retry_after_s=cfg.ingest_retry_after_s)
            # validation/encoding BEFORE the WAL write: a rejected
            # batch must never reach the durable log. The fallback
            # frame too — pd.to_datetime bounds are narrower than the
            # raw epoch-ms range the encoder accepts, and a failure
            # after the WAL ack would leave the batch durable+device-
            # visible but absent from the interpreter's view
            enc = encode_rows(table, canon,
                              require_time=entry.time_column is not None)
            delta_frame = self._delta_frame(entry, canon)
            seq = wal_bytes = None
            wal = self._wal_for(st)
            if wal is not None:
                maybe_inject(cfg, "wal-write", 0)
                seq, wal_bytes = wal.append(canon)
                st.acked_seq = seq
            new_table = extend_snapshot(table, enc)
            entry.segments = new_table
            st.frames.append(delta_frame)
            st.frames_version += 1
            st.appended_rows += len(canon)
            entry.delta_source = st.delta_source
            entry._frame_aug = None
        runner = eng.runner
        # scoped invalidation (the PR 9 contract, split per scope):
        # whole-result state is stale (keys carry the moved overall
        # generation; purge eagerly), sealed-segment partials are NOT
        # (their scope generation did not move) — docs/INGEST.md
        runner.result_cache.invalidate_full(name)
        self._m_rows.inc(len(canon), table=name)
        self._m_delta.set(new_table.delta_rows, table=name)
        if wal_bytes is not None:
            self._m_wal.set(wal_bytes, table=name)
        runner.events.emit(
            "ingest", table=name, kind="append", rows=len(canon),
            generation=new_table.generation,
            sealed_generation=new_table.sealed_generation,
            delta_rows=new_table.delta_rows, wal_seq=seq)
        if cfg.ingest_auto_compact and \
                new_table.delta_rows >= int(cfg.ingest_compact_rows):
            self._ensure_compactor()
            self._wake.set()
        return {"table": name, "rows": len(canon),
                "generation": new_table.generation,
                "sealed_generation": new_table.sealed_generation,
                "delta_rows": new_table.delta_rows,
                "watermark": new_table.watermark, "wal_seq": seq}

    # ------------------------------------------------- register / replay

    def on_register(self, entry):
        """register_table hook. A table already live in THIS engine is
        being REPLACED: its logged appends belonged to the old data —
        reset the log. A first registration with an existing log is
        crash RECOVERY: replay to the acknowledged state
        (cfg.ingest_wal_replay gates it)."""
        cfg = self.config
        name = entry.name
        with self._lock:
            st_prev = self._states.pop(name, None)
        if st_prev is not None:
            self._m_delta.set(0, table=name)
            wal = st_prev.wal
            if wal is not None and not wal._closed and not wal.tainted:
                wal.reset()
                wal.close()
                self._m_wal.set(0, table=name)
            elif cfg.ingest_wal_dir:
                # no live handle to reset through (never appended, or
                # closed by Engine.close, or tainted by a failed
                # write): drop the file itself — the next append
                # recreates it from seq 0
                if wal is not None:
                    wal.close(final_sync=False)
                try:
                    os.unlink(wal_path(cfg.ingest_wal_dir, name))
                except OSError:
                    pass
                self._m_wal.set(0, table=name)
            return
        if not entry.is_accelerated or name.startswith("__cube_") \
                or not cfg.ingest_wal_dir or not cfg.ingest_wal_replay:
            return
        records = replay_wal(wal_path(cfg.ingest_wal_dir, name))
        if records:
            self._replay(entry, records)

    def _replay(self, entry, records):
        """Apply replayed WAL records as ONE batched extension (the
        per-append tail-rebuild fill is deterministic, so the batched
        result is block-identical to the original append sequence).
        Failure mid-replay restores the clean base snapshot — the
        table is registered base-only, never half-recovered; a retry
        (re-registration) replays again."""
        eng = self.engine
        cfg = self.config
        name = entry.name
        st = self._state(name)
        base_snapshot = entry.segments
        t0 = time.perf_counter()
        try:
            with st.lock:
                all_rows: list = []
                for seq, rows in records:
                    maybe_inject(cfg, "wal-replay", 0)
                    all_rows.extend(rows)
                enc = encode_rows(
                    entry.segments, all_rows,
                    require_time=entry.time_column is not None)
                entry.segments = extend_snapshot(entry.segments, enc)
                if all_rows:
                    st.frames.append(self._delta_frame(entry, all_rows))
                    st.frames_version += 1
                st.appended_rows += len(all_rows)
                st.replayed_rows = len(all_rows)
                st.acked_seq = records[-1][0]
                entry.delta_source = st.delta_source
        except Exception:
            with st.lock:
                entry.segments = base_snapshot
                entry.delta_source = None
            with self._lock:
                self._states.pop(name, None)
            raise
        ms = (time.perf_counter() - t0) * 1000
        self._m_rows.inc(len(all_rows), table=name)
        self._m_delta.set(entry.segments.delta_rows, table=name)
        eng.runner.events.emit(
            "wal_replay", table=name, records=len(records),
            rows=len(all_rows), ms=round(ms, 3),
            generation=entry.segments.generation)
        if cfg.ingest_auto_compact and entry.segments.delta_rows \
                >= int(cfg.ingest_compact_rows):
            self._ensure_compactor()
            self._wake.set()

    def on_drop(self, name: str):
        with self._lock:
            st = self._states.pop(name, None)
        if st is not None:
            self._m_delta.set(0, table=name)
            if st.wal is not None:
                st.wal.delete()
                self._m_wal.set(0, table=name)

    # ---------------------------------------------------------- compactor

    def _ensure_compactor(self):
        if self._stopped or not self.config.ingest_auto_compact:
            return
        with self._lock:
            if self._compactor is not None \
                    and self._compactor.is_alive():
                return
            t = threading.Thread(target=self._compact_loop,
                                 name="tpu-olap-compactor", daemon=True)
            self._compactor = t
            t.start()

    def _compact_loop(self):
        cfg = self.config
        while not self._stopped:
            self._wake.wait(
                max(0.05, float(cfg.ingest_compact_interval_s)))
            self._wake.clear()
            if self._stopped:
                return
            with self._lock:
                names = list(self._states)
            for name in names:
                if self._stopped:
                    return
                try:
                    entry = self.engine.catalog.maybe(name)
                    if entry is None or not entry.is_accelerated:
                        continue
                    if entry.segments.delta_rows \
                            >= int(cfg.ingest_compact_rows):
                        self.compact_now(name)
                except QueryShed:
                    pass     # admission saturated: retry next tick
                except Exception as e:  # noqa: BLE001 — retried, but
                    # never silently: a persistently failing compaction
                    # means the delta grows until every append sheds,
                    # and the operator needs a visible cause
                    self._m_compact_err.inc(table=name)
                    try:
                        self.engine.runner.events.emit(
                            "compact_error", table=name,
                            error=f"{type(e).__name__}: {e}")
                    except Exception:  # noqa: BLE001
                        pass

    def compact_now(self, name: str) -> dict | None:
        """Seal the table's delta (sync spelling; the compactor loop
        calls this too). The rebuild runs OUTSIDE the ingest lock from
        an immutable snapshot; appends that race in are carried over
        as rebased delta blocks in the short swap section. Runs under
        an admission slot and skips while the breaker is open, so
        background sealing queues/sheds with foreground traffic
        instead of around it."""
        eng = self.engine
        runner = eng.runner
        entry = eng.catalog.maybe(name)
        if entry is None or not entry.is_accelerated:
            return None
        st = self._state(name)
        with st.lock:
            if st.compacting:
                return {"table": name, "status": "busy"}
            snapshot = entry.segments
            if snapshot.delta_rows == 0:
                return None
            st.compacting = True
        t0 = time.perf_counter()
        try:
            if runner.breaker.state == "open":
                # device sick: don't churn its caches now
                return {"table": name, "status": "breaker-open"}
            with runner.admission.slot(None):
                maybe_inject(self.config, "compact", 0)
                compacted = compact_table(snapshot)
            d_snap = snapshot.delta_rows
            with st.lock:
                live = entry.segments
                d_live = live.delta_rows
                dicts = dict(compacted.dictionaries)
                blocks: list = []
                if d_live > d_snap:
                    # appends raced the rebuild: carry the uncovered
                    # tail rows over, remapping string codes into the
                    # compacted (re-sorted, possibly extended) dicts
                    for c, ld in live.dictionaries.items():
                        missing = [v for v in ld.values
                                   if dicts[c].id_of(v) <= 0]
                        if missing:
                            dicts[c] = dicts[c].extended(missing)
                    cols, nulls = _gather_delta_rows(live, d_snap)
                    for c, typ in live.schema.items():
                        if typ is ColumnType.STRING:
                            r = _remap_codes(live.dictionaries[c],
                                             dicts[c])
                            cols[c] = r[np.asarray(cols[c], np.int64)] \
                                .astype(np.int32)
                    blocks = _emit_blocks(
                        live.schema, live.block_rows, cols, nulls,
                        len(compacted.segments))
                merged = TableSegments(
                    name, live.schema, dicts,
                    compacted.segments + blocks, live.block_rows,
                    sealed_count=len(compacted.segments))
                merged.time_partition = compacted.time_partition
                merged.star = snapshot.star
                entry.segments = merged
                st.compactions += 1
                st.last_compact_ms = (time.perf_counter() - t0) * 1000
                entry._frame_aug = None
                # consolidate the fallback frames this compaction
                # sealed into ONE frame (the carried tail stays
                # per-append): appended rows remain host-resident in
                # frame form — the fallback path needs them, exactly
                # as _frame duplicates base rows — but per-append
                # fragmentation no longer accumulates, so a long
                # append history costs one frame, not thousands
                carried = int(d_live - d_snap)
                keep, acc = [], 0
                for f in reversed(st.frames):
                    if acc >= carried:
                        break
                    keep.append(f)
                    acc += len(f)
                keep.reverse()
                folded = st.frames[:len(st.frames) - len(keep)]
                if len(folded) > 1:
                    import pandas as pd
                    folded = [pd.concat(folded, ignore_index=True)]
                st.frames = folded + keep
                st.frames_version += 1
            # the sealed set changed: BOTH cache tiers for this table
            # are stale at key level — purge eagerly; cubes over it are
            # stale too, the maintainer rebuilds them
            runner.result_cache.invalidate_table(name)
            self._m_compact.inc(table=name)
            self._m_delta.set(merged.delta_rows, table=name)
            runner.events.emit(
                "compact", table=name,
                rows_sealed=compacted.num_rows,
                delta_rows_folded=d_snap,
                delta_rows_carried=int(d_live - d_snap),
                segments=len(compacted.segments),
                ms=round(st.last_compact_ms, 3),
                generation=merged.generation,
                sealed_generation=merged.sealed_generation)
            eng.cubes.on_table_registered(name)
            return {"table": name, "status": "compacted",
                    "rows_sealed": compacted.num_rows,
                    "delta_rows_folded": d_snap,
                    "delta_rows_carried": int(d_live - d_snap),
                    "ms": st.last_compact_ms,
                    "generation": merged.generation,
                    "sealed_generation": merged.sealed_generation}
        finally:
            with st.lock:
                st.compacting = False

    def compact_all(self) -> dict:
        """Compact every table with a non-empty delta (tests, shutdown
        hygiene). Returns {table: result}."""
        out = {}
        with self._lock:
            names = list(self._states)
        for name in names:
            r = self.compact_now(name)
            if r is not None and r.get("status") == "compacted":
                out[name] = r
        return out

    # ------------------------------------------------------------- admin

    def snapshot(self) -> dict:
        """GET /debug/ingest payload: per-table delta sizes, WAL lag,
        compactor state."""
        cfg = self.config
        eng = self.engine
        tables = {}
        with self._lock:
            states = dict(self._states)
        for name, st in sorted(states.items()):
            entry = eng.catalog.maybe(name)
            if entry is None or not entry.is_accelerated:
                continue
            ts = entry.segments
            wal = None
            if st.wal is not None:
                wal = {"path": st.wal.path,
                       "bytes": st.wal.bytes_written,
                       "last_seq": st.wal.last_seq,
                       "synced_seq": st.wal.synced_seq,
                       "lag_records": st.wal.last_seq
                       - st.wal.synced_seq}
            tables[name] = {
                "delta_rows": ts.delta_rows,
                "delta_segments": len(ts.segments) - ts.sealed_count,
                "sealed_segments": ts.sealed_count,
                "watermark": ts.watermark,
                "generation": ts.generation,
                "sealed_generation": ts.sealed_generation,
                "appended_rows": st.appended_rows,
                "replayed_rows": st.replayed_rows,
                "acked_seq": st.acked_seq,
                "compacting": st.compacting,
                "compactions": st.compactions,
                "last_compact_ms": round(st.last_compact_ms, 3),
                "wal": wal,
            }
        return {
            "tables": tables,
            "compactor": {
                "running": self._compactor is not None
                and self._compactor.is_alive(),
                "auto": bool(cfg.ingest_auto_compact),
                "compact_rows": int(cfg.ingest_compact_rows),
                "interval_s": float(cfg.ingest_compact_interval_s),
                "max_delta_rows": int(cfg.ingest_max_delta_rows or 0),
            },
            "wal": {"dir": cfg.ingest_wal_dir,
                    "fsync": cfg.ingest_wal_fsync,
                    "replay_on_register": bool(cfg.ingest_wal_replay)},
        }

    def stop(self):
        """Deterministically stop + join the compactor and close every
        WAL (Engine.close). Appends afterwards reopen WALs lazily; the
        compactor restarts on the next append that wants it."""
        self._stopped = True
        self._wake.set()
        t = self._compactor
        joined = True
        if t is not None:
            t.join(timeout=10.0)
            joined = not t.is_alive()
            if joined:
                self._compactor = None
        with self._lock:
            states = list(self._states.values())
        for st in states:
            if st.wal is not None:
                st.wal.close()
        if joined:
            # re-arm: a later append may restart the compactor cleanly.
            # A join timeout (compaction wedged mid-rebuild) keeps the
            # stop flag set so the straggler exits at its next check
            # instead of being revived as a zombie.
            self._stopped = False
