"""Durable sealed-segment store: checkpointed spill files + an
atomically-swapped manifest (docs/DURABILITY.md).

The reference system's durability story is deep storage of immutable
sealed segments with the real-time log kept short (Druid's
segment/handoff model); the LSM literature ("bLSM", PAPERS.md) makes
the same point — logs are for the tail, checkpoints bound recovery.
This module is that half for the in-process engine: without it the WAL
is the sole durable copy of every appended row and recovery replays the
entire ingest history (O(total appends)); with it, recovery loads the
newest *verifiable* checkpoint and replays only the WAL tail past its
watermark (O(tail)).

On-disk layout (`EngineConfig.ingest_store_dir`), one directory per
table:

    <root>/<table>/seg-<sha16>.chunk      one sealed segment, columnar
    <root>/<table>/dict-<sha16>.chunk     the table's dictionaries
    <root>/<table>/manifest-<id>.json     checkpoint manifests

Chunk files are length+CRC32-framed per column::

    [u32 len][u32 crc32(payload)][payload] ...

frame 0 is canonical JSON metadata (schema-ordered column list, dtypes,
segment meta); the remaining frames are raw little-endian column bytes
(valid rows only — padding is reconstructed at load) followed by null
masks. The layout is *canonical* — sorted keys, no timestamps, content
purely a function of the segment — so a re-spill of unchanged data is
byte-identical and the content-addressed filename (`sha256[:16]` of the
file bytes) dedupes it: a checkpoint after incremental compaction
rewrites only the chunks of partitions the delta touched and reuses the
rest by name.

The manifest is the atomic commit point: canonical JSON wrapped with
its own CRC32, written temp -> fsync -> rename (+ directory fsync), so
a checkpoint is either fully visible or invisible. It records the chunk
files with per-file size+CRC32, the dictionary file, the sealed
generation's shape (schema/block_rows/time_partition), and the WAL
watermark seq the sealed scope covers.

Recovery ladder (`SegmentStore.load`): manifests newest-first; the
first whose manifest CRC, chunk checksums, and frame CRCs ALL verify
wins — a corrupt/missing chunk or torn manifest falls back to the
previous manifest, and past the ladder to base-only + full WAL replay.
Never a wrong answer: corruption is detected, surfaced (`fallbacks` on
the result), and stepped over. The WAL truncation policy in
segments/delta.py is lag-one (truncate only through the OLDEST retained
manifest's watermark), so falling back one checkpoint always finds the
covering WAL tail still on disk — a single corrupt chunk or manifest
loses nothing.
"""

from __future__ import annotations

import hashlib
import json
import os
import struct
import threading
import zlib

import numpy as np

from tpu_olap.resilience.faults import maybe_inject
from tpu_olap.segments.dictionary import Dictionary
from tpu_olap.segments.segment import (ColumnType, Segment, SegmentMeta,
                                       TableSegments, TIME_COLUMN)
from tpu_olap.segments.wal import _fsync_dir

_HEADER = struct.Struct("<II")  # payload length, crc32(payload)

# a corrupt length field must not make the reader allocate gigabytes
# before the CRC check can fail (same bound as the WAL)
MAX_FRAME_BYTES = 1 << 31

STORE_FORMAT = 1

__all__ = ["SegmentStore", "StoreCorrupt", "LoadedCheckpoint",
           "segments_to_frame"]


class StoreCorrupt(Exception):
    """A chunk or manifest failed verification (size/CRC/structure).
    Load treats it as a rung failure and falls down the ladder."""


# --------------------------------------------------------------------------
# framing

def _pack_frame(payload: bytes) -> bytes:
    return _HEADER.pack(len(payload), zlib.crc32(payload)) + payload


def _iter_frames(data: bytes):
    pos, n = 0, len(data)
    while pos < n:
        if n - pos < _HEADER.size:
            raise StoreCorrupt("truncated frame header")
        length, crc = _HEADER.unpack_from(data, pos)
        pos += _HEADER.size
        if length > MAX_FRAME_BYTES or n - pos < length:
            raise StoreCorrupt("truncated frame payload")
        payload = data[pos:pos + length]
        if zlib.crc32(payload) != crc:
            raise StoreCorrupt("frame CRC mismatch")
        pos += length
        yield payload


def _canon_json(obj) -> bytes:
    """Canonical JSON bytes: sorted keys, compact separators — the
    byte-identity contract for content addressing."""
    return json.dumps(obj, sort_keys=True,
                      separators=(",", ":")).encode("utf-8")


def _py(v):
    """numpy scalar -> JSON-native Python scalar (segment metas carry
    np.int64/np.float64 mins/maxes; canonical JSON must not depend on
    which path built them)."""
    if isinstance(v, np.integer):
        return int(v)
    if isinstance(v, np.floating):
        return float(v)
    return v


# --------------------------------------------------------------------------
# segment <-> chunk bytes

def encode_segment(seg: Segment) -> bytes:
    """One sealed segment -> canonical chunk bytes. Only valid rows are
    stored (padding reconstructs at load); columns and masks in sorted
    name order; segment_id is EXCLUDED (it lives in the manifest) so an
    identical segment re-numbered by compaction hashes identically."""
    nv = seg.meta.n_valid
    cols = sorted(seg.columns)
    masks = sorted(c for c, m in seg.null_masks.items()
                   if bool(np.asarray(m[:nv]).any()))
    meta = {
        "n_valid": int(nv),
        "time_min": int(seg.meta.time_min),
        "time_max": int(seg.meta.time_max),
        "column_min": {c: _py(seg.meta.column_min[c])
                       for c in sorted(seg.meta.column_min)},
        "column_max": {c: _py(seg.meta.column_max[c])
                       for c in sorted(seg.meta.column_max)},
        "columns": [{"name": c,
                     "dtype": np.asarray(seg.columns[c]).dtype.str}
                    for c in cols],
        "masks": masks,
    }
    parts = [_pack_frame(_canon_json(meta))]
    for c in cols:
        arr = np.ascontiguousarray(np.asarray(seg.columns[c])[:nv])
        parts.append(_pack_frame(arr.tobytes()))
    for c in masks:
        m = np.ascontiguousarray(
            np.asarray(seg.null_masks[c])[:nv].astype(bool))
        parts.append(_pack_frame(m.tobytes()))
    return b"".join(parts)


def decode_segment(data: bytes, block_rows: int,
                   segment_id: int) -> Segment:
    frames = _iter_frames(data)
    try:
        meta = json.loads(next(frames).decode("utf-8"))
    except (StopIteration, ValueError) as e:
        raise StoreCorrupt(f"bad chunk meta: {e}") from None
    nv = int(meta["n_valid"])
    cols: dict = {}
    for spec in meta["columns"]:
        payload = next(frames, None)
        if payload is None:
            raise StoreCorrupt("chunk missing column frame")
        dt = np.dtype(spec["dtype"])
        v = np.frombuffer(payload, dtype=dt)
        if len(v) != nv:
            raise StoreCorrupt(
                f"column {spec['name']!r}: {len(v)} rows, meta says {nv}")
        block = np.zeros(block_rows, dtype=dt)
        block[:nv] = v
        cols[spec["name"]] = block
    nulls: dict = {}
    for c in meta["masks"]:
        payload = next(frames, None)
        if payload is None:
            raise StoreCorrupt("chunk missing mask frame")
        m = np.frombuffer(payload, dtype=bool)
        if len(m) != nv:
            raise StoreCorrupt(f"mask {c!r}: {len(m)} rows")
        block = np.zeros(block_rows, dtype=bool)
        block[:nv] = m
        nulls[c] = block
    sm = SegmentMeta(
        segment_id=segment_id, n_valid=nv,
        time_min=int(meta["time_min"]), time_max=int(meta["time_max"]),
        column_min=dict(meta["column_min"]),
        column_max=dict(meta["column_max"]))
    return Segment(sm, cols, nulls)


def encode_dictionaries(dicts: dict) -> bytes:
    names = sorted(dicts)
    meta = {"columns": names,
            "is_sorted": {c: bool(dicts[c].is_sorted) for c in names}}
    parts = [_pack_frame(_canon_json(meta))]
    for c in names:
        vals = [str(v) for v in dicts[c].values]
        parts.append(_pack_frame(_canon_json(vals)))
    return b"".join(parts)


def decode_dictionaries(data: bytes) -> dict:
    frames = _iter_frames(data)
    try:
        meta = json.loads(next(frames).decode("utf-8"))
    except (StopIteration, ValueError) as e:
        raise StoreCorrupt(f"bad dictionary meta: {e}") from None
    out: dict = {}
    for c in meta["columns"]:
        payload = next(frames, None)
        if payload is None:
            raise StoreCorrupt("dictionary file missing a column frame")
        vals = json.loads(payload.decode("utf-8"))
        out[c] = Dictionary(
            np.array(vals, dtype=str) if vals
            else np.array([], dtype=str),
            is_sorted=bool(meta["is_sorted"].get(c, True)))
    return out


def segments_to_frame(ts: TableSegments, time_column: str | None):
    """Reconstruct the fallback-path DataFrame from stored segments —
    the recovered table's base frame (the original registration data no
    longer covers compacted appends). STRING columns decode through the
    dictionary; LONG columns with nulls take pandas' float64+NaN
    convention (what a round trip through DataFrame would produce);
    __time re-materializes as datetimes under the registered time
    column name, matching IngestManager._delta_frame."""
    import pandas as pd
    cols: dict = {}
    for c, typ in ts.schema.items():
        pieces = []
        for s in ts.segments:
            nv = s.meta.n_valid
            if not nv:
                continue
            v = np.asarray(s.columns[c][:nv])
            if typ is ColumnType.STRING:
                pieces.append(ts.dictionaries[c].decode(
                    v.astype(np.int64)))
                continue
            m = s.null_masks.get(c)
            if m is not None and np.asarray(m[:nv]).any():
                fv = v.astype(np.float64)
                fv[np.asarray(m[:nv])] = np.nan
                pieces.append(fv)
            else:
                pieces.append(v)
        if pieces:
            cols[c] = np.concatenate(
                [np.asarray(p, dtype=object) for p in pieces]) \
                if typ is ColumnType.STRING else np.concatenate(
                    [p.astype(np.float64) for p in pieces]) \
                if any(p.dtype.kind == "f" for p in pieces) \
                else np.concatenate([p.astype(np.int64) for p in pieces])
        else:
            cols[c] = np.zeros(
                0, np.float64 if typ is ColumnType.DOUBLE else np.int64) \
                if typ is not ColumnType.STRING \
                else np.array([], dtype=object)
    t = cols.pop(TIME_COLUMN)
    df = pd.DataFrame(cols)
    df[time_column or TIME_COLUMN] = pd.to_datetime(
        np.asarray(t, dtype=np.int64), unit="ms")
    return df


# --------------------------------------------------------------------------
# the store

class LoadedCheckpoint:
    """`SegmentStore.load` result: the recovered sealed TableSegments
    (None when no manifest verified), the winning manifest payload, and
    the (file, reason) rungs the ladder stepped over."""

    __slots__ = ("segments", "manifest", "fallbacks")

    def __init__(self, segments, manifest, fallbacks):
        self.segments = segments
        self.manifest = manifest
        self.fallbacks = fallbacks

    @property
    def wal_seq(self) -> int:
        return int(self.manifest["wal_seq"]) if self.manifest else 0


def _manifest_name(checkpoint_id: int) -> str:
    return f"manifest-{checkpoint_id:08d}.json"


def _manifest_id(fname: str) -> int:
    return int(fname[len("manifest-"):-len(".json")])


class SegmentStore:
    """Per-table checkpoint store rooted at `ingest_store_dir`. One
    instance per engine; per-table locks serialize checkpoints (loads
    happen at registration, already serialized by the caller)."""

    def __init__(self, root: str, keep_manifests: int = 2,
                 config=None):
        self.root = root
        self.keep = max(2, int(keep_manifests))
        self.config = config  # fault-injection sites only
        self._lock = threading.Lock()
        # RLocks: IngestManager._checkpoint_sealed holds a table's
        # lock across checkpoint + currency check + WAL truncation so
        # a concurrent delete_table (re-registration/drop) serializes
        # behind the whole commit instead of interleaving with it
        self._table_locks: dict[str, threading.RLock] = {}
        # per-table last checkpoint/load stats (GET /debug/ingest,
        # sys.checkpoints)
        self.stats: dict[str, dict] = {}

    def table_dir(self, name: str) -> str:
        return os.path.join(self.root, name)

    def _tlock(self, name: str) -> threading.RLock:
        with self._lock:
            lk = self._table_locks.get(name)
            if lk is None:
                lk = self._table_locks[name] = threading.RLock()
            return lk

    def table_lock(self, name: str) -> threading.RLock:
        """The per-table commit lock, for callers that need to bind a
        checkpoint to surrounding state checks (see
        IngestManager._checkpoint_sealed). Reentrant: checkpoint()/
        delete_table() re-acquire it safely."""
        return self._tlock(name)

    # -------------------------------------------------------- checkpoint

    def _list_manifests(self, d: str) -> list:
        try:
            names = os.listdir(d)
        except OSError:
            return []
        out = []
        for n in names:
            if n.startswith("manifest-") and n.endswith(".json"):
                try:
                    _manifest_id(n)
                except ValueError:
                    continue
                out.append(n)
        return sorted(out, key=_manifest_id)

    def _read_manifest(self, path: str) -> dict:
        try:
            with open(path, "rb") as f:
                wrapper = json.loads(f.read().decode("utf-8"))
            payload = wrapper["payload"]
            crc = int(wrapper["crc32"])
        except (OSError, ValueError, KeyError, TypeError) as e:
            raise StoreCorrupt(f"unreadable manifest: {e}") from None
        if zlib.crc32(_canon_json(payload)) != crc:
            raise StoreCorrupt("manifest CRC mismatch")
        if payload.get("format") != STORE_FORMAT:
            raise StoreCorrupt(
                f"unknown store format {payload.get('format')!r}")
        return payload

    def _write_blob(self, d: str, prefix: str, blob: bytes,
                    written: list) -> dict:
        """Content-addressed write: skip when the file already exists
        (the canonical layout guarantees identical content). Returns
        the manifest entry; appends to `written` when a file was
        actually created."""
        fname = f"{prefix}-{hashlib.sha256(blob).hexdigest()[:16]}.chunk"
        path = os.path.join(d, fname)
        entry = {"file": fname, "bytes": len(blob),
                 "crc32": zlib.crc32(blob)}
        if not os.path.exists(path):
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            os.rename(tmp, path)
            written.append(fname)
        return entry

    def checkpoint(self, name: str, sealed: TableSegments,
                   wal_seq: int) -> dict:
        """Spill the sealed scope and advance the manifest. `sealed`
        must be an immutable sealed-only view (TableSegments.
        sealed_view()); `wal_seq` is the highest WAL seq whose rows the
        sealed scope covers. Idempotent: an unchanged sealed set +
        watermark returns status "noop" without writing. Returns
        {status, checkpoint_id, segments, files_written, chunks_reused,
        bytes, truncate_through} — truncate_through is the lag-one
        watermark the caller may truncate the WAL through."""
        with self._tlock(name):
            d = self.table_dir(name)
            os.makedirs(d, exist_ok=True)
            manifests = self._list_manifests(d)
            prev_payload = None
            if manifests:
                try:
                    prev_payload = self._read_manifest(
                        os.path.join(d, manifests[-1]))
                except StoreCorrupt:
                    prev_payload = None
            maybe_inject(self.config, "spill-write", 0)
            written: list = []
            seg_entries = []
            reused = 0
            total_bytes = 0
            for s in sealed.segments:
                memo = getattr(s, "_spill_memo", None)
                if memo is not None and os.path.exists(
                        os.path.join(d, memo["file"])):
                    entry = dict(memo)
                    reused += 1
                else:
                    blob = encode_segment(s)
                    pre = len(written)
                    entry = self._write_blob(d, "seg", blob, written)
                    if len(written) == pre:
                        reused += 1
                    s._spill_memo = dict(entry)
                entry["segment_id"] = int(s.meta.segment_id)
                seg_entries.append(entry)
                total_bytes += entry["bytes"]
            dict_entry = self._write_blob(
                d, "dict", encode_dictionaries(sealed.dictionaries),
                written)
            total_bytes += dict_entry["bytes"]
            payload = {
                "format": STORE_FORMAT,
                "table": name,
                "checkpoint_id": (int(prev_payload["checkpoint_id"]) + 1
                                  if prev_payload else
                                  (_manifest_id(manifests[-1]) + 1
                                   if manifests else 1)),
                "wal_seq": int(wal_seq),
                "schema": {c: t.value for c, t in sealed.schema.items()},
                "block_rows": int(sealed.block_rows),
                "time_partition": sealed.time_partition,
                "num_rows": int(sealed.num_rows),
                "segments": seg_entries,
                "dictionary": dict_entry,
            }
            if prev_payload is not None and \
                    prev_payload["segments"] == seg_entries and \
                    prev_payload["dictionary"] == dict_entry and \
                    prev_payload["wal_seq"] == payload["wal_seq"]:
                info = {"status": "noop",
                        "checkpoint_id": prev_payload["checkpoint_id"],
                        "segments": len(seg_entries),
                        "files_written": 0, "chunks_reused": reused,
                        "bytes": total_bytes,
                        "truncate_through": self._truncate_watermark(d)}
                self._note(name, info, payload)
                return info
            _fsync_dir(d)  # chunk files durable before the commit point
            maybe_inject(self.config, "manifest-swap", 0)
            mpath = os.path.join(d, _manifest_name(
                payload["checkpoint_id"]))
            wrapper = {"payload": payload,
                       "crc32": zlib.crc32(_canon_json(payload))}
            tmp = mpath + ".tmp"
            with open(tmp, "wb") as f:
                f.write(json.dumps(wrapper, sort_keys=True,
                                   indent=1).encode("utf-8"))
                f.flush()
                os.fsync(f.fileno())
            os.rename(tmp, mpath)
            _fsync_dir(d)
            self._gc(d)
            info = {"status": "checkpointed",
                    "checkpoint_id": payload["checkpoint_id"],
                    "segments": len(seg_entries),
                    "files_written": len(written),
                    "chunks_reused": reused,
                    "bytes": total_bytes,
                    "truncate_through": self._truncate_watermark(d)}
            self._note(name, info, payload)
            return info

    def _truncate_watermark(self, d: str) -> int:
        """Lag-one truncation bound: the wal_seq of the OLDEST retained
        manifest. Every frame at or below it is covered by ALL retained
        checkpoints, so even falling back the full ladder keeps the
        covering tail. One manifest retained -> 0 (no truncation yet)."""
        manifests = self._list_manifests(d)
        if len(manifests) < 2:
            return 0
        try:
            return int(self._read_manifest(
                os.path.join(d, manifests[0]))["wal_seq"])
        except StoreCorrupt:
            return 0

    def _gc(self, d: str) -> None:
        """Drop manifests beyond the retention window and chunks no
        retained manifest references. Best-effort: a GC failure never
        fails the checkpoint."""
        try:
            manifests = self._list_manifests(d)
            for m in manifests[:-self.keep]:
                try:
                    os.unlink(os.path.join(d, m))
                except OSError:
                    pass
            live: set = set()
            for m in self._list_manifests(d):
                try:
                    p = self._read_manifest(os.path.join(d, m))
                except StoreCorrupt:
                    continue
                live.update(e["file"] for e in p["segments"])
                live.add(p["dictionary"]["file"])
            for fname in os.listdir(d):
                if fname.endswith(".chunk") and fname not in live:
                    try:
                        os.unlink(os.path.join(d, fname))
                    except OSError:
                        pass
                elif fname.endswith(".tmp"):
                    try:
                        os.unlink(os.path.join(d, fname))
                    except OSError:
                        pass
        except OSError:
            pass

    def _note(self, name: str, info: dict, payload: dict) -> None:
        self.stats[name] = {
            "checkpoint_id": info["checkpoint_id"],
            "wal_seq": int(payload["wal_seq"]),
            "segments": info["segments"],
            "bytes": info["bytes"],
            "files_written": info.get("files_written", 0),
            "chunks_reused": info.get("chunks_reused", 0),
            "manifests_retained": len(
                self._list_manifests(self.table_dir(name))),
        }

    # -------------------------------------------------------------- load

    def _load_manifest(self, d: str, mfile: str, name: str):
        payload = self._read_manifest(os.path.join(d, mfile))
        if payload["table"] != name:
            raise StoreCorrupt(
                f"manifest names table {payload['table']!r}")
        block_rows = int(payload["block_rows"])

        def read_verified(entry) -> bytes:
            path = os.path.join(d, entry["file"])
            try:
                with open(path, "rb") as f:
                    data = f.read()
            except OSError as e:
                raise StoreCorrupt(
                    f"missing chunk {entry['file']}: {e}") from None
            if len(data) != int(entry["bytes"]) or \
                    zlib.crc32(data) != int(entry["crc32"]):
                raise StoreCorrupt(
                    f"chunk {entry['file']} failed checksum")
            return data

        dicts = decode_dictionaries(read_verified(payload["dictionary"]))
        segments = []
        for e in payload["segments"]:
            seg = decode_segment(read_verified(e), block_rows,
                                 int(e["segment_id"]))
            seg._spill_memo = {"file": e["file"], "bytes": e["bytes"],
                               "crc32": e["crc32"]}
            segments.append(seg)
        segments.sort(key=lambda s: s.meta.segment_id)
        schema = {c: ColumnType(t) for c, t in payload["schema"].items()}
        ts = TableSegments(name, schema, dicts, segments, block_rows,
                           sealed_count=len(segments))
        ts.time_partition = payload["time_partition"]
        return ts, payload

    def load(self, name: str) -> LoadedCheckpoint | None:
        """Recovery ladder: newest manifest whose every checksum
        verifies wins; corrupt rungs are recorded and stepped over.
        None when the table has no store directory or no manifests at
        all (nothing was ever checkpointed)."""
        d = self.table_dir(name)
        manifests = self._list_manifests(d)
        if not manifests:
            return None
        fallbacks = []
        for mfile in reversed(manifests):
            try:
                ts, payload = self._load_manifest(d, mfile, name)
            except (StoreCorrupt, OSError, ValueError, KeyError,
                    TypeError) as e:
                fallbacks.append((mfile, f"{type(e).__name__}: {e}"))
                continue
            self._note(name, {"checkpoint_id": payload["checkpoint_id"],
                              "segments": len(payload["segments"]),
                              "bytes": sum(int(e["bytes"]) for e in
                                           payload["segments"])
                              + int(payload["dictionary"]["bytes"])},
                       payload)
            return LoadedCheckpoint(ts, payload, fallbacks)
        return LoadedCheckpoint(None, None, fallbacks)

    # ------------------------------------------------------------- admin

    def delete_table(self, name: str) -> None:
        """Drop the table's whole store (DROP TABLE, or a live
        re-registration replacing the data the checkpoints covered).
        Takes the table lock so it serializes behind an in-flight
        checkpoint commit instead of racing its file writes."""
        import shutil
        with self._tlock(name):
            self.stats.pop(name, None)
            shutil.rmtree(self.table_dir(name), ignore_errors=True)

    def table_stats(self, name: str) -> dict | None:
        return self.stats.get(name)
