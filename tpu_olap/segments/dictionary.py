"""Global sorted dictionary for string dimensions.

Id space: 0 = null, 1..n = sorted distinct values. Sorted order makes
lexicographic bound filters pure code-range comparisons, and a *global*
(not per-segment) dictionary makes group-by codes directly mergeable across
segments and chips — the TPU-first choice that replaces Druid's per-segment
dictionaries + broker-side string merge (SURVEY.md §3.7).
"""

from __future__ import annotations

import re

import numpy as np

NULL_ID = 0


class Dictionary:
    __slots__ = ("values", "is_sorted", "_index", "_value_hash_table")

    def __init__(self, values: np.ndarray, is_sorted: bool = True):
        """values: unique string array (no nulls). Batch ingest always
        builds sorted values (`is_sorted=True`, the fast-filter
        invariant); real-time appends EXTEND a dictionary by appending
        unseen values at the tail (`extended`), which may leave it
        unsorted until compaction re-sorts — bound filters then fall
        back from code-range compares to predicate tables
        (kernels.filtereval; docs/INGEST.md)."""
        self.values = values
        self.is_sorted = bool(is_sorted)
        self._index = None  # lazy value -> id dict
        self._value_hash_table = None  # memoized crc32 table (kernels)

    def extended(self, new_values) -> "Dictionary":
        """New Dictionary with `new_values` (unseen, in order) appended
        at the tail — existing codes stay stable, so sealed segments and
        their cached partials remain valid across the extension."""
        if not len(new_values):
            return self
        tail = np.asarray(new_values, dtype=str)
        cat = np.concatenate([np.asarray(self.values, dtype=str), tail])
        still = self.is_sorted and bool(
            np.all(cat[max(0, len(self.values) - 1):][:-1]
                   <= cat[max(0, len(self.values) - 1):][1:]))
        return Dictionary(cat, is_sorted=still)

    @staticmethod
    def build(arr) -> tuple["Dictionary", np.ndarray]:
        """Build from a string array (object/str dtype, None/NaN = null).

        Returns (dictionary, codes int32) with 0 for nulls.
        """
        import pandas as pd
        a = np.asarray(arr, dtype=object)
        mask = np.asarray(pd.isna(a), dtype=bool)
        clean = np.where(mask, "", a).astype(str)
        uniq, inv = np.unique(clean, return_inverse=True)
        # drop the "" placeholder from the dict if it only came from nulls
        has_empty_real = bool((~mask & (clean == "")).any())
        if not has_empty_real and (mask.any() and "" in uniq):
            keep = uniq != ""
            remap = np.cumsum(keep) - 1  # old idx -> new idx (for kept)
            codes = np.where(mask, -1, remap[inv]).astype(np.int64)
            uniq = uniq[keep]
        else:
            codes = np.where(mask, -1, inv).astype(np.int64)
        return Dictionary(uniq.astype(str)), (codes + 1).astype(np.int32)

    @property
    def size(self) -> int:
        """Number of real values (excluding the null slot)."""
        return len(self.values)

    @property
    def cardinality(self) -> int:
        return len(self.values)

    def id_of(self, value: str | None) -> int:
        """Id for a value; 0 for null; -1 if the value is absent."""
        if value is None:
            return NULL_ID
        if self._index is None:
            self._index = {v: i + 1 for i, v in enumerate(self.values)}
        return self._index.get(str(value), -1)

    def decode(self, codes: np.ndarray) -> np.ndarray:
        """codes -> object array of strings (None for null id)."""
        out = np.empty(len(codes), dtype=object)
        nz = codes > 0
        out[nz] = self.values[codes[nz] - 1]
        out[~nz] = None
        return out

    # ---- predicate compilation: value-space -> id-space ------------------

    def bound_code_range(self, lower, upper, lower_strict: bool,
                         upper_strict: bool) -> tuple[int, int]:
        """Lexicographic bound -> inclusive id range [lo, hi] (may be empty).

        Null (id 0) never matches a bound.
        """
        lo = 1
        hi = self.size
        if lower is not None:
            side = "right" if lower_strict else "left"
            lo = int(np.searchsorted(self.values, str(lower), side=side)) + 1
        if upper is not None:
            side = "left" if upper_strict else "right"
            hi = int(np.searchsorted(self.values, str(upper), side=side))
        return lo, hi

    def predicate_table(self, fn) -> np.ndarray:
        """bool[size+1] lookup table: table[id] = fn(value); table[0]=False.

        This is how regex/like/in/search predicates lower: O(|dict|) host
        work once per query, then a single gather on device
        (tpu_olap.kernels.filtereval).
        """
        t = np.zeros(self.size + 1, dtype=bool)
        for i, v in enumerate(self.values):
            if fn(v):
                t[i + 1] = True
        return t

    def regex_table(self, pattern: str) -> np.ndarray:
        rx = re.compile(pattern)
        return self.predicate_table(lambda v: rx.search(v) is not None)

    def like_table(self, pattern: str) -> np.ndarray:
        rx = re.compile(_like_to_regex(pattern))
        return self.predicate_table(lambda v: rx.fullmatch(v) is not None)

    def in_table(self, values) -> np.ndarray:
        t = np.zeros(self.size + 1, dtype=bool)
        for v in values:
            i = self.id_of(v)
            if i >= 0:
                t[i] = True
        return t


def _like_to_regex(pattern: str) -> str:
    """SQL LIKE pattern (% _) -> anchored regex, escaping everything else."""
    out = []
    for ch in pattern:
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
    return "".join(out)
