"""Columnar segment storage — the in-tree replacement for Druid's external
segment engine (SURVEY.md §3.7, §8.2 step 1).

Data model: a table is a set of fixed-size row *blocks* ("segments"), sorted
by the time column, with string dimensions dictionary-encoded against a
*global sorted dictionary* (id 0 reserved for null; ids 1..n are the sorted
distinct values — so per-value predicates become code-space predicates and
cross-segment group-by merges need no dictionary reconciliation). Numeric
metrics are stored in their natural width on host; the executor picks device
dtypes. A manifest records per-segment time ranges and column min/max for
interval/zone pruning (SURVEY.md §3.5 P4).
"""

from tpu_olap.segments.dictionary import Dictionary  # noqa: F401
from tpu_olap.segments.segment import (  # noqa: F401
    ColumnType, Segment, SegmentMeta, TableSegments, TIME_COLUMN,
)
from tpu_olap.segments.ingest import (  # noqa: F401
    ingest_arrow, ingest_parquet, ingest_pandas,
)
