"""Parquet/Arrow/pandas -> TableSegments.

The analog of the reference's L0→L1 data path: the raw fact table Druid
would have indexed is ingested into HBM-ready columnar blocks
(BASELINE.json:5 "streams Parquet→HBM"). Two entry shapes:

- In-memory (`ingest_arrow` / `ingest_pandas`): whole table at once,
  globally time-sorted (best interval pruning).
- Streaming (`ingest_parquet` / `ingest_parquet_stream`): row-group
  batches from one or many parquet files under bounded host memory —
  the SF100-shaped path (SURVEY.md §8.4 #4). Only one batch of decoded
  Arrow data is transient at a time; strings are dictionary-encoded to
  int32 temp codes immediately (the raw strings are dropped per batch)
  and remapped to the final *sorted* dictionary in a finalize pass, so
  lexicographic bound filters stay pure code-range compares.

Numeric storage narrows to the smallest int dtype the observed value
range allows (int8/int16/int32/int64; dictionary codes narrow by
cardinality) — at SF100 this is the difference between fitting in host
RAM + HBM or not. Kernels widen to accumulator dtypes on device
(kernels.exprs.widen_int_env), so narrowing is invisible to results.
"""

from __future__ import annotations

import numpy as np

from tpu_olap.segments.dictionary import Dictionary
from tpu_olap.segments.segment import (ColumnType, Segment, SegmentMeta,
                                       TableSegments, TIME_COLUMN, _scalar)

DEFAULT_BLOCK_ROWS = 1 << 16

_NARROW_INTS = (np.int8, np.int16, np.int32)


def _int_dtype_for(lo: int, hi: int):
    """Smallest signed int dtype holding [lo, hi]. The most negative
    value of each dtype is excluded (kept free as a sentinel, matching
    executor.dataset's convention)."""
    for dt in _NARROW_INTS:
        info = np.iinfo(dt)
        if lo >= info.min + 1 and hi <= info.max:
            return np.dtype(dt)
    return np.dtype(np.int64)


def _code_dtype_for(cardinality: int):
    """Dtype for dictionary codes 0..cardinality (0 = null slot)."""
    return _int_dtype_for(0, cardinality)


class DictBuilder:
    """Incremental string dictionary: values get insertion-order temp
    codes (1-based; 0 = null) during streaming; finalize() sorts and
    returns the remap so stored codes become sorted-order codes."""

    def __init__(self):
        self._map: dict[str, int] = {}

    def encode(self, arr) -> np.ndarray:
        """object array (None/NaN = null) -> int32 temp codes."""
        import pandas as pd
        a = np.asarray(arr, dtype=object)
        null = np.asarray(pd.isna(a), dtype=bool)
        codes = np.zeros(len(a), dtype=np.int32)
        if null.all():
            return codes
        real = a[~null].astype(str)
        uniq, inv = np.unique(real, return_inverse=True)
        codes[~null] = self._ids_for(uniq)[inv]
        return codes

    def encode_indices(self, indices: np.ndarray, values,
                       null_mask: np.ndarray) -> np.ndarray:
        """Arrow-dictionary fast path: `values` (the batch's dictionary,
        small) map through the builder once; row codes are a gather on
        `indices` — no per-row string sort (parquet already
        dictionary-encodes strings, re-deriving that with np.unique was
        ~70% of ingest time)."""
        ids = self._ids_for(np.asarray(values, dtype=object))
        if len(ids) == 0:  # all-null batch: empty dictionary
            return np.zeros(len(indices), dtype=np.int32)
        idx = np.where(null_mask, 0, indices).astype(np.int64)
        codes = ids[idx].astype(np.int32, copy=False)
        codes[null_mask] = 0
        return codes

    def _ids_for(self, uniq) -> np.ndarray:
        ids = np.empty(len(uniq), dtype=np.int32)
        m = self._map
        for i, v in enumerate(uniq):
            v = str(v)
            code = m.get(v)
            if code is None:
                code = len(m) + 1
                m[v] = code
            ids[i] = code
        return ids

    def finalize(self) -> tuple[Dictionary, np.ndarray]:
        """(sorted Dictionary, remap) with remap[temp_code] = final code."""
        values = np.array(sorted(self._map), dtype=str)
        remap = np.zeros(len(self._map) + 1, dtype=np.int32)
        for final_idx, v in enumerate(values):
            remap[self._map[v]] = final_idx + 1
        return Dictionary(values), remap


# --------------------------------------------------------------------------
# Arrow column conversion (shared by in-memory and streaming paths)

def _convert_time(tcol, n: int):
    import pyarrow as pa
    import pyarrow.compute as pc
    if tcol is None:
        return np.zeros(n, dtype=np.int64)
    if tcol.null_count:
        raise ValueError(
            "time column contains nulls; a non-null time value is "
            "required per row (like Druid's __time)")
    t = tcol.type
    if pa.types.is_timestamp(t):
        # Druid's __time is millisecond-grained: sub-ms precision FLOORS
        # via numpy's datetime64 unit conversion (uniform across the
        # epoch — an unsafe Arrow cast would truncate pre-1970 values
        # toward zero, i.e. 1 ms late) instead of raising ArrowInvalid
        v = tcol.combine_chunks().to_numpy(zero_copy_only=False)
        return v.astype("datetime64[ms]").astype(np.int64)
    if pa.types.is_date(t):
        return (tcol.combine_chunks().to_numpy(zero_copy_only=False)
                .astype("datetime64[ms]").astype(np.int64))
    return tcol.combine_chunks().to_numpy(zero_copy_only=False) \
        .astype(np.int64)


def _convert_column(arr, n: int):
    """Arrow array -> (ColumnType, values ndarray, null_mask | None).
    STRING returns the raw object array (encoding is the caller's job)."""
    import pyarrow as pa
    import pyarrow.compute as pc

    arr = arr.combine_chunks() if hasattr(arr, "combine_chunks") else arr
    t = arr.type
    if pa.types.is_dictionary(t):
        arr = pc.cast(arr, t.value_type)
        t = t.value_type
    null_mask = np.asarray(arr.is_null())
    if pa.types.is_null(t):  # all-null column: treat as all-null STRING
        return ColumnType.STRING, np.full(n, None, dtype=object), None
    if pa.types.is_string(t) or pa.types.is_large_string(t):
        return (ColumnType.STRING,
                arr.to_pandas().to_numpy(dtype=object), None)
    if pa.types.is_floating(t):
        v = arr.to_numpy(zero_copy_only=False).astype(np.float64)
        # genuine NaN values (valid Arrow values) fold into the null
        # mask, matching SQL NULL semantics and keeping kernels NaN-free;
        # +/-inf are preserved as real values
        null_mask = null_mask | np.isnan(v)
        return (ColumnType.DOUBLE, np.where(null_mask, 0.0, v),
                null_mask if null_mask.any() else None)
    if pa.types.is_integer(t) or pa.types.is_boolean(t):
        v = arr.to_numpy(zero_copy_only=False)
        if null_mask.any():
            return (ColumnType.LONG,
                    np.where(null_mask, 0, v).astype(np.int64), null_mask)
        return ColumnType.LONG, v.astype(np.int64), None
    if pa.types.is_timestamp(t) or pa.types.is_date(t):
        # numpy unit conversion floors uniformly (see time-column note)
        v = (arr.to_numpy(zero_copy_only=False)
             .astype("datetime64[ms]").astype(np.int64))
        return ColumnType.LONG, v, null_mask if null_mask.any() else None
    if pa.types.is_decimal(t):
        v = np.array([float(x) if x is not None else 0.0
                      for x in arr.to_pylist()], dtype=np.float64)
        return ColumnType.DOUBLE, v, null_mask if null_mask.any() else None
    raise TypeError(f"unsupported column type {t}")


# --------------------------------------------------------------------------
# Streaming ingestor

_PARTITION_UNIT = {"day": "D", "month": "M", "year": "Y"}


def _partition_ids(t_ms: np.ndarray, granularity: str) -> np.ndarray:
    """Calendar partition index per row (UTC, like Druid's default
    segmentGranularity bucketing) from epoch-millis int64."""
    return t_ms.astype("datetime64[ms]") \
        .astype(f"datetime64[{_PARTITION_UNIT[granularity]}]") \
        .astype(np.int64)


MAX_AUTO_PARTITIONS = 128


def resolve_time_partition(spec, t_min, t_max, total_rows: int,
                           block_rows: int):
    """Resolve "auto" to the finest calendar granularity whose expected
    partition count stays ≤ min(total_blocks/4, MAX_AUTO_PARTITIONS) —
    ≥ ~4 full blocks per partition bounds the finalize padding (≤ one
    partial block per partition) at roughly 12%, and the absolute cap
    bounds the streaming ingestor's per-partition remainder buffers
    (≤ one block each) so the bounded-host-memory invariant of
    SURVEY.md §8.4 #4 holds at any scale. Falls back to None (no
    partitioning) for tables too small to amortize even yearly
    partitions."""
    if spec != "auto":
        return spec
    if t_min is None or t_max is None or t_max <= t_min or not total_rows:
        return None
    budget = min(max(1, total_rows // block_rows) / 4,
                 MAX_AUTO_PARTITIONS)
    span_ms = t_max - t_min
    for g, unit_ms in (("day", 86_400_000),
                       ("month", 2_629_800_000),
                       ("year", 31_557_600_000)):
        if span_ms / unit_ms <= budget:
            return g
    return None


class StreamIngestor:
    """Accumulates converted batches into fixed-size segment blocks.

    Memory profile: the final encoded segment store (narrow ints + codes)
    plus one in-flight batch of decoded Arrow data; raw strings never
    outlive their batch. Rows are time-sorted within each flush chunk
    (not globally — per-segment time_min/max stay exact for pruning, like
    Druid segments, which are interval-partitioned but not row-sorted).

    `time_partition` ("day"/"month"/"year") is the Druid
    segmentGranularity analog: rows bucket into disjoint calendar
    partitions, each accumulating its own blocks, so segment time ranges
    never straddle a partition boundary. That is what makes interval
    pruning drop whole segments on time-filtered queries over streamed
    (unsorted) sources, and what lets the lowering elide the residual
    row-level interval mask — and with it the 8-bytes/row __time scan
    traffic — when every scanned segment sits inside one query interval
    (executor/lowering.py::_elide_covered_imask). Cost: up to one
    padded partial block per partition, emitted at finalize."""

    def __init__(self, name: str, time_column: str | None = None,
                 block_rows: int = DEFAULT_BLOCK_ROWS,
                 time_partition: str | None = None):
        if time_partition is not None \
                and time_partition not in _PARTITION_UNIT:
            raise ValueError(
                f"time_partition must be one of {sorted(_PARTITION_UNIT)}"
                " or None")
        self.name = name
        self.time_column = time_column
        self.block_rows = block_rows
        self.time_partition = time_partition
        self.schema: dict | None = None
        self._dicts: dict[str, DictBuilder] = {}
        self._segments: list[Segment] = []
        self._pending: list[dict] = []      # per-batch {col: values}
        self._pending_nulls: list[dict] = []
        self._pending_rows = 0
        # per-partition accumulators (time_partition only)
        self._pbuf: dict[int, list[dict]] = {}
        self._pbuf_nulls: dict[int, list[dict]] = {}
        self._pbuf_rows: dict[int, int] = {}
        self._finalized = False

    # ---- batch intake ----------------------------------------------------

    def add_arrow(self, table) -> None:
        """Add a pyarrow Table/RecordBatch worth of rows."""
        import pyarrow as pa
        if isinstance(table, pa.RecordBatch):
            table = pa.Table.from_batches([table])
        n = table.num_rows
        if n == 0 and self.schema is not None:
            return  # zero-row batches still establish the schema once
        tc = self.time_column
        if tc is None and self.schema is None \
                and TIME_COLUMN in table.schema.names:
            # a Druid-exported table carries its own __time column
            self.time_column = tc = TIME_COLUMN

        cols: dict = {}
        nulls: dict = {}
        cols[TIME_COLUMN] = _convert_time(
            table.column(tc) if tc is not None else None, n)
        schema = {TIME_COLUMN: ColumnType.LONG}
        import pyarrow.compute as pc
        for fld in table.schema:
            c = fld.name
            if c == tc or c == TIME_COLUMN:
                continue
            ftype = fld.type
            arr = None
            if pa.types.is_string(ftype) or pa.types.is_large_string(ftype):
                # flat strings (in-memory ingest): hash-encode in C++
                # so they ride the same dictionary fast path as parquet
                arr = pc.dictionary_encode(
                    table.column(c).combine_chunks())
                ftype = arr.type
            if pa.types.is_dictionary(ftype) and (
                    pa.types.is_string(ftype.value_type)
                    or pa.types.is_large_string(ftype.value_type)):
                # arrow-dictionary fast path: remap small dictionaries,
                # gather row indices (see DictBuilder.encode_indices)
                if arr is None:
                    arr = table.column(c).combine_chunks()
                null = np.asarray(arr.is_null())
                idx = pc.fill_null(arr.indices, 0).to_numpy(
                    zero_copy_only=False)
                vals = arr.dictionary.to_pylist()
                schema[c] = ColumnType.STRING
                cols[c] = self._dicts.setdefault(
                    c, DictBuilder()).encode_indices(idx, vals, null)
                continue
            try:
                typ, v, nm = _convert_column(table.column(c), n)
            except (TypeError, ValueError) as e:
                raise type(e)(f"column {c!r}: {e}") from None
            schema[c] = typ
            if typ is ColumnType.STRING:
                v = self._dicts.setdefault(c, DictBuilder()).encode(v)
            cols[c] = v
            if nm is not None:
                nulls[c] = nm

        if self.schema is None:
            self.schema = schema
        elif schema != self.schema:
            missing = set(self.schema) ^ set(schema)
            raise ValueError(
                f"batch schema mismatch for table {self.name!r}"
                + (f" (columns differ: {sorted(missing)})" if missing
                   else " (column types differ)"))
        self._pending.append(cols)
        self._pending_nulls.append(nulls)
        self._pending_rows += n
        if self._pending_rows >= self.block_rows:
            # emit every full block in one pass (one concatenate, not one
            # per block — an in-memory whole-table add stays O(N))
            self._flush(self._pending_rows
                        - self._pending_rows % self.block_rows)

    # ---- block emission --------------------------------------------------

    @staticmethod
    def _cat_pieces(pieces, npieces):
        """Concatenate buffered column pieces + zero-backfilled null
        masks (a piece that predates a column's first null has no mask
        entry). Shared by the pending drain and partition emission."""
        cat = {c: np.concatenate([p[c] for p in pieces])
               for c in pieces[0]}
        nset = set().union(*(n.keys() for n in npieces)) \
            if npieces else set()
        cat_nulls = {}
        for c in nset:
            cat_nulls[c] = np.concatenate([
                n.get(c, np.zeros(len(p[TIME_COLUMN]), bool))
                for p, n in zip(pieces, npieces)])
        return cat, cat_nulls

    def _cat_pending(self):
        return self._cat_pieces(self._pending, self._pending_nulls)

    def _flush(self, rows: int) -> None:
        """Emit full blocks from the first `rows` pending rows (the chunk
        is time-sorted first); the remainder is carried forward. With
        time_partition set, ALL pending rows instead drain into their
        calendar partition's accumulator, and each partition emits its
        own full blocks (remainders live in the partition buffers until
        finalize)."""
        if self.time_partition is not None:
            cat, cat_nulls = self._cat_pending()
            self._pending, self._pending_nulls = [], []
            self._pending_rows = 0
            order = np.argsort(cat[TIME_COLUMN], kind="stable")
            pids = _partition_ids(cat[TIME_COLUMN][order],
                                  self.time_partition)
            cuts = np.flatnonzero(np.diff(pids)) + 1
            bounds = np.concatenate([[0], cuts, [len(pids)]])
            for s, e in zip(bounds[:-1], bounds[1:]):
                if s == e:
                    continue
                pid = int(pids[s])
                idx = order[s:e]
                self._pbuf.setdefault(pid, []).append(
                    {c: v[idx] for c, v in cat.items()})
                self._pbuf_nulls.setdefault(pid, []).append(
                    {c: m[idx] for c, m in cat_nulls.items()})
                self._pbuf_rows[pid] = self._pbuf_rows.get(pid, 0) \
                    + (e - s)
                if self._pbuf_rows[pid] >= self.block_rows:
                    self._emit_partition(pid, final=False)
            # hard cap on total buffered remainders (bounded host
            # memory even under an explicitly fine granularity on a
            # huge span): force-emit the largest buffers as padded
            # partials — a little block padding, never an OOM
            budget = MAX_AUTO_PARTITIONS * self.block_rows
            while sum(self._pbuf_rows.values()) > budget:
                pid = max(self._pbuf_rows, key=self._pbuf_rows.get)
                self._emit_partition(pid, final=True)
            return
        cat, cat_nulls = self._cat_pending()

        order = np.argsort(cat[TIME_COLUMN][:rows], kind="stable")
        n_blocks = rows // self.block_rows if rows >= self.block_rows else 1
        emit = n_blocks * self.block_rows if rows >= self.block_rows else rows
        for b in range(n_blocks):
            lo = b * self.block_rows
            hi = min((b + 1) * self.block_rows, emit)
            idx = order[lo:hi]
            self._emit_block(
                {c: v[idx] for c, v in cat.items()},
                {c: m[idx] for c, m in cat_nulls.items()}, hi - lo)

        if emit < self._pending_rows:
            rest = np.arange(emit, self._pending_rows)
            self._pending = [{c: v[rest] for c, v in cat.items()}]
            self._pending_nulls = [
                {c: m[rest] for c, m in cat_nulls.items()}]
        else:
            self._pending = []
            self._pending_nulls = []
        self._pending_rows -= emit

    def _emit_partition(self, pid: int, final: bool) -> None:
        """Emit this partition's full blocks (all rows incl. a padded
        partial when final); the remainder rows stay buffered. Rows are
        re-time-sorted across the buffered pieces so blocks inside a
        partition stay locally sorted."""
        cat, cat_nulls = self._cat_pieces(self._pbuf[pid],
                                          self._pbuf_nulls[pid])
        rows = self._pbuf_rows[pid]
        emit = rows if final else rows - rows % self.block_rows
        order = np.argsort(cat[TIME_COLUMN], kind="stable")
        pos = 0
        while pos < emit:
            hi = min(pos + self.block_rows, emit)
            idx = order[pos:hi]
            self._emit_block({c: v[idx] for c, v in cat.items()},
                             {c: m[idx] for c, m in cat_nulls.items()},
                             hi - pos)
            pos = hi
        if final or emit == rows:
            del self._pbuf[pid], self._pbuf_nulls[pid], \
                self._pbuf_rows[pid]
        else:
            rest = order[emit:]
            self._pbuf[pid] = [{c: v[rest] for c, v in cat.items()}]
            self._pbuf_nulls[pid] = [{c: m[rest]
                                      for c, m in cat_nulls.items()}]
            self._pbuf_rows[pid] = rows - emit

    def _emit_block(self, vals: dict, nulls: dict, nv: int) -> None:
        cols, masks = {}, {}
        for c, v in vals.items():
            # per-block narrow storage (promoted to the global dtype at
            # finalize; global range ⊇ block range so promotion is safe)
            if v.dtype.kind == "i" and c != TIME_COLUMN and \
                    self.schema[c] is ColumnType.LONG and nv:
                v = v.astype(_int_dtype_for(int(v[:nv].min()),
                                            int(v[:nv].max())))
            block = np.zeros(self.block_rows, dtype=v.dtype)
            block[:nv] = v
            cols[c] = block
        for c, m in nulls.items():
            block = np.zeros(self.block_rows, dtype=bool)
            block[:nv] = m
            masks[c] = block
        t = cols[TIME_COLUMN][:nv]
        meta = SegmentMeta(
            segment_id=len(self._segments), n_valid=nv,
            time_min=int(t.min()) if nv else 0,
            time_max=int(t.max()) if nv else 0,
        )
        for c, typ in self.schema.items():
            if typ is not ColumnType.STRING and nv:
                cv = cols[c][:nv]
                nm = masks.get(c)
                if nm is not None and nm[:nv].all():
                    continue
                if nm is not None and nm[:nv].any():
                    cv = cv[~nm[:nv]]
                meta.column_min[c] = _scalar(cv.min())
                meta.column_max[c] = _scalar(cv.max())
        self._segments.append(Segment(meta, cols, masks))

    # ---- finalize --------------------------------------------------------

    def finalize(self) -> TableSegments:
        assert not self._finalized, "finalize() called twice"
        self._finalized = True
        if self._pending_rows:
            self._flush(self._pending_rows)
        for pid in sorted(self._pbuf):  # partition remainders, padded
            self._emit_partition(pid, final=True)
        if self.time_partition is not None and len(self._segments) > 1:
            # partition-contiguous id order: arrival-order emission and
            # the finalize partials interleave partitions, but each
            # segment lies inside ONE partition, so sorting by time_min
            # makes every partition a contiguous id run — which is what
            # lets the dispatcher's segment-window slice (runner.
            # _segment_window) cover a pruned interval with a tight
            # window instead of the whole store
            self._segments.sort(
                key=lambda s: (s.meta.time_min, s.meta.segment_id))
            for i, s in enumerate(self._segments):
                s.meta.segment_id = i
        if not self._segments:
            # empty table: one empty segment keeps shapes non-degenerate
            if self.schema is None:
                self.schema = {TIME_COLUMN: ColumnType.LONG}
            self._emit_block(
                {c: np.zeros(0, np.int64 if t is not ColumnType.DOUBLE
                             else np.float64)
                 for c, t in self.schema.items()}, {}, 0)

        # sorted-dictionary remap for stored temp codes
        dictionaries: dict = {}
        remaps: dict = {}
        for c, b in self._dicts.items():
            dictionaries[c], remaps[c] = b.finalize()
        for c, typ in self.schema.items():  # zero-batch STRING edge
            if typ is ColumnType.STRING and c not in dictionaries:
                dictionaries[c] = Dictionary(np.array([], dtype=str))

        # global dtype per column: codes narrow by cardinality, LONGs by
        # the manifest's min/max envelope
        target: dict = {}
        for c, typ in self.schema.items():
            if typ is ColumnType.STRING:
                d = dictionaries.get(c)
                target[c] = _code_dtype_for(d.cardinality if d else 0)
            elif typ is ColumnType.LONG and c != TIME_COLUMN:
                lo = hi = None
                for s in self._segments:
                    mlo = s.meta.column_min.get(c)
                    if mlo is None:
                        continue
                    mhi = s.meta.column_max.get(c)
                    lo = mlo if lo is None else min(lo, mlo)
                    hi = mhi if hi is None else max(hi, mhi)
                target[c] = _int_dtype_for(lo, hi) if lo is not None \
                    else np.dtype(np.int8)
        for s in self._segments:
            for c, dt in target.items():
                v = s.columns[c]
                r = remaps.get(c)
                if r is not None:
                    v = r[v]
                s.columns[c] = v.astype(dt, copy=False)

        out = TableSegments(self.name, self.schema, dictionaries,
                            self._segments, self.block_rows)
        # recorded so delta compaction re-partitions the same way
        # (segments/delta.py; docs/INGEST.md)
        out.time_partition = self.time_partition
        return out


# --------------------------------------------------------------------------
# Entry points

def ingest_arrow(name: str, table, time_column: str | None = None,
                 block_rows: int = DEFAULT_BLOCK_ROWS,
                 time_partition="auto") -> TableSegments:
    """In-memory ingest: globally time-sorted segments, partition-
    aligned per the resolved time_partition (segmentGranularity)."""
    if time_column is None and TIME_COLUMN in table.schema.names:
        time_column = TIME_COLUMN
    tvals = None
    if time_column is not None and table.num_rows:
        tvals = _convert_time(table.column(time_column), table.num_rows)
        order = np.argsort(tvals, kind="stable")
        if not np.array_equal(order, np.arange(table.num_rows)):
            table = table.take(order)
            tvals = tvals[order]
    tp = resolve_time_partition(
        time_partition,
        int(tvals[0]) if tvals is not None and len(tvals) else None,
        int(tvals[-1]) if tvals is not None and len(tvals) else None,
        table.num_rows, block_rows)
    ing = StreamIngestor(name, time_column, block_rows, tp)
    ing.add_arrow(table)
    return ing.finalize()


def ingest_pandas(name: str, df, time_column: str | None = None,
                  block_rows: int = DEFAULT_BLOCK_ROWS,
                  time_partition="auto") -> TableSegments:
    import pyarrow as pa
    return ingest_arrow(name, pa.Table.from_pandas(df, preserve_index=False),
                        time_column, block_rows, time_partition)


def ingest_parquet(name: str, path, time_column: str | None = None,
                   block_rows: int = DEFAULT_BLOCK_ROWS,
                   columns=None, column_map: dict | None = None,
                   batch_rows: int | None = None,
                   time_partition="auto") -> TableSegments:
    """Streaming parquet ingest; `path` may be one path or a list."""
    return ingest_parquet_stream(name, path, time_column, block_rows,
                                 columns, column_map, batch_rows,
                                 time_partition)


def _parquet_time_stats(paths, time_col):
    """(t_min_ms, t_max_ms, total_rows) from parquet row-group footer
    statistics — metadata only, no data read. (None, None, rows) when
    any row group lacks stats for the time column."""
    import pyarrow.parquet as pq
    lo = hi = None
    rows = 0
    for path in paths:
        md = pq.ParquetFile(path).metadata
        rows += md.num_rows
        try:
            sidx = md.schema.names.index(time_col)
        except ValueError:
            return None, None, rows
        for rg in range(md.num_row_groups):
            st = md.row_group(rg).column(sidx).statistics
            if st is None or not st.has_min_max:
                return None, None, rows
            mn, mx = st.min, st.max
            if hasattr(mn, "timestamp"):
                mn = int(mn.timestamp() * 1000)
                mx = int(mx.timestamp() * 1000)
            elif not isinstance(mn, (int, np.integer)):
                return None, None, rows
            lo = mn if lo is None else min(lo, mn)
            hi = mx if hi is None else max(hi, mx)
    return lo, hi, rows


def ingest_parquet_stream(name: str, paths, time_column: str | None = None,
                          block_rows: int = DEFAULT_BLOCK_ROWS,
                          columns=None, column_map: dict | None = None,
                          batch_rows: int | None = None,
                          time_partition="auto") -> TableSegments:
    """Row-group streaming ingest over one or many parquet files under
    bounded host memory (SURVEY.md §8.4 #4 / BASELINE.json:5 "streams
    Parquet→HBM"). `columns` / `column_map` use POST-rename names, like
    Engine.register_table. time_partition="auto" resolves the Druid
    segmentGranularity analog from the footer's time statistics."""
    import pyarrow.parquet as pq

    if isinstance(paths, str):
        paths = [paths]
    column_map = dict(column_map) if column_map else None
    inverse = {v: k for k, v in (column_map or {}).items()}
    read_cols = [inverse.get(c, c) for c in columns] if columns else None

    if time_partition == "auto" and time_column is not None:
        src_time = inverse.get(time_column, time_column)
        t_lo, t_hi, n_rows = _parquet_time_stats(paths, src_time)
        time_partition = resolve_time_partition(
            "auto", t_lo, t_hi, n_rows, block_rows)
    elif time_partition == "auto":
        time_partition = None

    ing = StreamIngestor(name, time_column, block_rows, time_partition)
    bs = batch_rows or block_rows
    dict_cols = None   # string columns read as arrow dictionaries
    for path in paths:
        if dict_cols is None:
            import pyarrow as pa
            schema = pq.read_schema(path)
            dict_cols = [
                f.name for f in schema
                if (pa.types.is_string(f.type)
                    or pa.types.is_large_string(f.type))
                and (read_cols is None or f.name in read_cols)]
        pf = pq.ParquetFile(path, read_dictionary=dict_cols)
        try:
            for batch in pf.iter_batches(batch_size=bs, columns=read_cols):
                if column_map:
                    batch = batch.rename_columns(
                        [column_map.get(c, c) for c in batch.schema.names])
                ing.add_arrow(batch)
        finally:
            pf.close()
    return ing.finalize()
