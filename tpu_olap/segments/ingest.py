"""Parquet/Arrow/pandas -> TableSegments.

The analog of the reference's L0→L1 data path: the raw fact table Druid
would have indexed is ingested directly into HBM-ready columnar blocks
(BASELINE.json:5 "streams Parquet→HBM"). Host-side work: type mapping,
time-sort, global dictionary build, fixed-size blocking with padding.
"""

from __future__ import annotations

import numpy as np

from tpu_olap.segments.dictionary import Dictionary
from tpu_olap.segments.segment import (ColumnType, Segment, SegmentMeta,
                                       TableSegments, TIME_COLUMN, _scalar)

DEFAULT_BLOCK_ROWS = 1 << 16


def ingest_parquet(name: str, path: str, time_column: str | None = None,
                   block_rows: int = DEFAULT_BLOCK_ROWS,
                   columns=None) -> TableSegments:
    import pyarrow.parquet as pq
    table = pq.read_table(path, columns=list(columns) if columns else None)
    return ingest_arrow(name, table, time_column, block_rows)


def ingest_pandas(name: str, df, time_column: str | None = None,
                  block_rows: int = DEFAULT_BLOCK_ROWS) -> TableSegments:
    import pyarrow as pa
    return ingest_arrow(name, pa.Table.from_pandas(df, preserve_index=False),
                        time_column, block_rows)


def ingest_arrow(name: str, table, time_column: str | None = None,
                 block_rows: int = DEFAULT_BLOCK_ROWS) -> TableSegments:
    import pyarrow as pa
    import pyarrow.compute as pc

    schema: dict = {}
    raw: dict = {}      # col -> numpy array (pre-encoding)
    nulls: dict = {}    # col -> bool mask

    # ---- time column -> __time (epoch millis int64) ----------------------
    n = table.num_rows
    if time_column is None and TIME_COLUMN in table.schema.names:
        # a Druid-exported table carries its own __time column; use it
        time_column = TIME_COLUMN
    if time_column is not None:
        tcol = table.column(time_column)
        if tcol.null_count:
            raise ValueError(
                f"time column {time_column!r} contains nulls; a non-null "
                "time value is required per row (like Druid's __time)")
        if pa.types.is_timestamp(tcol.type):
            tms = pc.cast(tcol, pa.timestamp("ms"))
            tvals = tms.combine_chunks().to_numpy(zero_copy_only=False)
            tvals = tvals.astype("datetime64[ms]").astype(np.int64)
        elif pa.types.is_date(tcol.type):
            tvals = (tcol.combine_chunks().to_numpy(zero_copy_only=False)
                     .astype("datetime64[ms]").astype(np.int64))
        else:  # already numeric epoch millis
            tvals = tcol.combine_chunks().to_numpy(zero_copy_only=False) \
                .astype(np.int64)
    else:
        tvals = np.zeros(n, dtype=np.int64)
    raw[TIME_COLUMN] = tvals
    schema[TIME_COLUMN] = ColumnType.LONG

    # ---- other columns ---------------------------------------------------
    for fld in table.schema:
        col = fld.name
        if col == time_column or col == TIME_COLUMN:
            continue
        arr = table.column(col).combine_chunks()
        t = fld.type
        if pa.types.is_dictionary(t):
            arr = pc.cast(arr, t.value_type)
            t = t.value_type
        null_mask = np.asarray(arr.is_null())
        if pa.types.is_null(t):  # all-null column: treat as all-null STRING
            schema[col] = ColumnType.STRING
            raw[col] = np.full(n, None, dtype=object)
        elif pa.types.is_string(t) or pa.types.is_large_string(t):
            schema[col] = ColumnType.STRING
            raw[col] = arr.to_pandas().to_numpy(dtype=object)
        elif pa.types.is_floating(t):
            schema[col] = ColumnType.DOUBLE
            v = arr.to_numpy(zero_copy_only=False).astype(np.float64)
            # genuine NaN values (valid Arrow values) fold into the null
            # mask, matching SQL NULL semantics and keeping kernels NaN-free;
            # +/-inf are preserved as real values
            null_mask = null_mask | np.isnan(v)
            raw[col] = np.where(null_mask, 0.0, v)
            if null_mask.any():
                nulls[col] = null_mask
        elif pa.types.is_integer(t) or pa.types.is_boolean(t):
            schema[col] = ColumnType.LONG
            v = arr.to_numpy(zero_copy_only=False)
            if null_mask.any():
                v = np.where(null_mask, 0, v)
                nulls[col] = null_mask
            raw[col] = v.astype(np.int64)
        elif pa.types.is_timestamp(t) or pa.types.is_date(t):
            schema[col] = ColumnType.LONG
            raw[col] = (pc.cast(arr, pa.timestamp("ms"))
                        .to_numpy(zero_copy_only=False)
                        .astype("datetime64[ms]").astype(np.int64))
            if null_mask.any():
                nulls[col] = null_mask
        elif pa.types.is_decimal(t):
            schema[col] = ColumnType.DOUBLE
            raw[col] = np.array([float(x) if x is not None else 0.0
                                 for x in arr.to_pylist()], dtype=np.float64)
            if null_mask.any():
                nulls[col] = null_mask
        else:
            raise TypeError(f"unsupported column type {t} for {col!r}")

    # ---- sort by time (Druid segments are time-ordered) ------------------
    order = np.argsort(raw[TIME_COLUMN], kind="stable")
    if not np.array_equal(order, np.arange(n)):
        raw = {c: v[order] for c, v in raw.items()}
        nulls = {c: v[order] for c, v in nulls.items()}

    # ---- global dictionaries + encoding ----------------------------------
    dictionaries: dict = {}
    encoded: dict = {}
    for col, typ in schema.items():
        if typ is ColumnType.STRING:
            d, codes = Dictionary.build(raw[col])
            dictionaries[col] = d
            encoded[col] = codes
        else:
            encoded[col] = raw[col]

    # ---- fixed-size blocking with padding --------------------------------
    segments = []
    n_blocks = max(1, -(-n // block_rows))
    for b in range(n_blocks):
        lo, hi = b * block_rows, min((b + 1) * block_rows, n)
        nv = hi - lo
        cols, masks = {}, {}
        for col, v in encoded.items():
            block = np.zeros(block_rows, dtype=v.dtype)
            block[:nv] = v[lo:hi]
            cols[col] = block
        for col, m in nulls.items():
            block = np.zeros(block_rows, dtype=bool)
            block[:nv] = m[lo:hi]
            masks[col] = block
        t = cols[TIME_COLUMN][:nv]
        meta = SegmentMeta(
            segment_id=b, n_valid=nv,
            time_min=int(t.min()) if nv else 0,
            time_max=int(t.max()) if nv else 0,
        )
        for col, typ in schema.items():
            if typ is not ColumnType.STRING and nv:
                cv = cols[col][:nv]
                nm = masks.get(col)
                if nm is not None and nm[:nv].all():
                    continue
                if nm is not None and nm[:nv].any():
                    cv = cv[~nm[:nv]]
                meta.column_min[col] = _scalar(cv.min())
                meta.column_max[col] = _scalar(cv.max())
        segments.append(Segment(meta, cols, masks))

    return TableSegments(name, schema, dictionaries, segments, block_rows)
