"""Device-side finalize + compact + pack: ONE host fetch per aggregate query.

Why this exists: the dominant per-query cost on real hardware is not the
scan/reduce (segment_sum over 300k rows is ~0.1 ms on a v5e) but
device->host result movement — each fresh buffer fetch pays a fixed
round-trip (~tens of ms through the runtime) plus bandwidth on the dense
group table (q4.3's year x city x brand table is ~2.3M groups x 8B per
aggregator). The reference has the same shape of problem (Druid broker
JSON -> JVM row iterator is its per-row hot loop, SURVEY.md §4.2); its
answer is streaming. The TPU-native answer is to finish the query ON
DEVICE and ship back only the answer:

  1. finalize sketches on device (HLL registers -> estimate, theta table
     -> estimate), so [K, 2048] register planes never cross the link;
  2. compact to the non-empty groups (BI group-bys are sparse: the dense
     mixed-radix table is mostly zeros) with a static-size
     `nonzero(size=cap)` so the program stays shape-stable and cacheable;
  3. bitcast every per-group array to int32 words and concatenate into a
     single 1-D buffer -> exactly one transfer, one round-trip.

If more than `cap` groups are non-empty (count is the buffer's header
word), the runner transparently re-runs the unpacked program — correct,
just slower; `result_group_cap` bounds the common case, not the maximum.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from tpu_olap.kernels import hll as hll_mod
from tpu_olap.kernels import theta as theta_mod

_WORD = np.dtype(np.int32)  # buffer word: everything bitcasts to int32


@dataclass(frozen=True)
class PackLayout:
    """Static buffer layout: [count:int32][idx:int32[cap]] then one
    [cap]-slot slab per field, each bitcast to int32 words."""
    cap: int
    total: int
    fields: tuple  # ((name, np.dtype), ...) in buffer order


def make_layout(plan, config, cap: int | None = None) -> PackLayout:
    cap = min(cap if cap is not None else config.result_group_cap,
              plan.total_groups)
    fdt = np.dtype(np.float64 if config.enable_x64 else np.float32)
    fields = [("_rows", np.dtype(np.int32))]
    for p in plan.agg_plans:
        if p.kind in ("count", "sum"):
            fields.append((p.name, np.dtype(p.acc_dtype)))
        else:  # min | max | hll | theta -> finalized float
            fields.append((p.name, fdt))
    return PackLayout(cap, plan.total_groups, tuple(fields))


def device_finalize(out: dict, agg_plans, layout: PackLayout, xp) -> dict:
    """Partial-aggregate dict -> final per-group values (device analog of
    results.finalize_aggs; HLL rounding stays host-side since it is
    per-spec)."""
    fdt = [dt for _, dt in layout.fields if dt.kind == "f"]
    fdt = fdt[0] if fdt else np.dtype(np.float64)
    res = {"_rows": out["_rows"].astype(xp.int32)}
    for p in agg_plans:
        v = out[p.name]
        if p.kind in ("count", "sum"):
            res[p.name] = v
        elif p.kind in ("min", "max"):
            nn = out[f"_nn_{p.name}"]
            res[p.name] = xp.where(nn > 0, v.astype(fdt), xp.asarray(
                np.nan, fdt))
        elif p.kind == "hll":
            res[p.name] = hll_mod.hll_estimate(v, xp, fdt)
        elif p.kind == "theta":
            res[p.name] = theta_mod.theta_estimate(v, xp, fdt)
        else:
            raise AssertionError(p.kind)
    return res


def build_packer(inner, plan, layout: PackLayout):
    """Wrap a partials kernel (single-chip or sharded+merged) so the jitted
    program returns the single packed int32 buffer."""
    import jax.numpy as jnp

    agg_plans = plan.agg_plans

    def fn(env, valid, seg_mask, consts):
        out = inner(env, valid, seg_mask, consts)
        fin = device_finalize(out, agg_plans, layout, jnp)
        present = fin["_rows"] > 0
        count = present.sum(dtype=jnp.int32)
        idx = jnp.nonzero(present, size=layout.cap, fill_value=0)[0] \
            .astype(jnp.int32)
        parts = [count.reshape(1), idx]
        for name, dt in layout.fields:
            parts.append(_as_words(fin[name][idx].astype(dt)))
        return jnp.concatenate(parts)

    return fn


def _as_words(x):
    import jax
    import jax.numpy as jnp

    if x.dtype == jnp.int32:
        return x.reshape(-1)
    return jax.lax.bitcast_convert_type(x, jnp.int32).reshape(-1)


def unpack(buf, layout: PackLayout):
    """Packed buffer (host numpy int32[...]) -> (count, idx[n], {name:
    array[n]}) with n = min(count, cap). count > cap means overflow: the
    caller must re-run unpacked."""
    words = np.asarray(buf)
    count = int(words[0])
    cap = layout.cap
    n = min(count, cap)
    idx = np.asarray(words[1:1 + cap][:n], np.int64)
    pos = 1 + cap
    arrays = {}
    for name, dt in layout.fields:
        w = dt.itemsize // _WORD.itemsize
        slab = words[pos:pos + cap * w]
        pos += cap * w
        arrays[name] = np.ascontiguousarray(slab).view(dt)[:n]
    return count, idx, arrays


def densify(idx, compact: dict, layout: PackLayout, agg_plans) -> dict:
    """Compacted results -> dense [total] arrays (what the host assembly
    paths consume). Empty groups: 0 for counts/sums/sketch estimates, NaN
    for min/max (rendered as SQL null)."""
    kinds = {p.name: p.kind for p in agg_plans}
    out = {}
    for name, dt in layout.fields:
        fill = np.nan if kinds.get(name) in ("min", "max") else 0
        a = np.full(layout.total, fill, dt)
        a[idx] = compact[name]
        out[name] = a
    return out
