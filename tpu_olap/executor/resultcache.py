"""Two-tier semantic result cache (the Druid caching hierarchy analog).

The reference system's hot path is Druid's cache stack: brokers answer
repeated queries from a full-result cache, historicals answer the
per-segment slices they already computed, and only the segments that
changed since the last ingest are recomputed. This module is that stack
for the in-process engine:

Tier 1 — per-segment partial aggregates (`SegmentCache`). Keyed by
  (table generation, segment id, query template minus intervals).  A
  cached entry holds the segment's UNFINALIZED partial-aggregate dict —
  exactly what `kernels.groupby.group_reduce` emits for one segment —
  so serving is a host-side `merge_partials` fold: sums/counts add,
  min/max reduce elementwise, HLL registers max-merge, theta tables
  re-merge EXACTLY (sketch merge is lossless).  A repeated aggregate
  over a moving time window recomputes only the uncached segments in
  one device pass (QueryRunner._run_seg_partials) and merges the rest
  from cache.  Entries are interval-independent by construction: only
  segments ENTIRELY covered by the query's intervals are stored (a
  straddling segment's partials depend on the row-level interval mask
  and always recompute), and bucketed layouts are re-anchored by bucket
  START TIMESTAMP at serve time (`_rebase`), so a day-granularity
  timeseries sliding its window re-uses yesterday's per-segment rows.
  Non-mergeable shapes bypass the tier (sparse group-by — its compact
  tables are capacity-dependent; scan/select/search — row sets, not
  partials; interval-dependent timeformat dimensions). Mesh-sharded
  dispatch IS served: per-(chip, segment) partials come back sharded
  per chip and fold at the host broker with the same merge algebra.

Tier 2 — full results (`FullResultCache`). Keyed by (normalized query
  JSON including intervals, table generation).  A bounded LRU over the
  assembled rows/druid payloads with byte-budget eviction — the broker
  result cache: the BI-dashboard storm where eight users refresh the
  same panel costs one device pass.

Invalidation is generational: every `TableSegments` construction takes
the next per-table generation (segments/segment.py), so ingest and
re-registration orphan every cached entry for that table at key level —
a stale-generation entry can never be SERVED even before it is purged.
`invalidate_table` (called at registration) and `clear` (CLEAR DRUID
CACHE) purge eagerly so the byte gauges drop immediately.

Observability: hit/miss/bypass counters per tier
(`result_cache_requests_total{tier,result}`), eviction counters, byte/
entry gauges, `cache_invalidate` events, and the `/debug/cache`
snapshot.  See docs/CACHING.md for the full contract.
"""

from __future__ import annotations

import json
import threading
from collections import OrderedDict

import numpy as np

__all__ = ["ResultCache"]


def _approx_bytes(obj, _depth=0) -> int:
    """Cheap recursive payload-size estimate for byte-budget accounting.
    Long lists are sampled (first 64 entries extrapolated) so sizing a
    large Scan result never costs a full serialization pass."""
    if obj is None or isinstance(obj, (bool, int, float)):
        return 8
    if isinstance(obj, str):
        return 48 + len(obj)
    if isinstance(obj, np.ndarray):
        return int(obj.nbytes)
    if _depth >= 6:
        return 64
    if isinstance(obj, dict):
        return 64 + sum(_approx_bytes(k, _depth + 1)
                        + _approx_bytes(v, _depth + 1)
                        for k, v in obj.items())
    if isinstance(obj, (list, tuple)):
        n = len(obj)
        if n == 0:
            return 56
        if n <= 64:
            return 56 + sum(_approx_bytes(x, _depth + 1) for x in obj)
        head = sum(_approx_bytes(x, _depth + 1) for x in obj[:64])
        return 56 + head * n // 64
    return 64


def _partials_bytes(partials: dict) -> int:
    return sum(int(np.asarray(v).nbytes) for v in partials.values())


def _strip_intervals(qjson: dict) -> dict:
    """Top-level `intervals` removed: the one field of the query JSON a
    moving time window changes.  Filters keep every literal (a filter
    literal changes the partials, so it must fragment the key)."""
    return {k: v for k, v in qjson.items() if k != "intervals"}


def _config_sig(config) -> tuple:
    """The config knobs that change RESULT VALUES (not just execution
    strategy): dtype policy, sketch widths, granularity timezone.
    Anything else (pallas, batching, budgets that only reroute between
    numerically-equivalent paths) stays out so it cannot fragment the
    cache."""
    return (config.platform, config.enable_x64,
            str(config.long_dtype), str(config.double_dtype),
            config.theta_k_cap, config.sparse_theta_k_cap,
            config.time_zone, config.skip_empty_buckets)


def _fill_value(name: str, plans_by_name: dict):
    """Identity fill for one partial array when re-anchoring a bucketed
    layout: additive state fills 0; min/max fill their fold identity;
    theta tables fill the EMPTY sentinel."""
    from tpu_olap.kernels.groupby import _ident
    from tpu_olap.kernels.theta import EMPTY
    if name == "_rows" or name.startswith("_nn_"):
        return 0
    p = plans_by_name.get(name)
    if p is None:
        return 0
    if p.kind in ("min", "max"):
        return _ident(p.acc_dtype, p.kind)
    if p.kind == "theta":
        return EMPTY
    return 0  # count/sum/hll: additive / max-merge from zero


class _SegmentEntry:
    __slots__ = ("partials", "n_buckets", "starts", "dim_sizes",
                 "bucket_kind", "nbytes", "table")

    def __init__(self, partials, plan, table_name):
        # copies, not views: a view would pin the whole [W*K] dispatch
        # buffer it was sliced from for the life of the cache entry
        self.partials = {k: np.ascontiguousarray(v).copy()
                         for k, v in partials.items()}
        bp = plan.bucket_plan
        self.n_buckets = plan.sizes[0] if plan.sizes else 1
        self.starts = np.asarray(bp.starts, np.int64).copy()
        self.dim_sizes = tuple(plan.sizes[1:])
        self.bucket_kind = bp.kind
        self.nbytes = _partials_bytes(self.partials) + 256
        self.table = table_name


class ResultCache:
    """Both tiers behind one lock, owned by QueryRunner.

    Thread-safety matches the runner's other caches: every mutation is
    a few dict ops under `_lock`, and cached numpy arrays are immutable
    by convention (consumers merge/finalize into fresh arrays)."""

    def __init__(self, config, metrics=None, events=None):
        self.config = config
        self.events = events
        self._lock = threading.Lock()
        self._full: OrderedDict = OrderedDict()   # key -> (rows, druid, meta)
        self._seg: OrderedDict = OrderedDict()    # key -> _SegmentEntry
        self._full_bytes = 0
        self._seg_bytes = 0
        self.stats = {"full": {"hit": 0, "miss": 0, "bypass": 0,
                               "evict": 0},
                      "segment": {"hit": 0, "miss": 0, "bypass": 0,
                                  "evict": 0}}
        self._m_req = self._m_evict = None
        self._m_bytes = self._m_entries = None
        if metrics is not None:
            self._m_req = metrics.counter(
                "result_cache_requests_total",
                "Semantic result-cache lookups by tier and outcome "
                "(tier=full is per query, tier=segment is per segment "
                "consulted; bypass counts ineligible queries).",
                ("tier", "result"))
            self._m_evict = metrics.counter(
                "result_cache_evictions_total",
                "Byte-budget LRU evictions from the result caches.",
                ("tier",))
            self._m_bytes = metrics.gauge(
                "result_cache_bytes",
                "Bytes resident in the result caches.", ("tier",))
            self._m_entries = metrics.gauge(
                "result_cache_entries",
                "Entries resident in the result caches.", ("tier",))

    # ------------------------------------------------------------ enables

    @property
    def full_enabled(self) -> bool:
        return bool(self.config.result_cache_enabled)

    @property
    def seg_enabled(self) -> bool:
        return bool(self.config.segment_cache_enabled)

    # ------------------------------------------------------------- common

    def _count(self, tier: str, result: str, n: int = 1):
        if not n:
            return
        # under the lock: tier-2 lookups run BEFORE the dispatch lock by
        # design, so concurrent callers would otherwise lose increments
        # and /debug/cache would drift from the (locked) /metrics
        # counters. Callers never hold self._lock here.
        with self._lock:
            self.stats[tier][result] += n
        if self._m_req is not None:
            self._m_req.inc(n, tier=tier, result=result)

    def _refresh_gauges(self):
        if self._m_bytes is None:
            return
        self._m_bytes.set(self._full_bytes, tier="full")
        self._m_bytes.set(self._seg_bytes, tier="segment")
        self._m_entries.set(len(self._full), tier="full")
        self._m_entries.set(len(self._seg), tier="segment")

    def _evict_over_budget_locked(self, tier: str):
        """Oldest-first (LRU — hits move-to-end) until under budget."""
        if tier == "full":
            store, budget = self._full, self.config.result_cache_max_bytes
        else:
            store, budget = self._seg, self.config.segment_cache_max_bytes
        n = 0
        while store and self._bytes(tier) > max(0, int(budget)):
            _, victim = store.popitem(last=False)
            self._drop_bytes(tier, victim)
            n += 1
        if n:
            self.stats[tier]["evict"] += n
            if self._m_evict is not None:
                self._m_evict.inc(n, tier=tier)

    def _bytes(self, tier: str) -> int:
        return self._full_bytes if tier == "full" else self._seg_bytes

    def _drop_bytes(self, tier: str, victim):
        if tier == "full":
            self._full_bytes -= victim[2]["nbytes"]
        else:
            self._seg_bytes -= victim.nbytes

    # ------------------------------------------------------ tier 2 (full)

    def _full_key(self, query, table) -> tuple:
        return (table.name, table.generation,
                json.dumps(query.to_json(), sort_keys=True, default=str),
                _config_sig(self.config))

    def get_full(self, query, table):
        """(rows, druid, meta) or None.  Counts the hit/miss."""
        key = self._full_key(query, table)
        with self._lock:
            hit = self._full.get(key)
            if hit is not None:
                try:
                    self._full.move_to_end(key)
                except KeyError:
                    pass
        self._count("full", "hit" if hit is not None else "miss")
        return hit

    def put_full(self, query, table, rows, druid, meta: dict):
        key = self._full_key(query, table)
        meta = dict(meta)
        meta["nbytes"] = nbytes = (_approx_bytes(rows)
                                   + _approx_bytes(druid) + 512)
        if nbytes > max(0, int(self.config.result_cache_max_bytes)):
            return  # larger than the whole budget: never admit
        with self._lock:
            old = self._full.pop(key, None)
            if old is not None:
                self._full_bytes -= old[2]["nbytes"]
            self._full[key] = (rows, druid, meta)
            self._full_bytes += nbytes
            self._evict_over_budget_locked("full")
            self._refresh_gauges()

    # --------------------------------------------------- tier 1 (segment)

    def tier1_bypass_reason(self, plan, mesh) -> str | None:
        """None when the per-segment tier can serve this plan, else why
        not — surfaced in the record (`segment_cache`) and the
        EXPLAIN ANALYZE span so the decision is operator-visible.
        Mesh-sharded dispatch is served too: the per-(chip, segment)
        partials come back sharded per chip and merge at the broker
        (QueryRunner._run_seg_partials mesh variant) — budgets below
        use the chip-padded segment count that program covers."""
        if plan.kind != "agg":
            return "not an aggregation plan"
        if plan.sparse or plan.key_fn is None:
            return "sparse group-by partials are capacity-dependent"
        if plan.empty or not plan.pruned_ids:
            return "no scanned segments"
        if any(dp.kind == "timeformat" for dp in plan.dim_plans):
            return "timeformat dimension layout is interval-dependent"
        n_seg = len(plan.table.segments)
        if mesh is not None:
            from tpu_olap.executor.sharding import (is_multihost,
                                                    pad_segments)
            if is_multihost(mesh):
                return "multi-host mesh (remote shards not addressable)"
            n_seg = pad_segments(max(n_seg, 1), mesh.devices.size)
        from tpu_olap.kernels.groupby import partials_radix
        radix = partials_radix(plan.agg_plans)
        state = n_seg * plan.total_groups * radix
        if state > self.config.segment_cache_state_budget:
            return (f"per-segment state {n_seg}x{plan.total_groups}"
                    f"x{radix} exceeds segment_cache_state_budget")
        if n_seg * plan.total_groups >= (1 << 31):
            return "per-segment key space overflows int32"
        return None

    def template_key(self, query, table) -> tuple:
        """The 'plan fingerprint minus interval': full query JSON with
        the top-level intervals stripped (filter/dim/agg literals all
        kept), plus the result-affecting config signature."""
        return (table.name,
                json.dumps(_strip_intervals(query.to_json()),
                           sort_keys=True, default=str),
                _config_sig(self.config))

    def get_segments(self, tkey, table, plan, seg_ids) -> dict:
        """{segment id: partials} for the cached subset of `seg_ids`,
        re-anchored to this plan's bucket layout.  Counts one hit/miss
        per segment consulted.  Keys carry each segment's SCOPE
        generation (segments/segment.py): sealed segments share the
        sealed generation, so their partials survive delta-only
        appends — the overall generation only re-keys them when the
        sealed set itself changes (registration, compaction)."""
        out = {}
        for sid in seg_ids:
            key = (tkey, table.segment_cache_token(sid), sid)
            with self._lock:
                e = self._seg.get(key)
                if e is not None:
                    try:
                        self._seg.move_to_end(key)
                    except KeyError:
                        pass
            if e is not None:
                served = self._serve_entry(e, plan,
                                           table.segments[sid].meta)
                if served is not None:
                    out[sid] = served
                    continue
            self._count("segment", "miss")
        self._count("segment", "hit", len(out))
        return out

    def put_segment(self, tkey, table, plan, sid, partials):
        entry = _SegmentEntry(partials, plan, table.name)
        key = (tkey, table.segment_cache_token(sid), sid)
        with self._lock:
            old = self._seg.pop(key, None)
            if old is not None:
                self._seg_bytes -= old.nbytes
            if entry.nbytes > max(
                    0, int(self.config.segment_cache_max_bytes)):
                self._refresh_gauges()
                return
            self._seg[key] = entry
            self._seg_bytes += entry.nbytes
            self._evict_over_budget_locked("segment")
            self._refresh_gauges()

    def _serve_entry(self, e: _SegmentEntry, plan, seg_meta):
        """Entry partials in THIS plan's group layout, or None when the
        layouts cannot be reconciled (then the segment recomputes).
        Dimension radixes must match exactly (they depend only on
        filter+dictionary, both in the key — a mismatch is defensive).
        Bucket layouts re-anchor by start timestamp: granularity `all`
        is layout-free; otherwise every bucket the segment's time range
        touches must exist in the new grid at the searchsorted position
        (true whenever the sliding window keeps the same granularity —
        the grids are phase-aligned — and false otherwise, which safely
        degrades to a recompute)."""
        n_new = plan.sizes[0] if plan.sizes else 1
        if e.dim_sizes != tuple(plan.sizes[1:]):
            return None
        if e.bucket_kind == "all" and plan.bucket_plan.kind == "all":
            return e.partials
        if e.n_buckets == n_new and np.array_equal(
                e.starts, np.asarray(plan.bucket_plan.starts, np.int64)):
            return e.partials
        return self._rebase(e, plan, seg_meta, n_new)

    def _rebase(self, e: _SegmentEntry, plan, seg_meta, n_new: int):
        new_starts = np.asarray(plan.bucket_plan.starts, np.int64)
        pos = np.searchsorted(new_starts, e.starts)
        pos_c = np.minimum(pos, n_new - 1)
        ok = new_starts[pos_c] == e.starts
        # old buckets the segment's rows can occupy
        b_lo = int(np.searchsorted(e.starts, seg_meta.time_min,
                                   side="right")) - 1
        b_hi = int(np.searchsorted(e.starts, seg_meta.time_max,
                                   side="right")) - 1
        b_lo, b_hi = max(b_lo, 0), min(b_hi, e.n_buckets - 1)
        if b_lo > b_hi or not ok[b_lo:b_hi + 1].all():
            return None
        d = 1
        for s in e.dim_sizes:
            d *= s
        plans_by_name = {p.name: p for p in plan.agg_plans}
        out = {}
        for name, arr in e.partials.items():
            a = arr.reshape((e.n_buckets, d) + arr.shape[1:])
            new = np.full((n_new, d) + arr.shape[1:],
                          _fill_value(name, plans_by_name), arr.dtype)
            new[pos_c[ok]] = a[ok]
            out[name] = new.reshape((n_new * d,) + arr.shape[1:])
        return out

    # -------------------------------------------------------------- admin

    def cached_segments(self) -> set:
        """{(table, segment_id)} pairs with at least one live tier-1
        partial entry — the `cache_pinned` column of sys.segments
        (catalog.systables). Key layout: (tkey, generation, sid) with
        tkey leading with the table name."""
        with self._lock:
            return {(k[0][0], k[2]) for k in self._seg}

    def shard_entries(self, num_shards: int) -> dict:
        """{chip index: live tier-1 entries} under the interleaved
        placement (chip of segment sid = sid mod D) — the cache-shard
        census behind sys.devices / GET /debug/devices."""
        d = max(1, int(num_shards))
        out: dict = {}
        with self._lock:
            for k in self._seg:
                c = int(k[2]) % d
                out[c] = out.get(c, 0) + 1
        return out

    def shard_bytes(self, num_shards: int) -> dict:
        """{chip index: live tier-1 entry bytes} under the interleaved
        placement (chip of segment sid = sid mod D) — the cache-pin
        byte attribution the HbmLedger folds into its per-(chip,
        owner-class) breakdown (ISSUE 17)."""
        d = max(1, int(num_shards))
        out: dict = {}
        with self._lock:
            for k, e in self._seg.items():
                c = int(k[2]) % d
                out[c] = out.get(c, 0) + int(e.nbytes)
        return out

    def count_bypass(self, tier: str = "segment"):
        self._count(tier, "bypass")

    def clear(self, table: str | None = None,
              tiers: tuple = ("full", "segment")) -> dict:
        """Purge the given tiers (optionally one table's entries).
        Returns {tier: purged count} for the cache_clear event."""
        purged = {"full": 0, "segment": 0}
        with self._lock:
            if table is None:
                purged["full"], purged["segment"] = \
                    len(self._full), len(self._seg)
                self._full.clear()
                self._seg.clear()
                self._full_bytes = self._seg_bytes = 0
            else:
                if "full" in tiers:
                    for key in [k for k in list(self._full)
                                if k[0] == table]:
                        v = self._full.pop(key, None)
                        if v is not None:
                            self._full_bytes -= v[2]["nbytes"]
                            purged["full"] += 1
                if "segment" in tiers:
                    for key in [k for k in list(self._seg)
                                if k[0][0] == table]:
                        v = self._seg.pop(key, None)
                        if v is not None:
                            self._seg_bytes -= v.nbytes
                            purged["segment"] += 1
            self._refresh_gauges()
        return purged

    def invalidate_table(self, table: str):
        """Eager purge at ingest/DROP.  Correctness never depends on it
        (keys carry the generation), but the byte budget should not stay
        occupied by unreachable entries."""
        purged = self.clear(table)
        if self.events is not None and (purged["full"]
                                        or purged["segment"]):
            self.events.emit("cache_invalidate", table=table, **purged)
        return purged

    def invalidate_compacted(self, table: str, live_tokens: set):
        """Compaction swap: tier-2 purges fully (the overall generation
        moved, every full result is stale), but tier-1 keeps entries
        whose segment token is still LIVE — untouched partitions carry
        their Segment uid through the incremental rebuild
        (segments/delta.py), so only the delta-touched partitions'
        entries drop (under a mesh: only the affected chips' cache
        shards)."""
        purged = self.clear(table, tiers=("full",))
        with self._lock:
            dead = [k for k in list(self._seg)
                    if k[0][0] == table and k[1] not in live_tokens]
            for k in dead:
                v = self._seg.pop(k, None)
                if v is not None:
                    self._seg_bytes -= v.nbytes
            purged["segment"] = len(dead)
            self._refresh_gauges()
        if self.events is not None and (purged["full"]
                                        or purged["segment"]):
            self.events.emit("cache_invalidate", table=table,
                             scope="compacted", **purged)
        return purged

    def invalidate_full(self, table: str):
        """Tier-2-only purge for delta-only appends (docs/INGEST.md):
        full results cover the delta so they are stale (and already
        unreachable — the overall generation moved), but per-SEALED-
        segment partials stay servable and must survive."""
        purged = self.clear(table, tiers=("full",))
        if self.events is not None and purged["full"]:
            self.events.emit("cache_invalidate", table=table,
                             scope="full", **purged)
        return purged

    def snapshot(self) -> dict:
        """GET /debug/cache payload."""
        with self._lock:
            return {
                "enabled": {"full": self.full_enabled,
                            "segment": self.seg_enabled},
                "full": {
                    "entries": len(self._full),
                    "bytes": self._full_bytes,
                    "budget_bytes": int(self.config.result_cache_max_bytes),
                    **dict(self.stats["full"]),
                },
                "segment": {
                    "entries": len(self._seg),
                    "bytes": self._seg_bytes,
                    "budget_bytes": int(
                        self.config.segment_cache_max_bytes),
                    "min_rows": int(self.config.segment_cache_min_rows),
                    **dict(self.stats["segment"]),
                },
            }
