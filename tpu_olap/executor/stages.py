"""Stage-graph execution scheduler (docs/EXECUTION.md).

Query execution is an explicit per-query stage graph —

    plan -> enqueue -> transfer -> finalize/post-agg -> assemble

— and this module is the small scheduler that drives it. Each stage
class owns a bounded worker pool (StagePool): a stage section occupies
one pool slot for its duration, waiters queue on the pool, and async
submissions (the per-chip transfer fan-out, background graphs) run on
real pool worker threads. The executor's previous shape — the caller's
thread doing host-transfer AND assembly while the next query waits on
one coarse lock — becomes independent per-stage capacities: transfer
and assembly scale independently of the enqueue section, which stays
width 1 because the chip has one program queue (SURVEY.md §3.5 P1).

Pipeline depth is graph admission: `EngineConfig.pipeline_depth` bounds
how many per-query graphs are in flight at once (StageScheduler.graph
wraps AdmissionController.pipeline_slot, so shed/deadline/metrics
semantics are unchanged), and the per-stage queues absorb bursts inside
an admitted graph.

Background work rides the same machinery instead of bespoke daemon
threads: cube maintenance, delta compaction (checkpointing chained on
it), and WAL interval flush register as periodic background graphs
(register_periodic). One ticker thread schedules all of them onto the
`background` pool; their bodies keep their existing admission slots,
breaker checks, and fault-injection sites, so foreground deadlines and
the breaker govern background device work too.

Observability: every stage section exports `stage_queue_depth{stage}`,
`stage_wait_ms{stage}`, `stage_active_workers{stage}` and
`stage_busy_ms_total{stage}`, opens a `stage:<name>` span (visible in
EXPLAIN ANALYZE and /debug/queries), appends a record to the query's
`stages` metrics block, and fires the `stage-<name>` fault-injection
site (resilience.faults) at entry.

Stranded-worker recovery mirrors AdmissionController.reset_pipeline: a
deadline-abandoned thread wedged inside a stage section holds its slot;
reclaim_stranded() (called from wedge recovery) frees slots held longer
than the deadline so a healed device gets its stage capacity back. A
stranded holder that later wakes releases a reclaimed token, which is
ignored — worst case one transiently over-occupied stage, never
permanent starvation.
"""

from __future__ import annotations

import contextvars
import threading
import time
from collections import deque
from contextlib import contextmanager, nullcontext

from tpu_olap.obs.trace import span as _span

# foreground stage classes, in graph order
FOREGROUND_STAGES = ("plan", "enqueue", "transfer", "finalize", "assemble")
BACKGROUND_STAGE = "background"

_WORKER_IDLE_S = 5.0     # idle pool worker exits after this long
_TICK_MAX_WAIT_S = 0.5   # ticker re-checks at least this often


class _Future:
    """Minimal result box for StagePool.submit."""

    __slots__ = ("_done", "_res", "_err")

    def __init__(self):
        self._done = threading.Event()
        self._res = None
        self._err = None

    def _finish(self, res=None, err=None):
        self._res, self._err = res, err
        self._done.set()

    def result(self, timeout=None):
        if not self._done.wait(timeout):
            raise TimeoutError("stage task did not complete in time")
        if self._err is not None:
            raise self._err
        return self._res


class StagePool:
    """One stage class's bounded worker pool.

    Two execution shapes share the slot accounting:

    - section(): the calling thread occupies one slot for the body
      (synchronous stages on the query's own thread — no handoff cost,
      the pool bounds stage *concurrency* and accounts queue wait);
    - submit(): the task runs on a pool worker thread (asynchronous
      stages: per-chip transfer fan-out, background graph bodies),
      spawned on demand up to max_workers and reaped when idle.

    Slots are re-entrant per thread (a nested section on the same
    thread is free), matching the admission controller's guard, so a
    batch leg that re-enters a stage never deadlocks on its own slot.
    """

    def __init__(self, name: str, max_workers: int, sched):
        self.name = name
        self.max_workers = max(1, int(max_workers))
        self._sched = sched
        self._cond = threading.Condition()
        self._active: dict = {}      # token -> start perf_counter
        self._queued = 0
        self._tasks: deque = deque()
        self._idle = 0
        self._threads = 0
        self._local = threading.local()
        self._stopped = False
        # lifetime totals for occupancy snapshots (under _cond)
        self.submitted = 0
        self.busy_ms = 0.0
        self.wait_ms = 0.0
        self.stranded = 0

    # ------------------------------------------------------------ slots

    def _acquire(self, budget_s):
        """Block until a slot frees; returns (token, waited_ms)."""
        with self._cond:
            if len(self._active) < self.max_workers:
                token = object()
                self._active[token] = time.perf_counter()
                self._gauges()
                return token, 0.0
            self._queued += 1
            self._gauges()
            t0 = time.perf_counter()
            deadline = None if budget_s is None else t0 + budget_s
            try:
                while len(self._active) >= self.max_workers:
                    timeout = None
                    if deadline is not None:
                        timeout = deadline - time.perf_counter()
                        if timeout <= 0:
                            # defined in executor.runner (lazy: the
                            # runner constructs this module's scheduler)
                            from tpu_olap.executor.runner import \
                                QueryDeadlineExceeded
                            raise QueryDeadlineExceeded(
                                f"no {self.name!r} stage slot within the "
                                f"{budget_s}s deadline budget "
                                f"({self.max_workers} occupied)") from None
                    self._cond.wait(timeout)
            finally:
                self._queued -= 1
                self._gauges()
            token = object()
            self._active[token] = time.perf_counter()
            self._gauges()
            return token, (time.perf_counter() - t0) * 1000

    def _release(self, token):
        with self._cond:
            start = self._active.pop(token, None)
            if start is not None:  # None: reclaimed while stranded
                self.busy_ms += (time.perf_counter() - start) * 1000
            self._gauges()
            self._cond.notify()

    def _gauges(self):
        s = self._sched
        if s._m_depth is not None:
            s._m_depth.set(self._queued, stage=self.name)
            s._m_active.set(len(self._active), stage=self.name)

    @contextmanager
    def section(self, budget_s=None):
        """Occupy one slot on the calling thread for the body.
        Re-entrant per thread; yields the queue wait in ms."""
        if getattr(self._local, "held", 0):
            yield 0.0
            return
        token, waited_ms = self._acquire(budget_s)
        with self._cond:
            self.submitted += 1
            self.wait_ms += waited_ms
        self._local.held = 1
        try:
            yield waited_ms
        finally:
            self._local.held = 0
            self._release(token)

    def reclaim_stranded(self, older_than_s: float):
        """Free slots whose holders have been inside the section longer
        than `older_than_s` (deadline-abandoned threads wedged on a sick
        device). The holder's own release becomes a no-op."""
        now = time.perf_counter()
        with self._cond:
            victims = [t for t, s in self._active.items()
                       if now - s > older_than_s]
            for t in victims:
                self._active.pop(t, None)
                self.stranded += 1
            if victims:
                self._gauges()
                self._cond.notify_all()
        return len(victims)

    # ---------------------------------------------------------- workers

    def submit(self, fn) -> _Future:
        """Run `fn` on a pool worker thread inside the caller's
        contextvars snapshot (trace propagation). Tasks queue when all
        workers are busy; an idle worker exits after _WORKER_IDLE_S."""
        fut = _Future()
        ctx = contextvars.copy_context()
        with self._cond:
            if self._stopped:
                raise RuntimeError(f"stage pool {self.name!r} stopped")
            self._tasks.append((fn, ctx, fut, time.perf_counter()))
            self._queued += 1
            self._gauges()
            if self._idle:
                self._cond.notify()
            elif self._threads < self.max_workers:
                self._threads += 1
                threading.Thread(
                    target=self._worker, daemon=True,
                    name=f"tpu-olap-stage-{self.name}").start()
        return fut

    def _worker(self):
        while True:
            with self._cond:
                while not self._tasks:
                    if self._stopped:
                        self._threads -= 1
                        return
                    self._idle += 1
                    signaled = self._cond.wait(_WORKER_IDLE_S)
                    self._idle -= 1
                    if not signaled and not self._tasks:
                        self._threads -= 1
                        return
                fn, ctx, fut, enq_t = self._tasks.popleft()
                self._queued -= 1
                waited_ms = (time.perf_counter() - enq_t) * 1000
                token = object()
                self._active[token] = time.perf_counter()
                self.submitted += 1
                self.wait_ms += waited_ms
                self._gauges()
            if self._sched._m_wait is not None:
                self._sched._m_wait.observe(waited_ms, stage=self.name)
            try:
                fut._finish(res=ctx.run(fn))
            except BaseException as e:  # noqa: BLE001 - relayed via future
                fut._finish(err=e)
            finally:
                self._release(token)

    def stop(self):
        with self._cond:
            self._stopped = True
            self._cond.notify_all()

    def drain(self):
        """Reap idle workers now (shutdown hygiene) but stay usable:
        a worker that misses the wakeup is reclaimed by the idle
        timeout instead — never a stuck submit afterwards."""
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        with self._cond:
            self._stopped = False

    # ------------------------------------------------------------ stats

    def totals(self) -> dict:
        with self._cond:
            return {"max_workers": self.max_workers,
                    "active": len(self._active),
                    "queued": self._queued,
                    "submitted": self.submitted,
                    "busy_ms": round(self.busy_ms, 3),
                    "wait_ms": round(self.wait_ms, 3),
                    "stranded": self.stranded}


class PeriodicHandle:
    """One registered background graph: `body` runs on the background
    pool every `interval_fn()` seconds (None/0 = wake-driven only), or
    immediately on wake(). Never concurrent with itself; cancel() stops
    future runs and optionally joins an in-progress one."""

    def __init__(self, sched, name: str, interval_fn, body):
        self._sched = sched
        self.name = name
        self.interval_fn = interval_fn
        self.body = body
        self.woken = False
        self.cancelled = False
        self.running = False
        self.runs = 0
        self.errors = 0
        self.last_error: str | None = None
        self.next_due = self._compute_due()

    def _compute_due(self):
        try:
            iv = self.interval_fn()
        except Exception:  # noqa: BLE001 - config probe must not kill ticker
            iv = None
        if iv is None or iv <= 0:
            return None  # wake-driven only
        return time.monotonic() + max(0.05, float(iv))

    def wake(self):
        """Request an immediate run (e.g. ingest backpressure needs the
        compactor NOW, not at the next interval tick)."""
        with self._sched._tick_cond:
            self.woken = True
            self._sched._tick_cond.notify()

    def cancel(self, join_timeout: float | None = None):
        with self._sched._tick_cond:
            self.cancelled = True
            if join_timeout is not None:
                deadline = time.monotonic() + join_timeout
                while self.running:
                    left = deadline - time.monotonic()
                    if left <= 0:
                        break
                    self._sched._tick_cond.wait(left)

    def snapshot(self) -> dict:
        return {"name": self.name, "running": self.running,
                "runs": self.runs, "errors": self.errors,
                "last_error": self.last_error,
                "cancelled": self.cancelled}


class StageScheduler:
    """The per-engine stage scheduler: foreground stage pools, graph
    admission, and the background periodic-graph ticker."""

    def __init__(self, config, metrics=None, admission=None, inject=None,
                 events=None):
        self.config = config
        self.admission = admission
        self._inject = inject          # callable(stage_site) or None
        self._events = events
        self._m_depth = self._m_active = self._m_wait = None
        self._m_busy = self._m_runs = None
        if metrics is not None:
            from tpu_olap.obs.metrics import QUEUE_WAIT_BUCKETS_MS
            self._m_depth = metrics.gauge(
                "stage_queue_depth",
                "Callers queued for a stage-pool slot.", ("stage",))
            self._m_active = metrics.gauge(
                "stage_active_workers",
                "Stage-pool slots currently occupied.", ("stage",))
            self._m_wait = metrics.histogram(
                "stage_wait_ms",
                "Queue wait for a stage-pool slot.", ("stage",),
                buckets=QUEUE_WAIT_BUCKETS_MS)
            self._m_busy = metrics.counter(
                "stage_busy_ms_total",
                "Total milliseconds spent inside each stage.", ("stage",))
            self._m_runs = metrics.counter(
                "stage_runs_total",
                "Stage sections/tasks executed.", ("stage",))
        depth = max(1, int(getattr(config, "pipeline_depth", 0) or 0) or 2)
        self.pools = {
            "plan": StagePool("plan", max(2, depth), self),
            # one chip program queue -> enqueue is width 1 by design
            "enqueue": StagePool("enqueue", 1, self),
            "transfer": StagePool("transfer", max(2, depth), self),
            "finalize": StagePool("finalize", max(2, depth), self),
            "assemble": StagePool("assemble", max(2, depth), self),
            BACKGROUND_STAGE: StagePool(BACKGROUND_STAGE, 2, self),
        }
        self._tick_cond = threading.Condition()
        self._handles: list[PeriodicHandle] = []
        self._ticker: threading.Thread | None = None
        self._stopped = False

    # ----------------------------------------------------- foreground

    @contextmanager
    def graph(self, budget_s=None):
        """Admit one per-query stage graph: pipeline_depth bounds how
        many graphs are in flight engine-wide (the admission
        controller's pipeline slot — same shed reason, same metrics,
        re-entrant per thread, reclaimed by wedge recovery)."""
        if self.admission is None:
            yield
            return
        with self.admission.pipeline_slot(budget_s):
            yield

    @contextmanager
    def stage(self, name: str, metrics: dict | None = None,
              budget_s=None):
        """One stage section of the current query's graph: occupies a
        pool slot (queue wait accounted), opens a `stage:<name>` span,
        fires the `stage-<name>` fault site, and appends to the query
        record's `stages` block."""
        pool = self.pools[name]
        if self._inject is not None:
            # a latency-mode fault (FaultInjector.latency_s) stalls the
            # query BETWEEN stages; count that stall as this stage's
            # wait so the regression sentinel attributes the drift to
            # the stage the slow link sits in front of (ISSUE 17)
            ti = time.perf_counter()
            self._inject(f"stage-{name}")
            inject_ms = (time.perf_counter() - ti) * 1000
        else:
            inject_ms = 0.0
        if budget_s is None:
            budget_s = getattr(self.config, "query_deadline_s", None)
        with pool.section(budget_s) as waited_ms:
            waited_ms += inject_ms
            if self._m_wait is not None:
                self._m_wait.observe(waited_ms, stage=name)
            t0 = time.perf_counter()
            with _span(f"stage:{name}",
                       **({"queue_wait_ms": round(waited_ms, 3)}
                          if waited_ms else {})):
                try:
                    yield
                finally:
                    run_ms = (time.perf_counter() - t0) * 1000
                    if self._m_busy is not None:
                        self._m_busy.inc(run_ms, stage=name)
                        self._m_runs.inc(stage=name)
                    if metrics is not None:
                        metrics.setdefault("stages", []).append(
                            {"stage": name,
                             "wait_ms": round(waited_ms, 3),
                             "run_ms": round(run_ms, 3)})

    def submit(self, name: str, fn) -> _Future:
        """Run `fn` asynchronously on the named stage's pool (the
        per-chip transfer fan-out: enqueue D programs, then overlap D
        fetches on transfer workers)."""
        return self.pools[name].submit(fn)

    def map_stage(self, name: str, fns):
        """Fan a list of thunks across the named stage's pool and
        return results in order; with one thunk (or a stopped pool) run
        inline — a single-device transfer must not pay a thread hop."""
        fns = list(fns)
        if len(fns) <= 1:
            return [fn() for fn in fns]
        try:
            futs = [self.pools[name].submit(fn) for fn in fns[1:]]
        except RuntimeError:  # pool stopped (engine closing): run inline
            return [fn() for fn in fns]
        first = fns[0]()  # caller participates instead of idling
        return [first] + [f.result() for f in futs]

    def reclaim_stranded(self, older_than_s: float | None = None) -> int:
        """Wedge recovery: free stage slots held by abandoned threads
        (see StagePool.reclaim_stranded). Defaults to the deadline."""
        if older_than_s is None:
            older_than_s = getattr(
                self.config, "query_deadline_s", None) or 0.0
        return sum(p.reclaim_stranded(older_than_s)
                   for p in self.pools.values())

    # ----------------------------------------------------- background

    def register_periodic(self, name: str, interval_fn,
                          body) -> PeriodicHandle:
        """Register a background graph: `body()` runs on the background
        pool every `interval_fn()` seconds and on every wake(). The one
        scheduler ticker replaces the per-subsystem daemon loops (cube
        maintainer, compactor, WAL flusher)."""
        h = PeriodicHandle(self, name, interval_fn, body)
        with self._tick_cond:
            if self._stopped:
                h.cancelled = True
                return h
            self._handles.append(h)
            if self._ticker is None or not self._ticker.is_alive():
                self._ticker = threading.Thread(
                    target=self._tick_loop, daemon=True,
                    name="tpu-olap-stage-ticker")
                self._ticker.start()
            self._tick_cond.notify()
        return h

    def _tick_loop(self):
        while True:
            with self._tick_cond:
                if self._stopped:
                    return
                now = time.monotonic()
                due = [h for h in self._handles
                       if not h.cancelled and not h.running
                       and (h.woken or (h.next_due is not None
                                        and now >= h.next_due))]
                for h in due:
                    h.woken = False
                    h.running = True
                if not due:
                    # a running handle's next_due is stale until its
                    # finally-block recomputes it — skip it, or a body
                    # outliving its interval spins the ticker at 100 Hz
                    waits = [h.next_due - now for h in self._handles
                             if not h.cancelled and not h.running
                             and h.next_due is not None]
                    self._tick_cond.wait(
                        min([_TICK_MAX_WAIT_S] + [max(0.01, w)
                                                  for w in waits]))
                    continue
            for h in due:
                self._launch(h)

    def _launch(self, h: PeriodicHandle):
        def run():
            try:
                with self.stage(BACKGROUND_STAGE):
                    with _span(f"background:{h.name}"):
                        h.body()
                h.runs += 1
            except Exception as e:  # noqa: BLE001 - periodic: retry next tick
                h.errors += 1
                h.last_error = f"{type(e).__name__}: {e}"
                if self._events is not None:
                    try:
                        self._events.emit("background_error",
                                          graph=h.name,
                                          error=h.last_error)
                    except Exception:  # noqa: BLE001
                        pass
            finally:
                with self._tick_cond:
                    h.running = False
                    h.next_due = h._compute_due()
                    self._tick_cond.notify_all()

        try:
            self.pools[BACKGROUND_STAGE].submit(run)
        except RuntimeError:  # pool stopped mid-shutdown
            with self._tick_cond:
                h.running = False

    # ----------------------------------------------------------- admin

    def snapshot(self) -> dict:
        """Per-stage occupancy totals + background graph states — the
        bench's per-stage occupancy source and /status's `stages`."""
        with self._tick_cond:
            graphs = [h.snapshot() for h in self._handles]
        return {"pools": {n: p.totals() for n, p in self.pools.items()},
                "background_graphs": graphs}

    def stop(self, join_timeout: float = 5.0):
        """Deterministic shutdown: cancel background graphs (joining
        in-progress bodies briefly), join the ticker, and reap idle
        pool workers. The scheduler then RE-ARMS — Engine.close keeps
        the engine queryable, and a later append must be able to
        re-register the compactor/WAL-flush graphs on demand."""
        with self._tick_cond:
            self._stopped = True
            handles = list(self._handles)
            self._tick_cond.notify_all()
        for h in handles:
            h.cancel(join_timeout=join_timeout)
        t = self._ticker
        if t is not None and t.is_alive():
            t.join(timeout=join_timeout)
        with self._tick_cond:
            self._ticker = None
            self._handles = [h for h in self._handles if not h.cancelled]
            self._stopped = False
        for p in self.pools.values():
            p.drain()
