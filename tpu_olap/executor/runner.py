"""QueryRunner: execute a QuerySpec against a registered table.

The analog of the reference's DruidRDD.compute + broker round-trip
(SURVEY.md §4.2) collapsed into an in-process call: lower -> (cached) jit
-> device dispatch -> host assembly. Per-query observability records
(segments pruned, rows scanned, compile/execute/assemble times) mirror the
reference's DruidQueryHistory (SURVEY.md §3.2 "Query-history").
"""

from __future__ import annotations

import functools
import itertools
import time
from collections import OrderedDict
from contextlib import contextmanager, nullcontext
from dataclasses import dataclass, field

import numpy as np

from tpu_olap.executor.config import EngineConfig
from tpu_olap.executor.dataset import DeviceDataset
from tpu_olap.obs.events import EventLog
from tpu_olap.obs.metrics import MetricsRegistry
from tpu_olap.obs.profile import annotate_dispatch
from tpu_olap.obs.slo import SloTracker
from tpu_olap.obs.trace import (Tracer, current_query_id,
                                current_traceparent,
                                in_nested_execution, short_str,
                                span as _span)
from tpu_olap.obs.workload import (WorkloadProfiler, fingerprint_ir,
                                   in_introspection)
from tpu_olap.resilience.admission import AdmissionController
from tpu_olap.resilience.breaker import CircuitBreaker
from tpu_olap.resilience.errors import QueryError
from tpu_olap.resilience.faults import maybe_inject
from tpu_olap.executor.lowering import PhysicalPlan, lower
from tpu_olap.executor.packing import (build_packer, densify, make_layout,
                                       unpack)
from tpu_olap.executor.results import (agg_specs_by_name, eval_having,
                                       eval_post_aggs, finalize_aggs, iso,
                                       render_value, theta_raw_fields)
from tpu_olap.ir.query import (GroupByQuerySpec, ScanQuerySpec,
                               SearchQuerySpec, SegmentMetadataQuerySpec,
                               SelectQuerySpec, TimeBoundaryQuerySpec,
                               TimeseriesQuerySpec, TopNQuerySpec)
from tpu_olap.ir.interval import ETERNITY
from tpu_olap.ir.aggregations import CountAggregation
from tpu_olap.ir.dimensions import DefaultDimensionSpec
from tpu_olap.segments.segment import TIME_COLUMN


@dataclass
class QueryResult:
    query: object
    rows: list                 # flat records (dims/aggs/postaggs [+timestamp])
    druid: list                # Druid-wire-shaped result
    metrics: dict = field(default_factory=dict)

    def to_pandas(self):
        import pandas as pd
        return pd.DataFrame(self.rows)


class QueryDeadlineExceeded(QueryError):
    """Raised when a query exceeds EngineConfig.query_deadline_s. The
    in-process analog of the reference's task-kill -> HTTP query abort
    (SURVEY.md §3.5): the caller falls back; the abandoned dispatch thread
    finishes (and is discarded) in the background since an in-flight XLA
    computation cannot be interrupted. Part of the resilience error
    taxonomy: HTTP surfaces map it to 504 when no fallback answered."""

    code = "deadline_exceeded"
    retriable = True
    http_status = 504


class HistoryRing(list):
    """Bounded per-query history (EngineConfig.history_limit): append
    evicts oldest-first past maxlen, so a long-running server's memory
    no longer grows per query. A list subclass on purpose — callers
    (bench.py, tests, tools) slice and len() it freely, and the ring is
    small enough that the O(maxlen) front-eviction memmove is noise
    next to any query. Aggregate counters never re-sum this structure;
    QueryRunner.record maintains them incrementally. Appends are
    internally locked: pipelined execution completes queries on
    concurrent stage-2 threads, and two racing evictions must not each
    delete a survivor."""

    def __init__(self, maxlen: int | None = None):
        import threading
        super().__init__()
        self.maxlen = maxlen if maxlen is None else max(1, int(maxlen))
        self._mu = threading.Lock()

    def append(self, item):
        with self._mu:
            super().append(item)
            if self.maxlen is not None:
                while len(self) > self.maxlen:
                    del self[0]


# core metric keys every completed-query record carries, whatever path
# served it (dense / sparse / pallas / fallback / batch leg / cache hit)
# — the stable dashboard schema (tests/test_observability.py contract)
CORE_METRIC_DEFAULTS = (
    ("total_ms", 0.0), ("rows_scanned", 0), ("segments_scanned", 0),
    ("cache_hit", False), ("query_type", "?"), ("datasource", "?"),
    ("pipelined", False),
)


def sanitize_metric_value(v, _depth=0):
    """Exception-carrying (or otherwise non-JSON) metric values -> short
    strings AT RECORD TIME, so /status, /sql responses, and
    /debug/queries never hit serialization failures on raw exception
    objects. JSON-native scalars pass through untouched."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return short_str(v) if isinstance(v, str) and len(v) > 300 else v
    if _depth < 4:
        if isinstance(v, (list, tuple)):
            return [sanitize_metric_value(x, _depth + 1) for x in v]
        if isinstance(v, dict):
            return {str(k): sanitize_metric_value(x, _depth + 1)
                    for k, x in v.items()}
    if isinstance(v, np.bool_):
        return bool(v)
    if isinstance(v, np.integer):
        return int(v)
    if isinstance(v, np.floating):
        return float(v)
    return short_str(v)


def _evict_one(cache: dict) -> None:
    """Evict the LEAST-RECENTLY-USED entry: the runner caches are
    OrderedDicts whose hits move-to-end (_cache_lru_hit), so the first
    key is the coldest — previously this popped an arbitrary first
    entry, which under insertion order is plain FIFO and evicts hot
    compiled templates during churn. Tolerates the abandoned-deadline-
    thread concurrency (_run_with_deadline): a concurrent insert between
    iter() and next() raises RuntimeError, a concurrent pop raises
    KeyError — either just means someone else made room."""
    try:
        cache.pop(next(iter(cache), None), None)
    except (KeyError, RuntimeError):
        pass


def _cache_lru_hit(cache, key) -> None:
    """Mark a cache hit for LRU eviction: move the key to the
    OrderedDict's end, tolerating concurrent mutation by an abandoned
    deadline thread (a vanished key is just a racing purge)."""
    try:
        cache.move_to_end(key)
    except (KeyError, RuntimeError):
        pass


class QueryRunner:
    def __init__(self, config: EngineConfig | None = None):
        import threading
        self.config = config or EngineConfig()
        self.config.apply_x64()
        if self.config.platform == "cpu" and (self.config.num_shards or 1) > 1:
            raise ValueError(
                "num_shards > 1 requires the jax device platform; the "
                "numpy path ('cpu') is single-shard by construction")
        # Serializes device dispatch (the chip has one program queue,
        # SURVEY.md §3.5 P1). Engine.device_lock aliases this object so
        # engine-level admin ops and runner-level dispatch share one
        # lock; coalesced callers wait OUTSIDE it (executor.batch).
        self.dispatch_lock = threading.RLock()
        # pipelined execution (EngineConfig.pipeline_depth > 0): stage 1
        # (enqueue) holds dispatch_lock only while the device program is
        # fired; stage 2 (transfer/finalize/assemble) runs lock-free on
        # the caller's thread. The plan cache gets its own mutex because
        # lowering now runs outside the dispatch critical section.
        self._cache_lock = threading.Lock()
        self._tls = threading.local()   # per-thread _last_metrics
        self._inflight_seq = itertools.count(1)  # ledger pin keys
        self._transfer_count = 0        # live stage-2 transfers (gauge)
        self._coalescer = None
        self._batch_seq = 0
        if (self.config.batch_window_ms or 0) > 0:
            self.set_batch_window(self.config.batch_window_ms)
        self._datasets: dict = {}
        from tpu_olap.executor.dataset import HbmLedger
        self._hbm_ledger = HbmLedger(self.config.hbm_budget_bytes)
        # OrderedDicts so eviction is LRU: hits move-to-end
        # (_cache_lru_hit), _evict_one pops the coldest entry
        self._jit_cache: OrderedDict = OrderedDict()
        self._arg_cache: OrderedDict = OrderedDict()  # uploaded consts/
        #                                  seg-mask, content-keyed
        self._cap_hints: dict = {}   # template -> last observed group count
        self._plan_cache: OrderedDict = OrderedDict()  # lowered
        #                                  PhysicalPlans, per query JSON
        self._mesh = None
        self._active_shards = config.num_shards if config else None
        self._chip_dispatches: dict = {}  # chip index -> dispatches
        self._wedged = False   # a deadline expired; re-probe before trusting
        self.history = HistoryRing(self.config.history_limit)
        # observability (tpu_olap.obs): span-tree tracer + incremental
        # metrics registry, both fed through record() at query completion
        self.tracer = Tracer(enabled=self.config.tracing_enabled,
                             ring_limit=self.config.trace_history_limit,
                             slow_ms=self.config.slow_query_ms,
                             slow_limit=self.config.slow_log_limit)
        self.metrics = MetricsRegistry()
        # structured event log (obs.events): query completions, breaker
        # transitions, admission sheds, cache clears, ingest — the ring
        # behind GET /debug/events, with an optional JSONL file sink
        self.events = EventLog(limit=self.config.event_log_limit,
                               path=self.config.event_log_path,
                               max_bytes=self.config.event_log_max_bytes,
                               rotate_keep=self.config.event_log_rotate_keep)
        # latency SLO accounting (obs.slo): every record() classifies
        # good/bad against slo_latency_ms and updates the burn-rate gauge
        self.slo = SloTracker(self.config.slo_latency_ms,
                              self.config.slo_target,
                              self.config.slo_window_s,
                              metrics=self.metrics)
        self._totals_lock = threading.Lock()
        self._profile_seq = 0  # profiler trace dirs outlive ring eviction
        self._totals = {"queries": 0, "rows_scanned": 0,
                        "segments_scanned": 0, "segments_pruned": 0,
                        "cache_hits": 0, "total_ms": 0.0}
        self._by_query_type: dict = {}
        m = self.metrics
        self._m_queries = m.counter(
            "queries_total", "Queries completed, by type and path.",
            ("query_type", "path"))
        self._m_latency = m.histogram(
            "query_latency_ms", "End-to-end query latency (ms).",
            ("query_type", "path"))
        self._m_rows = m.counter(
            "rows_scanned_total", "Rows scanned across all queries.")
        self._m_segments = m.counter(
            "segments_scanned_total",
            "Segments scanned across all queries.")
        self._m_compile = m.counter(
            "compile_cache_requests_total",
            "Dispatches by compile-cache outcome.", ("result",))
        self._m_retries = m.counter(
            "dispatch_retries_total", "Device dispatch retries.")
        self._m_deadline = m.counter(
            "deadline_exceeded_total",
            "Queries killed by query_deadline_s.")
        self._m_hbm_bytes = m.gauge(
            "hbm_bytes_in_use", "HBM ledger bytes resident.")
        self._m_hbm_evict = m.counter(
            "hbm_evictions_total", "HBM ledger column evictions.")
        self._m_batch = m.histogram(
            "batch_size", "Logical queries per shared-scan batch.",
            buckets=(1, 2, 4, 8, 16, 32, 64))
        self._m_degraded = m.counter(
            "degraded_queries_total",
            "Queries served by the interpreter while the breaker was "
            "open (path=fallback_breaker).")
        # memory & compile accounting (ISSUE 8): live device bytes per
        # table, cache population/eviction, and executable builds — the
        # gauges are point-in-time, refreshed by refresh_resource_gauges
        # at scrape; the counters update at their event sites
        self._m_device_bytes = m.gauge(
            "device_bytes",
            "Live device bytes resident per table (segment/derived "
            "buffers + cached const/seg-mask uploads, via nbytes).",
            ("table",))
        self._m_cache_entries = m.gauge(
            "cache_entries", "Entries in the runner caches.", ("cache",))
        self._m_cache_evict = m.counter(
            "cache_evictions_total",
            "Capacity evictions from the runner caches.", ("cache",))
        self._m_cache_clears = m.counter(
            "cache_clears_total",
            "Explicit cache clears (CLEAR DRUID CACHE / recovery "
            "purges).", ("scope",))
        self._m_recompile = m.counter(
            "recompiles_total",
            "Device executables built (jit-cache misses), by dispatch "
            "flavor.", ("kind",))
        self._m_compile_ms = m.counter(
            "compile_ms_total",
            "Milliseconds spent in cold dispatches that built an "
            "executable (trace + XLA compile + first execution).")
        # pipelined-execution observability (ISSUE 10): how long callers
        # wait for the dispatch lock (the contention the pipeline
        # shrinks) and how many stage-2 transfers are live right now
        from tpu_olap.obs.metrics import QUEUE_WAIT_BUCKETS_MS
        self._m_lock_wait = m.histogram(
            "dispatch_lock_wait_ms",
            "Wait to acquire the dispatch lock (stage-1 enqueue in "
            "pipelined mode; whole-query hold in serialized mode).",
            buckets=QUEUE_WAIT_BUCKETS_MS)
        self._m_transfers = m.gauge(
            "inflight_transfers",
            "Device->host result transfers currently in flight "
            "(stage-2 completions).")
        # resilience layer (tpu_olap.resilience; docs/RESILIENCE.md):
        # bounded admission in front of dispatch_lock, plus the device
        # circuit breaker whose healer probes via _healer_probe
        self.admission = AdmissionController(
            self.config.max_inflight_dispatches,
            self.config.admission_queue_limit, metrics=m,
            events=self.events,
            pipeline_depth=self.config.pipeline_depth)
        self.breaker = CircuitBreaker(
            self.config.breaker_failure_threshold,
            self.config.breaker_open_cooldown_s,
            probe=self._healer_probe, metrics=m, events=self.events)
        # two-tier semantic result cache (executor.resultcache;
        # docs/CACHING.md): tier 2 full results consulted at execute()
        # entry, tier 1 per-segment partials consulted inside _run_agg —
        # both generation-invalidated, cleared by clear_cache
        from tpu_olap.executor.resultcache import ResultCache
        self.result_cache = ResultCache(self.config, metrics=m,
                                        events=self.events)
        # workload profiler (obs.workload; ISSUE 11): record() folds
        # every completed-query record into per-template rolling stats —
        # the sys.query_templates / cube-advisor demand signal
        self.workload = WorkloadProfiler(
            max_templates=self.config.workload_max_templates,
            latency_window=self.config.workload_latency_window,
            enabled=self.config.workload_profile_enabled, metrics=m)
        self._attempt_local = threading.local()  # host-transfer inject
        # stage-graph scheduler (executor.stages; docs/EXECUTION.md):
        # per-stage bounded pools + graph admission for the query path,
        # and the periodic-graph ticker the background subsystems (cube
        # maintainer, compactor, WAL flusher) register with
        from tpu_olap.executor.stages import StageScheduler
        self.stages = StageScheduler(self.config, metrics=m,
                                     admission=self.admission,
                                     inject=self._inject,
                                     events=self.events)
        # telemetry plane (obs.timeseries + obs.sentinel; ISSUE 17):
        # the sampler snapshots every metric series into bounded rings
        # (sys.metrics_history / GET /debug/timeseries); the sentinel
        # keeps per-template/per-stage drift baselines fed by record()
        # and runs resource checks on the same periodic tick. Both are
        # observers only — neither executes SQL nor emits query
        # records, so the ISSUE 11 no-self-attribution contract holds.
        from tpu_olap.obs.sentinel import RegressionSentinel
        from tpu_olap.obs.timeseries import TimeseriesSampler
        self.telemetry = TimeseriesSampler(
            m, retention=self.config.telemetry_retention)
        self.sentinel = RegressionSentinel(self.config, metrics=m,
                                           events=self.events)
        ledger = self._hbm_ledger
        ledger.register_external(
            "cache_pins", lambda d: self.result_cache.shard_bytes(d))
        self.sentinel.add_probe("hbm", lambda: {
            "bytes_in_use": ledger.bytes_in_use,
            "budget": ledger.budget, "evictions": ledger.evictions})
        self.sentinel.add_probe(
            "breaker", lambda: {"state": self.breaker.state})
        shed_counter = m.counter("queries_shed_total",
                                 "Queries shed by admission control.",
                                 ("reason",))
        self.sentinel.add_probe("admission", lambda: {
            "shed_total": sum(s.value for s in
                              list(shed_counter.series.values()))})
        self._telemetry_handle = None
        if self.config.telemetry_enabled and \
                (self.config.telemetry_interval_s or 0) > 0:
            self._telemetry_handle = self.stages.register_periodic(
                "telemetry",
                lambda: self.config.telemetry_interval_s,
                self._telemetry_tick)

    def _telemetry_tick(self):
        """One telemetry-graph beat: sample the registry into the
        history rings, then run the sentinel's resource checks and
        stale-alert clearing."""
        self.telemetry.sample_once()
        self.sentinel.check()

    def _inject(self, stage: str):
        """Generalized fault-injection hook (resilience.faults): fires
        the configured injector at `stage` with the current dispatch
        attempt (thread-local, set by _dispatch), so a fault at e.g.
        host-transfer rides the same retry accounting as a dispatch
        fault."""
        maybe_inject(self.config, stage,
                     getattr(self._attempt_local, "value", 0))

    # --------------------------------------------- pipelined execution

    @property
    def _last_metrics(self) -> dict:
        """Per-THREAD current-query metrics dict: pipelined execution
        runs several queries' stages concurrently, so a shared attr
        would let one query's failure handler read another's record."""
        return getattr(self._tls, "last_metrics", {})

    @_last_metrics.setter
    def _last_metrics(self, value: dict):
        self._tls.last_metrics = value

    @property
    def _pipelined(self) -> bool:
        """Pipelined mode: dispatch_lock held only for stage-1 enqueue
        (EngineConfig.pipeline_depth > 0); 0 restores the serialized
        whole-query hold."""
        return (self.config.pipeline_depth or 0) > 0

    def _pipeline_slot(self):
        """Bound one dispatch's enqueue->complete region (admission-
        accounted, docs/PERF_MODEL.md). No-op when serialized."""
        if not self._pipelined:
            return nullcontext()
        return self.admission.pipeline_slot(self.config.query_deadline_s)

    @contextmanager
    def _enqueue_lock(self, metrics: dict | None = None):
        """The enqueue stage's critical section (width-1 stage pool +
        dispatch_lock: the chip has one program queue). Pipelined mode:
        acquire dispatch_lock (bounded by the deadline budget so an
        abandoned watchdog thread blocked here eventually exits instead
        of leaking), time the wait into dispatch_lock_wait_ms, and
        stamp the record. Serialized mode: the caller already holds the
        lock across the whole query (QueryRunner.execute) — possibly on
        the watchdog's parent thread — so only the stage accounting
        runs."""
        with self.stages.stage("enqueue", metrics):
            if not self._pipelined:
                yield
                return
            deadline = self.config.query_deadline_s
            t0 = time.perf_counter()
            ok = self.dispatch_lock.acquire(timeout=deadline) \
                if deadline is not None else self.dispatch_lock.acquire()
            waited = (time.perf_counter() - t0) * 1000
            self._m_lock_wait.observe(waited)
            if metrics is not None:
                metrics["pipelined"] = True
                metrics["lock_wait_ms"] = round(
                    metrics.get("lock_wait_ms", 0.0) + waited, 3)
            if not ok:
                raise QueryDeadlineExceeded(
                    f"dispatch lock unavailable within the {deadline}s "
                    "deadline (a dispatch is wedged holding it)") from None
            try:
                yield
            finally:
                self.dispatch_lock.release()

    @contextmanager
    def _timed_dispatch_lock(self):
        """Serialized-mode whole-query lock hold, with the wait observed
        into the same dispatch_lock_wait_ms histogram the pipelined
        sections feed — so an A/B reads lock contention from one
        series."""
        t0 = time.perf_counter()
        with self.dispatch_lock:
            self._m_lock_wait.observe((time.perf_counter() - t0) * 1000)
            yield

    def _note_transfer(self, delta: int):
        with self._totals_lock:
            self._transfer_count += delta
            self._m_transfers.set(self._transfer_count)

    def _pin_inflight(self, out):
        """Account a just-enqueued dispatch's output buffers in the HBM
        ledger until stage 2 transfers them (shapes/dtypes are known
        without blocking on the async computation). Returns the pin key
        for _fetch_tree, or None on the numpy platform."""
        if self.config.platform == "cpu":
            return None
        import jax
        nbytes = sum(int(getattr(a, "nbytes", 0) or 0)
                     for a in jax.tree_util.tree_leaves(out))
        key = ("__inflight__", next(self._inflight_seq))
        self._hbm_ledger.pin_inflight(key, nbytes)
        return key

    def _fetch_tree(self, out, metrics: dict | None = None, pin=None):
        """Stage-2 device->host transfer: ONE jax.device_get round trip
        for the whole output tree (instead of one np.asarray per
        aggregate column — one tunnel RTT, not one per array). Unpins
        the in-flight ledger entry and maintains the transfer gauge;
        the host-transfer fault site fires here."""
        t0 = time.perf_counter()
        if self.config.platform != "cpu" and pin is None:
            pin = self._pin_inflight(out)
        self._note_transfer(1)
        try:
            with self.stages.stage("transfer", metrics):
                self._inject("host-transfer")
                if self.config.platform == "cpu":
                    host = {k: np.asarray(v) for k, v in out.items()} \
                        if isinstance(out, dict) else np.asarray(out)
                else:
                    import jax
                    host = jax.device_get(out)
        finally:
            self._note_transfer(-1)
            if pin is not None:
                self._hbm_ledger.unpin_inflight(pin)
        if metrics is not None:
            metrics["transfer_ms"] = round(
                metrics.get("transfer_ms", 0.0)
                + (time.perf_counter() - t0) * 1000, 3)
        return host

    def _fetch_trees(self, outs: list, metrics: dict | None = None,
                     pin=None):
        """Per-chip transfer nodes (docs/EXECUTION.md): each chip's
        output tree fetches on its own transfer-stage slot
        (stages.map_stage), so D transfers overlap one another AND the
        next query's enqueue instead of serializing behind one
        device_get. The numpy platform (or a single tree) degrades to
        the one-call fetch — no thread hop for nothing."""
        if self.config.platform == "cpu" or len(outs) <= 1:
            return self._fetch_tree(outs, metrics, pin)
        t0 = time.perf_counter()
        try:
            host = self.stages.map_stage(
                "transfer",
                [(lambda o=o: self._fetch_chip(o, metrics))
                 for o in outs])
        finally:
            if pin is not None:
                self._hbm_ledger.unpin_inflight(pin)
        if metrics is not None:
            metrics["transfer_ms"] = round(
                metrics.get("transfer_ms", 0.0)
                + (time.perf_counter() - t0) * 1000, 3)
            metrics["transfer_fanout"] = len(outs)
        return host

    def _fetch_chip(self, out, metrics: dict | None = None):
        """One chip's transfer node: its own transfer-stage slot + the
        host-transfer fault site. No pin bookkeeping — the caller's
        fan-out pin covers the whole set until every chip lands."""
        self._note_transfer(1)
        try:
            with self.stages.stage("transfer", metrics):
                self._inject("host-transfer")
                import jax
                return jax.device_get(out)
        finally:
            self._note_transfer(-1)

    def _metric_path(self, m: dict) -> str:
        """Dashboard path label: which execution flavor served this
        record (docs/OBSERVABILITY.md)."""
        if m.get("query_type") == "fallback" or m.get("fallback"):
            # degraded-but-correct serving while the breaker is open is
            # its own first-class path (docs/RESILIENCE.md)
            if m.get("fallback_breaker"):
                return "fallback_breaker"
            return "fallback"
        if m.get("cache_tier") == "full":
            # served wholly from the full-result cache: no dispatch ran,
            # so none of the execution-flavor labels apply (tier-1
            # partial hits keep their real dispatch path — a device pass
            # still computed the uncached segments)
            return "cache"
        if m.get("cube"):
            # served by the aggregate rewrite from a materialized
            # rollup cube (planner.cuberewrite; docs/CUBES.md)
            return "cube"
        if m.get("batch_dedup") or m.get("batch_legs", 0) > 1:
            return "batch"
        if m.get("sparse"):
            return "sparse"
        if m.get("pallas"):
            return "pallas"
        return "dense"

    def record(self, m: dict) -> dict:
        """The one gate every per-query observability record passes
        through: sanitize exception-carrying values to short strings,
        stamp the core metric keys (query_id from the active trace),
        fold the record into the incremental totals (Engine.counters
        stays exact after ring eviction) and the metrics registry, then
        append to the bounded history ring. Sanitization is IN PLACE so
        a QueryResult.metrics dict sharing this object stays the
        consistent view."""
        # the transient fingerprint rides under `_wl` (obs.workload):
        # popped before sanitization so the object never stringifies
        fp = m.pop("_wl", None)
        had_jit_key = "jit_cache_hit" in m
        for k in list(m):
            m[k] = sanitize_metric_value(m[k])
        if in_introspection():
            # sys.* introspection statements leave NO trace of
            # themselves: no history record, no metrics/SLO, no event,
            # no profiler observation — a query over sys.queries can
            # never recurse into its own stats (ISSUE 11)
            return m
        m.setdefault("query_id",
                     current_query_id() or self.tracer.new_query_id())
        m.setdefault("ts_ms", int(time.time() * 1000))
        # W3C trace context (ISSUE 17): a validated incoming
        # traceparent (Engine._sql_traced / sql_batch_ids / append)
        # propagates by contextvar and stamps every record the request
        # produced, so the future fleet router joins one distributed
        # trace across replicas
        tp = current_traceparent()
        if tp is not None:
            m.setdefault("traceparent", tp)
        if fp is not None:
            m.setdefault("template_id", fp.template_id)
        for k, v in CORE_METRIC_DEFAULTS:
            m.setdefault(k, v)
        qt, path = m["query_type"], self._metric_path(m)
        m["path"] = path
        if qt == "?":
            # runner NOTES (healer/reprobe outcomes), not queries: they
            # log + land in history but must not inflate queries_total,
            # the latency histogram, or the /status totals — a breaker
            # outage's healer loop would otherwise add one phantom 0 ms
            # "query" per cooldown for exactly the window an operator
            # is debugging
            self.events.emit(
                "device", query_id=m["query_id"],
                **{k: v for k, v in m.items()
                   if k.startswith("device_probe")})
            self.history.append(m)
            return m
        # workload attribution (obs.workload): every real query record —
        # device, fallback, cache hit, batch leg, dedup fan-out, nested
        # leg — folds into its template's rolling stats
        self.workload.observe(m, fp)
        with self._totals_lock:
            t = self._totals
            t["queries"] += 1
            t["rows_scanned"] += m["rows_scanned"] or 0
            t["segments_scanned"] += m["segments_scanned"] or 0
            t["segments_pruned"] += max(
                0, (m.get("segments_total", 0) or 0)
                - (m["segments_scanned"] or 0))
            t["cache_hits"] += 1 if m["cache_hit"] else 0
            t["total_ms"] += m["total_ms"] or 0.0
            self._by_query_type[qt] = self._by_query_type.get(qt, 0) + 1
        self._m_queries.inc(query_type=qt, path=path)
        self._m_latency.observe(m["total_ms"] or 0.0,
                                query_type=qt, path=path)
        self._m_rows.inc(m["rows_scanned"] or 0)
        self._m_segments.inc(m["segments_scanned"] or 0)
        if had_jit_key:
            self._m_compile.inc(
                result="hit" if m["jit_cache_hit"] else "miss")
        if m.get("retries"):
            self._m_retries.inc(m["retries"])
        if m.get("deadline_exceeded"):
            self._m_deadline.inc()
        if m.get("fallback_breaker"):
            self._m_degraded.inc()
        if "hbm_bytes" in m:
            self._m_hbm_bytes.set(m["hbm_bytes"])
        if "hbm_evictions" in m:
            self._m_hbm_evict.set_total(m["hbm_evictions"])
        if m.get("recompiles"):
            # cold-dispatch wall: the miss's first call is where tracing
            # + XLA compilation happen, so a recompile storm shows up as
            # compile_ms on the records that paid it (and in the
            # compile_ms_total counter). Approximate by construction —
            # it includes the first execution (docs/OBSERVABILITY.md).
            m.setdefault("compile_ms",
                         m.get("execute_ms") or m.get("scan_ms_shared")
                         or 0.0)
            self._m_compile_ms.inc(m["compile_ms"] or 0.0)
        if in_nested_execution():
            # an internal leg of a larger statement (grouping-sets
            # union, planner subquery, fallback derived table): it
            # keeps its history record and per-path metrics, but the
            # SLO observation and `query` event belong to the OUTER
            # statement — one served response, one event
            self.history.append(m)
            return m
        # SLO classification + the structured event log: record() is the
        # one chokepoint every per-query record passes through, so both
        # see every path (dense/sparse/fallback/batch leg/failed).
        # INTERIM device failures (failed/deadline records on a
        # non-fallback path) log as `query_error`, not `query`, and are
        # never SLO-counted here: the served outcome is accounted
        # exactly once elsewhere — by the compensating fallback record
        # when the engine falls back, or at the statement/raw-IR
        # boundary (Engine._observe_failure / execute_ir) when the
        # failure propagates to the client. Everything else is a served
        # response: one `query` event + one SLO observation.
        failed = bool(m.get("failed") or m.get("deadline_exceeded"))
        interim = failed and qt != "fallback"
        if interim:
            self.events.emit(
                "query_error", query_id=m["query_id"], query_type=qt,
                path=path, datasource=m["datasource"],
                total_ms=round(m["total_ms"] or 0.0, 3),
                **({"deadline_exceeded": True}
                   if m.get("deadline_exceeded") else {}))
        else:
            # the SLO sees the USER-VISIBLE latency: a compensating
            # fallback adds the wall its query already burned on the
            # failed device attempt (deadline wait, exhausted retries).
            # Client-shaped failures (unsupported SQL -> 400) are
            # event-logged but never burn the error budget.
            if not (failed and m.get("client_error")):
                self.slo.observe((m["total_ms"] or 0.0)
                                 + (m.get("device_attempt_ms") or 0.0),
                                 failed=failed)
            self.events.emit(
                "query", query_id=m["query_id"], query_type=qt,
                path=path, datasource=m["datasource"],
                total_ms=round(m["total_ms"] or 0.0, 3),
                cache_hit=bool(m["cache_hit"]),
                **({"cache_tier": m["cache_tier"]}
                   if m.get("cache_tier") else {}),
                **({"failed": True} if failed else {}))
        # regression sentinel (obs.sentinel): served responses only —
        # introspection returned above, nested legs returned above, and
        # the sentinel itself skips failed/deadline records, so the
        # baselines see exactly the user-visible latency stream
        self.sentinel.observe(m)
        self.history.append(m)
        return m

    def _note_compile(self, kind: str, metrics: dict | None = None):
        """Called at every jit-cache miss that builds a device
        executable: bumps the recompile counter (by dispatch flavor) and
        stamps the record so record() can attribute compile_ms — the
        signal that makes a recompile storm (cap churn, layout drift,
        config flapping) visible in /metrics instead of just 'queries
        got slow'."""
        self._m_recompile.inc(kind=kind)
        if metrics is not None:
            metrics["recompiles"] = metrics.get("recompiles", 0) + 1

    def device_bytes_by_table(self) -> dict:
        """Live device bytes per table: each dataset's resident column/
        null/derived stacks plus this table's cached const/seg-mask
        uploads (_arg_cache keys lead with the table name). Snapshots
        tolerate the abandoned-thread concurrency the caches allow."""
        out: dict = {}
        for name, ds in list(self._datasets.items()):
            out[name] = ds.resident_bytes()
        for key, val in list(self._arg_cache.items()):
            try:
                consts_dev, seg_arg = val
                n = sum(int(getattr(a, "nbytes", 0) or 0)
                        for a in consts_dev.values())
                n += int(getattr(seg_arg, "nbytes", 0) or 0)
            except Exception:  # noqa: BLE001 — accounting, not serving
                continue
            out[key[0]] = out.get(key[0], 0) + n
        return out

    def refresh_resource_gauges(self):
        """Point-in-time memory/cache gauges, refreshed at scrape time
        (GET /metrics) rather than per query — walking every resident
        buffer is O(buffers), too heavy for the per-record hot path."""
        by_table = self.device_bytes_by_table()
        for t, b in by_table.items():
            self._m_device_bytes.set(b, table=t)
        for key in list(self._m_device_bytes.series):
            if key[0] not in by_table:  # evicted table: zero, not stale
                self._m_device_bytes.set(0.0, table=key[0])
        self._m_cache_entries.set(len(self._jit_cache), cache="jit")
        self._m_cache_entries.set(len(self._plan_cache), cache="plan")
        self._m_cache_entries.set(len(self._arg_cache), cache="arg")
        self.result_cache._refresh_gauges()
        self._refresh_hbm_chip_gauges()

    def _refresh_hbm_chip_gauges(self):
        """Per-(chip, owner-class) HBM gauges (ISSUE 17): exact ledger
        attribution plus high-watermark and headroom-vs-budget — the
        /metrics face of sys.devices' per-chip columns."""
        m = self.metrics
        g_bytes = m.gauge(
            "hbm_chip_bytes",
            "HBM-resident bytes per chip and owner class (exact "
            "HbmLedger attribution; cache_pins via the ResultCache "
            "reporter).", ("chip", "owner"))
        g_hwm = m.gauge(
            "hbm_chip_high_watermark_bytes",
            "Ledger-managed per-chip HBM high-watermark.", ("chip",))
        g_head = m.gauge(
            "hbm_chip_headroom_bytes",
            "Per-chip share of hbm_budget_bytes minus ledger-managed "
            "resident bytes (absent without a budget).", ("chip",))
        ledger = self._hbm_ledger
        snap = ledger.breakdown()
        hwm = ledger.watermarks()
        D = ledger.num_chips
        per_chip_ledger = [0] * D
        seen = set()
        for (c, owner), b in snap.items():
            if 0 <= c < D and owner != "cache_pins":
                per_chip_ledger[c] += b
            g_bytes.set(b, chip=c, owner=owner)
            seen.add((str(c), owner))
        for key in list(g_bytes.series):
            if tuple(key) not in seen:  # released class: zero, not stale
                g_bytes.set(0.0, chip=key[0], owner=key[1])
        budget = ledger.budget
        for c in range(D):
            g_hwm.set(hwm["per_chip"][c] if c < len(hwm["per_chip"])
                      else 0, chip=c)
            if budget:
                g_head.set(budget / D - per_chip_ledger[c], chip=c)
        m.gauge("hbm_high_watermark_bytes",
                "Ledger-managed total HBM high-watermark.") \
            .set(hwm["total"])

    def device_snapshot(self) -> list:
        """Per-chip serving state behind sys.devices and
        GET /debug/devices: logical segments owned under the
        interleaved placement (segment i → chip i mod D), resident
        device bytes, multi-chip dispatch participation, and tier-1
        cache-shard entries (chip of an entry = its segment's owner).

        The per-chip HBM columns (ISSUE 17) come from the ledger's
        exact per-(chip, owner-class) attribution — table columns,
        cube tables, in-flight pins sum to the ledger's bytes_in_use;
        cache_pin_bytes rides alongside from the ResultCache reporter —
        plus ledger-managed high-watermark and headroom against the
        per-chip share of the HBM budget."""
        mesh = self.mesh
        if self.config.platform == "cpu":
            devs = [None]
        else:
            import jax
            devs = list(mesh.devices.flat) if mesh is not None \
                else jax.devices()[:1]
        D = len(devs)
        seg = [0] * D
        res_bytes = [0.0] * D
        rebased_cols = rebase_rows = 0
        for _name, ds in list(self._datasets.items()):
            n_seg = len(ds.table.segments)
            b = ds.resident_bytes()
            rebased_cols += ds.rebased_cols
            rebase_rows += ds.rebase_rows_uploaded
            if mesh is not None and D > 1:
                for c in range(D):
                    seg[c] += len(range(c, n_seg, D))
                    res_bytes[c] += b / D
            else:
                seg[0] += n_seg
                res_bytes[0] += b
        cache_by_chip = self.result_cache.shard_entries(D)
        ledger = self._hbm_ledger
        hbm = ledger.breakdown()
        hwm = ledger.watermarks()
        budget = ledger.budget
        chip_budget = (budget / D) if budget else None
        with self._totals_lock:
            disp = dict(self._chip_dispatches)
        rows = []
        for c, d in enumerate(devs):
            col_b = hbm.get((c, "table_columns"), 0)
            cube_b = hbm.get((c, "cube_tables"), 0)
            infl_b = hbm.get((c, "inflight"), 0)
            cache_b = hbm.get((c, "cache_pins"), 0)
            ledger_b = col_b + cube_b + infl_b
            chip_hwm = hwm["per_chip"][c] \
                if c < len(hwm["per_chip"]) else 0
            rows.append({
                "index": c,
                "device": str(d) if d is not None else "numpy-host",
                "platform": getattr(d, "platform", "numpy"),
                "process": getattr(d, "process_index", 0),
                "chips": D,
                "segments": seg[c],
                "resident_bytes": int(res_bytes[c]),
                "dispatches": disp.get(c, 0),
                "cache_shard_entries": cache_by_chip.get(c, 0),
                "rebased_cols": rebased_cols,
                "rebase_rows_uploaded": rebase_rows,
                "hbm_bytes": int(ledger_b),
                "table_column_bytes": int(col_b),
                "cube_table_bytes": int(cube_b),
                "inflight_bytes": int(infl_b),
                "cache_pin_bytes": int(cache_b),
                "hbm_high_watermark_bytes": int(chip_hwm),
                "hbm_headroom_bytes": (int(chip_budget - ledger_b)
                                       if chip_budget else None),
            })
        return rows

    def counters(self) -> dict:
        """Aggregate counters, maintained incrementally at record time —
        exact over the full query lifetime even after history-ring
        eviction (previously an O(history) re-sum per /status ping)."""
        with self._totals_lock:
            out = dict(self._totals)
            out["by_query_type"] = dict(self._by_query_type)
        return out

    @property
    def mesh(self):
        if self._mesh is None and self.config.platform != "cpu" and \
                (self._active_shards or 1) > 1:
            from tpu_olap.executor.sharding import make_mesh
            self._mesh = make_mesh(self._active_shards)
            # the ledger learns the chip count the moment the mesh
            # exists, so every subsequent add splits per chip exactly
            # (ISSUE 17 per-chip HBM attribution)
            self._hbm_ledger.set_num_chips(self._mesh.devices.size)
        return self._mesh

    def _dispatch(self, call, metrics: dict, table_name: str):
        """Run a device dispatch with retry-based recovery (SURVEY.md §6
        failure detection): on failure, purge the query's table-scoped
        device state (its buffers/programs could be poisoned by a device
        reset — other tables' warm caches are left alone) and re-run;
        with degrade_shards_on_retry, halve the mesh — the in-process
        analog of re-sharding the segment manifest after chip loss."""
        from tpu_olap.kernels.groupby import UnsupportedAggregation

        attempts = max(1, self.config.dispatch_retries + 1)
        for attempt in range(attempts):
            try:
                maybe_inject(self.config, "dispatch", attempt)
                self._attempt_local.value = attempt
                # while an on-demand jax.profiler capture is live
                # (obs.profile), annotate this dispatch with its
                # query_id so the captured XLA ops nest under the query;
                # otherwise a single module-flag probe
                with annotate_dispatch(current_query_id()):
                    out = call()
                # success resets the breaker's consecutive-failure count
                self.breaker.record_success()
                return out
            except UnsupportedAggregation:
                raise  # structural, not transient: straight to fallback
            except QueryError:
                # taxonomy failures originating inside a pipelined
                # dispatch (lock unavailable within the deadline, a
                # pipeline-slot shed): lock/queue starvation, not device
                # sickness — no retry (it would re-wait the same
                # resource), no breaker failure (the holder's own
                # watchdog accounts for a real wedge)
                raise
            except Exception as e:
                # record every retried error so poisoned-device vs
                # deterministic failures are distinguishable in history
                metrics.setdefault("retry_errors", []).append(
                    f"{type(e).__name__}: {e}")
                if attempt + 1 >= attempts:
                    # terminal (retries exhausted): one breaker failure —
                    # per-attempt errors the retry layer absorbed are not
                    # breaker events
                    self.breaker.record_failure()
                    raise
                metrics["retries"] = attempt + 1
                # in pipelined mode nothing outer holds dispatch_lock,
                # and the structural purges below must not race another
                # query's stage-1 enqueue; serialized mode keeps the
                # historical behavior (caller holds the lock — or, on a
                # deadline watchdog thread, the purge is lock-free and
                # tolerated, see _run_with_deadline)
                purge_lock = self.dispatch_lock if self._pipelined \
                    else nullcontext()
                with purge_lock:
                    if self.config.degrade_shards_on_retry and \
                            (self._active_shards or 1) > 1:
                        # mesh shrink invalidates every table's shardings
                        self.clear_cache()
                        self._mesh = None
                        self._active_shards = max(
                            1, self._active_shards // 2)
                        metrics["degraded_shards"] = self._active_shards
                    else:
                        self.clear_cache(table_name)

    # ------------------------------------------------------------------ API

    def set_batch_window(self, window_ms: float | None):
        """Enable/disable the shared-scan request coalescer at runtime
        (EngineConfig.batch_window_ms sets it at construction; the
        concurrency bench A/B toggles it). With a window, concurrent
        execute() callers of agg queries ride one fused dispatch
        (executor.batch.Coalescer); 0/None restores per-call dispatch."""
        from tpu_olap.executor.batch import Coalescer
        self.config.batch_window_ms = float(window_ms or 0.0)
        self._coalescer = Coalescer(self, float(window_ms) / 1000.0) \
            if window_ms else None

    def execute_batch(self, queries, table) -> list:
        """Execute N queries against one table as a shared-scan batch
        (executor.batch.run_batch): identical queries scan once,
        compatible dense-agg legs fuse into one device pass, everything
        else runs through the single-query path. Results come back in
        input order; the first failed leg's exception raises (callers
        that need per-leg failure isolation use _execute_batch_boxed)."""
        boxed = self._execute_batch_boxed(list(queries), table)
        for b in boxed:
            if isinstance(b, BaseException):
                raise b
        return boxed

    def _execute_batch_boxed(self, queries, table, query_ids=None) -> list:
        from tpu_olap.executor.batch import run_batch
        # one admission slot per batch submission: the fused dispatch is
        # one device occupancy however many logical queries ride it.
        # Pipelined mode: no outer lock — run_batch's device sections
        # take it per dispatch, so the leader no longer holds the lock
        # during per-leg finalize/assembly (docs/BATCH_EXECUTION.md).
        with self.admission.slot(self.config.query_deadline_s):
            if self._pipelined:
                return run_batch(self, queries, table, query_ids)
            with self._timed_dispatch_lock():
                return run_batch(self, queries, table, query_ids)

    def _next_batch_id(self) -> int:
        self._batch_seq += 1
        return self._batch_seq

    def compute_partials(self, query, table):
        """Run an aggregation query and return its RAW mergeable
        partials instead of finalized rows — the cube materializer's
        entry point (tpu_olap.cubes; docs/CUBES.md). Returns
        (plan, present flat group ids [G] int64, {name: [G, ...] compact
        partial arrays}, metrics). Rides the ordinary machinery: cached
        lowering, admission slot, breaker check, the dense partials or
        sparse dispatch path — so background cube builds queue behind
        (and shed with) foreground traffic instead of around it. No
        deadline wrapping: a rollup over the whole table is legitimate
        long-running background work."""
        from tpu_olap.kernels.groupby import UnsupportedAggregation

        with self.admission.slot(self.config.query_deadline_s):
            self.breaker.check()
            metrics = self._last_metrics = {}
            with _span("lower"):
                plan = self._lower_cached(query, table)
            if plan.kind != "agg":
                raise UnsupportedAggregation(
                    f"{query.query_type} has no mergeable partials")
            if plan.sparse:
                from tpu_olap.kernels.sparse_groupby import SENTINEL
                out, _ = self._dispatch(
                    lambda: self._run_sparse(plan, metrics), metrics,
                    table.name)
                keys = np.asarray(out["_keys"])
                pm = keys != SENTINEL
                present = keys[pm].astype(np.int64)
                compact = {k: np.asarray(v)[pm] for k, v in out.items()
                           if not k.startswith("_") or k == "_rows"
                           or k.startswith("_nn_")}
            else:
                partials = self._dispatch(
                    lambda: self._run_partials(plan, metrics), metrics,
                    table.name)
                rows = np.asarray(partials["_rows"])
                present = np.nonzero(rows > 0)[0].astype(np.int64)
                compact = {k: np.asarray(v)[present]
                           for k, v in partials.items()}
        return plan, present, compact, metrics

    def _guarded_dispatch(self, call, metrics: dict, table_name: str):
        """_dispatch under the same deadline/wedge guard as the
        single-query path: with query_deadline_s set, the fused batch
        dispatch runs on a fresh daemon thread and is abandoned on
        expiry (QueryDeadlineExceeded -> every leg's caller falls back),
        and a wedged device is reprobed before being trusted again. The
        batch executor's fused pass uses this so coalesced callers are
        never hung past the deadline the single-query path honors."""
        self.breaker.check()
        deadline = self.config.query_deadline_s
        if deadline is None:
            return self._dispatch(call, metrics, table_name)
        if self._wedged:
            self._reprobe_device(deadline)
        return self._join_abandoning(
            lambda: self._dispatch(call, metrics, table_name), deadline,
            {"datasource": table_name, "batch_dispatch": True,
             "query_type": "batch"},  # a real failure record, not a
            #                           runner note (record() routes
            #                           query_type "?" to the note path)
            name="tpu-olap-batch-dispatch")

    def execute(self, query, table) -> QueryResult:
        # full-result cache first: a hit needs no admission slot, no
        # dispatch lock, and no healthy device — it keeps serving
        # repeated queries through breaker-open windows and overload
        res = self._serve_full_cache(query, table)
        if res is not None:
            return res
        # breaker next: while open, fail in microseconds (the engine
        # routes fallback-capable queries to the interpreter) instead of
        # queueing doomed work onto the sick device
        self.breaker.check()
        if self._coalescer is not None and not in_nested_execution():
            # nested statements (subqueries, derived tables) dispatch
            # directly: the coalescer's leader would record their legs
            # OUTSIDE the nested context, double-counting them in the
            # SLO/event accounting (obs.trace.nested_execution)
            from tpu_olap.executor.batch import AGG_QUERY_TYPES
            if isinstance(query, AGG_QUERY_TYPES):
                # waits OUTSIDE dispatch_lock so concurrent callers can
                # coalesce; the batch leader takes the lock to dispatch
                # (and holds the one admission slot for the batch)
                with _span("coalesce") as sp:
                    res = self._coalescer.submit(query, table)
                    sp.set(batch_id=res.metrics.get("batch_id"),
                           batch_size=res.metrics.get("batch_size"))
                return res
        with self.admission.slot(self.config.query_deadline_s):
            if self._pipelined:
                # two-stage pipeline: _execute_guarded's dispatch
                # sections take dispatch_lock for stage-1 enqueue only;
                # transfer/finalize/assembly run lock-free, so query B's
                # device compute overlaps query A's RTT + assembly
                return self._execute_guarded(query, table)
            with self._timed_dispatch_lock():
                return self._execute_guarded(query, table)

    def _execute_guarded(self, query, table) -> QueryResult:
        """Breaker + deadline/wedge guard around _execute. Serialized
        mode: the caller holds dispatch_lock across this whole call.
        Pipelined mode: no outer lock — the per-dispatch enqueue
        sections (_enqueue_lock) take it."""
        self.breaker.check()
        deadline = self.config.query_deadline_s
        if deadline is not None:
            if self._wedged:
                # a previous dispatch timed out and was abandoned; before
                # trusting the device again, prove it answers a trivial
                # computation (the analog of the reference re-resolving a
                # live broker after task kill, SURVEY.md §3.5/§6). Still
                # dead -> fail fast so the engine keeps falling back
                # without stacking another full deadline wait.
                self._reprobe_device(deadline)
            return self._run_with_deadline(query, table, deadline)
        return self._execute(query, table)

    def _run_with_deadline(self, query, table, deadline: float):
        """Dispatch on a fresh daemon thread, abandoning it on expiry.

        An abandoned dispatch cannot be interrupted mid-XLA-computation;
        it finishes (or hangs) in the background while later queries run
        on new threads. Shared cache dicts tolerate that concurrency:
        individual dict ops are atomic, structural rebuilds snapshot
        first (clear_cache), and a stale entry written by an abandoned
        thread after a recovery purge costs at most one retried dispatch
        (the _dispatch retry purges again) — mirroring the reference,
        where a killed Spark task's Druid query keeps running server-side
        while the retry proceeds."""
        import threading
        abandoned = threading.Event()
        return self._join_abandoning(
            lambda: self._execute(query, table, abandoned), deadline,
            {"query_type": query.query_type, "datasource": table.name},
            on_timeout=abandoned.set)  # its history record is discarded

    def _join_abandoning(self, work, deadline: float, rec: dict,
                         on_timeout=None, name="tpu-olap-dispatch"):
        """Run `work` on a fresh daemon thread, abandoning it on expiry:
        mark the device wedged, record `rec` (stamped with the
        deadline), and raise QueryDeadlineExceeded. The one
        deadline/wedge join shared by the single-query path
        (_run_with_deadline) and the fused batch path
        (_guarded_dispatch); `on_timeout` runs before the wedge is set
        (e.g. flagging the abandoned thread to discard its record).
        The worker runs inside a contextvars snapshot so the caller's
        active trace (obs.trace) spans the cross-thread dispatch."""
        import contextvars
        import threading
        box: dict = {}
        ctx = contextvars.copy_context()

        def run():
            try:
                box["res"] = ctx.run(work)
            except BaseException as e:  # noqa: BLE001 - relayed to caller
                box["err"] = e

        t = threading.Thread(target=run, daemon=True, name=name)
        t.start()
        t.join(deadline)
        if t.is_alive():
            if on_timeout is not None:
                on_timeout()
            self._wedged = True
            self.breaker.record_failure("deadline")
            self.record({**rec, "deadline_exceeded": True,
                         "total_ms": deadline * 1000})
            raise QueryDeadlineExceeded(
                f"query exceeded deadline of {deadline}s") from None
        if "err" in box:
            raise box["err"]
        return box["res"]

    def _probe_device(self, timeout: float) -> bool:
        """Trivial device round-trip on a watchdog thread; True iff it
        completes within `timeout`. The one probe primitive shared by
        the post-wedge reprobe and the breaker's healer thread. The
        "reprobe" fault-injection site lives here, so probe failure is
        testable without a real sick device."""
        import threading
        ok = threading.Event()

        def work():
            try:
                maybe_inject(self.config, "reprobe", 0)
                if self.config.platform != "cpu":
                    import jax.numpy as jnp
                    jnp.ones((8,), jnp.int32).sum().block_until_ready()
                ok.set()
            except Exception:
                pass

        t = threading.Thread(target=work, daemon=True,
                             name="tpu-olap-probe")
        t.start()
        t.join(timeout)
        return ok.is_set()

    def _recover_after_probe(self, lock_timeout_s: float | None = None
                             ) -> bool:
        """Probe succeeded: clear the wedge and purge device-resident
        DATA (buffers a reset would poison) but keep compiled
        executables — recompiling every template would eat the next
        query's deadline; if an executable is also poisoned, the
        _dispatch retry layer purges the table's full cache anyway.
        Holds dispatch_lock itself (re-entrant for the serialized path,
        where the caller already owns it): in pipelined mode the purge
        must not race another query's stage-1 env build. Pipelined
        acquisition is BOUNDED: an abandoned stage-1 thread can strand
        the lock (it hung inside the jitted fire), and blocking here
        forever would hang every recovery path on the caller thread —
        returns False instead (callers treat it as probe failure, so
        the breaker keeps the engine on degraded serving until the
        stranded holder drains). Success also reclaims pipeline slots
        stranded by abandoned dispatch threads."""
        if self._pipelined:
            t = 5.0 if lock_timeout_s is None \
                else max(1.0, float(lock_timeout_s))
            if not self.dispatch_lock.acquire(timeout=t):
                self.record({"device_probe_lock_stranded": True})
                return False
        else:
            self.dispatch_lock.acquire()
        try:
            self._wedged = False
            for ds in list(self._datasets.values()):
                ds.evict()
            self._datasets.clear()
            self._arg_cache.clear()
        finally:
            self.dispatch_lock.release()
        # reclaim in-flight pipeline slots held by abandoned dispatch
        # threads: the device is verified healthy and its state purged,
        # so the stranded holders' slots must not zero device capacity.
        # Stage-pool slots stranded the same way (a worker abandoned
        # mid-transfer still occupies its stage) are reclaimed too.
        self.admission.reset_pipeline()
        self.stages.reclaim_stranded()
        self.record({"device_probe_recovered": True})
        return True

    def _reprobe_device(self, deadline: float):
        """Post-wedge health check: a trivial device round-trip under the
        deadline. Success clears the wedge and purges device caches (the
        hang may have been a device reset poisoning buffers); failure
        raises immediately."""
        if not self._probe_device(deadline):
            self.record({"device_probe_failed": True})
            self.breaker.record_failure("probe")
            raise QueryDeadlineExceeded(
                "device still unresponsive after a deadline-expired query")
        if not self._recover_after_probe(deadline):
            self.breaker.record_failure("probe")
            raise QueryDeadlineExceeded(
                "device answered the probe but the dispatch lock is "
                "stranded by an abandoned dispatch")

    def _healer_probe(self) -> bool:
        """The breaker healer's half-open probe (resilience.breaker):
        same round-trip; success also clears the wedge and purges
        device-resident data so the first post-recovery query starts
        from trustworthy buffers."""
        timeout = self.config.query_deadline_s or 10.0
        if not self._probe_device(timeout):
            self.record({"device_probe_failed": True})
            return False
        # _recover_after_probe takes dispatch_lock itself (bounded in
        # pipelined mode): a query that slipped through during half-open
        # may be mid-enqueue on these datasets. A stranded lock returns
        # False -> the breaker stays open and the healer retries next
        # cooldown, until the stranded holder drains.
        return self._recover_after_probe(timeout)

    def _execute(self, query, table, abandoned=None) -> QueryResult:
        t0 = time.perf_counter()
        self._last_metrics = {}
        try:
            if self.config.profile_dir is not None:
                import os
                import jax
                # monotonic, NOT len(history): the ring plateaus at
                # history_limit and directory names would collide
                with self._totals_lock:
                    self._profile_seq += 1
                    seq = self._profile_seq
                trace_dir = os.path.join(
                    self.config.profile_dir,
                    f"q{seq:05d}_{query.query_type}")
                with jax.profiler.trace(trace_dir):
                    res = self._execute_inner(query, table)
                res.metrics["profile_trace"] = trace_dir
            else:
                res = self._execute_inner(query, table)
        except Exception:
            # failed queries still leave an observability record (with
            # retry_errors) so poisoned-device vs deterministic failures
            # are diagnosable from history
            m = self._last_metrics
            m["failed"] = True
            m["query_type"] = query.query_type
            m["datasource"] = table.name
            m["total_ms"] = (time.perf_counter() - t0) * 1000
            m["_wl"] = self.fingerprint(query, table.name)
            if abandoned is None or not abandoned.is_set():
                self.record(m)
            raise
        res.metrics["total_ms"] = (time.perf_counter() - t0) * 1000
        res.metrics["query_type"] = query.query_type
        res.metrics["datasource"] = table.name
        fp = self.fingerprint(query, table.name)
        res.metrics["_wl"] = fp
        if abandoned is None or not abandoned.is_set():
            self.record(res.metrics)
            self._store_full_cache(query, table, res, fp)
        return res

    def fingerprint(self, query, table_name: str):
        """Workload template of a device-path query spec (obs.workload)
        — None (profiling off / exotic spec) just skips attribution,
        never fails the query."""
        if not self.workload.enabled:
            return None
        try:
            return fingerprint_ir(query, table_name)
        except Exception:  # noqa: BLE001 — profiling must never raise
            return None

    # --------------------------------------------- semantic result cache

    _CACHEABLE_QUERY_TYPES = ("timeseries", "groupBy", "topN")

    def _serve_full_cache(self, query, table) -> QueryResult | None:
        """Tier-2 lookup (docs/CACHING.md): a hit returns a fresh
        QueryResult sharing the cached rows, with a real observability
        record (cache_hit=True, cache_tier="full", path="cache",
        rows_scanned=0). None = miss/bypass, caller executes."""
        rc = self.result_cache
        if not rc.full_enabled or in_introspection() \
                or getattr(query, "query_type", None) \
                not in self._CACHEABLE_QUERY_TYPES \
                or getattr(table, "generation", None) is None:
            return None
        t0 = time.perf_counter()
        with _span("result-cache") as sp:
            hit = rc.get_full(query, table)
            sp.set(tier="full", hit=hit is not None)
        if hit is None:
            return None
        rows, druid, meta = hit
        m = {"query_type": query.query_type, "datasource": table.name,
             "cache_hit": True, "cache_tier": "full",
             "rows_scanned": 0, "segments_scanned": 0,
             "segments_total": meta.get("segments_total", 0),
             "rows_returned": len(rows),
             # the fingerprint is memoized on the entry's meta at store
             # time: warm serves must not pay the normalization walk
             "_wl": meta.get("_wl_fp"),
             "total_ms": (time.perf_counter() - t0) * 1000}
        res = QueryResult(query, rows, druid, m)
        # the entry's live meta dict rides along so the SQL layer can
        # memoize its rendered DataFrame on the entry
        # (Engine._frame_from): frame construction is over half the
        # warm-serve wall for small results
        res._cache_meta = meta
        self.record(m)
        return res

    def _store_full_cache(self, query, table, res: QueryResult,
                          fp=None):
        """Populate tier 2 from a successfully served result (single
        path, batch singles, and fused batch legs all funnel here).
        `fp` is the query's workload fingerprint, memoized on the entry
        meta so warm serves re-stamp it without re-normalizing."""
        rc = self.result_cache
        if not rc.full_enabled or in_introspection() \
                or getattr(query, "query_type", None) \
                not in self._CACHEABLE_QUERY_TYPES \
                or getattr(table, "generation", None) is None \
                or res.metrics.get("failed"):
            return
        rc.put_full(query, table, res.rows, res.druid, {
            "segments_total": res.metrics.get("segments_total", 0),
            "_wl_fp": fp})

    def _lower_cached(self, query, table):
        """Memoized lower(): re-lowering an unchanged query template
        costs ~5-10 ms of pure Python (dim/filter/granularity compile +
        domain restriction) per execution — a large slice of the warm
        per-query budget. Keyed on the full query JSON plus the
        lowering-relevant config knobs; a table identity check (not just
        the name) invalidates on re-registration."""
        with self.stages.stage("plan", self._last_metrics):
            return self._lower_cached_inner(query, table)

    def _lower_cached_inner(self, query, table):
        import json as _json

        c = self.config
        # exactly the config knobs lower() reads (beyond what the query
        # JSON itself captures); anything else would either mask a live
        # config change or needlessly fragment the cache
        key = (table.name,
               _json.dumps(query.to_json(), sort_keys=True, default=str),
               c.use_pallas, c.platform, c.enable_x64,
               str(c.long_dtype), str(c.double_dtype),
               c.num_shards,
               c.dense_group_budget, c.numeric_dim_label_budget,
               c.theta_k_cap, c.sparse_theta_k_cap, c.pallas_group_cap,
               c.pallas_group_cap_factorized,
               c.dense_sketch_state_budget,
               c.pallas_rows_per_block, c.pallas_k_per_block,
               c.pallas_auto_flop_budget)
        # _cache_lock, not dispatch_lock: pipelined execution lowers
        # outside the dispatch critical section, concurrently across
        # threads. lower() itself runs unlocked (pure per-query work);
        # a duplicate concurrent lowering is last-write-wins.
        with self._cache_lock:
            hit = self._plan_cache.get(key)
            if hit is not None and hit[0] is table:
                _cache_lru_hit(self._plan_cache, key)
                return hit[1]
        plan = lower(query, table, self.config)
        with self._cache_lock:
            if len(self._plan_cache) > 512:
                _evict_one(self._plan_cache)
                self._m_cache_evict.inc(cache="plan")
            self._plan_cache[key] = (table, plan)
        return plan

    def _execute_inner(self, query, table) -> QueryResult:
        # one in-flight stage graph per query: pipeline_depth counts
        # graphs engine-wide (stages.StageScheduler.graph wraps the
        # admission controller's pipeline slot — re-entrant, so the
        # per-dispatch _pipeline_slot holds inside become no-ops here)
        with self.stages.graph(self.config.query_deadline_s):
            return self._execute_graph(query, table)

    def _execute_graph(self, query, table) -> QueryResult:
        if isinstance(query, TimeBoundaryQuerySpec):
            res = self._run_time_boundary(query, table)
        elif isinstance(query, SegmentMetadataQuerySpec):
            res = self._run_segment_metadata(query, table)
        elif isinstance(query, SearchQuerySpec):
            res = self._run_search(query, table)
        elif isinstance(query, (ScanQuerySpec, SelectQuerySpec)):
            res = self._run_scan(query, table)
        elif isinstance(query, (TimeseriesQuerySpec, GroupByQuerySpec,
                                TopNQuerySpec)):
            res = self._run_agg(query, table)
        else:
            raise TypeError(f"unknown query type {type(query).__name__}")
        return res

    def clear_cache(self, table_name: str | None = None):
        """Evict device-resident columns (+ compiled programs if full clear).
        The analog of `CLEAR DRUID CACHE` (SURVEY.md §4.5)."""
        self._m_cache_clears.inc(scope="table" if table_name else "full")
        purged = self.result_cache.clear(table_name)
        self.events.emit(
            "cache_clear", table=table_name or "*",
            jit_entries=len(self._jit_cache),
            plan_entries=len(self._plan_cache),
            arg_entries=len(self._arg_cache),
            result_entries=purged["full"],
            segment_entries=purged["segment"])
        # list() snapshots: an abandoned deadline thread may insert
        # concurrently (see _run_with_deadline) — never iterate live
        # dicts. Plan-cache mutation additionally takes _cache_lock:
        # pipelined lowering reads it outside dispatch_lock.
        if table_name is None:
            for ds in list(self._datasets.values()):
                ds.evict()
            self._datasets.clear()
            self._jit_cache.clear()
            self._arg_cache.clear()
            self._cap_hints.clear()
            with self._cache_lock:
                self._plan_cache.clear()
        elif table_name in self._datasets:
            self._datasets.pop(table_name).evict()
            self._jit_cache = OrderedDict(
                (k, v) for k, v in list(self._jit_cache.items())
                if k[0] != table_name)
            self._arg_cache = OrderedDict(
                (k, v) for k, v in list(self._arg_cache.items())
                if k[0] != table_name)
            self._cap_hints = {k: v for k, v in list(self._cap_hints.items())
                               if k[0] != table_name}
            # plans pin their TableSegments (host column arrays): drop
            # them too or a re-registration keeps the old data alive
            with self._cache_lock:
                self._plan_cache = OrderedDict(
                    (k, v) for k, v in list(self._plan_cache.items())
                    if k[0] != table_name)

    # ------------------------------------------------------------- dispatch

    def _dataset(self, table) -> DeviceDataset:
        key = table.name
        ds = self._datasets.get(key)
        if ds is None or ds.table is not table:
            prev = ds
            # the superseded snapshot rides in as `prev`: resident
            # columns REBASE device-side (only delta-touched segments'
            # rows upload — docs/INGEST.md "incremental re-place");
            # evict AFTER construction (the new dataset snapshots the
            # old stacks first), releasing the stale ledger accounting —
            # in-flight queries that captured its env keep their
            # buffers alive by reference
            ds = DeviceDataset(table, self.config.platform, self.mesh,
                               self._hbm_ledger, prev=prev)
            if prev is not None:
                prev.evict()
            self._datasets[key] = ds
        return ds

    def _prepare(self, plan: PhysicalPlan, metrics: dict):
        """Dataset env + validity/segment masks + scan metrics — common
        preamble of every dispatch flavor."""
        with _span("prepare") as sp:
            out = self._prepare_inner(plan, metrics)
            sp.set(rows_scanned=metrics.get("rows_scanned"),
                   segments_scanned=metrics.get("segments_scanned"),
                   num_shards=self._active_shards or 1)
        return out

    def _prepare_inner(self, plan: PhysicalPlan, metrics: dict):
        table = plan.table
        ds = self._dataset(table)
        env = ds.env(plan.columns, plan.null_cols)
        bp = plan.bucket_plan
        bp_token = bp.cache_token if bp is not None else None
        tokens = [dp.cache_token for dp in plan.dim_plans
                  if dp.cache_token is not None] \
            + ([bp_token] if bp_token else []) \
            + [t for t, _, _ in plan.filter_streams]
        if tokens:
            # pin this query's whole working set (columns + every derived
            # stream it needs) so one derived add cannot evict another
            pinned = frozenset(
                [(table.name, "col", c) for c in plan.columns]
                + [(table.name, "null", c) for c in plan.null_cols]
                + [(table.name, "derived", t) for t in tokens])
            for dp in plan.dim_plans:
                if dp.cache_token is not None:
                    env["cols"][dp.derived_name] = ds.derived(
                        dp.cache_token,
                        lambda dp=dp: self._build_derived(ds, plan, dp),
                        pinned)
            if bp_token:
                env["cols"][bp.derived_name] = ds.derived(
                    bp_token,
                    lambda: self._build_bucket_stream(ds, plan), pinned)
            for token, src, cname in plan.filter_streams:
                env["cols"]["\0d:" + token] = ds.derived(
                    token,
                    lambda src=src, cname=cname:
                        self._build_filter_stream(ds, plan, src, cname),
                    pinned)
        valid = ds.valid()
        seg_mask = ds.segment_mask(plan.pruned_ids if not plan.empty else [])
        metrics["segments_total"] = len(table.segments)
        metrics["segments_scanned"] = int(seg_mask.sum())
        metrics["rows_scanned"] = int(sum(
            table.segments[i].meta.n_valid for i in plan.pruned_ids)) \
            if not plan.empty else 0
        if self._hbm_ledger.budget is not None:
            metrics["hbm_bytes"] = self._hbm_ledger.bytes_in_use
            metrics["hbm_evictions"] = self._hbm_ledger.evictions
        return env, valid, seg_mask

    def _build_derived(self, ds, plan: PhysicalPlan, dp):
        """Materialize one precomputed dim id stream [S, R] int32 on the
        dataset's platform from its resident source column (dictionary
        codes for remap, __time for timeformat)."""
        src = dp.source_col if dp.source_col is not None else TIME_COLUMN
        col = ds.col(src)
        consts = plan.pool.consts
        if self.config.platform == "cpu":
            shape = np.asarray(col).shape
            flat = {"cols": {src: np.asarray(col).reshape(-1)},
                    "nulls": {}}
            return np.asarray(dp.ids(flat, consts, np),
                              np.int32).reshape(shape)
        import jax
        import jax.numpy as jnp

        def f(c):
            # no reshape: ids() is elementwise/shape-polymorphic, and
            # keeping [S, R] lets the output inherit the input's segment
            # sharding under a mesh without a gather
            env2 = {"cols": {src: c}, "nulls": {}}
            cdev = {k: jnp.asarray(v) for k, v in consts.items()}
            return dp.ids(env2, cdev, jnp).astype(jnp.int32)

        return jax.jit(f)(col)

    def _build_filter_stream(self, ds, plan: PhysicalPlan, src, cname):
        """Materialize a filter-owned derived id stream [S, R] int32:
        the columnComparison cross-dictionary translation gather, paid
        once per (table, column pair), not per dispatch (a 1-D gather
        over every row is ~60 ms on a v5e through XLA)."""
        col = ds.col(src)
        xmap = plan.pool.consts[cname]
        if self.config.platform == "cpu":
            return np.asarray(xmap)[np.asarray(col)].astype(np.int32)
        import jax
        import jax.numpy as jnp
        return jax.jit(
            lambda c: jnp.asarray(xmap)[c].astype(jnp.int32))(col)

    def _build_bucket_stream(self, ds, plan: PhysicalPlan):
        """Resident bucket stream [S, R] int32: the per-row pass
        (searchsorted for calendar boundary sets, floor-divide for
        uniform periods) is paid once per (table, token), not per
        dispatch — and uniform tokens are table-anchored, so a sliding
        query window re-uses the same stream (BucketPlan.build_stream /
        ids_from_cached)."""
        col = ds.col(TIME_COLUMN)
        consts = plan.pool.consts
        if self.config.platform == "cpu":
            return np.asarray(
                plan.bucket_plan.build_stream(np.asarray(col), consts),
                np.int32)
        import jax
        import jax.numpy as jnp

        def f(c):
            cdev = {k: jnp.asarray(v) for k, v in consts.items()}
            return plan.bucket_plan.build_stream(c, cdev).astype(jnp.int32)

        return jax.jit(f)(col)

    def _segment_window(self, plan: PhysicalPlan, n_segments: int):
        """(lo, W) covering every pruned segment, or None. Interval
        pruning is mask-only inside the kernel (pruned segments multiply
        by zero but their bytes are still read); with time-partitioned
        ingest the pruned set is contiguous on the segment axis, so the
        dispatch dynamic-slices the [S, R] working set down to a pow2-
        quantized window and reads ONLY those bytes — this is what turns
        SURVEY.md §3.5 P4 pruning into real HBM savings. Safe for the
        Pallas kernel too: its grid is shape-driven and its row block
        rb divides block_rows by eligibility (pallas_reduce.eligible),
        so a window of W blocks is always an exact rb multiple >= rb.
        Mask-kind plans window too: _run_partials re-embeds the
        windowed mask into the full segment stack, so the scan/select/
        search assemblers keep indexing by global segment id. Skipped
        when a mesh shards the segment axis (per-shard windows would
        need divisibility) and when the window saves <25%."""
        if self.mesh is not None or plan.empty:
            return None
        ids = plan.pruned_ids
        if not ids:
            return None
        lo, hi = min(ids), max(ids) + 1
        W = _next_pow2(hi - lo)
        if 4 * W >= 3 * n_segments:
            return None
        return min(lo, n_segments - W), W

    @staticmethod
    def _window_kernel(kernel, W: int):
        """Wrap a partials kernel so the jitted program dynamic-slices
        every [S, ...] input to [W, ...] at `lo` before compute. One
        compile per (template, W); `lo` is traced, so interval changes
        that keep the window size re-use the executable."""
        import jax

        def fn(env, valid, seg_mask, consts, lo):
            def sl(a):
                return jax.lax.dynamic_slice_in_dim(a, lo, W, axis=0)
            wenv = {"cols": {c: sl(a) for c, a in env["cols"].items()},
                    "nulls": {c: sl(a) for c, a in env["nulls"].items()}}
            return kernel(wenv, sl(valid), sl(seg_mask), consts)
        return fn

    @staticmethod
    def _window_numpy(env, valid, seg_mask, win):
        lo, W = win
        sl = slice(lo, lo + W)
        wenv = {"cols": {c: a[sl] for c, a in env["cols"].items()},
                "nulls": {c: a[sl] for c, a in env["nulls"].items()}}
        return wenv, valid[sl], seg_mask[sl]

    @staticmethod
    def _embed_windowed_mask(out: dict, plan: PhysicalPlan, win,
                             n_seg_full: int) -> dict:
        """Windowed mask back into the full segment stack: every
        consumer (scan/select/search assembly) indexes rows by
        GLOBAL segment id; segments outside the window are pruned,
        so their rows are legitimately all-False."""
        if win is None or plan.kind != "mask":
            return out
        lo, W = win
        w = np.asarray(out["mask"]).reshape(W, -1)
        full = np.zeros((n_seg_full, w.shape[1]), bool)
        full[lo:lo + W] = w
        out["mask"] = full.reshape(-1)
        return out

    def _run_partials(self, plan: PhysicalPlan, metrics: dict) -> dict:
        if self.config.platform == "cpu":
            return self._run_partials_numpy(plan, metrics)
        return self._run_partials_jax(plan, metrics)

    def _run_partials_numpy(self, plan: PhysicalPlan,
                            metrics: dict) -> dict:
        with self._pipeline_slot():
            # stage 1: only the env build (dataset/ledger mutation)
            # needs the lock — the numpy kernel reads its own slices
            with self._enqueue_lock(metrics):
                env, valid, seg_mask = self._prepare(plan, metrics)
            win = self._segment_window(plan, len(seg_mask))
            if win is not None:
                metrics["segments_window"] = win[1]
            n_seg_full = len(seg_mask)
            t0 = time.perf_counter()
            with _span("dispatch", jit_cache_hit=False, num_shards=1):
                if win is not None:
                    env, valid, seg_mask = self._window_numpy(
                        env, np.asarray(valid), seg_mask, win)
                out = plan.kernel(env, np.asarray(valid), seg_mask,
                                  plan.pool.consts)
            metrics["execute_ms"] = (time.perf_counter() - t0) * 1000
            metrics["jit_cache_hit"] = False
            metrics["num_shards"] = 1
            out = {k: np.asarray(v) for k, v in out.items()}
        return self._embed_windowed_mask(out, plan, win, n_seg_full)

    def _run_partials_jax(self, plan: PhysicalPlan,
                          metrics: dict) -> dict:
        import jax
        if self.mesh is not None:
            return self._run_partials_mesh(plan, metrics)
        with self._pipeline_slot():
            # stage 1 (enqueue, under dispatch_lock): env build, jit
            # cache, per-call args, and the async dispatch itself —
            # the lock releases once the device has the work and the
            # result buffers are pinned in the HbmLedger
            with self._enqueue_lock(metrics):
                env, valid, seg_mask = self._prepare(plan, metrics)
                win = self._segment_window(plan, len(seg_mask))
                if win is not None:
                    metrics["segments_window"] = win[1]
                n_seg_full = len(seg_mask)
                key = plan.fingerprint() \
                    + ((win[1],) if win else ())
                jitted = self._jit_cache.get(key)
                hit = jitted is not None
                if hit:
                    _cache_lru_hit(self._jit_cache, key)
                else:
                    if win is not None:
                        jitted = jax.jit(
                            self._window_kernel(plan.kernel, win[1]))
                    else:
                        jitted = jax.jit(plan.kernel)
                    self._jit_cache[key] = jitted
                    self._note_compile("partials", metrics)
                t0 = time.perf_counter()
                with _span("dispatch", jit_cache_hit=hit, num_shards=1):
                    consts_dev, seg_arg = self._args_for(plan, seg_mask,
                                                         None)
                    out = jitted(env, valid, seg_arg, consts_dev,
                                 win[0]) if win is not None \
                        else jitted(env, valid, seg_arg, consts_dev)
                pin = self._pin_inflight(out)
            # stage 2 (complete, lock-free): one device_get round trip
            # of the whole output tree
            with _span("host-transfer"):
                out = self._fetch_tree(out, metrics, pin)
        metrics["execute_ms"] = (time.perf_counter() - t0) * 1000
        metrics["jit_cache_hit"] = hit
        metrics["num_shards"] = 1
        return self._embed_windowed_mask(out, plan, win, n_seg_full)

    def _note_chip_dispatch(self, chips):
        """Per-chip dispatch-participation counters behind sys.devices /
        GET /debug/devices (dispatch occupancy)."""
        with self._totals_lock:
            for c in chips:
                self._chip_dispatches[c] = \
                    self._chip_dispatches.get(c, 0) + 1

    def _run_partials_mesh(self, plan: PhysicalPlan,
                           metrics: dict) -> dict:
        """Sharded dispatch on `jax.jit` + `NamedSharding` (executor.
        sharding; docs/TPU_NOTES.md "sharded serving"): columns sit
        placed per chip (interleaved segment→chip assignment), the
        per-chip LOCAL window slices each chip's pruned working set,
        and the merge strategy follows planner.cost — "historicals"
        brings per-chip unfinalized partials back sharded and merges
        them at the host broker with the segment-cache algebra;
        "broker" hands the whole program to GSPMD (replicated outputs,
        compiler-inserted psum/all-gather). Mask-kind plans (scan/
        select/search) fetch sharded row masks and inverse-permute the
        placed segment axis back to logical order."""
        from tpu_olap.executor import sharding as sh
        from tpu_olap.planner import cost as cost_mod

        mesh = self.mesh
        D = mesh.devices.size
        with self._pipeline_slot():
            with self._enqueue_lock(metrics):
                env, valid, seg_mask = self._prepare(plan, metrics)
                S = len(seg_mask)
                per_chip = S // D
                is_agg = plan.kind == "agg" and plan.key_fn is not None
                strategy = "mask"
                win = None
                if is_agg:
                    with _span("cost-decision") as sp:
                        decision = cost_mod.decide(plan, self.config, D)
                        strategy = decision.strategy
                        # chip-extended keys must fit int32; a dense
                        # table that large defers to the partitioner
                        if strategy == "historicals" and \
                                D * plan.total_groups >= (1 << 31):
                            strategy = "broker"
                        # DCN mesh: remote chips' shards are not host-
                        # addressable, so the broker merge cannot see
                        # them — GSPMD's replicated merge is the only
                        # correct spelling across processes
                        if strategy == "historicals" and \
                                sh.is_multihost(mesh):
                            strategy = "broker"
                        sp.set(strategy=strategy)
                    metrics["cost"] = decision.to_json()
                    win = sh.local_window(plan.pruned_ids, D, per_chip) \
                        if not plan.empty else None
                    if win is not None:
                        metrics["segments_window"] = win[1] * D
                        metrics["segments_window_per_chip"] = win[1]
                key = plan.fingerprint() + ("mesh", D, strategy,
                                            win[1] if win else 0)
                jitted = self._jit_cache.get(key)
                hit = jitted is not None
                if hit:
                    _cache_lru_hit(self._jit_cache, key)
                else:
                    if is_agg:
                        jitted = sh.mesh_agg_kernel(plan, mesh, per_chip,
                                                    strategy, win)
                    else:
                        jitted = sh.mesh_mask_kernel(plan, mesh)
                    self._jit_cache[key] = jitted
                    self._note_compile("mesh", metrics)
                t0 = time.perf_counter()
                with _span("dispatch", jit_cache_hit=hit, num_shards=D,
                           strategy=strategy):
                    consts_dev, seg_arg = self._args_for(plan, seg_mask,
                                                         mesh)
                    out = jitted(env, valid, seg_arg, consts_dev,
                                 win[0]) if win is not None \
                        else jitted(env, valid, seg_arg, consts_dev)
                pin = self._pin_inflight(out)
                self._note_chip_dispatch(range(D))
            # stage 2, lock-free: ONE device_get pulls every chip's
            # shard concurrently (per-device transfers overlap)
            with _span("host-transfer"):
                out = self._fetch_tree(out, metrics, pin)
        metrics["execute_ms"] = (time.perf_counter() - t0) * 1000
        metrics["jit_cache_hit"] = hit
        metrics["num_shards"] = D
        if is_agg and strategy == "historicals":
            with _span("broker-merge", num_shards=D):
                out = sh.broker_merge(out, plan.agg_plans, D)
            metrics["merge"] = "broker"
        elif is_agg:
            metrics["merge"] = "gspmd"
        if plan.kind == "mask":
            # placed -> logical segment order: the scan/select/search
            # assemblers index rows by GLOBAL logical segment id
            ds = self._datasets[plan.table.name]
            m = np.asarray(out["mask"]).reshape(S, -1)
            out = dict(out)
            out["mask"] = m[ds.to_place].reshape(-1)
        return out

    def _args_for(self, plan: PhysicalPlan, seg_mask: np.ndarray, mesh):
        """Device copies of the per-call inputs (const pool + segment
        mask), content-cached: a repeated query template with the same
        literals re-uses resident buffers instead of paying per-call
        host->device uploads (the BI-dashboard hot case)."""
        import jax

        consts = plan.pool.consts
        ckey = (plan.table.name,
                tuple((k, v.shape, str(v.dtype), v.tobytes())
                      for k, v in consts.items()),
                seg_mask.tobytes(),
                mesh.devices.size if mesh else 0)
        hit = self._arg_cache.get(ckey)
        if hit is not None:
            _cache_lru_hit(self._arg_cache, ckey)
            return hit
        if mesh is not None:
            from tpu_olap.executor.sharding import replicate_put, shard_put
            consts_dev = {k: replicate_put(v, mesh)
                          for k, v in consts.items()}
            seg_arg = shard_put(seg_mask, mesh)
        else:
            consts_dev = jax.device_put(consts)
            seg_arg = jax.device_put(seg_mask)
        if len(self._arg_cache) > 256:
            _evict_one(self._arg_cache)
            self._m_cache_evict.inc(cache="arg")
        self._arg_cache[ckey] = (consts_dev, seg_arg)
        return consts_dev, seg_arg

    def _packed_jit(self, plan: PhysicalPlan, cap: int, win=None):
        """(jitted packed program, layout) for a given group cap.
        Single-device only: packed buffers hold FINALIZED values, which
        cannot ride the mesh broker merge (partials must stay
        unfinalized to merge) — mesh dispatch takes _run_partials_mesh
        instead. `win` appends the segment-window slice."""
        import jax

        layout = make_layout(plan, self.config, cap)
        key = plan.fingerprint() + ("packed", layout.cap) \
            + ((win[1],) if win else ())
        jitted = self._jit_cache.get(key)
        if jitted is not None:
            _cache_lru_hit(self._jit_cache, key)
        if jitted is None:
            packed = build_packer(plan.kernel, plan, layout)
            if win is not None:
                packed = self._window_kernel(packed, win[1])
            jitted = jax.jit(packed)
            self._jit_cache[key] = jitted
            return jitted, layout, False
        return jitted, layout, True

    def _run_packed(self, plan: PhysicalPlan, metrics: dict):
        """Single-fetch path: jit(kernel + device finalize/compact/pack),
        one buffer back. The buffer cap adapts per template: first run
        uses the config cap, later runs size from the last observed group
        count (pow2 buckets keep the jit-template space small), with a
        sized retry if a run overflows its hint. Returns None only when
        the true group count exceeds the config cap (caller re-runs the
        unpacked per-array path)."""
        with self._pipeline_slot():
            with self._enqueue_lock(metrics):
                env, valid, seg_mask = self._prepare(plan, metrics)
                win = self._segment_window(plan, len(seg_mask))
                if win is not None:
                    metrics["segments_window"] = win[1]
            cap_limit = min(self.config.result_group_cap,
                            plan.total_groups)
            base_key = plan.fingerprint() + (1,)
            hint = self._cap_hints.get(base_key)
            cap = cap_limit if hint is None else \
                min(cap_limit, max(64, _next_pow2(2 * hint)))

            t0 = time.perf_counter()
            with _span("dispatch", packed=True) as dsp:
                while True:
                    # stage 1 per attempt: jit/arg caches + the async
                    # dispatch under the lock; a cap-overflow retry
                    # re-enters it (rare — the hint adapts)
                    with self._enqueue_lock(metrics):
                        consts_dev, seg_arg = self._args_for(
                            plan, seg_mask, None)
                        jitted, layout, hit = self._packed_jit(
                            plan, cap, win)
                        if not hit:
                            self._note_compile("packed", metrics)
                        buf = jitted(env, valid, seg_arg, consts_dev,
                                     win[0]) if win is not None else \
                            jitted(env, valid, seg_arg, consts_dev)
                        pin = self._pin_inflight(buf)
                    # stage 2: the packed path's transfer is already a
                    # single buffer — one round trip
                    with _span("host-transfer"):
                        buf = self._fetch_tree(buf, metrics, pin)
                        count, idx, compact = unpack(buf, layout)
                    if count <= layout.cap:
                        break
                    if count > cap_limit:
                        metrics["result_groups"] = count
                        metrics["jit_cache_hit"] = hit
                        dsp.set(jit_cache_hit=hit, overflow=True)
                        return None  # cap exceeded: unpacked re-run
                    cap = min(cap_limit, _next_pow2(count))
                dsp.set(jit_cache_hit=hit, num_shards=1)
        self._cap_hints[base_key] = count
        metrics["execute_ms"] = (time.perf_counter() - t0) * 1000
        metrics["jit_cache_hit"] = hit
        metrics["num_shards"] = 1
        metrics["result_groups"] = count
        metrics["result_cap"] = layout.cap
        metrics["packed"] = True
        return idx, compact, layout

    def _run_sparse(self, plan: PhysicalPlan, metrics: dict):
        """Sort-based sparse group-by dispatch with adaptive compact-table
        cap (kernels.sparse_groupby). Multi-chip merge strategy per
        EngineConfig.sparse_merge: "exchange" hash-partitions compacted
        entries to key-owner chips over all_to_all (capacity scales
        D × budget); "gather" all-gathers every chip's table. Returns
        (partials dict, count); exchange partial arrays are [D·cap_owner]
        slot tables (SENTINEL-keyed empties), others are [cap] compacts."""
        with _span("dispatch", sparse=True) as sp:
            out = self._run_sparse_inner(plan, metrics)
            sp.set(jit_cache_hit=metrics.get("jit_cache_hit"),
                   result_groups=metrics.get("result_groups"),
                   num_shards=metrics.get("num_shards"))
        return out

    def _run_sparse_inner(self, plan: PhysicalPlan, metrics: dict):
        with self._pipeline_slot():
            return self._run_sparse_staged(plan, metrics)

    def _run_sparse_staged(self, plan: PhysicalPlan, metrics: dict):
        """Adaptive-cap sparse dispatch, two-staged: each attempt's jit
        build + async dispatch runs under the enqueue lock; the _count
        probe (a one-element sync) and the final whole-tree fetch run
        lock-free, so an overflow retry re-enters stage 1."""
        from tpu_olap.kernels.groupby import UnsupportedAggregation

        with self._enqueue_lock(metrics):
            env, valid, seg_mask = self._prepare(plan, metrics)
        win = self._segment_window(plan, len(seg_mask))
        if win is not None:
            metrics["segments_window"] = win[1]
        mesh = self.mesh
        n_shards = mesh.devices.size if mesh else 1
        base_key = plan.fingerprint() + ("sparse", n_shards)
        use_exchange = mesh is not None and n_shards > 1 and \
            self.config.sparse_merge == "exchange"
        budget = self.config.sparse_group_budget
        # exchange scales global capacity with the mesh; local compaction
        # and per-owner tables each stay within the per-chip budget
        cap_limit = min(budget * (n_shards if use_exchange else 1),
                        plan.total_groups)
        local_limit = min(budget, plan.total_groups)
        hint = self._cap_hints.get(base_key)
        cap = min(local_limit, self.config.sparse_group_cap) \
            if hint is None else min(local_limit, max(64, _next_pow2(2 * hint)))

        t0 = time.perf_counter()
        hit = False
        if self.config.platform == "cpu":
            if win is not None:
                env, valid, seg_mask = self._window_numpy(
                    env, np.asarray(valid), seg_mask, win)
            while True:
                out = plan.make_sparse_kernel(cap)(
                    env, np.asarray(valid), seg_mask, plan.pool.consts)
                count = int(out["_count"])
                if count <= cap:
                    break
                if count > cap_limit:
                    raise UnsupportedAggregation(
                        f"{count} present groups exceed sparse budget "
                        f"{cap_limit}")
                cap = min(cap_limit, _next_pow2(count))
            out = {k: np.asarray(v) for k, v in out.items()}
            metrics["num_shards"] = 1
        elif mesh is None:
            import jax
            # pin the enqueued output tree like every other device path
            # (the caller blocks on the _count probe while the buffers
            # occupy HBM); a retry/raise unpins the superseded pin
            pin = None
            try:
                while True:
                    with self._enqueue_lock(metrics):
                        consts_dev, seg_arg = self._args_for(
                            plan, seg_mask, None)
                        key = base_key + (cap,) \
                            + ((win[1],) if win else ())
                        jitted = self._jit_cache.get(key)
                        hit = jitted is not None
                        if hit:
                            _cache_lru_hit(self._jit_cache, key)
                        else:
                            kern = plan.make_sparse_kernel(cap)
                            if win is not None:
                                jitted = jax.jit(
                                    self._window_kernel(kern, win[1]))
                            else:
                                jitted = jax.jit(kern)
                            self._jit_cache[key] = jitted
                            self._note_compile("sparse", metrics)
                        out = jitted(env, valid, seg_arg, consts_dev,
                                     win[0]) if win is not None else \
                            jitted(env, valid, seg_arg, consts_dev)
                        prev, pin = pin, self._pin_inflight(out)
                    if prev is not None:
                        self._hbm_ledger.unpin_inflight(prev)
                    count = int(out["_count"])
                    if count <= cap:
                        break
                    if count > cap_limit:
                        raise UnsupportedAggregation(
                            f"{count} present groups exceed sparse "
                            f"budget {cap_limit}")
                    cap = min(cap_limit, _next_pow2(count))
                out = self._fetch_tree(out, metrics, pin)
                pin = None  # consumed (fetch unpins)
            finally:
                if pin is not None:
                    self._hbm_ledger.unpin_inflight(pin)
            metrics["num_shards"] = 1
        else:
            # multi-chip sparse: per-chip FAN-OUT dispatch + broker
            # merge (docs/TPU_NOTES.md "sharded serving"). Each chip's
            # resident shard runs the local sort/compact kernel as its
            # own single-device program (the shards are addressable
            # arrays — no re-upload, and the D async dispatches
            # enqueue before any is fetched, so per-chip compute and
            # transfers overlap); the host broker re-merges the D
            # compact tables with kernels.sparse_groupby.merge_sparse.
            # sparse_merge="exchange" lets the broker table hold
            # D x sparse_group_budget present groups (capacity scales
            # with chip count); "gather" keeps the legacy global-budget
            # contract (every group must fit one chip's table).
            import jax

            from tpu_olap.executor import sharding as sh
            from tpu_olap.kernels.sparse_groupby import merge_sparse
            if sh.is_multihost(mesh):
                # DCN mesh: remote chips' compact tables are not host-
                # addressable, so neither the fan-out nor the broker
                # merge can run — hand the WHOLE sparse program to
                # GSPMD with replicated outputs (global-budget
                # capacity, like the gather contract)
                pin = None
                try:
                    while True:
                        with self._enqueue_lock(metrics):
                            consts_dev, seg_arg = self._args_for(
                                plan, seg_mask, mesh)
                            key = base_key + ("gspmd", cap)
                            jitted = self._jit_cache.get(key)
                            hit = jitted is not None
                            if hit:
                                _cache_lru_hit(self._jit_cache, key)
                            else:
                                jitted = jax.jit(
                                    plan.make_sparse_kernel(cap),
                                    out_shardings=sh.replicated_spec(
                                        mesh))
                                self._jit_cache[key] = jitted
                                self._note_compile("sparse", metrics)
                            out = jitted(env, valid, seg_arg,
                                         consts_dev)
                            prev, pin = pin, self._pin_inflight(out)
                        if prev is not None:
                            self._hbm_ledger.unpin_inflight(prev)
                        count = int(out["_count"])
                        if count <= cap:
                            break
                        if count > local_limit:
                            raise UnsupportedAggregation(
                                f"{count} present groups exceed sparse "
                                f"budget {local_limit}")
                        cap = min(local_limit, _next_pow2(count))
                    out = self._fetch_tree(out, metrics, pin)
                    pin = None
                finally:
                    if pin is not None:
                        self._hbm_ledger.unpin_inflight(pin)
                metrics["num_shards"] = n_shards
                self._cap_hints[base_key] = count
                metrics["execute_ms"] = \
                    (time.perf_counter() - t0) * 1000
                metrics["jit_cache_hit"] = hit
                metrics["sparse"] = True
                metrics["result_groups"] = count
                metrics["result_cap"] = cap
                return out, count
            lhint = self._cap_hints.get(base_key + ("local",))
            if lhint is not None:
                cap = min(local_limit, max(64, _next_pow2(2 * lhint)))
            pin = None
            try:
                while True:
                    with self._enqueue_lock(metrics):
                        consts_dev, seg_arg = self._args_for(
                            plan, seg_mask, mesh)
                        key = base_key + ("fanout", cap)
                        jitted = self._jit_cache.get(key)
                        hit = jitted is not None
                        if hit:
                            _cache_lru_hit(self._jit_cache, key)
                        else:
                            jitted = jax.jit(plan.make_sparse_kernel(cap))
                            self._jit_cache[key] = jitted
                            self._note_compile("sparse", metrics)
                        chips = sh.chip_args(env, valid, seg_arg,
                                             consts_dev, mesh)
                        outs = [jitted(e, v, m, c)
                                for (e, v, m, c) in chips]
                        prev, pin = pin, self._pin_inflight(outs)
                        self._note_chip_dispatch(range(n_shards))
                    if prev is not None:
                        self._hbm_ledger.unpin_inflight(prev)
                    counts = [int(o["_count"]) for o in outs]
                    local_max = max(counts)
                    if local_max <= cap:
                        break
                    if local_max > local_limit:
                        raise UnsupportedAggregation(
                            f"{local_max} per-chip present groups "
                            f"exceed sparse budget {local_limit}")
                    cap = min(local_limit, _next_pow2(local_max))
                parts = self._fetch_trees(outs, metrics, pin)
                pin = None  # consumed (fetch unpins)
            finally:
                if pin is not None:
                    self._hbm_ledger.unpin_inflight(pin)
            with _span("broker-merge", num_shards=n_shards):
                cap_global = min(cap_limit, max(64, _next_pow2(
                    max(1, sum(counts)))))
                out = merge_sparse(parts, plan.agg_plans, cap_global,
                                   np)
                count = int(out["_count"])
                if count > cap_limit:
                    raise UnsupportedAggregation(
                        f"{count} present groups exceed sparse budget "
                        f"{cap_limit}")
            self._cap_hints[base_key + ("local",)] = local_max
            metrics["num_shards"] = n_shards
            if use_exchange:
                metrics["sparse_merge"] = "exchange"
                metrics["result_cap_owner"] = cap_global
        self._cap_hints[base_key] = count
        metrics["execute_ms"] = (time.perf_counter() - t0) * 1000
        metrics["jit_cache_hit"] = hit
        metrics["sparse"] = True
        metrics["result_groups"] = count
        metrics["result_cap"] = cap
        return out, count

    # ------------------------------------------------------------ agg paths

    def _run_agg(self, query, table) -> QueryResult:
        metrics = self._last_metrics = {}
        t0 = time.perf_counter()
        with _span("lower"):
            plan = self._lower_cached(query, table)
        metrics["lower_ms"] = (time.perf_counter() - t0) * 1000
        if getattr(plan, "pallas_reason", "off") is None:
            metrics["pallas"] = True  # fused Pallas reduce kernel active
        specs = agg_specs_by_name(query.aggregations)
        # theta set-op post-aggs consume RAW sketch tables host-side;
        # the packed path finalizes sketches on device, so those queries
        # ride the unpacked per-array fetch instead
        keep_raw = theta_raw_fields(query.post_aggregations)

        if plan.sparse:
            from tpu_olap.kernels.sparse_groupby import SENTINEL
            out, count = self._dispatch(
                lambda: self._run_sparse(plan, metrics), metrics, table.name)
            t0 = time.perf_counter()
            with self.stages.stage("finalize", metrics):
                with _span("finalize"):
                    arrays = finalize_aggs(out, plan.agg_plans, specs,
                                           keep_raw)
                with _span("post-agg"):
                    eval_post_aggs(arrays, query.post_aggregations)
            names = self._out_names(query)
            # present groups by sentinel mask: compact tables fill the
            # tail with SENTINEL; exchange slot tables interleave empties
            keys = np.asarray(out["_keys"])
            pm = keys != SENTINEL
            present = keys[pm].astype(np.int64)
            sub = {n: np.asarray(arrays[n])[pm] for n in names}
            with self.stages.stage("assemble", metrics), \
                    _span("assemble"):
                res = self._emit_groupby(query, plan, present, sub)
            res.metrics = metrics
            metrics["assemble_ms"] = (time.perf_counter() - t0) * 1000
            return res

        if self.result_cache.seg_enabled:
            arrays = self._run_agg_segcached(query, plan, metrics, specs,
                                             keep_raw, table)
            if arrays is not None:
                t0 = time.perf_counter()
                with self.stages.stage("finalize", metrics), \
                        _span("post-agg"):
                    eval_post_aggs(arrays, query.post_aggregations)
                with self.stages.stage("assemble", metrics), \
                        _span("assemble"):
                    res = self._assemble_agg(query, plan, arrays)
                res.metrics = metrics
                metrics["assemble_ms"] = (time.perf_counter() - t0) * 1000
                return res

        packed = None
        use_packed = self.config.platform != "cpu" and not keep_raw \
            and self.mesh is None  # mesh: unfinalized partials only
        #                            (the broker merge needs them)
        if use_packed:
            packed = self._dispatch(
                lambda: self._run_packed(plan, metrics), metrics,
                table.name)
        if packed is not None:
            idx, compact, layout = packed
            for p in plan.agg_plans:
                if p.kind == "hll" and \
                        getattr(specs.get(p.name), "round", True):
                    compact[p.name] = np.round(compact[p.name])
            t0 = time.perf_counter()
            with self.stages.stage("finalize", metrics), \
                    _span("finalize"):
                arrays = densify(idx, compact, layout, plan.agg_plans)
        else:
            if use_packed:
                metrics["packed"] = False  # cap overflow: unpacked re-run
            partials = self._dispatch(
                lambda: self._run_partials(plan, metrics), metrics,
                table.name)
            t0 = time.perf_counter()
            with self.stages.stage("finalize", metrics), \
                    _span("finalize"):
                arrays = finalize_aggs(partials, plan.agg_plans, specs,
                                       keep_raw)
        with self.stages.stage("finalize", metrics), _span("post-agg"):
            eval_post_aggs(arrays, query.post_aggregations)
        with self.stages.stage("assemble", metrics), _span("assemble"):
            res = self._assemble_agg(query, plan, arrays)
        res.metrics = metrics
        metrics["assemble_ms"] = (time.perf_counter() - t0) * 1000
        return res

    def _run_agg_segcached(self, query, plan, metrics, specs, keep_raw,
                           table):
        """Tier-1 per-segment partial-aggregate path (docs/CACHING.md):
        serve every cached, fully-interval-covered segment from the
        cache, recompute the rest in ONE device pass that keys the
        group space by (segment, group) so each computed segment's
        partials come back separately (cacheable), then fold everything
        on the host via the aggregators' merge semantics and finalize.
        Returns finalized arrays, or None when the plan bypasses the
        tier (the caller falls through to the packed/partials paths).
        The bypass reason and per-segment decision are stamped on the
        record and the `segment-cache` span (EXPLAIN ANALYZE shows
        them)."""
        import functools as _ft

        from tpu_olap.kernels.groupby import merge_partials

        rc = self.result_cache
        if in_introspection():
            # sys.* introspection must not consult, populate, or tick
            # counters on EITHER cache tier (same rule as
            # _serve_full_cache): observing the system cannot change
            # sys.caches / cache_pinned / result_cache_* metrics
            return None
        reason = rc.tier1_bypass_reason(plan, self.mesh)
        if reason is not None:
            metrics["segment_cache"] = f"bypass: {reason}"
            rc.count_bypass()
            return None
        intervals = query.intervals or (ETERNITY,)
        tkey = rc.template_key(query, table)
        floor = max(0, int(self.config.segment_cache_min_rows))
        covered, always_compute = [], []
        for sid in plan.pruned_ids:
            sm = table.segments[sid].meta
            # only segments ENTIRELY inside one query interval have
            # interval-independent partials; straddlers (and sub-floor
            # segments, where entry overhead beats the recompute win)
            # are computed fresh every time and never stored. DELTA
            # blocks (real-time appends, docs/INGEST.md) also always
            # recompute: their contents change block-in-place across
            # append snapshots, so caching them would churn the budget
            # for entries one append away from unreachable.
            if sm.n_valid >= floor and table.segment_sealed(sid) \
                    and any(
                    iv.start <= sm.time_min and iv.end > sm.time_max
                    for iv in intervals):
                covered.append(sid)
            else:
                always_compute.append(sid)
        with _span("segment-cache") as sp:
            hits = rc.get_segments(tkey, table, plan, covered)
            to_compute = sorted(
                [s for s in covered if s not in hits] + always_compute)
            sp.set(segments_cached=len(hits),
                   segments_computed=len(to_compute),
                   segments_uncovered=len(always_compute))
            if to_compute:
                fresh = self._dispatch(
                    lambda: self._run_seg_partials(plan, metrics,
                                                   to_compute),
                    metrics, table.name)
                storable = set(covered)
                for sid in to_compute:
                    if sid in storable:
                        rc.put_segment(tkey, table, plan, sid, fresh[sid])
            else:
                fresh = {}
                metrics["segments_total"] = len(table.segments)
                metrics["segments_scanned"] = 0
                metrics["rows_scanned"] = 0
                metrics["num_shards"] = 1
        metrics["cache_hit"] = bool(hits)
        if hits:
            metrics["cache_tier"] = "segment"
        metrics["segments_cached"] = len(hits)
        metrics["segments_computed"] = len(to_compute)
        parts = [hits[s] if s in hits else fresh[s]
                 for s in sorted(set(covered) | set(always_compute))]
        merged = _ft.reduce(
            lambda a, b: merge_partials(a, b, plan.agg_plans), parts)
        with _span("finalize"):
            return finalize_aggs(merged, plan.agg_plans, specs, keep_raw)

    def _run_seg_partials(self, plan: PhysicalPlan, metrics: dict,
                          compute_ids: list) -> dict:
        """One pass computing PER-SEGMENT partials for `compute_ids`:
        the plan's key_fn front half runs over a window covering the
        segments, the group key is extended to (local segment, group),
        and one group_reduce over W*K groups yields every segment's own
        mergeable partials dict ({segment id: partials}). One compiled
        program per (template, W) serves ANY to-compute subset — the
        subset rides in through the seg-mask runtime argument."""
        with self._pipeline_slot():
            with self._enqueue_lock(metrics):
                env, valid, _ = self._prepare(plan, metrics)
                table = plan.table
                ds = self._dataset(table)
                seg_mask = ds.segment_mask(compute_ids)
            # honest scan accounting: only the computed segments are read
            metrics["segments_scanned"] = len(compute_ids)
            metrics["rows_scanned"] = int(sum(
                table.segments[i].meta.n_valid for i in compute_ids))
            S = len(seg_mask)
            K = plan.total_groups
            lo, hi = min(compute_ids), max(compute_ids) + 1
            t0 = time.perf_counter()
            if self.config.platform == "cpu":
                W = hi - lo
                with _span("dispatch", jit_cache_hit=False, segcache=True,
                           num_shards=1):
                    wenv, wvalid, wmask = self._window_numpy(
                        env, np.asarray(valid), seg_mask, (lo, W))
                    fenv, mask, key = plan.key_fn(wenv, wvalid, wmask,
                                                  plan.pool.consts)
                    from tpu_olap.kernels.groupby import group_reduce
                    r = mask.size // W
                    key2 = (np.repeat(np.arange(W, dtype=np.int64), r)
                            * K + key.astype(np.int64))
                    out = group_reduce(key2, mask, fenv, plan.agg_plans,
                                       W * K, plan.pool.consts)
                out = {k: np.asarray(v) for k, v in out.items()}
                metrics["jit_cache_hit"] = False
                metrics["num_shards"] = 1
            elif self.mesh is not None:
                # mesh variant (docs/CACHING.md "cache shards"): the
                # per-chip LOCAL window slices each chip's placed
                # segments, the key extends by placed window position,
                # and the [D·W·K] table comes back SHARDED per chip —
                # each (chip, segment) partials entry is cut out on the
                # host and cached per segment; serving folds cached +
                # fresh entries at the broker via merge_partials
                from tpu_olap.executor import sharding as sh
                mesh = self.mesh
                D = mesh.devices.size
                per_chip = S // D
                lo_l = min(i // D for i in compute_ids)
                hi_l = max(i // D for i in compute_ids) + 1
                W = min(_next_pow2(hi_l - lo_l), per_chip)
                lo_l = min(lo_l, per_chip - W)
                with self._enqueue_lock(metrics):
                    jkey = plan.fingerprint() + ("segcache-mesh", D, W)
                    jitted = self._jit_cache.get(jkey)
                    hit = jitted is not None
                    if hit:
                        _cache_lru_hit(self._jit_cache, jkey)
                    else:
                        jitted = sh.mesh_seg_partials_kernel(
                            plan, mesh, per_chip, W, K)
                        self._jit_cache[jkey] = jitted
                        self._note_compile("segcache", metrics)
                    with _span("dispatch", jit_cache_hit=hit,
                               segcache=True, num_shards=D):
                        consts_dev, seg_arg = self._args_for(
                            plan, seg_mask, mesh)
                        out = jitted(env, valid, seg_arg, consts_dev,
                                     lo_l)
                    pin = self._pin_inflight(out)
                    self._note_chip_dispatch(range(D))
                with _span("host-transfer"):
                    out = self._fetch_tree(out, metrics, pin)
                metrics["jit_cache_hit"] = hit
                metrics["num_shards"] = D
                metrics["execute_ms"] = (time.perf_counter() - t0) * 1000
                shaped = {name: np.asarray(a).reshape(
                    (D, W, K) + np.asarray(a).shape[1:])
                    for name, a in out.items()}
                # logical sid -> (chip sid mod D, local sid // D)
                return {sid: {name: a[sid % D, sid // D - lo_l]
                              for name, a in shaped.items()}
                        for sid in compute_ids}
            else:
                import jax
                W = min(_next_pow2(hi - lo), S)
                lo = min(lo, S - W)
                with self._enqueue_lock(metrics):
                    jkey = plan.fingerprint() + ("segcache", W)
                    jitted = self._jit_cache.get(jkey)
                    hit = jitted is not None
                    if hit:
                        _cache_lru_hit(self._jit_cache, jkey)
                    else:
                        jitted = jax.jit(
                            self._seg_partials_kernel(plan, W, K))
                        self._jit_cache[jkey] = jitted
                        self._note_compile("segcache", metrics)
                    with _span("dispatch", jit_cache_hit=hit,
                               segcache=True, num_shards=1):
                        consts_dev, seg_arg = self._args_for(
                            plan, seg_mask, None)
                        out = jitted(env, valid, seg_arg, consts_dev,
                                     lo)
                    pin = self._pin_inflight(out)
                with _span("host-transfer"):
                    out = self._fetch_tree(out, metrics, pin)
                metrics["jit_cache_hit"] = hit
                metrics["num_shards"] = 1
            metrics["execute_ms"] = (time.perf_counter() - t0) * 1000
        shaped = {name: arr.reshape((W, K) + arr.shape[1:])
                  for name, arr in out.items()}
        return {sid: {name: arr[sid - lo]
                      for name, arr in shaped.items()}
                for sid in compute_ids}

    @staticmethod
    def _seg_partials_kernel(plan: PhysicalPlan, W: int, K: int):
        """fn(env, valid, seg_mask, consts, lo): window-slice every
        [S, ...] input to [W, ...], run the plan's filter/dim front
        half, extend the key by the local segment index, reduce over
        W*K groups. `lo` is traced, so a sliding to-compute window of
        the same width re-uses the executable. The int32 key is safe:
        tier1_bypass_reason rejects plans whose segment-extended key
        space reaches 2^31."""
        import jax
        import jax.numpy as jnp

        from tpu_olap.kernels.groupby import group_reduce

        def fn(env, valid, seg_mask, consts, lo):
            def sl(a):
                return jax.lax.dynamic_slice_in_dim(a, lo, W, axis=0)
            wenv = {"cols": {c: sl(a) for c, a in env["cols"].items()},
                    "nulls": {c: sl(a) for c, a in env["nulls"].items()}}
            fenv, mask, key = plan.key_fn(wenv, sl(valid), sl(seg_mask),
                                          consts)
            r = mask.shape[0] // W
            seg_local = jnp.repeat(jnp.arange(W, dtype=jnp.int32), r)
            key2 = seg_local * jnp.int32(K) + key.astype(jnp.int32)
            return group_reduce(key2, mask, fenv, plan.agg_plans, W * K,
                                consts)
        return fn

    def _assemble_agg(self, query, plan, arrays) -> QueryResult:
        """Final-arrays -> QueryResult by query type. Shared tail of the
        single-query agg path and the batch executor's per-leg finish."""
        if isinstance(query, TimeseriesQuerySpec):
            return self._assemble_timeseries(query, plan, arrays)
        if isinstance(query, GroupByQuerySpec):
            return self._assemble_groupby(query, plan, arrays)
        return self._assemble_topn(query, plan, arrays)

    def _out_names(self, query):
        names = [a.name for a in query.aggregations]
        names += [p.name for p in query.post_aggregations]
        return names

    def _bucket_emit_ids(self, query, plan):
        """Bucket ids to emit, honoring intervals and descending order."""
        if plan.empty:
            return []
        intervals = query.intervals or (ETERNITY,)
        starts = plan.bucket_plan.starts
        ids = [b for b in range(plan.bucket_plan.n_buckets)
               if any(iv.overlaps(int(starts[b]),
                                  int(starts[b + 1])
                                  if b + 1 < len(starts) else plan.t_max + 1)
                      for iv in intervals)]
        return ids

    def _assemble_timeseries(self, query, plan, arrays) -> QueryResult:
        names = self._out_names(query)
        rows, druid = [], []
        skip_empty = bool(dict(query.context).get(
            "skipEmptyBuckets", self.config.skip_empty_buckets))
        bucket_ids = self._bucket_emit_ids(query, plan)
        if query.descending:
            bucket_ids = bucket_ids[::-1]
        present = arrays["_rows"] > 0
        for b in bucket_ids:
            if skip_empty and not present[b]:
                continue
            vals = {n: render_value(arrays[n][b]) for n in names}
            ts = iso(plan.bucket_plan.starts[b])
            rows.append({"timestamp": ts, **vals})
            druid.append({"timestamp": ts, "result": vals})
        return QueryResult(query, rows, druid)

    def _decode_groups(self, plan, idx: np.ndarray):
        """Present flat group ids -> (bucket ids, {dim name -> values})."""
        sizes = plan.sizes
        rem = idx
        radix_vals = []
        for s in sizes[::-1]:
            radix_vals.append(rem % s)
            rem = rem // s
        radix_vals = radix_vals[::-1]  # bucket first, then dims in order
        buckets = radix_vals[0]
        dim_vals = {}
        for dp, ids in zip(plan.dim_plans, radix_vals[1:]):
            dim_vals[dp.name] = dp.labels[ids]
        return buckets, dim_vals

    def _assemble_groupby(self, query, plan, arrays) -> QueryResult:
        names = self._out_names(query)
        present = np.nonzero(arrays["_rows"] > 0)[0]
        sub = {n: np.asarray(arrays[n])[present] for n in names}
        return self._emit_groupby(query, plan, present, sub)

    def _emit_groupby(self, query, plan, present, sub) -> QueryResult:
        """present: flat group ids (any int width); sub: compact per-group
        final values. Shared tail of the dense and sparse paths."""
        names = self._out_names(query)
        buckets, dim_vals = self._decode_groups(plan, present)

        if query.having is not None:
            hmask = eval_having(query.having, sub, dim_vals)
            present = present[hmask]
            buckets = buckets[hmask]
            dim_vals = {k: v[hmask] for k, v in dim_vals.items()}
            sub = {k: v[hmask] for k, v in sub.items()}

        order = np.arange(len(present))
        ls = query.limit_spec
        if ls is not None and ls.columns:
            keys = []
            for c in ls.columns[::-1]:
                if c.dimension == "timestamp":
                    k = np.asarray(buckets, np.float64)
                elif c.dimension in dim_vals:
                    v = dim_vals[c.dimension]
                    k = np.asarray([("" if x is None else str(x)) for x in v])
                    if c.dimension_order == "numeric":
                        k = np.asarray([float(x) if x else -np.inf for x in k])
                else:
                    k = np.asarray(sub[c.dimension], np.float64)
                if c.direction == "descending":
                    k = _invert_sort_key(k)
                keys.append(k)
            order = np.lexsort(keys)
        if ls is not None:
            lo = ls.offset
            hi = None if ls.limit is None else lo + ls.limit
            order = order[lo:hi]

        rows, druid = [], []
        starts = plan.bucket_plan.starts
        for i in order:
            ts = iso(starts[buckets[i]])
            ev = {dp.name: render_value(dim_vals[dp.name][i])
                  for dp in plan.dim_plans}
            ev.update({n: render_value(sub[n][i]) for n in names})
            rows.append({"timestamp": ts, **ev})
            druid.append({"version": "v1", "timestamp": ts, "event": ev})
        return QueryResult(query, rows, druid)

    def _assemble_topn(self, query, plan, arrays) -> QueryResult:
        names = self._out_names(query)
        n_b = plan.sizes[0]
        d_size = plan.sizes[1]
        metric = np.asarray(arrays[query.metric], np.float64) \
            .reshape(n_b, d_size)
        present = (arrays["_rows"] > 0).reshape(n_b, d_size)
        dp = plan.dim_plans[0]
        rows, druid = [], []
        for b in self._bucket_emit_ids(query, plan):
            m = np.where(present[b],
                         -metric[b] if query.inverted else metric[b],
                         -np.inf)
            order = np.argsort(-m, kind="stable")
            order = order[m[order] > -np.inf][:query.threshold]
            ts = iso(plan.bucket_plan.starts[b])
            result = []
            for g in order:
                flat = b * d_size + g
                ev = {dp.name: render_value(dp.labels[g])}
                ev.update({n: render_value(np.asarray(arrays[n])[flat])
                           for n in names})
                result.append(ev)
                rows.append({"timestamp": ts, **ev})
            druid.append({"timestamp": ts, "result": result})
        return QueryResult(query, rows, druid)

    # ----------------------------------------------------------- scan paths

    def _run_scan(self, query, table) -> QueryResult:
        metrics = self._last_metrics = {}
        t0 = time.perf_counter()
        with _span("lower"):
            plan = self._lower_cached(query, table)
        metrics["lower_ms"] = (time.perf_counter() - t0) * 1000
        partials = self._dispatch(
            lambda: self._run_partials(plan, metrics), metrics, table.name)
        mask = partials["mask"].reshape(-1, table.block_rows)
        mask = mask[:len(table.segments)]  # drop shard-padding segments

        t0 = time.perf_counter()
        if isinstance(query, ScanQuerySpec):
            cols = list(query.columns) if query.columns else \
                [c for c in table.schema]
            offset, limit = query.offset, query.limit
            descending = query.order == "descending"
        else:
            dims = list(query.dimensions) or [
                c for c, t in table.schema.items() if t.is_dim]
            mets = list(query.metrics) or [
                c for c, t in table.schema.items()
                if not t.is_dim and c != TIME_COLUMN]
            cols = [TIME_COLUMN] + dims + mets
            offset, limit = query.paging_offset, query.page_size
            descending = query.descending

        with self.stages.stage("assemble", metrics), _span("assemble"):
            events = self._gather_rows(table, mask, cols, offset, limit,
                                       descending)
        metrics["assemble_ms"] = (time.perf_counter() - t0) * 1000

        if isinstance(query, ScanQuerySpec):
            druid = [{"columns": cols, "events": events}]
            res = QueryResult(query, events, druid)
        else:
            druid = [{
                "timestamp": iso(plan.t_min),
                "result": {
                    "pagingIdentifiers": {"offset": offset + len(events)},
                    "events": [{"offset": offset + i, "event": e}
                               for i, e in enumerate(events)],
                },
            }]
            res = QueryResult(query, events, druid)
        res.metrics = metrics
        return res

    def _gather_rows(self, table, mask, cols, offset, limit, descending):
        """Columnar assembly: pick (segment, row) takes under the
        offset/limit budget, then decode and convert each COLUMN once
        (dictionary decode, C-level tolist, vectorized null substitution)
        and zip into the wire's list-of-dicts at the end — O(cols)
        vectorized passes instead of a Python render per cell."""
        seg_iter = table.segments[::-1] if descending else table.segments
        takes = []       # (segment, row-index array)
        n_taken = 0
        skipped = 0
        budget = None if limit is None else offset + limit
        for s in seg_iter:
            m = mask[s.meta.segment_id]
            idx = np.nonzero(m)[0]
            if descending:
                idx = idx[::-1]
            if idx.size == 0:
                continue
            if budget is not None and skipped + n_taken + idx.size > budget:
                idx = idx[:budget - skipped - n_taken]
            take = idx
            if skipped < offset:
                drop = min(offset - skipped, take.size)
                skipped += drop
                take = take[drop:]
            if take.size:
                takes.append((s, take))
                n_taken += take.size
            if budget is not None and skipped + n_taken >= budget:
                break
        if not takes:
            return []

        out_cols = []
        for c in cols:
            v = np.concatenate([s.columns[c][take] for s, take in takes])
            d = table.dictionaries.get(c)
            if d is not None:
                out_cols.append(d.decode(v).tolist())
                continue
            vals = v.tolist()  # numpy -> plain python in C
            if any(c in s.null_masks for s, _ in takes):
                nm = np.concatenate(
                    [s.null_masks[c][take] if c in s.null_masks
                     else np.zeros(take.size, bool) for s, take in takes])
                if nm.any():
                    vals = [None if n else x for x, n in zip(vals, nm)]
            if v.dtype.kind == "f":
                vals = [None if x != x else x for x in vals]  # NaN -> null
            out_cols.append(vals)
        return [dict(zip(cols, row)) for row in zip(*out_cols)]

    # ------------------------------------------------------------- metadata

    def _run_search(self, query, table) -> QueryResult:
        """Single-pass search: ONE device dispatch computes the
        filter+interval row mask (shared across every searched
        dimension), then per-dimension value counts are host-side
        bincounts over the dictionary-coded columns — instead of one
        full GroupBy dispatch per dimension (VERDICT round-2 weak #6).
        Non-string dimensions (no dictionary) keep the GroupBy path."""
        dims = list(query.search_dimensions) or [
            c for c, t in table.schema.items() if t.is_dim]
        matcher = _search_matcher(query.query)
        hits = []

        coded = [d for d in dims if d in table.dictionaries]
        if coded:
            mask_query = ScanQuerySpec(
                data_source=query.data_source,
                intervals=query.intervals,
                filter=query.filter,
                virtual_columns=query.virtual_columns,
            )
            metrics = self._last_metrics
            plan = self._lower_cached(mask_query, table)
            partials = self._dispatch(
                lambda: self._run_partials(plan, metrics), metrics,
                table.name)
            # per-dimension masked value counts over the stacked code
            # columns, all dims packed into ONE result vector. On the
            # device platform this is one extra jitted call (~0.2 ms of
            # scatter-adds for all SSB dims at SF1) plus one mask
            # round-trip (_run_partials materializes outputs to host;
            # fusing the counts into the mask program itself would
            # remove that transfer — future work). The numpy platform
            # does the same bincounts in C. The dispatch mask may be
            # padded past the segment stack (shard-multiple rounding) —
            # slice, never the reverse (the kernels mask pruned
            # segments in place rather than compacting them away)
            with self._pipeline_slot():
                # the column fetch mutates the dataset cache and the
                # counts program is a device dispatch: both stage-1
                # work; the host bincounts / transfer run lock-free
                with self._enqueue_lock(metrics):
                    ds = self._dataset(table)
                    cards = tuple(table.dictionaries[d].cardinality
                                  for d in coded)
                    pins = frozenset((table.name, "col", d)
                                     for d in coded)
                    cols = tuple(ds.col(d, pins) for d in coded)
                    n_flat = cols[0].size
                    dev_mask = partials["mask"]
                    if dev_mask.size < n_flat:
                        raise AssertionError(
                            "search mask shorter than the segment stack")
                    packed_dev = None
                    if self.config.platform != "cpu" \
                            and ds.to_logical is None:
                        packed_dev = _search_counts_packed(
                            cards, dev_mask.reshape(-1)[:n_flat], cols)
                if packed_dev is None:
                    m = np.asarray(dev_mask).reshape(-1)[:n_flat]
                    if ds.to_logical is not None:
                        # mesh: the fetched mask was inverse-permuted to
                        # LOGICAL segment order, but the resident column
                        # stacks sit in PLACEMENT order — re-permute so
                        # mask and codes walk the same rows (bincounts
                        # are order-insensitive, consistency is all
                        # that matters)
                        m = m.reshape(len(ds.to_logical), -1)[
                            ds.to_logical].reshape(-1)
                    packed = np.concatenate(
                        [np.bincount(np.asarray(c).reshape(-1)[m],
                                     minlength=card + 1)
                         for c, card in zip(cols, cards)])
                else:
                    packed = np.asarray(packed_dev)
            off = 0
            for dim, card in zip(coded, cards):
                d = table.dictionaries[dim]
                counts = packed[off:off + card + 1]
                off += card + 1
                for code in np.nonzero(counts[1:])[0]:
                    v = d.values[code]
                    if matcher(v):
                        hits.append({"dimension": dim, "value": v,
                                     "count": int(counts[code + 1])})

        for dim in [d for d in dims if d not in table.dictionaries]:
            inner = GroupByQuerySpec(
                data_source=query.data_source,
                intervals=query.intervals,
                filter=query.filter,
                virtual_columns=query.virtual_columns,
                dimensions=(DefaultDimensionSpec(dim),),
                aggregations=(CountAggregation("count"),),
            )
            res = self._run_agg(inner, table)
            for r in res.rows:
                v = r[dim]
                if v is not None and matcher(v):
                    hits.append({"dimension": dim, "value": v,
                                 "count": int(r["count"])})
        hits.sort(key=lambda h: (_search_sort_key(query.sort, h["value"]),
                                 h["dimension"]))
        hits = hits[:query.limit]
        t0, _ = table.time_boundary
        druid = [{"timestamp": iso(t0), "result": hits}]
        return QueryResult(query, hits, druid)

    def _run_time_boundary(self, query, table) -> QueryResult:
        t0, t1 = table.time_boundary
        intervals = query.intervals or (ETERNITY,)
        lo = max(t0, min(iv.start for iv in intervals))
        hi = min(t1, max(iv.end for iv in intervals) - 1)
        result = {}
        if query.bound in (None, "minTime"):
            result["minTime"] = iso(lo)
        if query.bound in (None, "maxTime"):
            result["maxTime"] = iso(hi)
        druid = [{"timestamp": iso(lo), "result": result}]
        return QueryResult(query, [result], druid)

    def _run_segment_metadata(self, query, table) -> QueryResult:
        cols = table.column_metadata(set(query.to_include) or None)
        t0, t1 = table.time_boundary
        record = {
            "id": f"{table.name}_merged",
            "intervals": [f"{iso(t0)}/{iso(t1 + 1)}"],
            "columns": cols,
            "numRows": table.num_rows,
            "size": int(sum(c.get("size", 0) for c in cols.values())),
        }
        return QueryResult(query, [record], [record])


def _next_pow2(n: int) -> int:
    return 1 << max(0, int(n) - 1).bit_length() if n > 1 else 1


def _invert_sort_key(k: np.ndarray):
    if k.dtype.kind in "fiu":
        return -k.astype(np.float64)
    # lexicographic descending for strings: invert via codes trick
    uniq, inv = np.unique(k, return_inverse=True)
    return -inv


_search_counts_jit = None


def _search_counts_packed(cards: tuple, mask, cols):
    """One jitted program: masked value counts for every searched
    dimension, concatenated so the host fetches a single small vector.
    Code 0 is the NULL slot (bincount layout identical to the host
    np.bincount(minlength=card+1) it replaces). The jit wrapper is
    module-cached; distinct (cards, shapes) compile once each."""
    global _search_counts_jit
    if _search_counts_jit is None:
        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit, static_argnums=0)
        def run(cards, mask, cols):
            m = mask.reshape(-1).astype(jnp.int32)
            outs = [jnp.zeros(c + 1, jnp.int32)
                    .at[col.reshape(-1).astype(jnp.int32)]
                    .add(m, mode="drop")
                    for c, col in zip(cards, cols)]
            return jnp.concatenate(outs)

        _search_counts_jit = run
    return _search_counts_jit(cards, mask, tuple(cols))


def _search_sort_key(sort: str, value: str):
    if sort == "strlen":
        return (len(value), value)
    if sort == "alphanumeric":
        # natural order: digit runs compare numerically
        import re
        parts = re.split(r"(\d+)", value)
        return tuple((1, int(p)) if p.isdigit() else (0, p)
                     for p in parts if p != "")
    return value  # lexicographic


def _search_matcher(sq):
    if sq.fragments:
        frags = [f if sq.case_sensitive else f.lower() for f in sq.fragments]

        def m(v):
            s = v if sq.case_sensitive else v.lower()
            return all(f in s for f in frags)
        return m
    needle = sq.value if sq.case_sensitive else sq.value.lower()

    def m(v):
        s = v if sq.case_sensitive else v.lower()
        return needle in s
    return m
